"""Multi-process execution: cooperating processes over the TCP mesh.

Reference parity: the reference's worker architecture (docs
10.worker-architecture.md) — every process builds the same dataflow,
sources are partitioned, and records hash-exchange between processes so
each key's state lives on exactly one worker. These tests spawn real OS
processes via the cli spawn contract and assert (a) combined outputs
equal the single-process results and (b) rows genuinely crossed the
process boundary.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port_base(n: int) -> int:
    socks = []
    ports = []
    for _ in range(n + 4):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return max(ports) + 1  # a fresh contiguous-ish range


SCRIPT = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw
    from pathway_tpu.io.python import ConnectorSubject

    OUT = sys.argv[1]
    PID = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class Part(ConnectorSubject):
        # each process's connector instance reads a DIFFERENT slice of the
        # global stream (sources are partitioned: this connector only runs
        # on its owner process; a second connector covers the other slice)
        def __init__(self, lo, hi):
            self.lo, self.hi = lo, hi

        def run(self):
            import time
            for i in range(self.lo, self.hi):
                self.next(g=f"g{{i % 5}}", v=i)
                time.sleep(0.002)

    # two sources -> round-robin ownership across the 2 processes
    a = pw.io.python.read(Part(0, 30), schema=pw.schema_from_types(g=str, v=int), name="a")
    b = pw.io.python.read(Part(30, 60), schema=pw.schema_from_types(g=str, v=int), name="b")
    t = a.concat_reindex(b)
    agg = t.groupby(t.g).reduce(t.g, total=pw.reducers.sum(t.v), n=pw.reducers.count())
    out = open(OUT + f".{{PID}}", "w")
    rows = {{}}
    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[row["g"]] = (row["total"], row["n"])
        elif rows.get(row["g"]) == (row["total"], row["n"]):
            del rows[row["g"]]
    pw.io.subscribe(agg, on_change=on_change)
    pw.run()
    json.dump(rows, out)
    out.close()
    """
)


def test_two_processes_cooperate_exact_results(tmp_path):
    out = str(tmp_path / "out.json")
    base = _free_port_base(2)
    procs = []
    for pid in range(2):
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PATHWAY_PROCESSES": "2",
            "PATHWAY_PROCESS_ID": str(pid),
            "PATHWAY_FIRST_PORT": str(base),
        }
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", SCRIPT.format(repo=REPO), out],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    for p in procs:
        try:
            _stdout, stderr = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, stderr[-3000:]

    # combined per-process shares = exact global aggregates
    combined: dict = {}
    shares = []
    for pid in range(2):
        with open(out + f".{pid}") as f:
            share = json.load(f)
        shares.append(share)
        for g, (total, n) in share.items():
            assert g not in combined, f"group {g} on two processes"
            combined[g] = (total, n)
    expected = {}
    for i in range(60):
        g = f"g{i % 5}"
        t0, n0 = expected.get(g, (0, 0))
        expected[g] = (t0 + i, n0 + 1)
    assert combined == expected, (combined, expected)
    # the work was actually split: both processes own some groups
    assert all(shares), f"one process owned everything: {shares}"


def test_processes_times_threads(tmp_path):
    """2 processes x 2 thread shards: the exchanges compose — exact
    results with state partitioned at both levels."""
    out = str(tmp_path / "out.json")
    base = _free_port_base(2)
    procs = []
    for pid in range(2):
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PATHWAY_PROCESSES": "2",
            "PATHWAY_PROCESS_ID": str(pid),
            "PATHWAY_FIRST_PORT": str(base),
            "PATHWAY_THREADS": "2",
        }
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", SCRIPT.format(repo=REPO), out],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    for p in procs:
        _stdout, stderr = p.communicate(timeout=180)
        assert p.returncode == 0, stderr[-3000:]
    combined: dict = {}
    for pid in range(2):
        with open(out + f".{pid}") as f:
            combined.update(json.load(f))
    assert sum(n for (_t, n) in combined.values()) == 60
    assert sum(t for (t, _n) in combined.values()) == sum(range(60))


def test_spawn_cli_contract(tmp_path):
    """`python -m pathway_tpu spawn -n 2` launches cooperating processes."""
    out = str(tmp_path / "out.json")
    base = _free_port_base(2)
    script = tmp_path / "pipeline.py"
    script.write_text(SCRIPT.format(repo=REPO).replace("sys.argv[1]", repr(out)))
    r = subprocess.run(
        [
            sys.executable, "-m", "pathway_tpu", "spawn",
            "-n", "2", "--first-port", str(base),
            "--", str(script),
        ],
        capture_output=True, text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO},
        cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    combined = {}
    for pid in range(2):
        with open(out + f".{pid}") as f:
            combined.update(json.load(f))
    assert sum(n for (_t, n) in combined.values()) == 60


ITERATE_SCRIPT = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw

    OUT = sys.argv[1]
    PID = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    def collatz_step(t):
        return {{"t": t.select(
            a=pw.if_else(t.a == 1, 1,
                         pw.if_else(t.a % 2 == 0, t.a // 2, 3 * t.a + 1)))}}

    start = pw.debug.table_from_markdown("a\\n3\\n7\\n27").with_id_from(pw.this.a)
    res = pw.iterate(collatz_step, t=start)
    rows = []
    pw.io.subscribe(res, on_change=lambda key, row, time, is_addition:
                    rows.append(row["a"]) if is_addition else None)
    pw.run()
    json.dump(rows, open(OUT + f".{{PID}}", "w"))
    """
)


def test_iterate_under_two_processes(tmp_path):
    """pw.iterate pins its body to process 0; the other process must not
    deadlock on phantom exchange barriers inside the loop."""
    out = str(tmp_path / "it.json")
    base = _free_port_base(2)
    procs = []
    for pid in range(2):
        env = {
            **os.environ, "JAX_PLATFORMS": "cpu",
            "PATHWAY_PROCESSES": "2", "PATHWAY_PROCESS_ID": str(pid),
            "PATHWAY_FIRST_PORT": str(base),
        }
        procs.append(subprocess.Popen(
            [sys.executable, "-c", ITERATE_SCRIPT.format(repo=REPO), out],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    for p in procs:
        _stdout, stderr = p.communicate(timeout=120)
        assert p.returncode == 0, stderr[-3000:]
    all_rows = []
    for pid in range(2):
        with open(out + f".{pid}") as f:
            all_rows.extend(json.load(f))
    assert sorted(all_rows) == [1, 1, 1], all_rows


SLOW_SCRIPT = textwrap.dedent(
    """
    import os, sys, time
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw
    from pathway_tpu.io.python import ConnectorSubject

    READY = sys.argv[1]
    PID = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class Slow(ConnectorSubject):
        def run(self):
            for i in range(100000):
                self.next(g=f"g{{i % 5}}", v=i)
                if i == 5:
                    open(READY + f".{{PID}}", "w").write("up")
                time.sleep(0.05)

    t = pw.io.python.read(Slow(), schema=pw.schema_from_types(g=str, v=int), name="slow")
    agg = t.groupby(t.g).reduce(t.g, total=pw.reducers.sum(t.v))
    pw.io.subscribe(agg, on_change=lambda key, row, time, is_addition: None)
    pw.run()
    """
)


def test_worker_failure_detected_not_hung(tmp_path):
    """Killing one process mid-run must surface a clear peer-death error
    on the survivor (failure detection), never an indefinite hang."""
    ready = str(tmp_path / "ready")
    base = _free_port_base(2)
    procs = []
    for pid in range(2):
        env = {
            **os.environ, "JAX_PLATFORMS": "cpu",
            "PATHWAY_PROCESSES": "2", "PATHWAY_PROCESS_ID": str(pid),
            "PATHWAY_FIRST_PORT": str(base),
        }
        procs.append(subprocess.Popen(
            [sys.executable, "-c", SLOW_SCRIPT.format(repo=REPO), ready],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    import time as _time

    # the single source lives on process 0; once it streams, lockstep
    # control rounds prove BOTH meshes are up
    deadline = _time.monotonic() + 60
    while _time.monotonic() < deadline:
        if os.path.exists(ready + ".0"):
            break
        _time.sleep(0.1)
    else:
        for p in procs:
            p.kill()
        raise AssertionError("workers did not come up")
    _time.sleep(0.5)  # let a few more waves cross the mesh
    procs[1].kill()
    t0 = _time.monotonic()
    _stdout, stderr = procs[0].communicate(timeout=120)
    detect_s = _time.monotonic() - t0
    procs[1].wait()
    assert procs[0].returncode != 0
    assert "died" in stderr or "peer" in stderr, stderr[-1500:]
    # detection is prompt (socket EOF), not a timeout expiry
    assert detect_s < 30, f"took {detect_s:.1f}s to notice the dead peer"


PERSIST_SCRIPT = textwrap.dedent(
    """
    import json, os, sys, time
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw
    from pathway_tpu.io.python import ConnectorSubject

    PDIR, OUT, READY = sys.argv[1], sys.argv[2], sys.argv[3]
    PID = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class Nums(ConnectorSubject):
        def run(self):
            for i in range(200):
                self.next(g=f"g{{i % 4}}", v=i)
                if i == 5:
                    open(READY + f".{{PID}}", "w").write("up")
                time.sleep(0.01)

    t = pw.io.python.read(Nums(), schema=pw.schema_from_types(g=str, v=int), name="nums")
    agg = t.groupby(t.g).reduce(t.g, total=pw.reducers.sum(t.v), n=pw.reducers.count())
    sink = open(OUT + f".{{PID}}", "a")
    def on_change(key, row, time, is_addition):
        sink.write(json.dumps({{**row, "add": is_addition}}) + "\\n"); sink.flush()
    pw.io.subscribe(agg, on_change=on_change)
    pw.run(persistence_config=pw.persistence.Config(
        pw.persistence.Backend.filesystem(PDIR)))
    """
)


def test_multiprocess_kill_both_and_resume_exact(tmp_path):
    """Both cooperating processes die mid-run (possibly between each
    other's checkpoint commits); restart negotiates the minimum common
    epoch and resumes to EXACT global aggregates."""
    import time as _time

    pdir = str(tmp_path / "pstate")
    out = str(tmp_path / "deliveries")
    ready = str(tmp_path / "ready")
    base = _free_port_base(2)

    def launch():
        procs = []
        for pid in range(2):
            env = {
                **os.environ, "JAX_PLATFORMS": "cpu",
                "PATHWAY_PROCESSES": "2", "PATHWAY_PROCESS_ID": str(pid),
                "PATHWAY_FIRST_PORT": str(base),
            }
            procs.append(subprocess.Popen(
                [sys.executable, "-c", PERSIST_SCRIPT.format(repo=REPO),
                 pdir, out, ready],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            ))
        return procs

    # phase 1: run until waves flow, then SIGKILL both (at slightly
    # different instants — the window between peers' checkpoint commits)
    procs = launch()
    deadline = _time.monotonic() + 60
    while _time.monotonic() < deadline and not os.path.exists(ready + ".0"):
        _time.sleep(0.1)
    assert os.path.exists(ready + ".0"), "phase 1 did not come up"
    _time.sleep(1.0)
    procs[0].kill()
    _time.sleep(0.05)
    procs[1].kill()
    for p in procs:
        p.wait()

    # phase 2: resume with the same dirs; must run to completion
    os.unlink(ready + ".0")
    procs = launch()
    for p in procs:
        _stdout, stderr = p.communicate(timeout=180)
        assert p.returncode == 0, stderr[-3000:]

    # reconstruct per-group finals from the accumulated delivery streams
    state: dict = {}
    for pid in range(2):
        with open(out + f".{pid}") as f:
            for line in f:
                ev = json.loads(line)
                if ev["add"]:
                    state[ev["g"]] = (ev["total"], ev["n"])
                elif state.get(ev["g"]) == (ev["total"], ev["n"]):
                    del state[ev["g"]]
    expected: dict = {}
    for i in range(200):
        g = f"g{i % 4}"
        t0, n0 = expected.get(g, (0, 0))
        expected[g] = (t0 + i, n0 + 1)
    assert state == expected, (state, expected)


CRASH_SCRIPT = textwrap.dedent(
    """
    import json, os, sys, threading, time
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw
    from pathway_tpu.io.python import ConnectorSubject

    OUT = sys.argv[1]       # deliveries jsonl, appended across runs
    PDIR = sys.argv[2]
    MODE = sys.argv[3]      # 'crash' or 'finish'
    PID = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class Part(ConnectorSubject):
        def __init__(self, lo, hi):
            self.lo, self.hi = lo, hi

        def run(self):
            for i in range(self.lo, self.hi):
                self.next(g=f"g{{i % 5}}", v=i)
                time.sleep(0.002)

    a = pw.io.python.read(Part(0, 200), schema=pw.schema_from_types(g=str, v=int), name="a")
    b = pw.io.python.read(Part(200, 400), schema=pw.schema_from_types(g=str, v=int), name="b")
    t = a.concat_reindex(b)
    agg = t.groupby(t.g).reduce(t.g, total=pw.reducers.sum(t.v), n=pw.reducers.count())
    sink = open(OUT + f".{{PID}}", "a")
    def on_change(key, row, time, is_addition):
        sink.write(json.dumps(
            {{"g": row["g"], "total": row["total"], "n": row["n"], "add": is_addition}}
        ) + "\\n")
        sink.flush()
    pw.io.subscribe(agg, on_change=on_change)

    if MODE == "crash" and PID == 1:
        def crasher():
            # kill -9 semantics AFTER both processes committed an epoch
            metas = [os.path.join(PDIR, f"proc-{{p}}", "metadata.json") for p in (0, 1)]
            deadline = time.time() + 60
            while time.time() < deadline:
                if all(os.path.exists(m) for m in metas):
                    os._exit(9)
                time.sleep(0.005)
            os._exit(3)
        threading.Thread(target=crasher, daemon=True).start()

    pw.run(persistence_config=pw.persistence.Config(
        pw.persistence.Backend.filesystem(PDIR),
        snapshot_interval_ms=60))
    """
)


def _consolidate_deliveries(path):
    state = {}
    if not os.path.exists(path):
        return state
    with open(path) as f:
        for line in f:
            ev = json.loads(line)
            if ev["add"]:
                state[ev["g"]] = (ev["total"], ev["n"])
            elif state.get(ev["g"]) == (ev["total"], ev["n"]):
                del state[ev["g"]]
    return state


def _spawn_mesh(out, pdir, mode, base, n=2):
    procs = []
    for pid in range(n):
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PATHWAY_PROCESSES": str(n),
            "PATHWAY_PROCESS_ID": str(pid),
            "PATHWAY_FIRST_PORT": str(base),
        }
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", CRASH_SCRIPT.format(repo=REPO), out, pdir, mode],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    return procs


def test_mesh_kill9_coordinated_recovery(tmp_path):
    """Fault injection (the wordcount test_recovery pattern): kill -9 one
    process of a 2-process mesh mid-stream after a committed epoch, kill
    the stalled survivor, restart the mesh on the same persistence roots
    — coordinated min-epoch recovery yields EXACT aggregates."""
    out = str(tmp_path / "deliv")
    pdir = str(tmp_path / "pstorage")
    base = _free_port_base(2)

    procs = _spawn_mesh(out, pdir, "crash", base)
    # process 1 self-kills (os._exit(9)) after both epochs commit
    try:
        _o, err1 = procs[1].communicate(timeout=120)
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise
    assert procs[1].returncode == 9, (procs[1].returncode, err1[-2000:])
    # the survivor is now stuck/broken on the dead peer: kill -9 it too
    try:
        procs[0].wait(timeout=5)
    except subprocess.TimeoutExpired:
        procs[0].kill()
        procs[0].wait()

    # restart the whole mesh on fresh ports, same persistence roots
    base2 = _free_port_base(2)
    procs2 = _spawn_mesh(out, pdir, "finish", base2)
    for p in procs2:
        try:
            _o, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs2:
                q.kill()
            raise
        assert p.returncode == 0, err[-3000:]

    combined: dict = {}
    for pid in range(2):
        share = _consolidate_deliveries(out + f".{pid}")
        for g, tn in share.items():
            assert g not in combined, f"group {g} delivered on two processes"
            combined[g] = tn
    expected: dict = {}
    for i in range(400):
        g = f"g{i % 5}"
        t0, n0 = expected.get(g, (0, 0))
        expected[g] = (t0 + i, n0 + 1)
    assert combined == expected, (combined, expected)


NATIVE_WIRE_SCRIPT = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw

    OUT = sys.argv[1]
    INPUT = sys.argv[2]
    PID = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class S(pw.Schema):
        word: str

    # one fs source (owned by process 0); the groupby exchange ships the
    # token batches to their owner processes in wire form
    t = pw.io.fs.read(INPUT, format="json", schema=S, mode="streaming",
                      autocommit_duration_ms=20, _single_pass=True)
    agg = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())
    rows = {{}}
    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[row["word"]] = row["n"]
        elif rows.get(row["word"]) == row["n"]:
            del rows[row["word"]]
    pw.io.subscribe(agg, on_change=on_change)
    pw.run()
    json.dump(rows, open(OUT + f".{{PID}}", "w"))
    """
)


def test_native_batches_cross_process_wire(tmp_path):
    """Token-resident fs ingest under a 2-process mesh: batches split in
    C and cross the TCP mesh in wire form; combined counts are exact."""
    inp = tmp_path / "in.jsonl"
    with open(inp, "w") as f:
        for i in range(900):
            f.write('{"word": "w%d"}\n' % (i % 6))
    out = str(tmp_path / "out")
    base = _free_port_base(2)
    procs = []
    for pid in range(2):
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PATHWAY_PROCESSES": "2",
            "PATHWAY_PROCESS_ID": str(pid),
            "PATHWAY_FIRST_PORT": str(base),
        }
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c",
                 NATIVE_WIRE_SCRIPT.format(repo=REPO), out, str(inp)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    for p in procs:
        try:
            _o, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, err[-3000:]
    combined = {}
    shares = []
    for pid in range(2):
        share = json.load(open(out + f".{pid}"))
        shares.append(share)
        for w, n in share.items():
            assert w not in combined
            combined[w] = n
    assert combined == {f"w{i}": 150 for i in range(6)}
    assert all(shares), f"one process owned everything: {shares}"
