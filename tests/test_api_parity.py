"""Top-level API surface parity with the reference: TableSlice,
type-level Table methods, PyObjectWrapper, free-function joins, enum
namespaces, module aliases, deprecated reducer aliases."""

import pytest

import pathway_tpu as pw

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
from utils import run_capture  # noqa: E402


def _t():
    return pw.debug.table_from_markdown(
        """
        age | owner | pet
        10  | Alice | dog
        9   | Bob   | cat
        """
    )


def _vals(table):
    cap = run_capture(table)
    return sorted(tuple(r) for r in cap.state.rows.values())


def test_table_slice_ops():
    t = _t()
    s = t.slice
    assert list(s.keys()) == ["age", "owner", "pet"]
    assert s.without("age").keys() == {"owner": 0, "pet": 0}.keys()
    renamed = s.rename({"age": "years"})
    assert list(renamed.keys()) == ["years", "owner", "pet"]
    assert list(s.with_prefix("p_").keys()) == ["p_age", "p_owner", "p_pet"]
    assert s["age"].name == "age"
    assert s[["age", "owner"]].keys() == {"age": 0, "owner": 0}.keys()
    assert s.owner.name == "owner"
    with pytest.raises(KeyError):
        s.without("nope")
    # renamed slices expand in select under their NEW names
    res = t.select(*s.without("pet").with_suffix("_x"))
    assert res.column_names() == ["age_x", "owner_x"]
    assert _vals(res) == [(9, "Bob"), (10, "Alice")]


def test_from_columns():
    t = _t()
    res = pw.Table.from_columns(t.owner, years=t.age)
    assert res.column_names() == ["owner", "years"]
    assert _vals(res) == [("Alice", 10), ("Bob", 9)]


def test_update_types_and_typehints():
    t = _t()
    assert t.typehints() == {"age": int, "owner": str, "pet": str}
    t2 = t.update_types(age=float)
    assert t2.typehints()["age"] is float
    with pytest.raises(ValueError):
        t.update_types(nope=int)
    assert t.eval_type(t.age + 0.5) is float
    assert t.eval_type(t.owner) is str


def test_update_types_preserves_primary_key():
    class S(pw.Schema):
        k: str = pw.column_definition(primary_key=True)
        v: int

    t = pw.debug.table_from_rows(S, [("a", 1)])
    t2 = t.update_types(v=float)
    assert t2.schema.primary_key_columns() == ["k"]


def test_update_id_type_observable():
    t = _t()
    t2 = t.update_id_type(int)
    assert t2.eval_type(t2.id) is int  # declared id type is visible
    assert t.eval_type(t.id) is not int  # original keeps the generic type


def test_from_columns_requires_shared_universe():
    t1 = _t()
    t2 = pw.debug.table_from_markdown(
        """
        city
        Paris
        """
    )
    with pytest.raises(ValueError, match="universe"):
        pw.Table.from_columns(t1.owner, t2.city)


def test_assert_matches_schema_subtype():
    S = pw.schema_from_types(v=int)
    S.assert_matches_schema(pw.schema_from_types(v=float))  # INT narrows FLOAT
    with pytest.raises(AssertionError):
        S.assert_matches_schema(
            pw.schema_from_types(v=float), allow_subtype=False
        )
    with pytest.raises(AssertionError):
        pw.schema_from_types(v=str).assert_matches_schema(
            pw.schema_from_types(v=float)
        )


def test_generate_class_parameterized_hints(tmp_path):
    import numpy as np

    S = pw.schema_from_types(a=(int | None), arr=np.ndarray)
    src = S.generate_class(class_name="Gen2", generate_imports=True)
    ns: dict = {}
    exec(src, ns)  # noqa: S102 — generated source must be importable
    assert ns["Gen2"].column_names() == ["a", "arr"]


def test_slice_ix_ref_keeps_renames():
    best = pw.debug.table_from_markdown(
        """
        owner | age
        Alice | 10
        """
    ).with_id_from(pw.this.owner)
    queries = pw.debug.table_from_markdown(
        """
        who
        Alice
        """
    )
    s = best.slice.rename({"age": "years"}).ix_ref(
        queries.who, context=queries
    )
    assert list(s.keys()) == ["owner", "years"]
    res = queries.select(*s[["years"]])
    assert _vals(res) == [(10,)]


def test_from_columns_rejects_non_refs():
    with pytest.raises(TypeError):
        pw.Table.from_columns(42)
    with pytest.raises(TypeError):
        pw.Table.from_columns(x=42)


def test_cast_to_types_runtime():
    t = _t()
    t2 = t.cast_to_types(age=float)
    assert t2.typehints()["age"] is float
    assert _vals(t2.select(t2.age)) == [(9.0,), (10.0,)]
    with pytest.raises(ValueError):
        t.cast_to_types(nope=float)


class Blob:
    def __init__(self, x):
        self.x = x

    def __eq__(self, other):
        return isinstance(other, Blob) and other.x == self.x

    def __hash__(self):
        return hash(("Blob", self.x))


def test_py_object_wrapper_through_engine():
    t = _t()
    res = t.select(obj=pw.apply(lambda a: pw.PyObjectWrapper(Blob(a)), t.age))
    cap = run_capture(res)
    vals = [tuple(r) for r in cap.state.rows.values()]
    assert {v[0].value.x for v in vals} == {9, 10}
    # wrapper round-trips the codec (persistence escape path)
    from pathway_tpu.persistence import codec

    w = pw.wrap_py_object(Blob(7))
    got = codec.decode_value(codec.encode_value(w))
    assert isinstance(got, pw.PyObjectWrapper) and got.value.x == 7


def test_free_function_join_and_groupby():
    t = _t()
    owners = pw.debug.table_from_markdown(
        """
        owner | city
        Alice | Paris
        """
    )
    j = pw.join(t, owners, t.owner == owners.owner).select(t.pet, owners.city)
    assert _vals(j) == [("dog", "Paris")]
    g = pw.groupby(t, t.owner).reduce(t.owner, n=pw.reducers.count())
    assert _vals(g) == [("Alice", 1), ("Bob", 1)]


def test_namespaces_and_aliases():
    assert pw.PersistenceMode.UDF_CACHING == "UDF_CACHING"
    assert pw.MonitoringLevel is not None
    assert pw.Joinable is pw.Table and pw.TableLike is pw.Table
    assert pw.UDFSync is pw.UDF and pw.UDFAsync is pw.UDF
    assert pw.csv is pw.io.csv and pw.kafka is pw.io.kafka
    assert pw.AsyncTransformer is not None
    for cls in (
        pw.JoinResult, pw.GroupedTable, pw.AsofJoinResult,
        pw.IntervalJoinResult, pw.WindowJoinResult, pw.TableSlice,
    ):
        assert isinstance(cls, type)


def test_schema_surface():
    class S(pw.Schema):
        k: str = pw.column_definition(primary_key=True)
        v: int = pw.column_definition(default_value=7)
        w: float

    assert S.get_dtype("v").typehint() is int
    assert S.has_default_value("v") and not S.has_default_value("w")
    cp = S.column_properties("k")
    assert cp.dtype.typehint() is str and cp.append_only is False
    assert S.id_type is not None
    src = S.generate_class(class_name="Gen", generate_imports=True)
    assert "class Gen(pw.Schema):" in src
    assert "primary_key=True" in src and "default_value=7" in src
    # the generated class round-trips through exec
    ns: dict = {}
    exec(src, ns)  # noqa: S102
    assert ns["Gen"].column_names() == ["k", "v", "w"]
    # matching
    S.assert_matches_schema(pw.schema_from_types(k=str, v=int))
    with pytest.raises(AssertionError):
        S.assert_matches_schema(pw.schema_from_types(missing=int))
    with pytest.raises(AssertionError):
        S.assert_matches_schema(
            pw.schema_from_types(k=str), allow_superset=False
        )


def test_parquet_roundtrip(tmp_path):
    t = _t()
    p = str(tmp_path / "t.parquet")
    pw.debug.table_to_parquet(t, p)
    t2 = pw.debug.table_from_parquet(p)
    assert sorted(t2.column_names()) == ["age", "owner", "pet"]
    assert sorted(r[0] for r in _vals(t2.select(t2.age))) == [9, 10]


def test_deprecated_reducer_aliases():
    t = _t()
    with pytest.warns(DeprecationWarning):
        e = pw.reducers.int_sum(t.age)
    with pytest.warns(DeprecationWarning):
        e2 = pw.reducers.npsum(t.age)
    res = t.groupby(t.owner).reduce(t.owner, s=e)
    assert _vals(res) == [("Alice", 10), ("Bob", 9)]
    _ = e2