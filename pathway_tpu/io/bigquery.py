"""pw.io.bigquery — API-parity connector (reference: io/bigquery).

Client library gated: see io/_external.py.
"""

from pathway_tpu.io._external import gated_reader, gated_writer

read = gated_reader("bigquery", "google.cloud.bigquery")
write = gated_writer("bigquery", "google.cloud.bigquery")
