"""Windows: tumbling / sliding / session / intervals_over + windowby.

Reference: stdlib/temporal/_window.py (session :595, sliding :660,
tumbling :737, intervals_over :795, windowby :865). Windows lower to: a
rowwise window-id assignment (+ flatten for overlapping windows), optional
behavior ops (engine buffer/forget/freeze), then a groupby on
(_pw_window, _pw_instance).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import pathway_tpu.internals.reducers as red
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals.common import apply_with_type, if_else
from pathway_tpu.internals.expression import ColumnExpression, wrap_arg
from pathway_tpu.internals.groupbys import GroupedTable
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.temporal.temporal_behavior import (
    CommonBehavior,
    ExactlyOnceBehavior,
)


def _num(v: Any) -> Any:
    """Window arithmetic works for both numeric and datetime/duration cols."""
    return v


class Window:
    def assign(
        self,
        table: Table,
        time_expr: ColumnExpression,
        extra: dict | None = None,
    ) -> Table:
        """Return table with added columns: _pw_window_start,
        _pw_window_end, _pw_shard_time (original time), plus any `extra`
        columns — folded into the SAME select where possible, so the
        whole window assignment is one row-build pass over the wave."""
        raise NotImplementedError


@dataclass
class TumblingWindow(Window):
    duration: Any
    origin: Any = None
    offset: Any = None

    def assign(
        self,
        table: Table,
        time_expr: ColumnExpression,
        extra: dict | None = None,
    ) -> Table:
        duration = self.duration
        origin = self.origin if self.origin is not None else self.offset

        if isinstance(duration, (int, float)) and (
            origin is None or isinstance(origin, (int, float))
        ):
            # numeric times: pure expression arithmetic — vectorizable,
            # no tuple column, so rows stay token-resident through the
            # window assignment and the behavior buffer. _pw_window is
            # the window START (it uniquely identifies a tumbling window
            # for a fixed duration; window_join applies one window to
            # both sides, so equality semantics are unchanged). All four
            # columns (plus extras) build in ONE select: repeating the
            # start expression costs two vector subtracts, where a second
            # select would re-build every row in the wave.
            delta = (
                time_expr % duration
                if origin is None
                else (time_expr - origin) % duration
            )
            return table.with_columns(
                _pw_time=time_expr,
                _pw_window_start=time_expr - delta,
                _pw_window=time_expr - delta,
                _pw_window_end=time_expr - delta + duration,
                **(extra or {}),
            )

        def win(t: Any) -> Any:
            o = origin
            if o is None:
                o = t - t if not hasattr(t, "timestamp_ns") else type(t)(ns=0)
            k = (t - o) // duration
            return o + k * duration

        t2 = table.with_columns(
            _pw_window_start=apply_with_type(win, dt.ANY, time_expr),
            _pw_time=time_expr,
            **(extra or {}),
        )
        return t2.with_columns(
            _pw_window=ex.this._pw_window_start,
            _pw_window_end=ex.this._pw_window_start + duration,
        )


def tumbling(duration: Any, origin: Any = None, offset: Any = None) -> TumblingWindow:
    return TumblingWindow(duration, origin, offset)


@dataclass
class SlidingWindow(Window):
    hop: Any
    duration: Any = None
    ratio: int | None = None
    origin: Any = None
    offset: Any = None

    def assign(
        self,
        table: Table,
        time_expr: ColumnExpression,
        extra: dict | None = None,
    ) -> Table:
        hop = self.hop
        duration = self.duration if self.duration is not None else self.ratio * hop
        origin = self.origin if self.origin is not None else self.offset

        def windows(t: Any) -> tuple:
            o = origin
            if o is None:
                o = t - t if not hasattr(t, "timestamp_ns") else type(t)(ns=0)
            # all window starts s with s <= t < s + duration, s = o + k*hop
            first_k = (t - o - duration) // hop + 1
            out = []
            k = first_k
            while True:
                start = o + k * hop
                if start > t:
                    break
                if t < start + duration:
                    out.append((start, start + duration))
                k += 1
            return tuple(out)

        expanded = table.with_columns(
            _pw_windows=apply_with_type(windows, tuple, time_expr),
            _pw_time=time_expr,
            **(extra or {}),
        ).flatten(ex.this._pw_windows)
        return expanded.with_columns(
            _pw_window=ex.this._pw_windows,
            _pw_window_start=ex.this._pw_windows[0],
            _pw_window_end=ex.this._pw_windows[1],
        ).without("_pw_windows")


def sliding(
    hop: Any, duration: Any = None, ratio: int | None = None,
    origin: Any = None, offset: Any = None,
) -> SlidingWindow:
    return SlidingWindow(hop, duration, ratio, origin, offset)


@dataclass
class SessionWindow(Window):
    predicate: Any = None
    max_gap: Any = None

    def assign(self, table: Table, time_expr: ColumnExpression) -> Table:
        # windows form per-instance; assignment happens inside windowby
        raise RuntimeError("session windows are assigned within windowby")


def session(predicate: Any = None, max_gap: Any = None) -> SessionWindow:
    if (predicate is None) == (max_gap is None):
        raise ValueError("session(): provide exactly one of predicate / max_gap")
    return SessionWindow(predicate, max_gap)


@dataclass
class IntervalsOverWindow(Window):
    at: Any
    lower_bound: Any
    upper_bound: Any
    is_outer: bool = True


def intervals_over(
    *, at: Any, lower_bound: Any, upper_bound: Any, is_outer: bool = True
) -> IntervalsOverWindow:
    """Windows at each time t of `at` over [t+lower_bound, t+upper_bound].
    is_outer=True (the reference default) emits EVERY `at` point's window;
    empty ones carry a single all-None data row, so e.g. sorted_tuple
    reduces to (None,) (reference: _window.py:795 intervals_over)."""
    return IntervalsOverWindow(at, lower_bound, upper_bound, is_outer)


class WindowedTable:
    """Result of windowby: behaves like a GroupedTable whose grouping is
    (_pw_window, _pw_instance); reduce() exposes pw.this._pw_window_start
    etc."""

    def __init__(self, expanded: Table, instance_given: bool):
        self._expanded = expanded
        self._instance_given = instance_given

    def reduce(self, *args: Any, **kwargs: Any) -> Table:
        t = self._expanded
        gb_cols = [t._pw_window, t._pw_window_start, t._pw_window_end]
        if "_pw_window_location" in t._column_names():
            gb_cols.append(t._pw_window_location)  # intervals_over probes
        if self._instance_given:
            gb_cols.append(t._pw_instance)
        grouped = t.groupby(*gb_cols)
        # rewrite pw.this._pw_* references to the expanded table
        bound_kwargs = {}
        for name, e in kwargs.items():
            bound_kwargs[name] = _bind_this(wrap_arg(e), t)
        bound_args = []
        for a in args:
            if isinstance(a, ex.ColumnReference):
                if isinstance(a.table, ex.ThisMarker):
                    a = ex.ColumnReference(t, a.name)
            bound_args.append(a)
        return grouped.reduce(*bound_args, **bound_kwargs)


def _bind_this(e: ex.ColumnExpression, table: Table) -> ex.ColumnExpression:
    if isinstance(e, ex.ColumnReference) and isinstance(e.table, ex.ThisMarker):
        if isinstance(e, ex.IdReference):
            return ex.IdReference(table)
        return ex.ColumnReference(table, e.name)
    for name, val in list(vars(e).items()):
        if isinstance(val, ex.ColumnExpression):
            setattr(e, name, _bind_this(val, table))
        elif isinstance(val, tuple) and any(isinstance(v, ex.ColumnExpression) for v in val):
            setattr(e, name, tuple(
                _bind_this(v, table) if isinstance(v, ex.ColumnExpression) else v
                for v in val
            ))
    return e


def _assign_sessions(times_and_keys: tuple, max_gap: Any, predicate: Any) -> tuple:
    """Given sorted ((t, key), ...) produce ((key, start, end), ...)."""
    out = []
    cur: list[tuple] = []
    prev_t = None
    for (t, key) in times_and_keys:
        if prev_t is not None:
            joinable = (
                predicate(prev_t, t) if predicate is not None else (t - prev_t) < max_gap
            )
        else:
            joinable = True
        if not joinable and cur:
            start, end = cur[0][0], cur[-1][0]
            for (ct, ck) in cur:
                out.append((ck, start, end))
            cur = []
        cur.append((t, key))
        prev_t = t
    if cur:
        start, end = cur[0][0], cur[-1][0]
        for (ct, ck) in cur:
            out.append((ck, start, end))
    return tuple(out)


def windowby(
    table: Table,
    time_expr: ColumnExpression,
    *,
    window: Window,
    instance: Any = None,
    behavior: Any = None,
    shard: Any = None,
) -> WindowedTable:
    if instance is None and shard is not None:
        instance = shard
    time_expr = _bind_this(wrap_arg(time_expr), table)
    if instance is not None:
        instance = _bind_this(wrap_arg(instance), table)

    if isinstance(window, SessionWindow):
        expanded = _windowby_session(table, time_expr, window, instance)
    elif isinstance(window, IntervalsOverWindow):
        expanded = _windowby_intervals_over(table, time_expr, window, instance)
    else:
        # _pw_instance folds into the window-assign select: one row-build
        # pass for the whole assignment instead of a second full-wave map
        expanded = window.assign(
            table, time_expr,
            extra={"_pw_instance": instance if instance is not None else 0},
        )

    # Behavior operator ORDER mirrors the reference exactly
    # (reference _window.py:395-415): the cutoff FREEZE sits UPSTREAM of
    # the buffer so its watermark advances with every arriving row —
    # downstream of the buffer it would only see released rows, lag
    # behind, and let late window updates through (breaking
    # exactly-once). After the buffer, event times clamp to the release
    # time so the post-buffer forget's watermark tracks releases.
    if isinstance(behavior, ExactlyOnceBehavior):
        # reference: common_behavior(duration + shift, shift, True)
        shift = behavior.shift
        thr = (
            ex.this._pw_window_end
            if shift is None
            else ex.this._pw_window_end + shift
        )
        expanded = expanded._freeze(
            _bind_this(thr, expanded), ex.ColumnReference(expanded, "_pw_time")
        )
        thr = (
            ex.this._pw_window_end
            if shift is None
            else ex.this._pw_window_end + shift
        )
        expanded = expanded._buffer(
            _bind_this(thr, expanded), ex.ColumnReference(expanded, "_pw_time")
        )
    elif isinstance(behavior, CommonBehavior):
        if behavior.cutoff is not None:
            expanded = expanded._freeze(
                ex.ColumnReference(expanded, "_pw_window_end") + behavior.cutoff,
                ex.ColumnReference(expanded, "_pw_time"),
            )
        if behavior.delay is not None:
            release = (
                ex.ColumnReference(expanded, "_pw_window_start") + behavior.delay
            )
            expanded = expanded._buffer(
                release, ex.ColumnReference(expanded, "_pw_time")
            )
            if behavior.cutoff is not None and not behavior.keep_results:
                # clamp event times to the release time so the post-
                # buffer forget's watermark tracks releases — only the
                # forget consumes this (vectorized: if_else compiles to
                # a numpy plan, so the wave stays token-resident)
                expanded = expanded.with_columns(
                    _pw_time=if_else(
                        ex.ColumnReference(expanded, "_pw_time")
                        > ex.ColumnReference(expanded, "_pw_window_start")
                        + behavior.delay,
                        ex.ColumnReference(expanded, "_pw_time"),
                        ex.ColumnReference(expanded, "_pw_window_start")
                        + behavior.delay,
                    )
                )
        if behavior.cutoff is not None and not behavior.keep_results:
            expanded = expanded._forget(
                ex.ColumnReference(expanded, "_pw_window_end") + behavior.cutoff,
                ex.ColumnReference(expanded, "_pw_time"),
            )

    return WindowedTable(expanded, True)


def _windowby_session(
    table: Table, time_expr: ColumnExpression, window: SessionWindow, instance: Any
) -> Table:
    inst_expr = instance if instance is not None else wrap_arg(0)
    base = table.with_columns(_pw_time=time_expr, _pw_instance=inst_expr)
    # per instance: collect sorted (t, key), segment into sessions
    per_inst = base.groupby(base._pw_instance).reduce(
        base._pw_instance,
        _pw_sessions=ex.ApplyExpression(
            _assign_sessions,
            tuple,
            red.sorted_tuple(ex.MakeTupleExpression(ex.this._pw_time, ex.this.id)),
            window.max_gap,
            window.predicate,
        ),
    )
    flat = per_inst.flatten(per_inst._pw_sessions)
    assignments = flat.select(
        _pw_key=ex.this._pw_sessions[0],
        _pw_window_start=ex.this._pw_sessions[1],
        _pw_window_end=ex.this._pw_sessions[2],
        _pw_instance=ex.this._pw_instance,
    ).with_id(ex.this._pw_key)
    joined = base.join(
        assignments, base.id == assignments._pw_key, id=base.id
    ).select(
        *[ex.ColumnReference(base, n) for n in table._column_names()],
        _pw_time=ex.left._pw_time,
        _pw_instance=ex.right._pw_instance,
        _pw_window_start=ex.right._pw_window_start,
        _pw_window_end=ex.right._pw_window_end,
    )
    return joined.with_columns(
        _pw_window=ex.MakeTupleExpression(
            ex.this._pw_instance, ex.this._pw_window_start, ex.this._pw_window_end
        )
    )


def _windowby_intervals_over(
    table: Table, time_expr: ColumnExpression, window: IntervalsOverWindow, instance: Any
) -> Table:
    at_ref = window.at
    at_table: Table = at_ref.table
    lb, ub = window.lower_bound, window.upper_bound
    span = ub - lb

    def buckets_of(t: Any) -> tuple:
        b = t // span if not hasattr(t, "timestamp_ns") else t.timestamp_ns() // int(span)
        return (b - 1, b, b + 1)

    # expand data rows to covering buckets of their time
    data = table.with_columns(_pw_time=time_expr, _pw_instance=instance if instance is not None else 0)
    data_b = data.with_columns(
        _pw_bucket=apply_with_type(lambda t: (t // span) if not hasattr(t, "timestamp_ns") else t.timestamp_ns() // int(span), int, ex.this._pw_time)
    )
    # expand window centers to all buckets their interval overlaps
    centers = at_table.select(_pw_at=at_ref).with_columns(
        _pw_buckets=apply_with_type(
            lambda t: tuple(
                range(
                    int(((t + lb) // span) if not hasattr(t, "timestamp_ns") else (t + lb).timestamp_ns() // int(span)),
                    int(((t + ub) // span) if not hasattr(t, "timestamp_ns") else (t + ub).timestamp_ns() // int(span)) + 1,
                )
            ),
            tuple,
            ex.this._pw_at,
        )
    ).flatten(ex.this._pw_buckets)
    joined = data_b.join(
        centers, data_b._pw_bucket == centers._pw_buckets
    ).select(
        *[ex.ColumnReference(data_b, n) for n in table._column_names()],
        _pw_time=ex.left._pw_time,
        _pw_instance=ex.left._pw_instance,
        _pw_at=ex.right._pw_at,
    ).filter(
        (ex.this._pw_time >= ex.this._pw_at + lb)
        & (ex.this._pw_time <= ex.this._pw_at + ub)
    )
    expanded = joined.with_columns(
        _pw_window=ex.this._pw_at,
        _pw_window_start=ex.this._pw_at + lb,
        _pw_window_end=ex.this._pw_at + ub,
        _pw_window_location=ex.this._pw_at,
    )
    if window.is_outer:
        # outer windows: every `at` point yields a window even with no
        # data in [at+lb, at+ub] — one all-None data row per empty
        # window, exactly the reference's LEFT interval_join row
        # (reference: _window.py _IntervalsOverWindow._apply:555,
        # tests/temporal/test_windows.py is_outer=True fixture)
        at_distinct = (
            at_table.select(_pw_at=at_ref)
            .groupby(ex.this._pw_at)
            .reduce(_pw_at=ex.this._pw_at)
            .with_id_from(ex.this._pw_at)
        )
        have = (
            joined.groupby(ex.this._pw_at)
            .reduce(_pw_at=ex.this._pw_at)
            .with_id_from(ex.this._pw_at)
        )
        missing = at_distinct.join_left(
            have, at_distinct._pw_at == have._pw_at
        ).select(
            _pw_at=ex.left._pw_at, _pw_hit=ex.right._pw_at
        ).filter(ex.this._pw_hit.is_none())
        empty = missing.select(
            **{n: None for n in table._column_names()},
            _pw_time=None,
            _pw_instance=None,
            _pw_at=ex.this._pw_at,
            _pw_window=ex.this._pw_at,
            _pw_window_start=ex.this._pw_at + lb,
            _pw_window_end=ex.this._pw_at + ub,
            _pw_window_location=ex.this._pw_at,
        )
        expanded = expanded.concat_reindex(empty)
    return expanded
