"""pw.io.jsonlines (reference: io/jsonlines)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import fs


def read(path: Any, *, schema: Any = None, mode: str = "streaming", **kwargs: Any):
    return fs.read(path, format="json", schema=schema, mode=mode, **kwargs)


def write(table: Any, filename: Any, **kwargs: Any) -> None:
    fs.write(table, filename, format="json", **kwargs)
