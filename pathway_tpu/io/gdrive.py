"""pw.io.gdrive — API-parity connector (reference: io/gdrive).

Client library gated: see io/_external.py.
"""

from pathway_tpu.io._external import gated_reader, gated_writer

read = gated_reader("gdrive", "google.oauth2")
write = gated_writer("gdrive", "google.oauth2")
