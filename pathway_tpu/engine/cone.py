"""Wave-cone megakernel: one host dispatch from scan to exchange.

The planner identifies a *wave cone* — scan source (`InputNode`) →
optional fused rowwise run (`FusedRowwiseNode` with a native program) →
bucketized groupby update (`GroupByNode`, possibly wrapped by a
`ShardedNode` whose exchange pack rides the PR 13 column plane) — and
this module compiles it into a single fire per wave: `Graph.step` skips
the absorbed interior members and drives the whole cone at the head's
topo slot, so a steady-state wave pays O(1) host dispatches for the
cone instead of one per operator (the `pathway_wave_dispatches`
histogram measures the claim).

Why the output stays byte-identical to the per-node plan
--------------------------------------------------------

The per-node path concatenates a wave's scan segments once at the
`InputNode` (`_emit_merged`: `NativeBatch.concat` + distinct check) and
every downstream operator sees ONE batch. The cone never builds that
concat — it streams the segments — so it must prove the merge
commutes through each member:

* the fused rowwise program is row-local: running it per segment and
  concatenating the outputs is row-for-row the run over the
  concatenation (selection masks, `build_rows`, and the BAD-row
  fallback indices are all per-row functions, and per-segment fallback
  order equals global sorted order because segments are processed in
  arrival order);
* `zs_agg_update` returns affected groups in FIRST-OCCURRENCE order of
  its input with LIVE post-update values, and its float accumulation
  visits rows in batch order — so per-segment updates merged by
  first-occurrence / last-value-wins (`_merge_agg`) equal one update
  over the concatenation, PROVIDED `_emit_agg` runs once on the merged
  result (`delta_emit` mutates the emitted-state; per-segment emission
  would leak intermediate retract/insert pairs the concat never made).

Eligibility is re-checked per wave; anything the proof does not cover
degrades to the existing per-node path for that wave — never silently
(`fallback_fires` + reason are counted in the plan report):

* an object entry or a segment without ``distinct_hint`` in the scan
  pending (the per-node path may consolidate; the cone must not guess),
* a group projection / column decode the plan rejects,
* BAD rows surfacing from the fused program (the captured per-segment
  outputs are replayed through the target as the concat the per-node
  path would have built — same bytes, one wave of per-node semantics),
* a skewed (multi-round) exchange layout — the sharded split itself
  falls back inside `ColumnExchanger`, and donation is only taken on
  single-round layouts (`plan_respill_layout`; re-proved by
  `internals/verifier.check_cone_contract` before any compile).

`PATHWAY_MEGAKERNEL=0` (read once at the lowering seam —
`planner.megakernel_enabled`) skips installation entirely: the graph is
byte-identical to the PR 9 fused plan. The frontier scheduler drives
nodes individually, so `Runtime._make_scheduler` dissolves cones loudly
(plan report `megakernel.dissolved`) instead of leaving them dormant.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from pathway_tpu.engine.core import (
    FusedRowwiseNode,
    GroupByNode,
    InputNode,
    _NativeProgramBuilder,
    _nb_type,
)
from pathway_tpu.engine import morsel as _morsel
from pathway_tpu.engine.workers import ShardedNode, _pool

__all__ = [
    "WaveCone",
    "ConeProgramBuilder",
    "install_cones",
    "dissolve_cones",
]


class _Capture:
    """Duck-typed sink standing in for a member's downstream during a
    cone fire: collects emissions (NativeBatch segments and entry lists)
    in arrival order so the cone can merge them instead of letting them
    land per-segment in the target's buffers."""

    __slots__ = ("items",)

    def __init__(self) -> None:
        self.items: list = []

    def accept(self, _idx: int, entries: Any) -> None:
        self.items.append(entries)


class ConeProgramBuilder:
    """Assembles one cone's compiled-plan descriptor from its members —
    the `_NativeProgramBuilder` grown over the whole cone: the fused
    interior program is re-adopted (and re-validated by the plan
    verifier's schema check), the groupby plan and the exchange layout
    ride along, and the donation contract is stated explicitly so
    `check_cone_contract` can refuse it before any compile."""

    def __init__(self) -> None:
        self._interior: dict | None = None
        self._gb_cols: list[int] = []
        self._n_reducers: int = 0
        self._n_shards: int = 1

    def adopt_interior(self, program: dict) -> None:
        b = _NativeProgramBuilder()
        b.adopt(program)
        b.src_width = program.get("src_width")
        self._interior = b.build()

    def set_groupby(self, plan: dict, n_reducers: int) -> None:
        self._gb_cols = list(plan["gb_cols"])
        self._n_reducers = n_reducers

    def set_exchange(self, n_shards: int) -> None:
        self._n_shards = n_shards

    def build(self) -> dict:
        return {
            "interior": self._interior,
            "gb_cols": list(self._gb_cols),
            "n_reducers": self._n_reducers,
            "n_shards": self._n_shards,
            # a NativeBatch ships (key_lo, key_hi, token, diff): four
            # u64 lanes — the staging buffer shape the exchange pads
            "lanes": 4,
            # donated staging buffers alias the receive buffers, which
            # is sound only for single-round layouts; multi-round waves
            # run undonated (exchange.plan_respill_layout)
            "donation": "single-round",
            "rounds": 1,
        }


class WaveCone:
    """One installed cone: members stay live (fallback, persistence,
    Graph.end all still see them) but `Graph.step` skips the absorbed
    interior and fires the cone once at the head's topo slot."""

    def __init__(
        self,
        head: InputNode,
        fused: FusedRowwiseNode | None,
        target: Any,  # GroupByNode | ShardedNode over GroupByNode
        report: dict,
    ):
        self.head = head
        self.fused = fused
        self.target = target
        self.members = [head] + ([fused] if fused is not None else []) + [target]
        self.report = report
        self.program = self._build_program()

    def _build_program(self) -> dict:
        b = ConeProgramBuilder()
        if self.fused is not None and self.fused._program is not None:
            b.adopt_interior(self.fused._program)
        t = self.target
        if isinstance(t, ShardedNode):
            b.set_exchange(t.n_shards)
            gb = t.replicas[0]
        else:
            gb = t
        if isinstance(gb, GroupByNode) and gb._plan is not None:
            b.set_groupby(gb._plan, len(gb.reducers))
        return b.build()

    # ----------------------------------------------------------- firing

    def fire(self, time: int) -> int:
        """Drive one wave through the cone; returns the number of host
        dispatches it cost (1 on the cone path, the member count on a
        fallback wave — Graph.step folds this into dispatch_count so the
        `pathway_wave_dispatches` histogram stays honest)."""
        head = self.head
        if not head.pending:
            return 1
        nb_t = _nb_type()
        if nb_t is None or any(
            type(s) is not nb_t or not s.distinct_hint for s in head.pending
        ):
            # the per-node path may consolidate such a wave; replay it
            # through the members unchanged (head.pending untouched)
            return self._fallback(time, "object-or-unhinted-wave")
        segs, head.pending = head.pending, []
        head.rows_out += sum(len(s) for s in segs)
        if _morsel.enabled_cached():
            # cache-sized morsels: oversized scan segments split into
            # row-contiguous slices so the fused run and the sharded
            # update below get steal-balanceable units. Concatenating
            # the slices reproduces the segment row-for-row (bool-mask
            # select keeps distinct_hint), so the segment-merge proof
            # above covers morsels unchanged.
            rows = _morsel.morsel_rows_cached()
            if any(len(s) > rows for s in segs):
                split = [m for s in segs for m in _morsel.split_batch(s, rows)]
                from pathway_tpu.internals import observability as _obs

                if _obs.PLANE is not None:
                    _obs.PLANE.metrics.counter(
                        "pathway_morsel_split_total",
                        inc=len(split) - len(segs),
                        help="extra segments created by morsel splitting",
                    )
                segs = split
        batches: list = segs
        entries: list = []
        fused = self.fused
        if fused is not None:
            sink = _Capture()
            saved = fused.downstream
            fused.downstream = [(sink, 0)]  # type: ignore[list-item]
            try:
                for b in segs:
                    fused.rows_in += len(b)
                    fused._run_batch(time, b)
            finally:
                fused.downstream = saved
            batches = [s for s in sink.items if type(s) is not list]
            entries = [e for s in sink.items if type(s) is list for e in s]
        if entries:
            # BAD rows ran the composed per-row path: replay the
            # captured outputs through the target as the concat the
            # per-node path would have built (same rows, same order)
            return self._replay_target(time, batches, entries, "bad-rows")
        if not batches:
            self._count_fire()
            return 1
        target = self.target
        if isinstance(target, ShardedNode):
            ok = self._fire_sharded(time, target, batches)
        else:
            ok = self._fire_groupby(time, target, batches)
        if ok:
            self._count_fire()
            return 1
        return self._replay_target(time, batches, [], "plan-rejected-batch")

    # ------------------------------------------------- target: groupby

    def _fire_groupby(self, time: int, gb: GroupByNode, batches: list) -> bool:
        if gb._native is None or gb._plan is None:
            return False
        preps = []
        for b in batches:
            p = gb._prepare_native_batch(b)
            if p is None:
                return False  # nothing applied yet: clean per-node replay
            preps.append(p)
        parts = []
        for b, (gtok, vals_i, vals_f, tags) in zip(batches, preps):
            gb.rows_in += len(b)
            parts.append(
                gb._native.update(
                    gtok, vals_i, vals_f, tags, np.ascontiguousarray(b.diff)
                )
            )
        gb._emit_agg(time, *_merge_agg(parts))
        return True

    # ------------------------------------------- target: sharded groupby

    def _fire_sharded(self, time: int, sh: ShardedNode, batches: list) -> bool:
        from pathway_tpu.engine.native import dataplane as dp
        from pathway_tpu.parallel.column_plane import engine_column_exchanger

        plan = sh.native_routes[0]
        if plan is None or plan[0] != "group":
            return False
        replicas = sh.replicas
        if any(r._native is None or r._plan is None for r in replicas):
            return False
        n = sh.n_shards
        gb_cols = plan[1]
        ce = engine_column_exchanger()
        # phase A (pure): one fused projection per segment yields BOTH
        # the group tokens and the shard routing — the exchange pack and
        # the groupby update share the projection instead of each
        # re-projecting their side of the wire
        prepared = []  # (sub_batches, sub_gtoks) per segment
        for b in batches:
            res = dp.project_group(b.tab, b.token, gb_cols, n_shards=n)
            if res is None:
                return False
            gtok_full, shards = res
            subs = ce.split_batch(b, shards, n) if ce is not None else None
            if subs is None:
                subs = [b.select(shards == s) for s in range(n)]
            # split_batch is row-for-row identical to the select path,
            # so the per-shard group tokens are just the sliced rows
            gtoks = [gtok_full[shards == s] for s in range(n)]
            preps = []
            for s in range(n):
                if not len(subs[s]):
                    preps.append(None)
                    continue
                p = replicas[s]._prepare_native_batch(subs[s], gtok=gtoks[s])
                if p is None:
                    return False
                preps.append(p)
            prepared.append((subs, preps))
        # phase B (stateful): per-replica updates merge across segments
        # and emit ONCE per replica, mirroring the unsharded cone
        sh.rows_in += sum(len(b) for b in batches)
        touched = sorted(
            {
                s
                for _subs, preps in prepared
                for s, p in enumerate(preps)
                if p is not None
            }
        )
        if not touched:
            return True

        def run_replica(s: int) -> None:
            gb = replicas[s]
            parts = []
            for subs, preps in prepared:
                if preps[s] is None:
                    continue
                gtok, vals_i, vals_f, tags = preps[s]
                gb.rows_in += len(subs[s])
                parts.append(
                    gb._native.update(
                        gtok, vals_i, vals_f, tags,
                        np.ascontiguousarray(subs[s].diff),
                    )
                )
            gb._emit_agg(time, *_merge_agg(parts))

        if len(touched) == 1:
            run_replica(touched[0])
        elif _morsel.enabled_cached():
            # per-replica morsel queues: each (replica, segment) update
            # is one steal-able unit, each queue runs in segment order
            # on exactly one thread at a time (StealScheduler's busy
            # latch), parts collect in segment order, and the closing
            # task merges + emits ONCE per replica — exactly the serial
            # run_replica, just drained by whichever worker is idle.
            _morsel.run_stealing(
                [self._replica_queue(replicas[s], time, prepared, s)
                 for s in touched]
            )
        else:
            futures = [_pool().submit(run_replica, s) for s in touched]
            for f in futures:
                f.result()  # wave barrier; re-raises replica errors
        sh._emit_collected(time, touched)
        return True

    @staticmethod
    def _replica_queue(gb, time: int, prepared: list, s: int) -> list:
        """Ordered morsel tasks for one replica: one native update per
        prepared segment appending into `parts`, then one merge+emit
        tail. The queue's in-order, single-consumer execution is what
        makes parts == the serial segment loop."""
        parts: list = []

        def update_task(subs, prep):
            gtok, vals_i, vals_f, tags = prep

            def run() -> None:
                gb.rows_in += len(subs[s])
                parts.append(
                    gb._native.update(
                        gtok, vals_i, vals_f, tags,
                        np.ascontiguousarray(subs[s].diff),
                    )
                )

            return run

        tasks = [
            update_task(subs, preps[s])
            for subs, preps in prepared
            if preps[s] is not None
        ]

        def emit_tail() -> None:
            gb._emit_agg(time, *_merge_agg(parts))

        tasks.append(emit_tail)
        return tasks

    # --------------------------------------------------------- fallback

    def _replay_target(
        self, time: int, batches: list, entries: list, reason: str
    ) -> int:
        """Degrade the rest of this wave to the per-node path: feed the
        target exactly what it would have received from the concat plan
        (one merged batch, then the entry tail) and fire it normally."""
        nb_t = _nb_type()
        target = self.target
        if batches:
            nb = batches[0] if len(batches) == 1 else nb_t.concat(batches)
            target.accept(0, nb)
        if entries:
            target.accept(0, list(entries))
        target.finish_time(time)
        self._count_fallback(time, reason, drive_members=False)
        return len(self.members)

    def _fallback(self, time: int, reason: str) -> int:
        """Whole-wave degrade: drive every member's own finish_time in
        topo order — literally the per-node plan for this wave."""
        for m in self.members:
            m.finish_time(time)
        self._count_fallback(time, reason, drive_members=True)
        return len(self.members)

    # ------------------------------------------------------- accounting

    def _count_fire(self) -> None:
        self.report["cone_fires"] = self.report.get("cone_fires", 0) + 1

    def _count_fallback(self, time: int, reason: str, drive_members: bool) -> None:
        self.report["fallback_fires"] = self.report.get("fallback_fires", 0) + 1
        reasons = self.report.setdefault("fallback_reasons", {})
        reasons[reason] = reasons.get(reason, 0) + 1
        from pathway_tpu.internals import observability as _obs

        if _obs.PLANE is not None:
            _obs.PLANE.record(
                "cone.fallback", export=False, reason=reason, t=time,
                members=len(self.members), whole_wave=drive_members,
            )


def _merge_agg(parts: list) -> tuple:
    """Merge per-segment `zs_agg_update` results into what ONE update
    over the concatenation returns: affected groups in first-occurrence
    order across segments, each carrying the LAST segment's live value
    (dict assignment keeps the original insertion position)."""
    if len(parts) == 1:
        return parts[0]
    pick: dict[int, tuple[int, int]] = {}
    for pi, part in enumerate(parts):
        g_ids = part[0]
        for j in range(len(g_ids)):
            pick[int(g_ids[j])] = (pi, j)
    m = len(pick)
    p0 = parts[0]
    g_ids = np.empty(m, p0[0].dtype)
    totals = np.empty(m, p0[1].dtype)
    isum = np.empty((m,) + p0[2].shape[1:], p0[2].dtype)
    fsum = np.empty((m,) + p0[3].shape[1:], p0[3].dtype)
    cnts = np.empty((m,) + p0[4].shape[1:], p0[4].dtype)
    flags = np.empty((m,) + p0[5].shape[1:], p0[5].dtype)
    for k, (gid, (pi, j)) in enumerate(pick.items()):
        part = parts[pi]
        g_ids[k] = gid
        totals[k] = part[1][j]
        isum[k] = part[2][j]
        fsum[k] = part[3][j]
        cnts[k] = part[4][j]
        flags[k] = part[5][j]
    return g_ids, totals, isum, fsum, cnts, flags


# --------------------------------------------------------- install / dissolve


def install_cones(session) -> list[WaveCone]:
    """Identify and install wave cones over a lowered session's live
    graph (planner.find_cone_chains does the identification; this marks
    the members and registers the cones on the graph). Runs BEFORE the
    plan verifier so `check_cone_contract` re-proves every installed
    cone's contract ahead of any compile."""
    from pathway_tpu.internals import planner as _planner

    graph = session.graph
    rep = session.plan_report
    mk = rep.setdefault(
        "megakernel", {"enabled": True, "cones": [], "dissolved": None}
    )
    cones: list[WaveCone] = []
    for chain in _planner.find_cone_chains(graph):
        head, fused, target = chain
        cone_rep = {
            "members": [m.describe() for m in (head, fused, target) if m is not None],
            "cone_fires": 0,
            "fallback_fires": 0,
        }
        cone = WaveCone(head, fused, target, cone_rep)
        for m in cone.members[1:]:
            m._cone_absorbed = True
        head._cone = cone
        mk["cones"].append(cone_rep)
        cones.append(cone)
    graph._cones = cones
    return cones


def dissolve_cones(graph, reason: str) -> int:
    """Uninstall every cone on a graph — loudly, never silently: the
    frontier scheduler drives nodes individually, so an installed cone
    would simply never fire there; dissolving records WHY the plan fell
    back to per-node dispatch."""
    cones = getattr(graph, "_cones", None)
    if not cones:
        return 0
    for cone in cones:
        cone.head._cone = None
        for m in cone.members[1:]:
            m._cone_absorbed = False
    n = len(cones)
    graph._cones = []
    rep = getattr(graph, "plan_report", None)
    if rep is not None and "megakernel" in rep:
        rep["megakernel"]["dissolved"] = reason
    from pathway_tpu.internals import observability as _obs

    if _obs.PLANE is not None:
        _obs.PLANE.record("cone.dissolve", reason=reason, cones=n)
    return n
