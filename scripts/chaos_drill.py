#!/usr/bin/env python
"""Crash-recovery equivalence drills: the exactly-once claim, regression-tested.

For a matrix of seeded fault schedules × fault kinds, this harness runs
the SAME streaming pipeline (a journaled python source → groupby counts →
a batched device-plane UDF → THREE real sinks: an atomic fs/jsonlines
file, a kafka producer against a mock broker, and an http writer against
a mock endpoint) three ways:

  1. fault-free baseline (``PATHWAY_FAULTS=0``),
  2. with an injected fault — crash mid-wave, torn metadata commit,
     truncated journal segment, lost operator snapshot, flapping
     connector reads, failing device dispatches, a dropping
     device-exchange wire (``mesh.device_wire`` — the sharded column
     plane must degrade to the host wire byte-identically,
     parallel/column_plane.py), and the sink-side crash windows of the
     transactional outbox (pre-seal, post-seal, torn mid-flush —
     io/outbox.py),
  3. (for crash kinds) a recovery generation that resumes from the same
     persistence directory.

and asserts the **delivered sink output** — post-replay, post-dedup,
consolidated to the final table — is **byte-identical** to the
baseline's, per sink. This is the end-to-end exactly-once contract: not
just engine state, but what actually reached the fs file / broker /
endpoint.

With ``PATHWAY_EXACTLY_ONCE=0`` the drill reproduces the pre-outbox
at-least-once behavior: sink kinds are skipped (their injection points
never probe), the queue/http sinks must still consolidate to the
baseline (duplicates absorbed), and the fs file — truncated per
generation by the direct writer — is excluded from comparison, which is
exactly the gap the outbox exists to close.

A second family of **elastic** kinds (``worker_join``, ``worker_leave``,
``swap_mid_commit``, ``swap_divergent``) drills the supervised mesh
instead: membership changes announced under load must rebalance through
the quiesce fence and still deliver the analytic table, and blue/green
swaps crashed mid-commit (roll forward) or diverged at replay (abort,
blue untouched) must leave the delivered sink output intact.

Usage::

    python scripts/chaos_drill.py --quick          # 8 kinds x 1 seed (CI leg)
    python scripts/chaos_drill.py                  # 15 kinds x 3 seeds
    python scripts/chaos_drill.py --kinds sink_torn_flush --seeds 0,1,2
    python scripts/chaos_drill.py --kinds worker_join,worker_leave --seeds 0,1,2
    python scripts/chaos_drill.py --json /tmp/chaos.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CRASH_EXIT = 17  # engine/faults.py CRASH_EXIT_CODE

# --------------------------------------------------------------- workload
#
# One pipeline exercising every failure domain: a paced seekable source
# whose reads go through pw.io.RetryPolicy (connector domain), journaled
# persistence with operator snapshots (persistence domain), a groupby
# (operator state), a batched UDF dispatching through a DevicePlane
# program (device domain), and three REAL sink code paths (sink domain):
# pw.io.jsonlines (atomic segments under exactly-once), pw.io.kafka
# against an injected mock confluent_kafka producer, and pw.io.http
# against a mocked requests.request. The mock targets append-log every
# delivery, so the harness can consolidate exactly what was delivered —
# across crash generations, after outbox replays, with dedup by the
# content-key headers.

WORKLOAD = textwrap.dedent(
    """
    import json, os, sys, types
    sys.path.insert(0, {repo!r})

    PDIR, OUTDIR, N_EVENTS = sys.argv[1], sys.argv[2], int(sys.argv[3])
    os.makedirs(OUTDIR, exist_ok=True)

    # ---- mock broker: a confluent_kafka stand-in that append-logs every
    # produced message (payload + headers) — drives the REAL
    # pw.io.kafka.write code path, incl. the pathway_msg_id content keys
    fake_ck = types.ModuleType("confluent_kafka")
    class _Producer:
        def __init__(self, settings):
            self._f = open(os.path.join(OUTDIR, "kafka.jsonl"), "a")
        def produce(self, topic, payload, key=None, headers=None):
            self._f.write(json.dumps({{
                "topic": topic,
                "payload": payload.decode("utf-8"),
                "headers": {{k: v.decode("utf-8") for k, v in (headers or [])}},
            }}) + "\\n")
            self._f.flush()
        def flush(self, timeout=None):
            self._f.flush(); os.fsync(self._f.fileno())
    fake_ck.Producer = _Producer
    sys.modules["confluent_kafka"] = fake_ck

    # ---- mock endpoint: requests.request append-logs every delivery
    try:
        import requests as _rq
    except Exception:
        _rq = types.ModuleType("requests")
        sys.modules["requests"] = _rq
    def _fake_request(method, url, json=None, headers=None, timeout=None):
        with open(os.path.join(OUTDIR, "http.jsonl"), "a") as f:
            f.write(__import__("json").dumps(
                {{"url": url, "body": json, "headers": dict(headers or {{}})}}
            ) + "\\n")
            f.flush(); os.fsync(f.fileno())
    _rq.request = _fake_request

    import numpy as np
    import pathway_tpu as pw
    from pathway_tpu.engine.device_plane import DeviceProgram, get_device_plane
    from pathway_tpu.internals import observability as obs
    from pathway_tpu.io import RetryPolicy
    from pathway_tpu.io.python import ConnectorSubject

    SPEC = os.environ.get("PATHWAY_FAULTS", "0")
    # arm the flight recorder BEFORE any fault can fire: every shot of
    # the schedule must land in the recorder timeline (harness asserts)
    obs.maybe_enable_from_env()

    DeviceProgram.PROBE_BASE_S = 0.01  # drill-speed re-probe backoff
    plane = get_device_plane()
    prog = plane.program("chaos_double", lambda x: x * 2 + 1)

    @pw.udf(batched=True, deterministic=True)
    def boost(vs: list[int]) -> list[int]:
        arr = np.asarray(vs, dtype=np.int32)
        b = plane.buckets.rows_bucket(len(arr))
        out = prog(np.pad(arr, (0, b - len(arr))), bucket=b)
        return [int(x) for x in np.asarray(out)[: len(arr)]]

    src_policy = RetryPolicy(
        "chaos-src", max_attempts=10, initial_delay_ms=1,
        backoff_factor=1.0, jitter_ms=0, breaker_threshold=None,
    )

    def committed_offset() -> int:
        try:
            with open(os.path.join(PDIR, "metadata.json")) as f:
                return int(json.load(f).get("offsets", {{}}).get("words", 0))
        except Exception:
            return 0

    class Words(ConnectorSubject):
        def run(self):
            import time
            for i in range(N_EVENTS):
                # the injectable read: io.retry.chaos-src faults land
                # here and the unified policy absorbs them
                w = src_policy.call(lambda i=i: f"w{{i % 7}}")
                self.next(word=w)
                time.sleep(0.004)
                if i % 10 == 9:
                    # deterministic mid-run epochs: stall until a commit
                    # covers everything emitted so far (in-flight device
                    # holds resolve, the cadence checkpoint cuts). Time-
                    # based gaps are flaky on slow CI boxes — the commit
                    # count then varies and seeded @hit schedules miss.
                    deadline = time.monotonic() + 5.0
                    while (
                        committed_offset() < i + 1
                        and time.monotonic() < deadline
                    ):
                        time.sleep(0.002)

    t = pw.io.python.read(
        Words(), schema=pw.schema_from_types(word=str), name="words"
    )
    counts = t.groupby(t.word).reduce(
        t.word, count=pw.reducers.count()
    )
    counts = counts.select(
        counts.word, counts.count, boosted=boost(counts.count)
    )
    # three real sink code paths; delivered output is what the harness
    # consolidates and compares (no subscribe side-channel, no newline
    # guards: the atomic fs path makes torn sink lines impossible)
    pw.io.jsonlines.write(counts, os.path.join(OUTDIR, "fs.jsonl"))
    pw.io.kafka.write(
        counts, {{"bootstrap.servers": "mock:9092"}}, "chaos-counts"
    )
    pw.io.http.write(counts, "http://chaos.test/sink", n_retries=2)
    pw.run(persistence_config=pw.persistence.Config(
        pw.persistence.Backend.filesystem(PDIR)))

    # a non-crash fault schedule must actually have exercised its domain
    if "io.retry.chaos-src" in SPEC:
        assert src_policy.retries_total > 0, "flap schedule never flapped"
    if "device.dispatch" in SPEC:
        assert prog.host_fallbacks > 0, "device schedule never degraded"
    if "mesh.device_wire" in SPEC:
        from pathway_tpu.parallel import column_plane
        assert column_plane.stats()["wire_faults"] > 0, (
            "device-wire schedule never probed the column plane"
        )
    # normal-exit black box (hard crashes dump inside faults.hard_crash)
    obs.dump_flight("drill-end")
    """
)


def exactly_once_mode() -> bool:
    return os.environ.get("PATHWAY_EXACTLY_ONCE", "1") != "0"


# ------------------------------------------------------------ fault kinds
#
# Hit numbers are seeded so each seed crashes at a different wave /
# commit / journal offset / sink flush; all stay comfortably inside the
# run's hit budget (~25+ pumped waves, N_EVENTS journal appends, and —
# thanks to the source's wait-for-commit pacing — at least
# N_EVENTS/10 + 2 checkpoint commits, each sealing + delivering to all
# three sinks).

KINDS = {
    "crash_mid_wave": lambda seed: f"seed={seed};runtime.wave@{3 + 3 * seed}",
    "torn_metadata": lambda seed: (
        f"seed={seed};persistence.metadata.torn@{2 + seed}"
    ),
    "torn_journal": lambda seed: (
        f"seed={seed};persistence.journal.torn@{10 + 9 * seed}"
    ),
    # crash right AFTER a mid-run commit, then the harness deletes one of
    # that epoch's snapshot files: restore must catch the manifest hole
    # and fall back to the history epoch
    "lost_snapshot": lambda seed: (
        f"seed={seed};persistence.checkpoint.post_commit@{2 + seed}"
    ),
    "connector_flap": lambda seed: f"seed={seed};io.retry.chaos-src~0.25",
    "device_dispatch": lambda seed: (
        f"seed={seed};device.dispatch.chaos_double@1+2"
    ),
    # sink-side crash windows of the transactional outbox (io/outbox.py)
    "sink_pre_seal": lambda seed: (
        f"seed={seed};sink.outbox.pre_seal@{2 + seed}"
    ),
    "sink_post_seal": lambda seed: (
        f"seed={seed};sink.outbox.post_seal@{2 + seed}"
    ),
    "sink_torn_flush": lambda seed: (
        f"seed={seed};sink.flush.torn@{3 + 2 * seed}"
    ),
    # the sharded column plane's wire drops every wave from hit 1+seed on
    # (both the first shot and its retry fire): every native split must
    # degrade to the host wire and the delivered output must stay
    # byte-identical to the unfaulted single-thread baseline
    # (parallel/column_plane.py; runs under PATHWAY_DEVICE_EXCHANGE=1 +
    # PATHWAY_THREADS=4 on a virtual 8-device mesh — KIND_ENV)
    "device_wire": lambda seed: (
        f"seed={seed};mesh.device_wire@{1 + seed}+"
    ),
    # crash INSIDE spill compaction: the merged run is written but the
    # generation swap never happens — recovery must restore from the
    # pre-merge runs (still on disk, still in the committed manifest)
    # and replay to output byte-identical with the unspilled baseline.
    # Runs under a 2-group resident budget so the 7-word state spills
    # and compacts constantly (KIND_ENV)
    "compaction_mid_merge": lambda seed: (
        f"seed={seed};state.compaction.mid_merge@{1 + seed}"
    ),
}
# per-kind workload environment (applied to the FAULTED runs only; the
# baseline stays the plain single-thread host-wire run, which is exactly
# the equivalence the kind claims)
KIND_ENV = {
    "device_wire": {
        "PATHWAY_THREADS": "4",
        "PATHWAY_DEVICE_EXCHANGE": "1",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    },
    # object plane (the native count-mode groupby never builds the
    # MultisetState tier that spills), tiny budget, eager compaction
    "compaction_mid_merge": {
        "PATHWAY_TPU_NATIVE": "0",
        "PATHWAY_SPILL": "1",
        "PATHWAY_SPILL_BUDGET": "2",
        "PATHWAY_SPILL_COMPACT": "2",
    },
}
SINK_KINDS = {"sink_pre_seal", "sink_post_seal", "sink_torn_flush"}
CRASH_KINDS = {
    "crash_mid_wave", "torn_metadata", "torn_journal", "lost_snapshot",
    "compaction_mid_merge",
} | SINK_KINDS
QUICK_KINDS = [
    "crash_mid_wave", "torn_metadata", "connector_flap", "device_dispatch",
    "sink_post_seal", "device_wire", "compaction_mid_merge",
    "swap_mid_commit",
]
MAX_GENERATIONS = 4  # a schedule may land a crash in the recovery window

# -------------------------------------------------------- elastic kinds
#
# Elasticity drills run the SUPERVISED mesh (parallel/supervisor.py +
# membership.py) rather than the single-process workload above: a worker
# joins or leaves mid-stream (quiesce -> fence checkpoint -> metadata
# rebalance -> respawn at the new width), or a blue/green plan swap is
# crashed/diverged mid-flight (parallel/bluegreen.py). The equivalence
# claim is the same one the static matrix makes: the DELIVERED sink
# output, consolidated to the final table, must be byte-identical to
# what an unfaulted, never-rescaled run delivers (tests/test_elastic.py
# proves static == analytic; the drill compares against the analytic
# table directly to keep the matrix runtime sane).

ELASTIC_EVENTS = 160  # paced stream long enough to straddle a rebalance

# the mesh workload: streaming groupby, subscribe sink stamped with wall
# time so deliveries consolidate across ownership moves (a rebalance
# moves groups between worker output files; per-file order would let a
# retired owner's stale final shadow the new owner's)
ELASTIC_WORKER = textwrap.dedent(
    """
    import json, os, sys, time
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw
    from pathway_tpu.io.python import ConnectorSubject

    PDIR, OUT, READY, N = sys.argv[1], sys.argv[2], sys.argv[3], int(sys.argv[4])
    PID = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class Nums(ConnectorSubject):
        def run(self):
            for i in range(N):
                self.next(g=f"g{{i % 4}}", v=i)
                if i == 5:
                    open(READY + f".{{PID}}", "w").write("up")
                time.sleep(0.01)

    t = pw.io.python.read(
        Nums(), schema=pw.schema_from_types(g=str, v=int), name="nums"
    )
    agg = t.groupby(t.g).reduce(
        t.g, total=pw.reducers.sum(t.v), n=pw.reducers.count()
    )
    sink = open(OUT + f".{{PID}}", "a")
    def on_change(key, row, time, is_addition):
        sink.write(json.dumps({{**row, "add": is_addition,
                               "ts": __import__("time").time()}}) + "\\n")
        sink.flush()
    pw.io.subscribe(agg, on_change=on_change)
    pw.run(persistence_config=pw.persistence.Config(
        pw.persistence.Backend.filesystem(PDIR)))
    """
).format(repo=REPO)

# the solo workload swap drills stage blue/green around: a real
# jsonlines sink so the delivered file is what the drill consolidates
ELASTIC_SOLO = textwrap.dedent(
    """
    import os, sys, time
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw
    from pathway_tpu.io.python import ConnectorSubject

    ROOT, OUT, N = sys.argv[1], sys.argv[2], int(sys.argv[3])

    class Nums(ConnectorSubject):
        def run(self):
            for i in range(N):
                self.next(g=f"g{{i % 4}}", v=i)
                time.sleep(0.005)

    t = pw.io.python.read(
        Nums(), schema=pw.schema_from_types(g=str, v=int), name="nums"
    )
    agg = t.groupby(t.g).reduce(
        t.g, total=pw.reducers.sum(t.v), n=pw.reducers.count()
    )
    pw.io.jsonlines.write(agg, OUT)
    pw.run(persistence_config=pw.persistence.Config(
        pw.persistence.Backend.filesystem(ROOT)))
    """
).format(repo=REPO)

# kind -> announce delay / blue-stream length, both seed-varied so each
# seed lands the membership change (or the swap) at a different point in
# the stream / a different fence epoch
ELASTIC_KINDS = {
    "worker_join": lambda seed: {"delay_s": 0.3 + 0.15 * seed},
    "worker_leave": lambda seed: {"delay_s": 0.3 + 0.15 * seed},
    "swap_mid_commit": lambda seed: {"blue_n": 32 + 16 * seed},
    "swap_divergent": lambda seed: {"blue_n": 32 + 16 * seed},
}


def _elastic_expected(n_events: int) -> dict:
    exp: dict = {}
    for i in range(n_events):
        g = f"g{i % 4}"
        t0, n0 = exp.get(g, (0, 0))
        exp[g] = (t0 + i, n0 + 1)
    return exp


def _elastic_consolidate(out_prefix: str, max_pids: int) -> dict:
    events = []
    for pid in range(max_pids):
        path = out_prefix + f".{pid}"
        if not os.path.exists(path):
            continue
        with open(path) as f:
            for i, line in enumerate(f):
                ev = json.loads(line)
                events.append((ev["ts"], pid, i, ev))
    events.sort(key=lambda e: (e[0], e[1], e[2]))
    state: dict = {}
    for _, _, _, ev in events:
        if ev["add"]:
            state[ev["g"]] = (ev["total"], ev["n"])
        elif state.get(ev["g"]) == (ev["total"], ev["n"]):
            del state[ev["g"]]
    return state


def _free_port_base(n: int) -> int:
    import socket

    for _ in range(60):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
        ok = True
        for i in range(n * n):
            try:
                with socket.socket() as s2:
                    s2.bind(("127.0.0.1", p + i))
            except OSError:
                ok = False
                break
        if ok:
            return p
    raise RuntimeError("no contiguous port range free")


def _sink_table(path: str) -> dict:
    state: dict = {}
    if os.path.exists(path):
        for line in open(path):
            rec = json.loads(line)
            if rec["diff"] > 0:
                state[rec["g"]] = (rec["total"], rec["n"])
            elif state.get(rec["g"]) == (rec["total"], rec["n"]):
                del state[rec["g"]]
    return state


def _run_membership_case(kind: str, seed: int, workdir: str) -> dict:
    """worker_join / worker_leave: a member change announced mid-stream
    must rebalance exactly once and deliver the analytic table."""
    import threading

    from pathway_tpu.parallel import membership as mb
    from pathway_tpu.parallel.supervisor import run_supervised

    params = ELASTIC_KINDS[kind](seed)
    start_n = 2 if kind == "worker_join" else 3
    want_n = start_n + (1 if kind == "worker_join" else -1)
    announce = (
        mb.announce_join if kind == "worker_join" else mb.announce_leave
    )
    case_dir = os.path.join(workdir, f"{kind}-s{seed}")
    os.makedirs(case_dir, exist_ok=True)
    pdir = os.path.join(case_dir, "pstate")
    out = os.path.join(case_dir, "deliveries")
    ready = os.path.join(case_dir, "ready")
    argv = [sys.executable, "-c", ELASTIC_WORKER, pdir, out, ready,
            str(ELASTIC_EVENTS)]

    def _announcer():
        deadline = time.monotonic() + 60
        while (
            time.monotonic() < deadline
            and not os.path.exists(ready + ".0")
        ):
            time.sleep(0.05)
        time.sleep(params["delay_s"])
        announce(pdir)

    th = threading.Thread(target=_announcer)
    th.start()
    try:
        res = run_supervised(
            argv, start_n, _free_port_base(max(start_n, want_n)),
            env={"JAX_PLATFORMS": "cpu", "PATHWAY_THREADS": "2",
                 "PATHWAY_FAULTS": "0"},
            timeout_s=240, state_dir=pdir,
        )
    finally:
        th.join()
    assert res["rebalances"] == 1, (
        f"{kind} seed {seed}: expected exactly one rebalance, got "
        f"{res['rebalances']}"
    )
    assert res["members"] == want_n, (
        f"{kind} seed {seed}: final width {res['members']} != {want_n}"
    )
    rec = mb.load_membership(pdir)
    assert rec is not None and rec["n"] == want_n and rec["rebalanced"]
    state = _elastic_consolidate(out, max(start_n, want_n))
    return {
        "outputs": {"mesh": json.dumps(sorted(state.items()))},
        "equivalent": state == _elastic_expected(ELASTIC_EVENTS),
        "generations": res["generations"],
    }


def _run_swap_case(kind: str, seed: int, workdir: str) -> dict:
    """swap_mid_commit / swap_divergent: a blue/green swap crashed in
    the commit window rolls FORWARD on recovery; a divergent replay
    aborts with blue byte-for-byte untouched. Either way the delivered
    sink file still consolidates to the analytic table."""
    from pathway_tpu.parallel import bluegreen as bg

    params = ELASTIC_KINDS[kind](seed)
    blue_n = params["blue_n"]
    case_dir = os.path.join(workdir, f"{kind}-s{seed}")
    os.makedirs(case_dir, exist_ok=True)
    blue = os.path.join(case_dir, "blue")
    sink = os.path.join(case_dir, "blue.jsonl")
    r = subprocess.run(
        [sys.executable, "-c", ELASTIC_SOLO, blue, sink, str(blue_n)],
        capture_output=True, text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PATHWAY_THREADS": "1",
             "PATHWAY_FAULTS": "0"},
    )
    assert r.returncode == 0, (
        f"{kind} seed {seed}: blue run rc={r.returncode}\n"
        + r.stderr[-2000:]
    )
    expected = _elastic_expected(blue_n)
    generations = 1

    def _snapshot(root):
        out = []
        for dp, _dirs, files in os.walk(root):
            for f in files:
                p = os.path.join(dp, f)
                st = os.stat(p)
                out.append(
                    (os.path.relpath(p, root), st.st_size, st.st_mtime_ns)
                )
        return sorted(out)

    if kind == "swap_mid_commit":
        # crash INSIDE the commit window (marker durable, renames maybe
        # partial) in a subprocess, then roll forward
        crasher = textwrap.dedent(
            """
            import sys
            sys.path.insert(0, {repo!r})
            from pathway_tpu.parallel import bluegreen as bg
            bg.swap_plan(sys.argv[1], lambda stage: None, verify=False)
            """
        ).format(repo=REPO)
        r = subprocess.run(
            [sys.executable, "-c", crasher, blue],
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PATHWAY_FAULTS": f"seed={seed};swap.mid_commit@1"},
        )
        assert r.returncode == CRASH_EXIT, (
            f"{kind} seed {seed}: swap never crashed (rc={r.returncode})\n"
            + r.stderr[-2000:]
        )
        assert os.path.exists(blue + ".swap.commit")
        assert bg.recover_swap(blue) == "completed"
        assert os.path.isdir(blue)
        assert not os.path.exists(blue + ".swap.commit")
        assert not os.path.isdir(blue + ".green")
        generations = 2
    else:  # swap_divergent
        from pathway_tpu.engine import faults

        before = _snapshot(blue)
        prev = os.environ.get("PATHWAY_FAULTS")
        os.environ["PATHWAY_FAULTS"] = (
            f"seed={seed};swap.replay.divergent@1"
        )
        faults.reset()
        try:
            res = bg.swap_plan(
                blue, lambda stage: expected, baseline=expected,
                verify=False,
            )
        finally:
            if prev is None:
                os.environ.pop("PATHWAY_FAULTS", None)
            else:
                os.environ["PATHWAY_FAULTS"] = prev
            faults.reset()
        assert not res["committed"] and "injected" in res["reason"], res
        assert _snapshot(blue) == before, (
            f"{kind} seed {seed}: aborted swap touched the blue root"
        )
    state = _sink_table(sink)
    return {
        "outputs": {"fs": json.dumps(sorted(state.items()))},
        "equivalent": state == expected,
        "generations": generations,
    }


def run_elastic_case(kind: str, seed: int, workdir: str) -> dict:
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    t0 = time.monotonic()
    if kind in ("worker_join", "worker_leave"):
        rec = _run_membership_case(kind, seed, workdir)
    else:
        rec = _run_swap_case(kind, seed, workdir)
    return {
        "kind": kind,
        "seed": seed,
        "spec": json.dumps(ELASTIC_KINDS[kind](seed)),
        "seconds": round(time.monotonic() - t0, 2),
        "note": "",
        "flight": {},
        **rec,
    }


def _run_workload(
    pdir: str, outdir: str, spec: str, n_events: int,
    flight_dir: str | None = None,
    extra_env: dict | None = None,
) -> int:
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PATHWAY_FAULTS": spec,
           **(extra_env or {})}
    if flight_dir is not None:
        env["PATHWAY_OBSERVABILITY"] = "1"
        env["PATHWAY_FLIGHT_DIR"] = flight_dir
        # a roomy ring: the default 4096 could evict early fault events
        # behind a long run's wave spans, failing _check_flight falsely
        env.setdefault("PATHWAY_OBS_RING", "65536")
    r = subprocess.run(
        [sys.executable, "-c", WORKLOAD.format(repo=REPO),
         pdir, outdir, str(n_events)],
        capture_output=True, text=True, timeout=240,
        env=env,
    )
    if r.returncode not in (0, CRASH_EXIT):
        raise RuntimeError(
            f"workload failed rc={r.returncode} (spec={spec!r}):\n"
            + r.stderr[-3000:]
        )
    return r.returncode


def _check_flight(flight_dir: str, kind: str, seed: int) -> dict:
    """Assert the flight-recorder contract on a faulted case's dumps:
    every shot the schedule logged (`faults_fired`) has a matching
    `fault` event in the recorder timeline — the postmortem never hides
    an injected failure. Returns summary counts for the case record."""
    import glob

    dumps = sorted(glob.glob(os.path.join(flight_dir, "flight-*.json")))
    assert dumps, f"{kind} seed {seed}: no flight-recorder dumps written"
    events: list[dict] = []
    fired: list[tuple] = []
    for path in dumps:
        with open(path) as f:
            payload = json.load(f)
        events.extend(payload.get("events", []))
        fired.extend(tuple(x) for x in payload.get("faults_fired", []))
    fault_events = {
        (e.get("point"), e.get("hit"))
        for e in events if e.get("k") == "fault"
    }
    missing = [shot for shot in fired if shot not in fault_events]
    assert not missing, (
        f"{kind} seed {seed}: {len(missing)} injected fault(s) absent from "
        f"the flight-recorder timeline: {missing[:5]}"
    )
    assert fired, (
        f"{kind} seed {seed}: schedule fired nothing — dumps carry no shots"
    )
    return {
        "dumps": len(dumps),
        "fault_shots": len(fired),
        "wave_events": sum(1 for e in events if e.get("k") == "wave"),
    }


# ---------------------------------------------------------- consolidation
#
# Per-sink canonical bytes of the FINAL delivered table. The dict-based
# consolidator applies add/remove updates in delivery order and removes
# only on exact match — the state-convergence contract the docs give
# at-least-once consumers; under exactly-once the streams contain no
# duplicates at all (the kafka/http consolidators additionally dedup on
# the outbox content keys first, proving replays are absorbable).


def _apply(state: dict, word: str, value: tuple, diff: int) -> None:
    if diff > 0:
        state[word] = value
    elif state.get(word) == value:
        del state[word]


def consolidate_fs(path: str, strict: bool = True) -> str:
    """Canonical rows of the fs/jsonlines sink. `strict` (exactly-once
    mode) tolerates NO torn/blank/unparsable lines — the atomic-segment
    path guarantees there are none, which is why the old drill's
    newline guards are gone."""
    state: dict[str, tuple] = {}
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                if not line.strip():
                    if strict:
                        raise AssertionError(f"blank line in atomic sink {path}")
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    if strict:
                        raise AssertionError(f"torn line in atomic sink {path}")
                    continue
                _apply(
                    state, rec["word"], (rec["count"], rec["boosted"]),
                    rec["diff"],
                )
    rows = sorted((w, c, b) for w, (c, b) in state.items())
    return json.dumps(rows, separators=(",", ":"))


def _consolidate_keyed_log(path: str, msg_id, record, diff) -> str:
    """Shared consolidator for the mock queue/endpoint targets: drop
    exact replays on the outbox content key, then apply signed updates
    in delivery order."""
    state: dict[str, tuple] = {}
    seen: set[str] = set()
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                ev = json.loads(line)
                mid = msg_id(ev)
                if mid is not None:
                    if mid in seen:
                        continue  # replayed delivery: content-key dedup
                    seen.add(mid)
                rec = record(ev)
                _apply(
                    state, rec["word"], (rec["count"], rec["boosted"]),
                    diff(ev, rec),
                )
    rows = sorted((w, c, b) for w, (c, b) in state.items())
    return json.dumps(rows, separators=(",", ":"))


def consolidate_kafka(path: str) -> str:
    return _consolidate_keyed_log(
        path,
        msg_id=lambda ev: ev["headers"].get("pathway_msg_id"),
        record=lambda ev: json.loads(ev["payload"]),
        diff=lambda ev, rec: int(ev["headers"]["pathway_diff"]),
    )


def consolidate_http(path: str) -> str:
    return _consolidate_keyed_log(
        path,
        msg_id=lambda ev: ev["headers"].get("X-Pathway-Msg-Id"),
        record=lambda ev: ev["body"],
        diff=lambda ev, rec: rec["diff"],
    )


def consolidate_outputs(outdir: str, exactly_once: bool) -> dict[str, str]:
    """All compared sinks' canonical final tables. In at-least-once mode
    the direct fs writer truncates its file every generation (losing
    pre-crash deliveries) — the exact gap the outbox closes — so fs is
    only compared under exactly-once."""
    out = {
        "kafka": consolidate_kafka(os.path.join(outdir, "kafka.jsonl")),
        "http": consolidate_http(os.path.join(outdir, "http.jsonl")),
    }
    if exactly_once:
        import glob as _glob

        fs_path = os.path.join(outdir, "fs.jsonl")
        leftover = _glob.glob(fs_path + ".pw-*.seg")
        assert not leftover, (
            f"fs sink left unconsolidated segments after a clean finish: "
            f"{leftover}"
        )
        out["fs"] = consolidate_fs(fs_path, strict=True)
    return out


def _tamper_lost_snapshot(pdir: str, seed: int) -> str:
    """Simulate a lost operator-snapshot file: delete one snapshot of the
    newest committed epoch (seed picks which). Restore must detect the
    manifest hole and fall back one epoch."""
    with open(os.path.join(pdir, "metadata.json")) as f:
        epoch = int(json.load(f)["epoch"])
    op_dir = os.path.join(pdir, "operator")
    files = sorted(
        fn for fn in os.listdir(op_dir) if fn.endswith(f".{epoch}.state")
    )
    if not files:
        return f"epoch {epoch} had no snapshots to lose"
    victim = files[seed % len(files)]
    os.unlink(os.path.join(op_dir, victim))
    return f"deleted {victim} (epoch {epoch})"


def run_case(kind: str, seed: int, n_events: int, workdir: str) -> dict:
    """One drill: fault run (+ recovery generations) in a fresh
    persistence dir; returns the case record incl. canonical per-sink
    delivered output."""
    eo = exactly_once_mode()
    pdir = os.path.join(workdir, f"{kind}-s{seed}-pdir")
    outdir = os.path.join(workdir, f"{kind}-s{seed}-out")
    flight_dir = os.path.join(workdir, f"{kind}-s{seed}-flight")
    spec = KINDS[kind](seed)
    extra_env = KIND_ENV.get(kind)
    t0 = time.monotonic()
    rc = _run_workload(pdir, outdir, spec, n_events, flight_dir=flight_dir,
                       extra_env=extra_env)
    generations = 1
    note = ""
    if kind in CRASH_KINDS:
        assert rc == CRASH_EXIT, (
            f"{kind} seed {seed}: schedule {spec!r} never crashed (rc={rc})"
        )
        if kind == "lost_snapshot":
            note = _tamper_lost_snapshot(pdir, seed)
        # recovery generations run fault-free (a hit-count schedule would
        # deterministically re-fire the same crash); a crash landing in
        # an earlier recovery window is itself recovered from
        while rc == CRASH_EXIT:
            if generations > MAX_GENERATIONS:
                raise AssertionError(f"{kind} seed {seed}: kept crashing")
            rc = _run_workload(pdir, outdir, "0", n_events,
                               flight_dir=flight_dir, extra_env=extra_env)
            generations += 1
    assert rc == 0, f"{kind} seed {seed}: final generation rc={rc}"
    flight = _check_flight(flight_dir, kind, seed)
    return {
        "kind": kind,
        "seed": seed,
        "spec": spec,
        "generations": generations,
        "seconds": round(time.monotonic() - t0, 2),
        "note": note,
        "flight": flight,
        "outputs": consolidate_outputs(outdir, eo),
    }


def run_matrix(
    kinds: list[str], seeds: list[int], n_events: int = 50,
    workdir: str | None = None,
) -> dict:
    own = workdir is None
    if own:
        workdir = tempfile.mkdtemp(prefix="pathway-chaos-")
    assert workdir is not None
    try:
        return _run_matrix(kinds, seeds, n_events, workdir)
    finally:
        if own:
            shutil.rmtree(workdir, ignore_errors=True)


def _run_matrix(
    kinds: list[str], seeds: list[int], n_events: int, workdir: str
) -> dict:
    eo = exactly_once_mode()
    elastic_kinds = [k for k in kinds if k in ELASTIC_KINDS]
    kinds = [k for k in kinds if k not in ELASTIC_KINDS]
    if not eo:
        skipped = [k for k in kinds if k in SINK_KINDS]
        kinds = [k for k in kinds if k not in SINK_KINDS]
        if skipped:
            print(
                "PATHWAY_EXACTLY_ONCE=0: sink-window kinds skipped "
                f"(outbox disarmed): {skipped}"
            )
        assert kinds or elastic_kinds, (
            "no fault kinds left to run — sink kinds skip under "
            "PATHWAY_EXACTLY_ONCE=0; an empty matrix must not report ok"
        )
    if "device_wire" in kinds:
        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        from pathway_tpu.engine.native import dataplane as _dp

        if not _dp.available():
            # the column plane lifts NativeBatch columns; under the
            # object plane (PATHWAY_TPU_NATIVE=0) its wire never probes
            kinds = [k for k in kinds if k != "device_wire"]
            print(
                "native dataplane unavailable: device_wire kind skipped "
                "(the column plane's wire rides NativeBatch)"
            )
            assert kinds or elastic_kinds, (
                "no fault kinds left to run — an empty matrix must not "
                "report ok"
            )
    t0 = time.monotonic()
    baseline: dict[str, str] = {}
    if kinds:
        base_pdir = os.path.join(workdir, "baseline-pdir")
        base_out = os.path.join(workdir, "baseline-out")
        rc = _run_workload(base_pdir, base_out, "0", n_events)
        assert rc == 0, f"baseline rc={rc}"
        baseline = consolidate_outputs(base_out, eo)
        assert all(v != "[]" for v in baseline.values()), (
            f"baseline produced no output: {baseline}"
        )
    cases = []
    failures = []
    for kind in kinds + elastic_kinds:
        for seed in seeds:
            if kind in ELASTIC_KINDS:
                # elastic cases carry their own equivalence verdict
                # (vs the analytic table, see the elastic-kinds note)
                case = run_elastic_case(kind, seed, workdir)
            else:
                case = run_case(kind, seed, n_events, workdir)
                case["equivalent"] = case["outputs"] == baseline
            cases.append(case)
            if not case["equivalent"]:
                failures.append(
                    f"{kind} seed {seed}: delivered output diverged from "
                    f"baseline\n  baseline: {baseline}\n"
                    f"  got:      {case['outputs']}"
                )
            status = "OK " if case["equivalent"] else "FAIL"
            print(
                f"[{status}] {kind:16s} seed={seed} "
                f"gen={case['generations']} {case['seconds']:.1f}s "
                f"spec={case['spec']!r}"
                + (f" ({case['note']})" if case["note"] else "")
            )
    report = {
        "ok": not failures,
        "exactly_once": eo,
        "baseline": baseline,
        "kinds": kinds + elastic_kinds,
        "seeds": seeds,
        "n_events": n_events,
        "cases": cases,
        "seconds": round(time.monotonic() - t0, 1),
    }
    if failures:
        report["failures"] = failures
    return report


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="8 kinds x 1 seed (the tier-1 CI leg, <=90s)")
    ap.add_argument("--kinds", default=None,
                    help=f"comma list from {sorted(KINDS) + sorted(ELASTIC_KINDS)}")
    ap.add_argument("--seeds", default=None, help="comma list of ints")
    ap.add_argument("--events", type=int, default=50)
    ap.add_argument("--json", dest="json_out", default=None)
    args = ap.parse_args()
    if args.quick:
        kinds = QUICK_KINDS
        seeds = [0]
    else:
        kinds = sorted(KINDS) + sorted(ELASTIC_KINDS)
        seeds = [0, 1, 2]
    if args.kinds:
        kinds = [k.strip() for k in args.kinds.split(",") if k.strip()]
        for k in kinds:
            if k not in KINDS and k not in ELASTIC_KINDS:
                ap.error(
                    f"unknown kind {k!r} "
                    f"(have {sorted(KINDS) + sorted(ELASTIC_KINDS)})"
                )
    if args.seeds:
        seeds = [int(s) for s in args.seeds.split(",")]
    report = run_matrix(kinds, seeds, n_events=args.events)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=2)
    print(
        f"chaos drill: {len(report['cases'])} cases, "
        f"{'ALL EQUIVALENT' if report['ok'] else 'FAILURES'} "
        f"in {report['seconds']}s"
    )
    if not report["ok"]:
        for f_ in report["failures"]:
            print(f_, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
