"""RowTransformerNode: the engine operator behind `@pw.transformer`.

Reference parity: the reference lowers row transformers through
complex_columns (internals/row_transformer.py) into pointer-chasing
dataflow; here one operator arranges every member table, evaluates output
attributes lazily per row (cross-table / cross-row references included),
and tracks ROW-LEVEL READ DEPENDENCIES: when input rows change, only the
transitive dependents re-evaluate — an O(affected) update, the same
incrementality contract as the rest of the engine.
"""

from __future__ import annotations

from typing import Any, Sequence

from pathway_tpu.engine.core import (
    Entry,
    Graph,
    InputNode,
    KeyedState,
    Node,
    delta_emit,
)
from pathway_tpu.internals.errors import ERROR
from pathway_tpu.internals.keys import Key


class _DeferEval(BaseException):
    """Internal control flow: evaluation too deep — compute `token`
    bottom-up first. BaseException so user-code `except Exception`
    blocks cannot swallow it."""

    def __init__(self, token: tuple):
        self.token = token


class _RowHandle:
    """`self` inside an output attribute: one row of one member table."""

    __slots__ = ("_node", "_tname", "_key")

    def __init__(self, node: "RowTransformerNode", tname: str, key: Key):
        self._node = node
        self._tname = tname
        self._key = key

    @property
    def id(self) -> Key:
        return self._key

    @property
    def transformer(self) -> "_TransformerHandle":
        return _TransformerHandle(self._node)

    def pointer_from(self, *args: Any) -> Key:
        from pathway_tpu.internals.keys import key_for_values

        return key_for_values(*args)

    def __getattr__(self, attr: str) -> Any:
        return self._node.value_of(self._tname, self._key, attr)


class _TransformerHandle:
    __slots__ = ("_node",)

    def __init__(self, node: "RowTransformerNode"):
        self._node = node

    def __getattr__(self, tname: str) -> "_TableHandle":
        if tname not in self._node.metas:
            raise AttributeError(f"transformer has no table {tname!r}")
        return _TableHandle(self._node, tname)


class _TableHandle:
    __slots__ = ("_node", "_tname")

    def __init__(self, node: "RowTransformerNode", tname: str):
        self._node = node
        self._tname = tname

    def __getitem__(self, key: Key) -> _RowHandle:
        return _RowHandle(self._node, self._tname, key)


class RowTransformerNode(Node):
    """Inputs: one per member table (same order as `metas`)."""

    def __init__(self, graph: Graph, inputs: Sequence[Node], metas: dict[str, Any]):
        super().__init__(graph, inputs)
        self.metas = metas  # name -> _ClassMeta
        self.table_names = list(metas)
        self.states = {name: KeyedState() for name in metas}
        self.col_idx: dict[str, dict[str, int]] = {name: {} for name in metas}
        self.columns: dict[str, list[str]] = {}
        # evaluation cache: (tname, key.value, attr) -> value
        self.memo: dict[tuple, Any] = {}
        # row-level read dependencies: (tname, key.value) read by set of
        # (tname, key.value) whose outputs consumed it
        self.rev_deps: dict[tuple, set[tuple]] = {}
        self.fwd_deps: dict[tuple, set[tuple]] = {}
        self._eval_stack: list[tuple] = []
        self._current_reader: tuple | None = None
        self.emitted: dict[str, dict[Key, tuple]] = {name: {} for name in metas}
        self.out_nodes: dict[str, InputNode] = {}
        self._key_cache: dict[str, dict[int, Key]] = {name: {} for name in metas}

    def set_columns(self, name: str, columns: list[str]) -> None:
        self.columns[name] = columns
        self.col_idx[name] = {c: i for i, c in enumerate(columns)}

    def set_output_node(self, name: str, node: InputNode) -> None:
        self.out_nodes[name] = node

    def persist_signature(self) -> str:
        parts = [
            f"{n}:[{','.join(m.inputs)}]->[{','.join(m.outputs)}]"
            for n, m in self.metas.items()
        ]
        return "RowTransformerNode/" + ";".join(parts)

    def persist_state(self) -> dict:
        return {"states": self.states, "emitted": self.emitted}

    def restore_state(self, st: dict) -> None:
        self.states = st["states"]
        self.emitted = st["emitted"]
        self.memo.clear()
        self.rev_deps.clear()
        self.fwd_deps.clear()
        for name, state in self.states.items():
            self._key_cache[name] = {k.value: k for k in state.rows}
        # the dependency graph is not persisted; without it, incremental
        # invalidation would miss dependents of the first post-restore
        # change — re-evaluate everything once to rebuild it (delta_emit
        # suppresses unchanged outputs, so nothing re-emits spuriously)
        self._rebuild_all = True

    # ---------------------------------------------------------- evaluation

    def _record_read(self, target: tuple) -> None:
        reader = self._current_reader
        if reader is not None and reader[:2] != target:
            self.rev_deps.setdefault(target, set()).add(reader[:2])
            self.fwd_deps.setdefault(reader[:2], set()).add(target)

    # Native recursion costs ~3 Python frames per cross-row hop; chains
    # longer than this budget switch to the defer/worklist driver below
    # instead of blowing the interpreter stack.
    _DEPTH_BUDGET = 64

    def value_of(self, tname: str, key: Key, attr: str) -> Any:
        meta = self.metas[tname]
        self._record_read((tname, key.value))
        row = self.states[tname].get(key)
        if attr in meta.inputs:
            if row is None:
                raise KeyError(f"{tname}[{key}] does not exist")
            return row[self.col_idx[tname][attr]]
        if attr in meta.outputs:
            token = (tname, key.value, attr)
            if token in self.memo:
                return self.memo[token]
            if token in self._eval_stack:
                raise RecursionError(
                    f"row transformer cycle at {tname}.{attr} for {key}"
                )
            if row is None:
                raise KeyError(f"{tname}[{key}] does not exist")
            if len(self._eval_stack) >= self._DEPTH_BUDGET:
                # too deep to recurse natively: hand the token to the
                # worklist driver, which memoizes it bottom-up and
                # re-runs the shallow evaluations
                raise _DeferEval(token)
            prev_reader = self._current_reader
            self._current_reader = token
            self._eval_stack.append(token)
            try:
                value = meta.outputs[attr](_RowHandle(self, tname, key))
            finally:
                self._eval_stack.pop()
                self._current_reader = prev_reader
            self.memo[token] = value
            return value
        helper = meta.helpers.get(attr)
        if helper is not None:
            if callable(helper):
                import types

                return types.MethodType(helper, _RowHandle(self, tname, key))
            return helper
        raise AttributeError(f"{tname} has no attribute {attr!r}")

    def eval_output(self, tname: str, key: Key, attr: str) -> Any:
        """Worklist driver: evaluates `attr`, resolving arbitrarily deep
        cross-row dependency chains without native recursion overflow.
        Each deferred dependency is computed (memoized) first, then the
        deferring evaluation re-runs — O(chain) total fn executions."""
        pending: list[tuple] = [(tname, key.value, attr)]
        keys: dict[int, Key] = {key.value: key}
        while pending:
            t, kv, a = pending[-1]
            if (t, kv, a) in self.memo:
                pending.pop()
                continue
            k = keys.get(kv) or self._key_cache[t].get(kv)
            if k is None:
                raise KeyError(f"{t} has no row for key value {kv}")
            try:
                self.value_of(t, k, a)
                pending.pop()
            except _DeferEval as d:
                if d.token in pending:
                    raise RecursionError(
                        f"row transformer cycle at {d.token[0]}.{d.token[2]}"
                    ) from None
                pending.append(d.token)
                keys.setdefault(d.token[1], self._key_cache[d.token[0]].get(d.token[1]))
        return self.memo[(tname, key.value, attr)]

    def _invalidate(self, changed: set[tuple]) -> set[tuple]:
        """Transitive closure of rows whose outputs may change."""
        dirty: set[tuple] = set()
        frontier = list(changed)
        while frontier:
            item = frontier.pop()
            if item in dirty:
                continue
            dirty.add(item)
            frontier.extend(self.rev_deps.get(item, ()))
        for item in dirty:
            tname, kv = item
            for attr in self.metas[tname].outputs:
                self.memo.pop((tname, kv, attr), None)
            # drop this row's outgoing read edges; they re-register on
            # re-evaluation
            for target in self.fwd_deps.pop(item, ()):
                self.rev_deps.get(target, set()).discard(item)
        return dirty

    def finish_time(self, time: int) -> None:
        changed: set[tuple] = set()
        for i, name in enumerate(self.table_names):
            batch = self.take_input(i)
            if not batch:
                continue
            self.states[name].update(batch)
            for key, _row, _diff in batch:
                changed.add((name, key.value))
                self._key_cache[name][key.value] = key
        if getattr(self, "_rebuild_all", False):
            self._rebuild_all = False
            for name, state in self.states.items():
                changed.update((name, k.value) for k in state.rows)
        if not changed:
            return
        dirty = self._invalidate(changed)
        out_per_table: dict[str, list[Entry]] = {name: [] for name in self.metas}
        for tname, kv in dirty:
            key = self._key_cache[tname].get(kv)
            if key is None:
                continue
            meta = self.metas[tname]
            if not meta.outputs:
                continue
            row = self.states[tname].get(key)
            new: tuple | None
            if row is None:
                new = None  # row deleted: retract its outputs
            else:
                vals = []
                for attr in meta.outputs:
                    try:
                        vals.append(self.eval_output(tname, key, attr))
                    except Exception as e:  # noqa: BLE001
                        self.log_error(
                            f"transformer {tname}.{attr}: {type(e).__name__}: {e}"
                        )
                        vals.append(ERROR)
                new = tuple(vals)
            delta_emit(self.emitted[tname], out_per_table[tname], key, new)
        for name, entries in out_per_table.items():
            out_node = self.out_nodes.get(name)
            if out_node is not None and entries:
                out_node.push(entries)
                out_node.finish_time(time)
