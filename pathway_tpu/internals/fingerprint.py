"""Structural fingerprints of op specs, INCLUDING function bodies.

Used by persistence to decide whether an operator snapshot is still
valid: the reference shares the caveat that a changed UDF body with an
unchanged pipeline shape silently reuses stale state (its signature
hashes only operator structure). Here every spec hashes its expression
trees and any embedded Python callables down to their bytecode, consts,
and closure contents — editing a lambda body invalidates the snapshot.

Determinism notes:
  * objects whose repr embeds a memory address (`... at 0x...`) hash by
    type name only, so fingerprints are stable across process restarts;
  * Table references inside expressions hash as an opaque marker — the
    referenced table's own node contributes its fingerprint to the
    pipeline signature separately (persistence/_pipeline_signature
    concatenates all nodes);
  * row Keys hash as a marker: sequential keys count from a process-wide
    counter, so their values are run-local, while the row VALUES beside
    them carry the data identity;
  * the object walk memoizes visited ids permanently (a revisit hashes
    as a marker), keeping it linear in the object graph — and only
    pathway-defined objects are traversed deeply: a connector or user
    object reaches sessions/threads/sockets, so it hashes by type.
"""

from __future__ import annotations

import functools
import hashlib
import re
import types
from typing import Any

_ADDR_RE = re.compile(r" at 0x[0-9a-fA-F]+")

_MAX_DEPTH = 40


def _is_table(obj: Any) -> bool:
    return hasattr(obj, "_spec") and hasattr(obj, "_column_names")


def _feed_code(h: Any, fn: Any, seen: dict, depth: int) -> None:
    code = getattr(fn, "__code__", None)
    if code is None:
        if isinstance(fn, functools.partial):
            h.update(b"partial")
            _feed(h, fn.func, seen, depth + 1)
            _feed(h, fn.args, seen, depth + 1)
            _feed(h, tuple(sorted(fn.keywords.items())), seen, depth + 1)
            return
        h.update(f"builtin:{getattr(fn, '__qualname__', repr(fn))}".encode())
        return
    h.update(b"fn")
    h.update(code.co_code)
    h.update(repr(code.co_names).encode())
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            h.update(const.co_code)
            h.update(repr(const.co_names).encode())
        else:
            _feed(h, const, seen, depth + 1)
    _feed(h, getattr(fn, "__defaults__", None), seen, depth + 1)
    closure = getattr(fn, "__closure__", None) or ()
    for cell in closure:
        try:
            _feed(h, cell.cell_contents, seen, depth + 1)
        except ValueError:  # empty cell
            h.update(b"emptycell")


def _feed(h: Any, obj: Any, seen: dict, depth: int = 0) -> None:
    if depth > _MAX_DEPTH:
        h.update(b"deep")
        return
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        h.update(f"{type(obj).__name__}:{obj!r}".encode())
        return
    oid = id(obj)
    if oid in seen:
        h.update(b"seen")
        return
    # the memo VALUE keeps the object alive for the walk's duration — a
    # plain id-set would let a freed temporary's id be reused by a
    # different object, which would then silently hash as b"seen"
    seen[oid] = obj
    if _is_table(obj):
        h.update(b"Table")
        return
    from pathway_tpu.internals.keys import Key

    if isinstance(obj, Key):
        h.update(b"Key")
        return
    if isinstance(
        obj,
        (
            types.FunctionType,
            types.MethodType,
            types.BuiltinFunctionType,
            functools.partial,
        ),
    ):
        _feed_code(h, obj, seen, depth)
        return
    if isinstance(obj, type):
        h.update(f"type:{obj.__module__}.{obj.__qualname__}".encode())
        return
    if isinstance(obj, (list, tuple)):
        h.update(f"seq{len(obj)}".encode())
        for v in obj:
            _feed(h, v, seen, depth + 1)
        return
    if isinstance(obj, dict):
        h.update(f"map{len(obj)}".encode())
        for k in sorted(obj, key=repr):
            _feed(h, k, seen, depth + 1)
            _feed(h, obj[k], seen, depth + 1)
        return
    if isinstance(obj, (set, frozenset)):
        h.update(f"set{len(obj)}".encode())
        for k in sorted(obj, key=repr):
            _feed(h, k, seen, depth + 1)
        return
    # expression trees / reducers / dtypes / behaviors: traverse their
    # state. Anything else (connector objects, user classes) hashes
    # shallowly — their reachable graphs can be huge (sessions, threads)
    # and their identity is their type.
    d = getattr(obj, "__dict__", None)
    if d is not None and type(obj).__module__.startswith("pathway_tpu"):
        h.update(f"obj:{type(obj).__qualname__}".encode())
        for k in sorted(d):
            if k.startswith("__"):
                continue
            h.update(k.encode())
            _feed(h, d[k], seen, depth + 1)
        return
    r = repr(obj)
    if " at 0x" in r:
        r = _ADDR_RE.sub("", r)
    h.update(f"{type(obj).__qualname__}:{r}".encode())


def fingerprint_spec(spec: Any) -> str:
    """8-byte hex fingerprint of one op spec (kind + params, with UDF
    bodies hashed). Never raises — an unhashable spec degrades to its
    kind alone (same caveat level as the reference)."""
    h = hashlib.blake2b(digest_size=8)
    try:
        h.update(str(getattr(spec, "kind", "?")).encode())
        params = getattr(spec, "params", None) or {}
        _feed(h, params, {})
    except Exception:  # noqa: BLE001 — degrade, never break lowering
        pass
    return h.hexdigest()
