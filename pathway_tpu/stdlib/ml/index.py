"""Classic `KNNIndex` API (the pre-DataIndex interface).

Reference parity: stdlib/ml/index.py `KNNIndex` (:8) —
`get_nearest_items` / `get_nearest_items_asof_now` with collapse_rows /
with_distances / metadata_filter, backed there by the LSH classifier
(`knn_lsh_classifier_train`). Here it is a facade over the same DataIndex
machinery; `distance_type` picks the metric and the backend is the exact
HBM-slab KNN by default ("euclidean"/"cosine"), or the LSH index when
`use_lsh=True` (reference behavior).
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    IvfPqKnn,
    LshKnn,
)

_METRIC = {"euclidean": "l2sq", "cosine": "cos", "cos": "cos", "l2": "l2sq"}


class KNNIndex:
    def __init__(
        self,
        data_embedding: ColumnExpression,
        data: Table,
        n_dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "euclidean",
        metadata: ColumnExpression | None = None,
        use_lsh: bool = False,
        use_ann: bool = False,
    ):
        self.data = data
        if distance_type not in _METRIC:
            raise ValueError(f"unsupported distance_type {distance_type!r}")
        if use_lsh:
            inner: Any = LshKnn(
                data_column=data_embedding,
                metadata_column=metadata,
                dimensions=n_dimensions,
                n_or=n_or,
                n_and=n_and,
                bucket_length=bucket_length,
                distance_type="l2" if distance_type in ("euclidean", "l2") else "cos",
            )
        elif use_ann:
            # incremental IVF-PQ (docs/retrieval.md); PATHWAY_ANN=0
            # drops this back to the exact slab at lowering time
            inner = IvfPqKnn(
                data_column=data_embedding,
                metadata_column=metadata,
                dimensions=n_dimensions,
                metric=_METRIC[distance_type],
            )
        else:
            inner = BruteForceKnn(
                data_column=data_embedding,
                metadata_column=metadata,
                dimensions=n_dimensions,
                metric=_METRIC[distance_type],
            )
        self._index = DataIndex(data_table=data, inner_index=inner)

    def _shape_result(
        self, result: Table, query_table: Table, collapse_rows: bool,
        with_distances: bool,
    ) -> Table:
        """Reference output shape (stdlib/ml/index.py
        _extract_data_collapsed_rows/_extract_data_flat): only the DATA
        table's columns, plus `dist` when requested, on the query universe
        (collapse) or one row per match (flat)."""
        from pathway_tpu.stdlib.indexing.colnames import (
            _INDEX_REPLY_SCORE,
            _SCORE,
        )

        cols = {n: result[n] for n in self.data._column_names()}
        if with_distances:
            cols["dist"] = result[_INDEX_REPLY_SCORE if collapse_rows else _SCORE]
        return result.select(**cols)

    def get_nearest_items(
        self,
        query_embedding: ColumnReference,
        k: ColumnExpression | int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: ColumnExpression | None = None,
    ) -> Table:
        """Results keep updating as better documents arrive."""
        result = self._index.query(
            query_embedding,
            number_of_matches=k,
            collapse_rows=collapse_rows,
            with_distances=True,
            metadata_filter=metadata_filter,
        )
        return self._shape_result(
            result, query_embedding.table, collapse_rows, with_distances
        )

    def get_nearest_items_asof_now(
        self,
        query_embedding: ColumnReference,
        k: ColumnExpression | int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: ColumnExpression | None = None,
    ) -> Table:
        """Results are frozen as of each query's arrival."""
        result = self._index.query_as_of_now(
            query_embedding,
            number_of_matches=k,
            collapse_rows=collapse_rows,
            with_distances=True,
            metadata_filter=metadata_filter,
        )
        return self._shape_result(
            result, query_embedding.table, collapse_rows, with_distances
        )
