"""pw.io.gdrive — stream files from a Google Drive folder.

Reference parity: python/pathway/io/gdrive/__init__.py (read). Implemented
against google-api-python-client + google-auth (service account): the
folder is polled for file additions/modifications/deletions; each object
is emitted as a binary row with `_metadata`, and changes flow as upserts/
deletions. Raises a clear ImportError when the client stack is missing.
"""

from __future__ import annotations

import logging
import time as _time
from typing import Any

from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.json import Json
from pathway_tpu.io._external import require_module
from pathway_tpu.io._retry import CircuitOpen, RetryPolicy

_LOG = logging.getLogger("pathway_tpu.io.gdrive")

_EXPORT_MIME = {
    "application/vnd.google-apps.document": "text/plain",
    "application/vnd.google-apps.spreadsheet": "text/csv",
    "application/vnd.google-apps.presentation": "application/pdf",
}


def read(
    object_id: str,
    *,
    mode: str = "streaming",
    object_size_limit: int | None = None,
    refresh_interval: int = 30,
    service_user_credentials_file: str,
    with_metadata: bool = False,
    file_name_pattern: str | list[str] | None = None,
    name: str | None = None,
    **kwargs: Any,
) -> Any:
    """Streams the binary contents of files under a Drive folder (or a
    single file); streaming mode polls every `refresh_interval` seconds
    and emits upserts for modified files and deletions for removed ones."""
    service_account = require_module("google.oauth2.service_account", "gdrive")
    discovery = require_module("googleapiclient.discovery", "gdrive")

    import fnmatch

    from pathway_tpu.io.python import ConnectorSubject
    from pathway_tpu.io.python import read as python_read

    schema = sch.schema_from_types(data=bytes, _metadata=Json)
    patterns = (
        [file_name_pattern]
        if isinstance(file_name_pattern, str)
        else list(file_name_pattern or [])
    )
    connector_name = name or f"gdrive:{object_id}"
    # unified download policy: bounded in-poll retries, and a circuit
    # breaker so a dead API (auth revoked, quota) stops hammering every
    # poll — the open transition is surfaced as ONE loud warning
    retry = RetryPolicy(
        connector_name,
        max_attempts=3,
        initial_delay_ms=500,
        max_delay_ms=5_000,
        breaker_threshold=5,
        breaker_reset_ms=60_000,
        on_breaker_open=lambda p: _LOG.warning(
            "connector %r: circuit breaker OPEN after repeated download "
            "failures (last: %s); downloads fail fast for a cooldown, "
            "then a single probe re-tests the API",
            p.name, p.last_error,
        ),
    )

    class GDriveSubject(ConnectorSubject):
        # rows are keyed by the Drive file id so a modified file replaces
        # its previous contents and a removed file retracts its row
        def _key_for(self, values: dict) -> Any:
            from pathway_tpu.internals.keys import key_for_values

            return key_for_values(values["_metadata"].value["id"])

        def run(self) -> None:
            creds = service_account.Credentials.from_service_account_file(
                service_user_credentials_file,
                scopes=["https://www.googleapis.com/auth/drive.readonly"],
            )
            drive = discovery.build("drive", "v3", credentials=creds)
            seen: dict[str, str] = {}  # file id -> modifiedTime
            emitted: dict[str, dict] = {}  # file id -> last emitted row
            while True:
                files = self._list(drive)
                current_ids = set()
                for f in files:
                    fid, mtime = f["id"], f.get("modifiedTime", "")
                    if patterns and not any(
                        fnmatch.fnmatch(f.get("name", ""), p) for p in patterns
                    ):
                        continue
                    if object_size_limit and int(f.get("size", 0)) > object_size_limit:
                        continue
                    current_ids.add(fid)
                    if seen.get(fid) == mtime:
                        continue
                    data = self._download(drive, f)
                    if data is None:
                        continue
                    seen[fid] = mtime
                    row = {
                        "data": data,
                        "_metadata": Json(
                            {
                                "id": fid,
                                "name": f.get("name"),
                                "path": f.get("name"),
                                "modified_at": mtime,
                                "seen_at": int(_time.time()),
                            }
                        ),
                    }
                    if fid in emitted:  # modified: retract old contents
                        self._remove(emitted[fid])
                    self.next(**row)
                    emitted[fid] = row
                for fid in list(seen):
                    if fid not in current_ids:  # deleted on Drive
                        del seen[fid]
                        old = emitted.pop(fid, None)
                        if old is not None:
                            self._remove(old)
                if mode != "streaming":
                    return
                _time.sleep(refresh_interval)

        def _list(self, drive: Any) -> list[dict]:
            query = f"'{object_id}' in parents and trashed = false"
            out, token = [], None
            while True:
                resp = drive.files().list(
                    q=query,
                    fields="nextPageToken, files(id, name, mimeType, modifiedTime, size)",
                    pageToken=token,
                ).execute()
                out.extend(resp.get("files", []))
                token = resp.get("nextPageToken")
                if not token:
                    break
            if not out:  # maybe object_id is a single file
                f = drive.files().get(
                    fileId=object_id,
                    fields="id, name, mimeType, modifiedTime, size",
                ).execute()
                out = [f]
            return out

        def _download_once(self, drive: Any, f: dict) -> bytes:
            mime = f.get("mimeType", "")
            if mime in _EXPORT_MIME:
                return drive.files().export(
                    fileId=f["id"], mimeType=_EXPORT_MIME[mime]
                ).execute()
            return drive.files().get_media(fileId=f["id"]).execute()

        def _download(self, drive: Any, f: dict) -> bytes | None:
            # a failed file is NOT marked seen, so the next poll retries
            # it — but never silently: every give-up is logged with the
            # connector name, and a run of failures opens the breaker
            try:
                return retry.call(self._download_once, drive, f)
            except CircuitOpen:
                return None  # breaker already warned; skip until re-probe
            except Exception as e:  # noqa: BLE001 — poll loop must survive
                _LOG.warning(
                    "connector %r: download of %r failed after "
                    "%d attempts: %s",
                    connector_name, f.get("name") or f.get("id"),
                    retry.max_attempts, e,
                )
                return None

    return python_read(
        GDriveSubject(),
        schema=schema,
        name=name or f"gdrive:{object_id}",
    )


__all__ = ["read"]
