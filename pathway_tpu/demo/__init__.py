"""pw.demo: streaming simulators (reference: python/pathway/demo/__init__.py
generate_custom_stream :28, noisy_linear_stream :118, range_stream,
replay_csv / replay_csv_with_time)."""

from __future__ import annotations

import csv as _csv
import random
import time as _time
from typing import Any, Callable, Mapping

from pathway_tpu.internals import schema as sch
from pathway_tpu.io.python import ConnectorSubject, read as _python_read


def generate_custom_stream(
    value_generators: Mapping[str, Callable[[int], Any]],
    *,
    schema: Any,
    nb_rows: int | None = None,
    input_rate: float = 1.0,
    autocommit_duration_ms: int = 20,
    persistent_id: str | None = None,
    name: str | None = None,
):
    class StreamSubject(ConnectorSubject):
        def run(self) -> None:
            i = 0
            while nb_rows is None or i < nb_rows:
                values = {name: gen(i) for name, gen in value_generators.items()}
                self.next(**values)
                self.commit()
                i += 1
                if input_rate > 0:
                    _time.sleep(1.0 / input_rate)

    return _python_read(
        StreamSubject(), schema=schema,
        autocommit_duration_ms=autocommit_duration_ms, name=name,
    )


def range_stream(
    nb_rows: int = 30, offset: int = 0, input_rate: float = 1.0,
    autocommit_duration_ms: int = 20, **kwargs: Any,
):
    schema = sch.schema_from_types(value=int)
    return generate_custom_stream(
        {"value": lambda i: i + offset},
        schema=schema, nb_rows=nb_rows, input_rate=input_rate,
        autocommit_duration_ms=autocommit_duration_ms,
    )


def noisy_linear_stream(
    nb_rows: int = 10, input_rate: float = 1.0,
    autocommit_duration_ms: int = 20, **kwargs: Any,
):
    schema = sch.schema_from_types(x=float, y=float)
    rng = random.Random(0)
    return generate_custom_stream(
        {"x": lambda i: float(i), "y": lambda i: float(i) + (2 * rng.random() - 1) / 10},
        schema=schema, nb_rows=nb_rows, input_rate=input_rate,
        autocommit_duration_ms=autocommit_duration_ms,
    )


def replay_csv(
    path: str, *, schema: Any, input_rate: float = 1.0,
    autocommit_ms: int = 20, **kwargs: Any,
):
    names = list(schema.__columns__)
    dtypes = {n: c.dtype for n, c in schema.__columns__.items()}

    class ReplaySubject(ConnectorSubject):
        def run(self) -> None:
            from pathway_tpu.io.fs import _coerce

            with open(path, newline="") as f:
                for rec in _csv.DictReader(f):
                    vals = {n: _coerce(rec[n], dtypes[n]) for n in names if n in rec}
                    self.next(**vals)
                    self.commit()
                    if input_rate > 0:
                        _time.sleep(1.0 / input_rate)

    return _python_read(ReplaySubject(), schema=schema, autocommit_duration_ms=autocommit_ms)


def replay_csv_with_time(
    path: str, *, schema: Any, time_column: str, unit: str = "s",
    autocommit_ms: int = 100, speedup: float = 1.0, **kwargs: Any,
):
    names = list(schema.__columns__)
    dtypes = {n: c.dtype for n, c in schema.__columns__.items()}
    mult = {"s": 1.0, "ms": 1e-3, "us": 1e-6, "ns": 1e-9}[unit]

    class ReplayTimeSubject(ConnectorSubject):
        def run(self) -> None:
            from pathway_tpu.io.fs import _coerce

            prev_t: float | None = None
            with open(path, newline="") as f:
                for rec in _csv.DictReader(f):
                    vals = {n: _coerce(rec[n], dtypes[n]) for n in names if n in rec}
                    t = float(vals[time_column]) * mult
                    if prev_t is not None and t > prev_t:
                        _time.sleep((t - prev_t) / speedup)
                    prev_t = t
                    self.next(**vals)
                    self.commit()

    return _python_read(ReplayTimeSubject(), schema=schema, autocommit_duration_ms=autocommit_ms)
