"""pw.io.mongodb — write table updates to a MongoDB collection.

Reference parity: python/pathway/io/mongodb/__init__.py (write :14)
backed by the native MongoWriter (src/connectors/data_storage.rs).
Implemented against pymongo; raises a clear ImportError when it is not
installed.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.json import Json
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io._external import require_module


def write(
    table: Any,
    *,
    connection_string: str,
    database: str,
    collection: str,
    max_batch_size: int | None = None,
) -> None:
    """Appends the table's update stream to a MongoDB collection; each
    document gets `time` and `diff` fields (reference :14)."""
    pymongo = require_module("pymongo", "mongodb")
    names = table._column_names()
    state: dict[str, Any] = {"client": None}

    def _coll() -> Any:
        if state["client"] is None:
            state["client"] = pymongo.MongoClient(connection_string)
        return state["client"][database][collection]

    def write_batch(time: int, entries: list) -> None:
        docs = []
        for _key, row, diff in entries:
            doc = {}
            for n, v in zip(names, row):
                doc[n] = v.value if isinstance(v, Json) else v
            doc["time"] = time
            doc["diff"] = diff
            docs.append(doc)
            if max_batch_size and len(docs) >= max_batch_size:
                _coll().insert_many(docs)
                docs = []
        if docs:
            _coll().insert_many(docs)

    def close() -> None:
        if state["client"] is not None:
            state["client"].close()

    G.add_sink("output", table, write_batch=write_batch, close=close)


__all__ = ["write"]
