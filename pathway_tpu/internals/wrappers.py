"""PyObjectWrapper: carry an arbitrary Python object through the engine
as a value (reference: api.py wrap_py_object / PyObjectWrapper dtype).

The wrapped object flows like any scalar: it groups/joins by identity of
its serialized form, persists via the codec's explicit escape, and comes
back out of `materialize`/subscribe unchanged. An optional serializer
(`dumps`/`loads` protocol, e.g. `pickle` or a module with those two
functions) controls the durable form.
"""

from __future__ import annotations

import pickle
from typing import Any


class PyObjectWrapper:
    __slots__ = ("value", "_serializer")

    def __init__(self, value: Any, *, serializer: Any = None):
        self.value = value
        self._serializer = serializer

    def __repr__(self) -> str:
        return f"pw.PyObjectWrapper({self.value!r})"

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, PyObjectWrapper) and other.value == self.value

    def __hash__(self) -> int:
        try:
            return hash(("PyObjectWrapper", self.value))
        except TypeError:
            return hash(("PyObjectWrapper", id(type(self.value))))

    # pickle protocol: route through the chosen serializer so the durable
    # form is what the user asked for
    def __reduce__(self):
        ser = self._serializer
        if ser is not None:
            return (_rebuild_wrapped, (ser.dumps(self.value), ser))
        return (_rebuild_plain, (pickle.dumps(self.value, protocol=4),))


def _rebuild_plain(data: bytes) -> PyObjectWrapper:
    return PyObjectWrapper(pickle.loads(data))  # noqa: S301


def _rebuild_wrapped(data: bytes, serializer: Any) -> PyObjectWrapper:
    return PyObjectWrapper(serializer.loads(data), serializer=serializer)


def wrap_py_object(value: Any, *, serializer: Any = None) -> PyObjectWrapper:
    return PyObjectWrapper(value, serializer=serializer)
