"""Plan verifier: re-derives, independently of the optimizer, every
invariant the plan transforms assume — at build time, between lowering
and engine construction.

The optimizer stack rests on hand-argued soundness proofs: fusion's
single-consumer gates, id-elision's observability vetoes, the donated
exchange buffers' single-round aliasing rule, token-resident iterate
scopes, the exactly-once outbox contract, and the fused native
programs' virtual schema (docs/planner.md, docs/static-analysis.md).
Each proof lives in the pass that uses it — so a bug there corrupts
data silently at runtime. This module is the second opinion: after
lowering builds the engine graph, ``verify_session`` re-walks the spec
DAG and the built nodes with its own transfer rules and raises a
structured :class:`PlanVerificationError` (node labels via
``Node.describe()``) on any disagreement, instead of letting a broken
plan run.

Gate: ``PATHWAY_VERIFY`` — ``0`` skips the verifier, ``strict``
escalates warnings to errors, anything else (the default) verifies.
The verdict lands in the plan report under ``planner.last_report()``
(key ``"verify"``) either way.

Checks (each independent of the code it audits; see the matching
``check_*`` function):

* ``fusion-single-consumer`` — every interior spec of a fused chain has
  exactly one consumer over the reachable spec DAG and is not itself a
  sink root.
* ``id-elision`` — a fresh forward re-derivation of key-origin flow:
  every cheap-keyed scan and cheap-id join is re-proven unobservable
  (no id-referencing expression, no key-observing sink per
  ``observes_ids``, no off-whitelist operator, session single-worker /
  mesh-free / persistence-free).
* ``iterate-scope`` — token-resident iterate scopes: captures all
  token-resident with the demotion ladder wired, no side-effecting node
  in the body; object-plane-only body members are warnings (demotion
  keeps them correct but breaks the zero-round-trip contract).
* ``exactly-once-outbox`` — with persistence attached and
  ``PATHWAY_EXACTLY_ONCE`` on, every streaming sink writes through the
  outbox; an armed outbox without the contract is equally an error.
* ``native-program-schema`` — the fused ``_NativeProgramBuilder``
  programs type-check structurally: every stage's column references
  resolve inside the virtual schema of the stage boundary they cross.
* ``exchange-donation`` — the respill layout planner is re-probed over
  a shape grid: a donated wave must be single-round with the
  byte-matching ``n_shards * (cap + 1)`` layout (aliasing on a
  multi-round wave would corrupt round 2+). The same rule guards the
  live decision via :func:`check_donation`.
* ``cone-contract`` — every installed wave cone (engine/cone.py) is
  re-proved before any compile: single-consumer interior (each member
  feeds ONLY the next member — a second consumer would observe the
  merged emission the cone elides), donation only on single-round
  layouts, byte-matching staging-buffer schema (4 u64 lanes per row;
  the interior program re-passes the native-program schema check), and
  absorbed-flag consistency with ``Graph.step``'s skip rule.
* ``morsel-contract`` — morsel-parallel wave execution
  (engine/morsel.py): a dynamic probe of the steal scheduler's claim
  protocol (exactly-once, per-queue order, single-consumer latch),
  every sharded replica wired only to its private collector, and no
  donation across stolen morsels (single-round cones only).
* ``join-reorder`` — every "auto"-mode join swap the planner applied is
  re-proved: sketches disagree by the promised ratio and no
  order-sensitive sink reaches the join (independent upstream closure).
* ``spill-contract`` — every out-of-core arrangement (engine/spill.py):
  positive resident budget, manifest covers the sealed runs exactly
  (count + record-total redundancy catches a run dropped from the
  listing), and the exclusive-residency invariant behind the probe
  ladder (a key live in two tiers would let tail-first-then-newest-run
  serve stale state). Restore re-runs the manifest checks on every
  spill manifest embedded in a checkpoint BEFORE any node mutates.
* ``index-tier-contract`` — every tiered ANN index
  (pathway_tpu/indexing/tiers.py): each live doc's PQ codes sit in
  EXACTLY one tier (a cold list with live rows still in the RAM cube,
  or a cold list with no live run record, breaks the probe ladder's
  exclusive-residency assumption), the tier store's manifest passes the
  spill manifest checks, and the resident/cold split agrees with the
  store's two-tier rule. Promotion must preserve no-lost-inserts: an
  append into a cold list promotes it first, which this check observes
  as the one-tier invariant holding after the fact.
"""

from __future__ import annotations

import os
from typing import Any

__all__ = [
    "PlanVerificationError",
    "mode",
    "enabled",
    "verify_session",
    "check_donation",
    "check_swap_contract",
]


def mode() -> str:
    """PATHWAY_VERIFY: "off" (=0), "strict", or the default "on"."""
    v = os.environ.get("PATHWAY_VERIFY", "1")
    if v == "0":
        return "off"
    if v == "strict":
        return "strict"
    return "on"


def enabled() -> bool:
    return mode() != "off"


# Hot-path mirror of enabled(): the live donation guard in
# parallel/exchange.py consults this per WAVE, where an env read is the
# PR 9(h) bug class. Refreshed from the environment at every session's
# execute seam (refresh_enabled, lowering-time), so an in-process
# PATHWAY_VERIFY flip applies uniformly from the next session build —
# never mid-run, and never half (build gate on, wave guard stale off).
_ENABLED_CACHE: bool | None = None


def enabled_cached() -> bool:
    global _ENABLED_CACHE
    if _ENABLED_CACHE is None:
        _ENABLED_CACHE = enabled()
    return _ENABLED_CACHE


def refresh_enabled() -> bool:
    """Re-read PATHWAY_VERIFY and refresh the hot-path cache; the
    build-time gate in Session.execute calls this instead of enabled()."""
    global _ENABLED_CACHE
    _ENABLED_CACHE = enabled()
    return _ENABLED_CACHE


class PlanVerificationError(RuntimeError):
    """A plan invariant failed re-derivation. ``findings`` carries the
    per-check messages; ``verdict`` the full report dict."""

    def __init__(self, findings: list[str], verdict: dict | None = None):
        super().__init__(
            "plan verification failed:\n  " + "\n  ".join(findings)
        )
        self.findings = findings
        self.verdict = verdict or {}


class _Verdict:
    def __init__(self, md: str):
        self.report: dict = {"mode": md, "checks": {}, "violations": [],
                             "warnings": []}
        self._strict = md == "strict"

    def start(self, check: str) -> None:
        self.report["checks"][check] = {"status": "ok"}

    def skip(self, check: str, why: str) -> None:
        self.report["checks"][check] = {"status": "skipped", "why": why}

    def violation(self, check: str, message: str) -> None:
        entry = self.report["checks"].setdefault(check, {"status": "ok"})
        entry["status"] = "violation"
        self.report["violations"].append(f"[{check}] {message}")

    def warning(self, check: str, message: str) -> None:
        if self._strict:
            self.violation(check, message + " (escalated by strict mode)")
            return
        entry = self.report["checks"].setdefault(check, {"status": "ok"})
        if entry["status"] == "ok":
            entry["status"] = "warning"
        self.report["warnings"].append(f"[{check}] {message}")

    def internal(self, check: str, exc: BaseException) -> None:
        # the verifier must never be the thing that breaks a valid plan:
        # its own failures surface as warnings (strict escalates)
        self.warning(
            check, f"verifier internal error: {type(exc).__name__}: {exc}"
        )


# ----------------------------------------------------- spec DAG walking
#
# The EDGE DEFINITION (what a spec consumes: inputs plus every table its
# params reach) is shared with the planner on purpose — two copies of
# that enumeration would silently drift, and a divergence would fail
# valid plans with PATHWAY_VERIFY on by default. What stays this
# module's own is everything the edges feed: the consumer counting, the
# key-origin transfer rules, and the id-reference walk — the logic the
# verifier exists to double-check.


def _param_exprs(spec) -> list:
    from pathway_tpu.internals.planner import _spec_exprs

    return _spec_exprs(spec)


def _input_tables(spec) -> list:
    from pathway_tpu.internals.planner import _spec_input_tables

    return _spec_input_tables(spec)


class _Walk:
    """One reachable-DAG traversal: postorder spec ids, sid -> spec,
    this module's own consumer counts (each input occurrence counts;
    sinks count their root), and the per-spec input tables / param
    expressions resolved ONCE — the flow analyses below reuse them
    instead of re-resolving expressions per pass."""

    __slots__ = ("order", "specs", "consumers", "in_tables", "exprs_of")

    def __init__(self, roots: list):
        self.specs: dict[int, Any] = {}
        self.consumers: dict[int, int] = {}
        self.order: list[int] = []
        self.in_tables: dict[int, list] = {}
        self.exprs_of: dict[int, list] = {}
        stack = [(t, False) for t in roots]
        while stack:
            table, expanded = stack.pop()
            spec = table._spec
            if expanded:
                if spec.id not in self.specs:
                    self.specs[spec.id] = spec
                    self.order.append(spec.id)
                continue
            if spec.id in self.specs:
                continue
            stack.append((table, True))
            exprs = _param_exprs(spec)
            tabs = _input_tables(spec)
            self.exprs_of[spec.id] = exprs
            self.in_tables[spec.id] = tabs
            for t_in in tabs:
                self.consumers[t_in._spec.id] = (
                    self.consumers.get(t_in._spec.id, 0) + 1
                )
                stack.append((t_in, False))
        for t in roots:
            self.consumers[t._spec.id] = (
                self.consumers.get(t._spec.id, 0) + 1
            )


def _has_id_reference(exprs: list) -> bool:
    from pathway_tpu.internals import expression as ex

    seen: set[int] = set()
    stack = [e for e in exprs if isinstance(e, ex.ColumnExpression)]
    while stack:
        e = stack.pop()
        if id(e) in seen:
            continue
        seen.add(id(e))
        if isinstance(e, ex.IdReference):
            return True
        stack.extend(
            s for s in e._sub_expressions()
            if isinstance(s, ex.ColumnExpression)
        )
    return False


# ------------------------------------------------ check: fusion groups


def _shared_walk(session, shared: dict):
    """One reachable-DAG walk per verify_session, shared by the checks
    that need it (the verifier runs on every build — don't pay the
    param-expression resolution twice)."""
    if "walk" not in shared:
        roots = getattr(session, "_plan_roots", None) or []
        shared["walk"] = _Walk(roots) if roots else None
    return shared["walk"]


def check_fusion_single_consumer(session, v: _Verdict, shared: dict) -> None:
    from pathway_tpu.engine.core import FusedRowwiseNode

    check = "fusion-single-consumer"
    v.start(check)
    roots = getattr(session, "_plan_roots", None) or []
    fused = [
        n for n in session.graph.nodes
        if isinstance(n, FusedRowwiseNode)
        and getattr(n, "_fused_spec_ids", None)
    ]
    if not fused:
        return
    if not roots:
        v.warning(check, "fused nodes present but no plan roots recorded")
        return
    walk = _shared_walk(session, shared)
    specs, consumers = walk.specs, walk.consumers
    root_ids = {t._spec.id for t in roots}
    groups = 0
    for node in fused:
        ids = node._fused_spec_ids
        interior = ids if node.rekey is not None else ids[:-1]
        groups += 1
        for sid in interior:
            if sid not in specs:
                v.violation(
                    check,
                    f"{node.describe()}: fused interior spec {sid} is not "
                    "reachable from the plan roots",
                )
                continue
            n_cons = consumers.get(sid, 0)
            if n_cons != 1:
                v.violation(
                    check,
                    f"{node.describe()}: interior stage spec {sid} "
                    f"({specs[sid].kind}) has {n_cons} consumers over the "
                    "reachable spec DAG — fusing it away drops the other "
                    "consumer(s)",
                )
            if sid in root_ids:
                v.violation(
                    check,
                    f"{node.describe()}: interior stage spec {sid} is "
                    "itself a sink root — its output must stay "
                    "materialized",
                )
    v.report["checks"][check]["groups"] = groups


# --------------------------------------------------- check: id elision

_ELISION_KINDS = frozenset({
    "static", "static_native", "connector", "rowwise", "filter",
    "groupby", "join", "concat", "flatten", "reindex",
    "update_rows", "update_cells", "setop", "with_universe_of", "having",
    "buffer", "forget", "freeze",
})
_KEY_MATCHING = frozenset({
    "update_rows", "update_cells", "setop", "with_universe_of", "having",
})
_PASSTHROUGH = frozenset({"rowwise", "filter", "buffer", "forget", "freeze"})
_REKEYED = frozenset({"groupby", "reindex"})


def _derive_safe_markers(
    walk: "_Walk", sink_meta: list
) -> tuple[set, set, str | None]:
    """The verifier's own key-origin flow: per spec the set of elidable
    origins its output keys derive from, and the set of origins whose
    key *values* anything can surface. Returns (safe source sids, safe
    join sids, whitelist-veto reason). Transfer rules written fresh from
    the soundness argument in docs/planner.md."""
    order, specs = walk.order, walk.specs
    for sid in order:
        if specs[sid].kind not in _ELISION_KINDS:
            return set(), set(), (
                f"operator kind {specs[sid].kind!r} outside the elision "
                "whitelist"
            )
    origin: dict[int, frozenset] = {}
    observed: set = set()
    for sid in order:
        spec = specs[sid]
        kind = spec.kind
        ins = [
            origin.get(t._spec.id, frozenset())
            for t in walk.in_tables[sid]
        ]
        if _has_id_reference(walk.exprs_of[sid]):
            for d in ins:
                observed.update(d)
        if kind == "static_native":
            origin[sid] = frozenset({("src", sid)})
        elif kind == "connector":
            origin[sid] = (
                frozenset({("src", sid)})
                if spec.params.get("native_plane")
                and not spec.params.get("upsert")
                else frozenset()
            )
        elif kind in _PASSTHROUGH:
            origin[sid] = ins[0] if ins else frozenset()
        elif kind in _REKEYED:
            origin[sid] = frozenset()
        elif kind == "join":
            l_o = origin.get(spec.inputs[0]._spec.id, frozenset())
            r_o = origin.get(spec.inputs[1]._spec.id, frozenset())
            id_mode = spec.params.get("id_mode", "hash")
            if id_mode == "left":
                origin[sid] = l_o
            elif id_mode == "right":
                origin[sid] = r_o
            else:
                origin[sid] = l_o | r_o | frozenset({("join", sid)})
        elif kind in ("concat", "flatten"):
            origin[sid] = frozenset().union(*ins) if ins else frozenset()
        elif kind in _KEY_MATCHING:
            base = ins[0] if ins else frozenset()
            if not all(d == base for d in ins):
                # matching keys across differently-derived inputs pins
                # the key VALUES across schemes — that observes them
                for d in ins:
                    observed.update(d)
            origin[sid] = frozenset().union(*ins) if ins else frozenset()
        else:  # "static" and anything keyless
            origin[sid] = frozenset()
    for table, observes_ids in sink_meta:
        if observes_ids:
            observed.update(origin.get(table._spec.id, frozenset()))
    safe_sources = {
        sid for sid in order
        if ("src", sid) in origin.get(sid, frozenset())
        and ("src", sid) not in observed
    }
    safe_joins = {
        sid for sid in order
        if specs[sid].kind == "join"
        and specs[sid].params.get("id_mode", "hash") == "hash"
        and ("join", sid) not in observed
    }
    return safe_sources, safe_joins, None


def check_id_elision(session, v: _Verdict, shared: dict) -> None:
    from pathway_tpu.engine.core import JoinNode

    check = "id-elision"
    v.start(check)
    # the engine-state claims: scans keyed cheap this session, joins
    # built with cheap pair-mix ids
    claimed_sources: list[int] = []
    claimed_joins: list[tuple[int | None, Any]] = []
    # claims come from the GRAPH, not the spec cache: on the object
    # plane a join's cached node is its select tail, and pushdown may
    # cache a join under the consuming filter's id — the elision proof
    # is keyed by the JOIN spec, which lowering stamps on the node
    for node in session.graph.nodes:
        if isinstance(node, JoinNode) and node.id_mode == "cheap":
            claimed_joins.append(
                (getattr(node, "_join_spec_id", None), node)
            )
    roots = getattr(session, "_plan_roots", None) or []
    sink_meta = getattr(session, "_sink_meta", None) or []
    walk = _shared_walk(session, shared)
    order: list[int] = walk.order if walk is not None else []
    specs: dict[int, Any] = walk.specs if walk is not None else {}
    if roots:
        for sid in order:
            tuning = specs[sid].params.get("scan_tuning")
            if (
                isinstance(tuning, dict)
                and tuning.get("session") == session._session_seq
                and tuning.get("key_mode") == 1
            ):
                claimed_sources.append(sid)
    if not claimed_sources and not claimed_joins:
        return
    if not roots:
        v.violation(
            check,
            "cheap-keyed nodes exist but no plan roots were recorded — "
            "the elision claims cannot be re-derived",
        )
        return

    def name_of(sid: int) -> str:
        node = session.cache.get(sid)
        if node is not None:
            return node.describe()
        sp = specs.get(sid)
        return f"spec#{sid}({sp.kind if sp is not None else '?'})"

    # session-level preconditions (cheap keys reshard under exchanges
    # and must never mix into persisted snapshots)
    for why, bad in (
        ("a multi-worker session", session.n_workers > 1),
        ("a process-mesh session", session.mesh is not None),
        ("an attached persistence config",
         getattr(session, "_persistent", False)
         or getattr(session, "checkpointer", None) is not None),
    ):
        if bad:
            v.violation(
                check,
                f"id elision is active under {why}: "
                + ", ".join(
                    [name_of(s) for s in claimed_sources]
                    + [n.describe() for _sid, n in claimed_joins]
                ),
            )
    safe_sources, safe_joins, veto = _derive_safe_markers(
        walk, sink_meta
    )
    if veto is not None and (claimed_sources or claimed_joins):
        v.violation(
            check,
            f"elided ids coexist with {veto} — the whitelist proof does "
            "not cover this plan",
        )
        return
    for sid in claimed_sources:
        if sid not in safe_sources:
            v.violation(
                check,
                f"{name_of(sid)}: scan keys elided (cheap sequential) but "
                "re-derivation finds the row ids OBSERVABLE — an "
                "id-referencing expression or key-observing sink "
                "(observes_ids) reaches them",
            )
    for sid, node in claimed_joins:
        if sid is None:
            v.violation(
                check,
                f"{node.describe()}: join ids elided (cheap pair mix) "
                "but the node carries no join-spec id — the claim "
                "cannot be re-derived",
            )
        elif sid not in safe_joins:
            v.violation(
                check,
                f"{node.describe()}: join ids elided (cheap pair mix) but "
                "re-derivation finds the output ids OBSERVABLE",
            )
    v.report["checks"][check]["sources"] = len(claimed_sources)
    v.report["checks"][check]["joins"] = len(claimed_joins)


# ----------------------------------------------- check: iterate scopes

# engine nodes that never ride the token plane: inside a token-resident
# scope they force per-round materialize round-trips (the demotion
# ladder keeps them CORRECT, so their presence is a warning — the
# zero-round-trip contract of docs/iterate.md is what breaks)
_OBJECT_ONLY_NODES = (
    "SortNode", "IxNode", "GradualBroadcastNode", "ExternalIndexNode",
    "RowTransformerNode", "AsyncApplyNode",
)
# side effects inside a fixpoint body would fire once per ROUND, not
# once per wave — never legal
_SIDE_EFFECT_NODES = ("OutputNode", "SubscribeNode")


def check_iterate_scopes(session, v: _Verdict, shared: dict) -> None:
    from pathway_tpu.engine.runtime import IterateNode

    check = "iterate-scope"
    v.start(check)
    scopes = 0

    def scan_graph(graph) -> None:
        nonlocal scopes
        for node in graph.nodes:
            if not isinstance(node, IterateNode):
                continue
            scopes += 1
            body_kinds = {type(n).__name__ for n in node.sub_graph.nodes}
            for bad in _SIDE_EFFECT_NODES:
                if bad in body_kinds:
                    v.violation(
                        check,
                        f"{node.describe()}: iterate body contains a "
                        f"{bad} — a sink inside a fixpoint scope fires "
                        "per round, not per wave",
                    )
            for name in node.iterated_names:
                if name not in node.placeholder_nodes:
                    v.violation(
                        check,
                        f"{node.describe()}: iterated input {name!r} has "
                        "no placeholder node in the body graph",
                    )
            if node._tok:
                for name, cap in node.captures.items():
                    if not cap._tok:
                        v.violation(
                            check,
                            f"{node.describe()}: token-resident scope "
                            f"with OBJECT-plane capture {name!r} "
                            f"({cap.describe()}) — mixed-plane feedback "
                            "desynchronizes the rounds",
                        )
                    elif cap.on_demote is None:
                        v.violation(
                            check,
                            f"{node.describe()}: capture {name!r} "
                            f"({cap.describe()}) is token-resident but "
                            "its demotion ladder (on_demote) is unwired "
                            "— a plane-unrepresentable row would lose "
                            "the scope's read positions",
                        )
                for bad in _OBJECT_ONLY_NODES:
                    if bad in body_kinds:
                        v.warning(
                            check,
                            f"{node.describe()}: token-resident scope "
                            f"contains object-plane-only {bad} — every "
                            "round pays a materialize round-trip "
                            "(docs/iterate.md zero-round-trip contract)",
                        )
            scan_graph(node.sub_graph)  # nested iterate scopes

    scan_graph(session.graph)
    v.report["checks"][check]["scopes"] = scopes


# ------------------------------------------ check: exactly-once outbox


def check_exactly_once_outbox(session, v: _Verdict, shared: dict) -> None:
    from pathway_tpu.engine.runtime import OutputNode
    from pathway_tpu.io.outbox import exactly_once_enabled

    check = "exactly-once-outbox"
    v.start(check)
    out_nodes = [
        n for n in session.graph.nodes if isinstance(n, OutputNode)
    ]
    if not out_nodes:
        return
    persistent = getattr(session, "checkpointer", None) is not None
    eo = exactly_once_enabled()
    required = persistent and eo and bool(session.connectors)
    for node in out_nodes:
        if required and node._outbox is None:
            v.violation(
                check,
                f"{node.describe()}: persistence is attached and "
                "exactly-once is on, but this sink writes DIRECTLY — "
                "a crash between its wave write and the epoch commit "
                "duplicates or drops deliveries (io/outbox.py)",
            )
        elif node._outbox is not None and not (persistent and eo):
            v.violation(
                check,
                f"{node.describe()}: outbox armed without the "
                "exactly-once contract (persistence "
                f"{'attached' if persistent else 'absent'}, "
                f"PATHWAY_EXACTLY_ONCE {'on' if eo else 'off'}) — "
                "sealed ranges would never commit",
            )
    v.report["checks"][check]["sinks"] = len(out_nodes)
    v.report["checks"][check]["outboxed"] = sum(
        1 for n in out_nodes if n._outbox is not None
    )


# ------------------------------------- check: fused native programs


def _validate_program(prog: dict) -> list[str]:
    """Structural type check of one fused native program: every column
    reference resolves inside the virtual schema of its stage boundary."""
    problems: list[str] = []
    src_w = prog.get("src_width")
    env_w = src_w  # None = unknown source width (runtime re-fusion)

    def in_env(idx: int) -> bool:
        return env_w is None or 0 <= idx < env_w

    for sno, stage in enumerate(prog.get("stages", [])):
        kind, payload = stage[0], stage[1]
        if kind == "map":
            for it in payload:
                tag = it[0]
                if tag == "env":
                    if not in_env(it[1]):
                        problems.append(
                            f"stage {sno}: env passthrough col {it[1]} "
                            f"outside the boundary schema (width {env_w})"
                        )
                elif tag == "keycols":
                    if src_w is not None and any(
                        not 0 <= c < src_w for c in it[1]
                    ):
                        problems.append(
                            f"stage {sno}: keycols {it[1]} outside the "
                            f"SOURCE schema (width {src_w})"
                        )
                elif tag == "plan":
                    bad = [
                        c for c in it[1].needed_cols if not in_env(c)
                    ]
                    if bad:
                        problems.append(
                            f"stage {sno}: plan needs cols {bad} outside "
                            f"the boundary schema (width {env_w})"
                        )
                else:
                    problems.append(f"stage {sno}: unknown map item {tag!r}")
            env_w = len(payload)
        elif kind == "filter":
            bad = [c for c in payload.needed_cols if not in_env(c)]
            if bad:
                problems.append(
                    f"stage {sno}: filter needs cols {bad} outside the "
                    f"boundary schema (width {env_w})"
                )
        else:
            problems.append(f"stage {sno}: unknown stage kind {kind!r}")
    fe = prog.get("final_env")
    if fe is not None:
        if env_w is not None and len(fe) != env_w:
            problems.append(
                f"final schema width {len(fe)} != last boundary width "
                f"{env_w}"
            )
        for j, it in enumerate(fe):
            if it[0] == "src":
                if src_w is not None and not 0 <= it[1] < src_w:
                    problems.append(
                        f"final col {j} passes through source col "
                        f"{it[1]} outside the source schema "
                        f"(width {src_w})"
                    )
            elif it[0] != "slot":
                problems.append(f"final col {j}: unknown item {it[0]!r}")
    if src_w is not None:
        bad = [c for c in prog.get("needed_src", []) if not 0 <= c < src_w]
        if bad:
            problems.append(
                f"needed_src {bad} outside the source schema "
                f"(width {src_w})"
            )
    return problems


def check_native_programs(session, v: _Verdict, shared: dict) -> None:
    from pathway_tpu.engine.core import FusedRowwiseNode

    check = "native-program-schema"
    v.start(check)
    programs = 0
    for node in session.graph.nodes:
        if not isinstance(node, FusedRowwiseNode) or node._program is None:
            continue
        programs += 1
        for problem in _validate_program(node._program):
            v.violation(check, f"{node.describe()}: {problem}")
    v.report["checks"][check]["programs"] = programs


# ------------------------------------------- check: exchange donation


def check_donation(donate: bool, rounds: int, rows_local: int | None = None,
                   n_shards: int | None = None, cap: int | None = None):
    """The donation aliasing rule, callable from the live decision point
    (parallel/exchange.py) and from the static probe below: a donated
    exchange wave MUST be single-round (the staging arrays alias the
    receive buffers; reuse across respill rounds would corrupt round
    2+), with the byte-matching padded layout."""
    if not donate:
        return
    if rounds != 1:
        raise PlanVerificationError([
            "[exchange-donation] donated exchange buffers on a "
            f"{rounds}-round wave — aliasing the staging arrays would "
            "corrupt every round after the first",
        ])
    if (
        rows_local is not None
        and n_shards is not None
        and cap is not None
        and rows_local != n_shards * (cap + 1)
    ):
        raise PlanVerificationError([
            "[exchange-donation] donated layout rows_local="
            f"{rows_local} != n_shards*(cap+1)={n_shards * (cap + 1)} — "
            "send/receive byte sizes must match for XLA to alias them",
        ])


# the planner function whose probe grid last passed: the grid verdict is
# process-invariant for a given function object, so re-probing it on
# every build would be pure waste — a monkeypatched/edited planner is a
# DIFFERENT object and re-probes
_DONATION_PROBED_FN: Any = None


def check_exchange_donation(session, v: _Verdict, shared: dict) -> None:
    global _DONATION_PROBED_FN
    check = "exchange-donation"
    v.start(check)
    import sys

    # only audit the exchange stack when this process has loaded it —
    # no exchange module means no donation can happen, and importing it
    # here would drag the jax/mesh machinery into every object-plane
    # session just to probe a decision it will never take
    _ex = sys.modules.get("pathway_tpu.parallel.exchange")
    if _ex is None:
        v.skip(check, "exchange stack not loaded in this process")
        return
    plan = getattr(_ex, "plan_respill_layout", None)
    if plan is None:
        v.skip(check, "no respill layout planner exported")
        return
    if plan is _DONATION_PROBED_FN:
        v.report["checks"][check]["probes"] = "cached"
        return
    probes = 0
    for n_shards in (2, 4, 8):
        for per in (0, 1, 7, 64, 4096):
            for max_bucket in (0, 1, per // 2, per, 4 * per + 3):
                for capacity in (None, 16):
                    probes += 1
                    donate, cap, rounds, rows_local = plan(
                        capacity, max_bucket, per, n_shards
                    )
                    try:
                        check_donation(
                            donate, rounds, rows_local, n_shards, cap
                        )
                    except PlanVerificationError as e:
                        v.violation(
                            check,
                            f"layout planner (n_shards={n_shards}, "
                            f"per={per}, max_bucket={max_bucket}, "
                            f"capacity={capacity}): {e.findings[0]}",
                        )
                        return
    v.report["checks"][check]["probes"] = probes
    _DONATION_PROBED_FN = plan


# ----------------------------------------------- check: cone contract


def check_cone_contract(session, v: _Verdict, shared: dict) -> None:
    """Re-prove every installed wave cone's contract (engine/cone.py)
    BEFORE any compile: a cone that fires one merged program instead of
    per-node waves is only sound when no third party can observe the
    emissions it elides and its donated buffers can actually alias."""
    check = "cone-contract"
    v.start(check)
    cones = getattr(session.graph, "_cones", None) or []
    v.report["checks"][check]["cones"] = len(cones)
    if not cones:
        return
    for cone in cones:
        name = cone.head.describe()
        # single-consumer interior: each member feeds ONLY the next one
        for m, nxt in zip(cone.members[:-1], cone.members[1:]):
            downs = [d for d, _i in m.downstream]
            if len(downs) != 1 or downs[0] is not nxt:
                v.violation(
                    check,
                    f"{name}: multi-consumer interior — {m.describe()} "
                    f"feeds {len(downs)} consumer(s); a cone member may "
                    "feed only the next member (any other consumer "
                    "would observe the per-node emission the cone "
                    "elides)",
                )
        prog = cone.program
        rounds = prog.get("rounds", 1)
        if prog.get("donation", "none") != "none" and rounds != 1:
            v.violation(
                check,
                f"{name}: donation on a multi-round layout "
                f"({rounds} rounds) — the donated staging buffers alias "
                "the receive buffers and would corrupt every round "
                "after the first (same rule as check_donation)",
            )
        if prog.get("lanes") != 4:
            v.violation(
                check,
                f"{name}: schema-mismatched staging buffer — "
                f"{prog.get('lanes')} lanes declared, the exchange pack "
                "ships exactly 4 u64 lanes per row (key_lo, key_hi, "
                "token, diff); send/receive byte sizes must match for "
                "XLA to alias them",
            )
        interior = prog.get("interior")
        if interior is not None:
            for problem in _validate_program(interior):
                v.violation(
                    check, f"{name}: interior program schema: {problem}"
                )
        for m in cone.members[1:]:
            if not m._cone_absorbed:
                v.violation(
                    check,
                    f"{name}: {m.describe()} is a cone member but not "
                    "absorbed — Graph.step would fire it a second time "
                    "on top of the cone's fire",
                )
        if cone.head._cone is not cone:
            v.violation(
                check,
                f"{name}: head does not point back at its cone — the "
                "cone would never fire while its members stay absorbed",
            )


# ---------------------------------------------- check: spill contract


def check_spill_contract(session, v: _Verdict, shared: dict) -> None:
    """Prove every out-of-core arrangement's spill contract
    (engine/spill.py) before data flows: the probe ladder is only sound
    when a key is live in EXACTLY one tier (resident tail or one run's
    live set — tail-first-then-newest-run-first stops at the first hit),
    the run manifest covers the runs exactly, and the resident budget is
    a positive group count (a zero budget would thrash every wave
    through disk)."""
    from pathway_tpu.engine import spill as _spill

    check = "spill-contract"
    v.start(check)
    stores = 0
    for node in session.graph.nodes:
        getter = getattr(node, "spill_stores", None)
        if getter is None:
            continue
        for store in getter():
            stores += 1
            who = f"{node.describe()}:{store.label}"
            if store.budget <= 0:
                v.violation(
                    check,
                    f"{who}: non-positive resident budget "
                    f"{store.budget}; every probe would take the disk "
                    "ladder",
                )
            try:
                _spill.verify_manifest(store.manifest(), who)
            except PlanVerificationError as e:
                v.violation(check, str(e.findings[0] if e.findings else e))
            try:
                _spill.check_two_tier(store, who)
            except PlanVerificationError as e:
                v.violation(check, str(e.findings[0] if e.findings else e))
    v.report["checks"][check]["stores"] = stores


# ----------------------------------------- check: index tier contract


def check_index_tier_contract(session, v: _Verdict, shared: dict) -> None:
    """Prove the tier placement of every tiered ANN index behind an
    `ExternalIndexNode` (see pathway_tpu/indexing/tiers.py): exclusive
    residency per list (RAM cube XOR a live run record), manifest
    integrity of the tier store, and agreement between the placement
    flags and the store's two-tier rule."""
    from pathway_tpu.indexing import tiers as _tiers

    _tiers.check_index_tier(session, v, shared)


# --------------------------------------------- check: morsel contract

# the StealScheduler class whose dynamic probe last passed — same
# process-invariance argument as _DONATION_PROBED_FN: the claim protocol
# is a pure property of the class object, a monkeypatched scheduler is a
# different object and re-probes
_MORSEL_PROBED_CLS: Any = None


def _probe_steal_scheduler(_morsel) -> list[str]:
    """Drain synthetic queues through a real StealScheduler on a private
    crew and re-derive the claim invariants from the observed trace:
    every task exactly once, per queue in index order, never two tasks
    of one queue in flight together (the single-consumer latch)."""
    import threading as _threading
    import time as _time
    from concurrent.futures import ThreadPoolExecutor

    n_queues, per, crew = 5, 4, 3
    trace_lock = _threading.Lock()
    started: list[tuple[int, int]] = []
    inflight = [0] * n_queues
    problems: list[str] = []

    def make(qi: int, ti: int):
        def run():
            with trace_lock:
                inflight[qi] += 1
                if inflight[qi] > 1:
                    problems.append(
                        f"queue {qi}: two morsels in flight at once "
                        "(single-consumer latch broken)"
                    )
                started.append((qi, ti))
            _time.sleep(0.0005)  # widen the race window
            with trace_lock:
                inflight[qi] -= 1
        return run

    queues = [[make(qi, ti) for ti in range(per)] for qi in range(n_queues)]
    sched = _morsel.StealScheduler(queues, crew)
    with ThreadPoolExecutor(
        max_workers=crew - 1, thread_name_prefix="pw-verify-steal"
    ) as pool:
        futs = [pool.submit(sched.runner, w) for w in range(1, crew)]
        sched.runner(0)
        for f in futs:
            f.result()
    # deliberately NOT sched.finish(): the probe's synthetic morsels must
    # not pollute the published pathway_morsel_*/pathway_steal_* counters
    # (every _complete already reconciled the live-depth gauge; our tasks
    # never raise, so there is no failure path to reconcile)
    if sched._fail is not None:
        problems.append(f"probe task raised: {sched._fail!r}")
    for qi in range(n_queues):
        ran = [ti for q, ti in started if q == qi]
        if ran != list(range(per)):
            problems.append(
                f"queue {qi}: start order {ran}, want exactly-once in "
                "index order (stateful replicas apply morsels in "
                "segment order)"
            )
    return problems


def check_morsel_contract(session, v: _Verdict, shared: dict) -> None:
    """Re-prove the morsel/steal execution contract (engine/morsel.py)
    whenever this build will run with morsels on:

    * dynamic probe (cached per StealScheduler class object): a private
      crew drains synthetic queues and the observed trace must show
      exactly-once execution, per-queue index order, and never two
      morsels of one queue in flight (the single-consumer latch —
      exactly what keeps stateful replicas sound under stealing);
    * static: every ShardedNode replica's ONLY downstream is its own
      private collector — emission then happens after the wave barrier
      in replica order, so which thread ran a morsel is unobservable;
      a replica wired anywhere else would leak mid-wave emission from a
      stealing thread;
    * static: installed cone programs carry no donation across stolen
      morsels — donation must stay "single-round" with rounds == 1 (a
      stolen morsel re-firing into an aliased multi-round staging
      buffer is the check_donation corruption class).
    """
    global _MORSEL_PROBED_CLS
    from pathway_tpu.engine import morsel as _morsel
    from pathway_tpu.engine.workers import ShardedNode

    check = "morsel-contract"
    if not _morsel.enabled_cached():
        v.skip(check, "PATHWAY_MORSEL=0 — serial wave execution")
        return
    v.start(check)
    replicas = 0
    for node in session.graph.nodes:
        if not isinstance(node, ShardedNode):
            continue
        for i, (replica, coll) in enumerate(
            zip(node.replicas, node.collectors)
        ):
            replicas += 1
            downs = list(replica.downstream)
            if len(downs) != 1 or downs[0][0] is not coll:
                v.violation(
                    check,
                    f"{node.describe()}: replica {i} feeds "
                    f"{len(downs)} downstream(s) instead of exactly its "
                    "own collector — a stealing thread's emission would "
                    "be observable before the wave barrier",
                )
    v.report["checks"][check]["replicas"] = replicas
    for cone in getattr(session.graph, "_cones", None) or []:
        prog = cone.program
        donation = prog.get("donation", "none")
        if donation != "none" and (
            donation != "single-round" or prog.get("rounds", 1) != 1
        ):
            v.violation(
                check,
                f"{cone.head.describe()}: donation {donation!r} over "
                f"{prog.get('rounds', 1)} round(s) with morsels enabled "
                "— a stolen morsel re-entering an aliased multi-round "
                "staging buffer corrupts later rounds",
            )
    if _morsel.StealScheduler is _MORSEL_PROBED_CLS:
        v.report["checks"][check]["probe"] = "cached"
        return
    problems = _probe_steal_scheduler(_morsel)
    for p in problems:
        v.violation(check, f"steal-scheduler probe: {p}")
    if not problems:
        _MORSEL_PROBED_CLS = _morsel.StealScheduler
    v.report["checks"][check]["probe"] = "ran"


# ----------------------------------------------- check: join reorder


def check_join_reorder(session, v: _Verdict, shared: dict) -> None:
    """Re-prove every join swap the planner applied in "auto" mode with
    this module's own rules: the recorded sketches must disagree by at
    least the auto ratio, and no order-sensitive sink (``observes_ids``
    per the session's sink metadata — subscribe/capture) may reach the
    join, re-derived here by an independent upstream closure over the
    sink tables rather than by trusting ``PlanContext.order_sensitive``.
    Forced swaps (PATHWAY_JOIN_REORDER=1) are the user's explicit
    opt-in and are not judged."""
    from pathway_tpu.internals import planner as _planner

    check = "join-reorder"
    v.start(check)
    entries = [
        e for e in session.plan_report.get("join_orders", [])
        if e.get("applied") and e.get("mode") == "auto"
    ]
    v.report["checks"][check]["auto_swaps"] = len(entries)
    if not entries:
        return
    sensitive: set[int] = set()
    for table, observes_ids in getattr(session, "_sink_meta", None) or []:
        if not observes_ids:
            continue
        up = [table]
        while up:
            t = up.pop()
            sid = t._spec.id
            if sid in sensitive:
                continue
            sensitive.add(sid)
            up.extend(_input_tables(t._spec))
    ratio = _planner._REORDER_AUTO_RATIO
    for e in entries:
        l_rows = (e.get("left") or {}).get("rows")
        r_rows = (e.get("right") or {}).get("rows")
        if l_rows is None or r_rows is None or l_rows * ratio > r_rows:
            v.violation(
                check,
                f"join {e['join']}: auto swap applied on sketches "
                f"left={l_rows} right={r_rows} — below the {ratio}x bar "
                "the auto mode promises (a near-coin-flip swap buys "
                "nothing and still permutes emission order)",
            )
        if e["join"] in sensitive:
            v.violation(
                check,
                f"join {e['join']}: auto swap applied upstream of an "
                "order-sensitive sink — subscribe/capture observes "
                "intra-wave arrival order, which the swap permutes",
            )


# ------------------------------------------------------- swap contract


def _swap_meta_roots(root: str) -> dict[str, str]:
    """{slot name -> metadata-bearing dir} for a persistence root: either
    the root itself (single process) or its ``proc-N`` children (mesh)."""
    if os.path.exists(os.path.join(root, "metadata.json")):
        return {".": root}
    out: dict[str, str] = {}
    try:
        entries = os.listdir(root)
    except OSError:
        return out
    for fn in sorted(entries):
        if fn.startswith("proc-") and fn[5:].isdigit():
            out[fn] = os.path.join(root, fn)
    return out


def check_swap_contract(blue_root: str, green_root: str) -> dict:
    """Blue/green swap gate (parallel/bluegreen.py): the GREEN staged
    root may replace the BLUE serving root only if nothing the blue
    pipeline promised is lost. Re-proved from the roots alone — no trust
    in the green run's own claims: (1) shard-map consistency — same
    process slots on both sides; (2) offsets carried forward — every
    source the blue side committed exists on the green side at an offset
    at least as far; (3) outbox/sink compatibility — every blue sink's
    sealed delivery offset is carried forward, so exactly-once dedup
    survives the swap; (4) the green side actually warmed — its epoch is
    at least blue's (a cold-started green would replay the world onto
    already-delivered sinks). Raises PlanVerificationError on violation;
    returns the verdict report otherwise."""
    import json as _json

    check = "swap-contract"
    v = _Verdict(mode())
    v.start(check)
    blue = _swap_meta_roots(blue_root)
    green = _swap_meta_roots(green_root)
    if set(blue) != set(green):
        v.violation(
            check,
            f"shard map mismatch: blue has slots {sorted(blue)}, green "
            f"has {sorted(green)} — a swap must not change mesh "
            "membership (rebalance first, then swap)",
        )
    def _meta(d: str) -> dict | None:
        try:
            with open(os.path.join(d, "metadata.json")) as f:
                return _json.load(f)
        except (OSError, ValueError):
            return None

    for slot in sorted(set(blue) & set(green)):
        bm, gm = _meta(blue[slot]), _meta(green[slot])
        if bm is None:
            continue  # blue never committed: nothing promised, any green ok
        if gm is None:
            v.violation(
                check,
                f"slot {slot}: green has no committed metadata — it "
                "never warmed against the persisted state",
            )
            continue
        if int(gm.get("epoch", -1)) < int(bm.get("epoch", -1)):
            v.violation(
                check,
                f"slot {slot}: green epoch {gm.get('epoch')} is behind "
                f"blue epoch {bm.get('epoch')} — the fence epoch was "
                "not replayed",
            )
        boff = bm.get("offsets") or {}
        goff = gm.get("offsets") or {}
        for nm, off in boff.items():
            if nm not in goff:
                v.violation(
                    check,
                    f"slot {slot}: source {nm!r} committed by blue is "
                    "missing from green — its journal would be dropped",
                )
            elif int(goff[nm]) < int(off):
                v.violation(
                    check,
                    f"slot {slot}: source {nm!r} offset went backwards "
                    f"({off} -> {goff[nm]}) — green would re-consume "
                    "delivered input",
                )
        bout = bm.get("outbox") or {}
        gout = gm.get("outbox") or {}
        for sink, off in bout.items():
            if sink not in gout:
                v.violation(
                    check,
                    f"slot {slot}: sink {sink!r} outbox offset not "
                    "carried forward — exactly-once dedup would reset "
                    "and redeliver",
                )
            elif int(gout[sink]) < int(off):
                v.violation(
                    check,
                    f"slot {slot}: sink {sink!r} outbox offset went "
                    f"backwards ({off} -> {gout[sink]})",
                )
        if not gm.get("signature"):
            v.violation(
                check,
                f"slot {slot}: green metadata carries no pipeline "
                "signature — state cannot be mapped onto any plan",
            )
    if v.report["violations"]:
        raise PlanVerificationError(v.report["violations"], v.report)
    return v.report


# ---------------------------------------------------------------- driver

_CHECKS = (
    check_fusion_single_consumer,
    check_id_elision,
    check_iterate_scopes,
    check_exactly_once_outbox,
    check_native_programs,
    check_exchange_donation,
    check_cone_contract,
    check_spill_contract,
    check_index_tier_contract,
    check_morsel_contract,
    check_join_reorder,
)


def verify_session(session) -> dict:
    """Run every check over a lowered session. Returns the verdict dict
    (also what lands in the plan report); raises
    :class:`PlanVerificationError` when any invariant fails (strict mode
    escalates warnings). Callers gate on :func:`enabled`."""
    md = mode()
    v = _Verdict(md)
    shared: dict = {}
    for check in _CHECKS:
        try:
            check(session, v, shared)
        except PlanVerificationError:
            raise
        except Exception as e:  # noqa: BLE001 — see _Verdict.internal
            v.internal(check.__name__.replace("check_", "").replace(
                "_", "-"), e)
    if v.report["violations"]:
        raise PlanVerificationError(v.report["violations"], v.report)
    return v.report
