"""Token-resident iterate (engine/runtime.py IterateNode, docs/iterate.md).

Equivalence matrix: the graph algorithms (pagerank, bellman_ford,
connected_components, louvain) must produce BYTE-IDENTICAL outputs with
the token plane forced on and off (PATHWAY_ITERATE_NATIVE kill switch,
read at lowering time so it flips in-process), across the full-object
engine (PATHWAY_TPU_NATIVE=0, subprocess legs), under a 2-process mesh,
and across a persistence save/restore cycle. Plus the acceptance
counter: the pagerank fixpoint loop performs ZERO per-round
materialize()/intern_row round-trips (counter hook on InternTable).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals.lowering import Session
from pathway_tpu.engine.runtime import IterateNode
from pathway_tpu.stdlib.graphs import (
    Graph,
    bellman_ford,
    connected_components,
    louvain_level,
    pagerank,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _native_on() -> bool:
    from pathway_tpu.engine.native import dataplane

    return dataplane.available()


# ----------------------------------------------------------- fixtures


def _edges_md(update: bool = True) -> str:
    """Two components: a 12-ring (static) and a triangle whose closing
    edge arrives at t=4 (the O(affected) update wave)."""
    lines = ["u | w | __time__ | __diff__"]
    for i in range(12):
        lines.append(f"a{i} | a{(i + 1) % 12} | 2 | 1")
    lines += ["b0 | b1 | 2 | 1", "b1 | b2 | 2 | 1"]
    if update:
        lines.append("b2 | b0 | 4 | 1")
    return "\n".join(lines)


def _edges_table():
    t = pw.debug.table_from_markdown(_edges_md()).with_id_from(
        pw.this.u, pw.this.w
    )
    return t.select(u=t.u, v=t.w)


def _capture_form(table) -> list:
    """Canonical, order-insensitive form of a pipeline's full update
    stream + final state (byte-exact: repr of every value)."""
    session = Session()
    cap = session.capture(table)
    session.execute()
    stream = sorted(
        (t, k.value, repr(row), d) for (t, k, row, d) in cap.stream
    )
    state = sorted((k.value, repr(row)) for k, row in cap.state.rows.items())
    return [stream, state]


def _algo(name: str):
    if name == "pagerank":
        return pagerank(_edges_table(), steps=200)
    if name == "bellman_ford":
        md = """
        vid | is_source | __time__ | __diff__
        s   | True      | 2        | 1
        m   | False     | 2        | 1
        t   | False     | 2        | 1
        u   | False     | 4        | 1
        """
        v = pw.debug.table_from_markdown(md).with_id_from(pw.this.vid)
        emd = """
        a | b | dist | __time__ | __diff__
        s | m | 1.0  | 2        | 1
        m | t | 2.0  | 2        | 1
        s | t | 9.0  | 2        | 1
        m | u | 1.5  | 4        | 1
        """
        e = pw.debug.table_from_markdown(emd)
        e2 = e.select(
            u=e.pointer_from(e.a), v=e.pointer_from(e.b), dist=e.dist
        )
        return bellman_ford(v.select(is_source=v.is_source), e2)
    if name == "connected_components":
        return connected_components(_edges_table())
    if name == "louvain":
        md = """
        u | w | weight | __time__ | __diff__
        a | b | 1.0    | 2        | 1
        b | a | 1.0    | 2        | 1
        b | c | 1.0    | 2        | 1
        c | b | 1.0    | 2        | 1
        a | c | 1.0    | 2        | 1
        c | a | 1.0    | 2        | 1
        c | d | 1.0    | 4        | 1
        d | c | 1.0    | 4        | 1
        d | e | 1.0    | 2        | 1
        e | d | 1.0    | 2        | 1
        e | f | 1.0    | 2        | 1
        f | e | 1.0    | 2        | 1
        d | f | 1.0    | 2        | 1
        f | d | 1.0    | 2        | 1
        """
        E = pw.debug.table_from_markdown(md).with_id_from(
            pw.this.u, pw.this.w
        )
        ids = E.select(x=E.u).concat_reindex(E.select(x=E.w))
        V = ids.groupby(ids.x).reduce(vid=ids.x).with_id_from(ex.this.vid)
        E2 = E.select(
            u=V.pointer_from(E.u), v=V.pointer_from(E.w), weight=E.weight
        )
        return louvain_level(Graph(V, E2), iteration_limit=40)
    raise AssertionError(name)


ALGOS = ["pagerank", "bellman_ford", "connected_components", "louvain"]


# --------------------------------------------- kill-switch equivalence


@pytest.mark.parametrize("algo", ALGOS)
def test_token_vs_object_iterate_byte_identical(algo, monkeypatch):
    """PATHWAY_ITERATE_NATIVE=0 (today's object plumbing) and the token
    plane produce byte-identical streams and final states."""
    monkeypatch.delenv("PATHWAY_ITERATE_NATIVE", raising=False)
    on = _capture_form(_algo(algo))
    monkeypatch.setenv("PATHWAY_ITERATE_NATIVE", "0")
    off = _capture_form(_algo(algo))
    assert on == off


_SUBPROC_SCRIPT = textwrap.dedent(
    """
    import json, sys
    sys.path.insert(0, {repo!r})
    sys.path.insert(0, {repo!r} + "/tests")
    import test_iterate_native as tin
    print("FORM " + json.dumps(tin._capture_form(tin._algo({algo!r}))))
    """
)


def _subprocess_form(algo: str, env_extra: dict) -> list:
    env = {**os.environ, "JAX_PLATFORMS": "cpu", **env_extra}
    r = subprocess.run(
        [sys.executable, "-c", _SUBPROC_SCRIPT.format(repo=REPO, algo=algo)],
        capture_output=True, text=True, env=env, timeout=300, cwd=REPO,
    )
    for line in r.stdout.splitlines():
        if line.startswith("FORM "):
            return json.loads(line[5:])
    raise AssertionError(f"no FORM: {r.stdout[-300:]} {r.stderr[-1500:]}")


@pytest.mark.parametrize("algo", ["pagerank", "connected_components"])
def test_full_object_engine_byte_identical(algo):
    """The whole-engine kill switch (PATHWAY_TPU_NATIVE=0, flippable only
    per process) agrees byte-for-byte with the token engine — integer
    fixpoints make the iterate results summation-order independent."""
    native = _subprocess_form(algo, {})
    obj = _subprocess_form(algo, {"PATHWAY_TPU_NATIVE": "0"})
    assert native == obj


# ------------------------------------------------ acceptance counters


@pytest.mark.skipif(
    not _native_on() or os.environ.get("PATHWAY_ITERATE_NATIVE") == "0",
    reason="token-resident iterate off (plane unavailable or kill switch)",
)
def test_pagerank_scope_zero_roundtrips():
    """The acceptance gate: the pagerank bench shape performs ZERO
    per-round materialize()/intern_row round-trips inside the iterate
    scope — the InternTable counter hooks sampled by the IterateNode
    stay at zero across the cold fixpoint AND the warm update wave."""
    ranks = pagerank(_edges_table(), steps=500)
    session = Session()
    cap = session.capture(ranks)
    session.execute()
    its = [n for n in session.graph.nodes if isinstance(n, IterateNode)]
    assert len(its) == 1
    it = its[0]
    assert it._tok, "iterate scope fell off the token plane"
    assert it.plane_stats["rounds"] > 0
    # the scope never decoded a row to Python objects...
    assert it.plane_stats["scope_materialize_rows"] == 0, it.plane_stats
    # ...and the boundary plumbing never interned or materialized one
    assert it.plane_stats["boundary_intern_rows"] == 0, it.plane_stats
    assert it.plane_stats["boundary_materialize_rows"] == 0, it.plane_stats
    # the capture log carried ONLY native segments (no 4-tuples)
    for name, c in it.captures.items():
        assert getattr(c, "_tok", False), f"capture {name} demoted"
    # sanity: the pipeline actually produced ranks
    assert len(cap.state.rows) == 15


def test_exotic_rows_demote_scope_and_stay_correct():
    """The fallback ladder: a body emitting plane-unrepresentable rows
    (tuple-valued column) demotes the scope mid-run; results match the
    kill-switch run exactly."""

    def build():
        def stepfn(t):
            return {
                "t": t.select(
                    a=pw.if_else(t.a >= 64, t.a, t.a * 2),
                    trail=pw.apply_with_type(
                        lambda tr, a: tuple(list(tr) + [a]) if a < 64 else tr,
                        tuple, pw.this.trail, pw.this.a,
                    ),
                )
            }

        t = pw.debug.table_from_markdown(
            """
            a | __time__ | __diff__
            3 | 2        | 1
            5 | 4        | 1
            """
        ).with_id_from(pw.this.a)
        t2 = t.select(a=t.a, trail=pw.apply_with_type(lambda: (), tuple))
        return pw.iterate(stepfn, t=t2)

    on = _capture_form(build())
    os.environ["PATHWAY_ITERATE_NATIVE"] = "0"
    try:
        off = _capture_form(build())
    finally:
        del os.environ["PATHWAY_ITERATE_NATIVE"]
    assert on == off


# ------------------------------------------------------- persistence


@pytest.mark.parametrize("iterate_native", ["1", "0"])
def test_iterate_persistence_roundtrip(tmp_path, monkeypatch, iterate_native):
    """Iterate scope snapshots (fed mirrors, capture logs, body-node
    states) round-trip through a checkpoint on BOTH plumbing planes —
    token-mode state always exports the portable OBJECT form. (A
    checkpoint is pinned to its plane by the persist signature, same as
    the join/groupby native-kernel policy.)"""
    from pathway_tpu.persistence import Backend, CheckpointManager, Config

    monkeypatch.setenv("PATHWAY_ITERATE_NATIVE", iterate_native)

    def build():
        return pagerank(_edges_table(), steps=200)

    cfg = Config(Backend.filesystem(str(tmp_path)))
    s1 = Session()
    cap1 = s1.capture(build())
    s1.execute()
    m1 = CheckpointManager(s1, cfg)
    m1.checkpoint(finalized_time=100)

    s2 = Session()
    cap2 = s2.capture(build())
    m2 = CheckpointManager(s2, cfg)
    assert m2.signature == m1.signature
    m2.restore()
    assert m2.restored
    got = {k.value: repr(r) for k, r in cap2.state.rows.items()}
    want = {k.value: repr(r) for k, r in cap1.state.rows.items()}
    assert got == want


# ------------------------------------------------------- 2-proc mesh


_MESH_SCRIPT = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, {repo!r})
    sys.path.insert(0, {repo!r} + "/tests")
    import test_iterate_native as tin
    import pathway_tpu as pw
    from pathway_tpu.internals.lowering import Session

    table = tin._algo("pagerank")
    session = Session()
    cap = session.capture(table)
    session.execute()
    # downstream exchanges shard the final select's rows across the
    # processes: every process writes ITS capture shard; the test
    # compares the union against the single-process state
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    state = sorted(
        (k.value, repr(row)) for k, row in cap.state.rows.items()
    )
    with open(sys.argv[1] + "." + str(pid), "w") as f:
        json.dump(state, f)
    """
)


@pytest.mark.slow
def test_pagerank_mesh_two_process_invariance(tmp_path):
    """PATHWAY_PROCESSES=2: the iterate scope runs whole on process 0
    behind exchange wires (protocol-5 zero-copy frames); the final state
    is byte-identical to the single-process run."""
    import socket

    socks, ports = [], []
    for _ in range(6):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    base = max(ports) + 1

    single = _subprocess_form("pagerank", {})[1]
    out = str(tmp_path / "mesh_state.json")
    procs = []
    for pid in range(2):
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PATHWAY_PROCESSES": "2",
            "PATHWAY_PROCESS_ID": str(pid),
            "PATHWAY_FIRST_PORT": str(base),
        }
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-c",
                    _MESH_SCRIPT.format(repo=REPO), out,
                ],
                env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
        )
    for p in procs:
        try:
            p.wait(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
    for p in procs:
        assert p.returncode == 0, (p.stdout.read(), p.stderr.read())
    mesh_state: set = set()
    for pid in range(2):
        with open(f"{out}.{pid}") as f:
            mesh_state |= {tuple(x) for x in json.load(f)}
    assert sorted(mesh_state) == [tuple(x) for x in single]


# ------------------------------------------------- wire form (proto 5)


def test_native_wire_protocol5_and_legacy_roundtrip():
    """NativeBatch wire tuples survive pickle protocol 5 with
    out-of-band buffers AND the legacy all-bytes form (supervisor
    restart compatibility)."""
    import pickle

    import numpy as np

    from pathway_tpu.engine.native import dataplane as dp

    if not dp.available():
        pytest.skip("native plane unavailable")
    tab = dp.default_table()
    toks = [tab.intern_row((i, f"s{i}")) for i in range(8)]
    nb = dp.NativeBatch(
        tab,
        np.arange(8, dtype=np.uint64),
        np.zeros(8, np.uint64),
        np.asarray(toks, np.uint64),
        np.ones(8, np.int64),
    )
    wire = nb.to_wire()
    # protocol-5 out-of-band round trip (the mesh frame path)
    bufs: list = []
    body = pickle.dumps(wire, protocol=5, buffer_callback=bufs.append)
    assert bufs, "flat columns must ship out-of-band"
    wire2 = pickle.loads(body, buffers=[b.raw() for b in bufs])
    back = dp.NativeBatch.from_wire(wire2)
    assert back.materialize() == nb.materialize()
    # legacy frame: every field as bytes (pre-protocol-5 wire form)
    legacy = tuple(
        w.tobytes() if isinstance(w, np.ndarray) else bytes(w) for w in wire
    )
    back2 = dp.NativeBatch.from_wire(legacy)
    assert back2.materialize() == nb.materialize()
