"""Reducer matrix vs Python models: every reducer over static groups AND
update streams with retractions (the delta path must invert/rebuild
state exactly), plus stateful custom reducers (reference tier-2:
tests/test_reducers.py)."""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def _fresh_graph():
    G.clear()
    yield
    G.clear()


ROWS = [
    ("x", 3), ("x", 1), ("x", 4), ("y", 1), ("y", 5), ("z", 9),
    ("z", 2), ("z", 6), ("z", 5),
]


def _grouped(reducer_fn):
    t = pw.debug.table_from_rows(
        pw.schema_from_types(g=str, v=int), ROWS
    )
    res = t.groupby(t.g).reduce(g=t.g, out=reducer_fn(t))
    _ids, cols = pw.debug.table_to_dicts(res)
    return {cols["g"][k]: cols["out"][k] for k in cols["g"]}


def _groups():
    gs: dict = {}
    for g, v in ROWS:
        gs.setdefault(g, []).append(v)
    return gs


REDUCER_CASES = [
    ("count", lambda t: pw.reducers.count(), lambda vs: len(vs)),
    ("sum", lambda t: pw.reducers.sum(t.v), lambda vs: sum(vs)),
    ("min", lambda t: pw.reducers.min(t.v), lambda vs: min(vs)),
    ("max", lambda t: pw.reducers.max(t.v), lambda vs: max(vs)),
    ("avg", lambda t: pw.reducers.avg(t.v), lambda vs: sum(vs) / len(vs)),
    (
        "sorted_tuple",
        lambda t: pw.reducers.sorted_tuple(t.v),
        lambda vs: tuple(sorted(vs)),
    ),
    ("any", lambda t: pw.reducers.any(t.v), lambda vs: ("ANY", vs)),
]


@pytest.mark.parametrize(
    "name,red_fn,model", REDUCER_CASES, ids=[c[0] for c in REDUCER_CASES]
)
def test_reducers_static_groups(name, red_fn, model):
    got = _grouped(red_fn)
    for g, vs in _groups().items():
        want = model(vs)
        if isinstance(want, tuple) and want and want[0] == "ANY":
            assert got[g] in want[1], (name, g)
        else:
            assert got[g] == want, (name, g)


def test_int_sum_overflowless():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(g=str, v=int),
        [("a", 2**61), ("a", 2**61), ("a", 2**61)],
    )
    res = t.groupby(t.g).reduce(g=t.g, s=pw.reducers.int_sum(t.v))
    _ids, cols = pw.debug.table_to_dicts(res)
    assert list(cols["s"].values()) == [3 * 2**61]


def test_ndarray_reducer():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(g=str, v=int), [("a", 3), ("a", 1), ("b", 7)]
    )
    res = t.groupby(t.g).reduce(g=t.g, arr=pw.reducers.ndarray(t.v))
    _ids, cols = pw.debug.table_to_dicts(res)
    got = {cols["g"][k]: cols["arr"][k] for k in cols["g"]}
    assert sorted(np.asarray(got["a"]).tolist()) == [1, 3]
    assert np.asarray(got["b"]).tolist() == [7]


def test_unique_reducer_errors_on_conflict():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(g=str, v=int), [("a", 1), ("a", 1), ("b", 2)]
    )
    res = t.groupby(t.g).reduce(g=t.g, u=pw.reducers.unique(t.v))
    _ids, cols = pw.debug.table_to_dicts(res)
    got = {cols["g"][k]: cols["u"][k] for k in cols["g"]}
    assert got == {"a": 1, "b": 2}
    G.clear()
    t2 = pw.debug.table_from_rows(
        pw.schema_from_types(g=str, v=int), [("a", 1), ("a", 2)]
    )
    res2 = t2.groupby(t2.g).reduce(
        g=t2.g, u=pw.fill_error(pw.reducers.unique(t2.v), -1)
    )
    _ids2, cols2 = pw.debug.table_to_dicts(res2)
    assert list(cols2["u"].values()) == [-1]


STREAM = """
    g | v | __time__ | __diff__
    a | 3 | 2        | 1
    a | 9 | 2        | 1
    b | 4 | 2        | 1
    a | 9 | 4        | -1
    a | 7 | 4        | 1
    b | 4 | 6        | -1
    b | 1 | 6        | 1
    a | 2 | 8        | 1
"""

FINAL = {"a": [3, 7, 2], "b": [1]}


@pytest.mark.parametrize(
    "name,red_fn,model",
    [
        ("count", lambda t: pw.reducers.count(), len),
        ("sum", lambda t: pw.reducers.sum(t.v), sum),
        ("min", lambda t: pw.reducers.min(t.v), min),
        ("max", lambda t: pw.reducers.max(t.v), max),
        ("avg", lambda t: pw.reducers.avg(t.v), lambda vs: sum(vs) / len(vs)),
        (
            "sorted_tuple",
            lambda t: pw.reducers.sorted_tuple(t.v),
            lambda vs: tuple(sorted(vs)),
        ),
    ],
    ids=["count", "sum", "min", "max", "avg", "sorted_tuple"],
)
def test_reducers_update_stream(name, red_fn, model):
    """Retractions must invert reducer state exactly — min/max rebuild
    from the surviving multiset, sums subtract, tuples drop elements."""
    t = pw.debug.table_from_markdown(STREAM)
    res = t.groupby(t.g).reduce(g=t.g, out=red_fn(t))
    _ids, cols = pw.debug.table_to_dicts(res)
    got = {cols["g"][k]: cols["out"][k] for k in cols["g"]}
    for g, vs in FINAL.items():
        assert got[g] == model(vs), (name, g)


def test_earliest_latest_follow_processing_time():
    t = pw.debug.table_from_markdown(
        """
        g | v | __time__
        a | 1 | 2
        a | 2 | 4
        a | 3 | 6
        b | 9 | 4
        """
    )
    res = t.groupby(t.g).reduce(
        g=t.g,
        first=pw.reducers.earliest(t.v),
        last=pw.reducers.latest(t.v),
    )
    _ids, cols = pw.debug.table_to_dicts(res)
    got = {
        cols["g"][k]: (cols["first"][k], cols["last"][k]) for k in cols["g"]
    }
    assert got == {"a": (1, 3), "b": (9, 9)}


def test_stateful_single_custom_reducer():
    @pw.reducers.stateful_single
    def harmonic_mean_inv(state, value):
        # accumulate (count, sum of reciprocals)
        n, s = state if state is not None else (0, 0.0)
        return (n + 1, s + 1.0 / value)

    t = pw.debug.table_from_rows(
        pw.schema_from_types(g=str, v=int), [("a", 2), ("a", 4), ("b", 5)]
    )
    res = t.groupby(t.g).reduce(g=t.g, st=harmonic_mean_inv(t.v))
    _ids, cols = pw.debug.table_to_dicts(res)
    got = {cols["g"][k]: cols["st"][k] for k in cols["g"]}
    assert got["a"] == (2, pytest.approx(0.75))
    assert got["b"] == (1, pytest.approx(0.2))


def test_stateful_many_custom_reducer():
    @pw.reducers.stateful_many
    def span(state, rows):
        lo, hi = state if state is not None else (None, None)
        for row, cnt in rows:
            v = row[0]
            if cnt > 0:
                lo = v if lo is None or v < lo else lo
                hi = v if hi is None or v > hi else hi
        return (lo, hi)

    t = pw.debug.table_from_rows(
        pw.schema_from_types(g=str, v=int), [("a", 4), ("a", 9), ("a", 2)]
    )
    res = t.groupby(t.g).reduce(g=t.g, st=span(t.v))
    _ids, cols = pw.debug.table_to_dicts(res)
    assert list(cols["st"].values()) == [(2, 9)]


def test_multi_column_group_keys():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(a=str, b=int, v=int),
        [("x", 1, 10), ("x", 1, 20), ("x", 2, 5), ("y", 1, 7)],
    )
    res = t.groupby(t.a, t.b).reduce(
        a=t.a, b=t.b, s=pw.reducers.sum(t.v)
    )
    _ids, cols = pw.debug.table_to_dicts(res)
    got = {
        (cols["a"][k], cols["b"][k]): cols["s"][k] for k in cols["a"]
    }
    assert got == {("x", 1): 30, ("x", 2): 5, ("y", 1): 7}


def test_global_reduce_no_groupby():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(v=float), [(1.5,), (2.5,), (-1.0,)]
    )
    res = t.reduce(
        n=pw.reducers.count(),
        s=pw.reducers.sum(t.v),
        lo=pw.reducers.min(t.v),
        hi=pw.reducers.max(t.v),
    )
    _ids, cols = pw.debug.table_to_dicts(res)
    row = {n: next(iter(col.values())) for n, col in cols.items()}
    assert row == {"n": 3, "s": 3.0, "lo": -1.0, "hi": 2.5}


def test_group_disappears_when_all_rows_retracted():
    t = pw.debug.table_from_markdown(
        """
        g | v | __time__ | __diff__
        a | 1 | 2        | 1
        b | 2 | 2        | 1
        a | 1 | 4        | -1
        """
    )
    res = t.groupby(t.g).reduce(g=t.g, n=pw.reducers.count())
    _ids, cols = pw.debug.table_to_dicts(res)
    assert {v for v in cols["g"].values()} == {"b"}
