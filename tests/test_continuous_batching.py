"""Continuous batching for LLM decode (serving/continuous_batching.py).

Pins the acceptance contract of the slot scheduler:

  * a request admitted MID-GENERATION joins the in-flight decode batch at
    a step boundary — the device-plane compile ledger shows zero new XLA
    compilations for the join, and the slot counters (pool + metrics
    registry) prove the freed-slot re-fill happened;
  * `PATHWAY_CONTINUOUS_BATCH=0` (and `continuous_batching=False`) fall
    back to wave-aligned dispatch BYTE-identically — the slot path's
    per-row math is the same as the scanned `generate_serving` path;
  * slot-pool bookkeeping: acquire/release, refill + joined-in-flight
    counters, exhaustion, namespace cleanup.
"""

from __future__ import annotations

import time as _time

import pytest

from pathway_tpu.engine.device_plane import DevicePlane, SlotPool
from pathway_tpu.internals import observability as obs
from pathway_tpu.models import lm_config


@pytest.fixture(autouse=True)
def _plane_off():
    yield
    obs.disable()


TINY = dict(
    vocab_size=256, d_model=16, n_heads=2, n_layers=1, d_ff=32, max_len=64
)


def _chat(**kw):
    from pathway_tpu.xpacks.llm.llms import JaxLMChat

    kw.setdefault("config", lm_config(**TINY))
    kw.setdefault("max_new_tokens", 4)
    return JaxLMChat(**kw)


# ------------------------------------------------------------ slot pool


def test_slot_pool_acquire_release_and_counters():
    pool = SlotPool("t", 2)
    a = pool.acquire()
    b = pool.acquire()
    assert {a, b} == {0, 1}
    assert pool.acquire() is None  # exhausted: request stays queued
    assert pool.joined_inflight == 1  # b acquired while a was in flight
    assert pool.refills == 0
    pool.release(a)
    c = pool.acquire()
    assert c == a
    assert pool.refills == 1  # a freed row re-filled
    assert pool.joined_inflight == 2
    assert pool.high_water == 2
    with pytest.raises(ValueError):
        pool.release(b)
        pool.release(b)  # double release fails loudly


def test_plane_slot_pool_registry_and_namespace_drop():
    plane = DevicePlane()
    pool = plane.slot_pool("cb#1/slots", 4)
    assert plane.slot_pool("cb#1/slots", 4) is pool
    with pytest.raises(ValueError):
        plane.slot_pool("cb#1/slots", 8)  # size conflict fails loudly
    plane.program("cb#1/prefill", lambda x: x)
    plane.program("cb#10/prefill", lambda x: x)  # prefix sibling
    plane.restore(("cb_kv_cache", "cb#1", 4), {"k": 0})
    plane.drop_namespace("cb#1")
    assert "cb#1/prefill" not in plane.programs
    assert "cb#10/prefill" in plane.programs  # delimiter-aware match
    assert "cb#1/slots" not in plane._slot_pools
    assert not any(
        isinstance(k, tuple) and "cb#1" in k for k in plane._leases
    )


# ---------------------------------------------------- kill-switch equality


def test_continuous_batching_matches_wave_aligned_byte_identically():
    """The central equivalence: the slot scheduler's output equals the
    wave-aligned generate dispatch byte for byte, per request."""
    cb = _chat(continuous_batching=True, decode_slots=4)
    wa = _chat(continuous_batching=False)
    prompts = ["a b c", "d", "hello world longer prompt", "x y", "q", "z z z"]
    futs = [cb._cb.submit(p) for p in prompts]
    got_cb = [f.result(timeout=60) for f in futs]
    got_wa = wa._generate_batch(prompts)
    assert got_cb == got_wa
    cb._cb.drain()


def test_kill_switch_env_restores_wave_aligned_path(monkeypatch):
    monkeypatch.setenv("PATHWAY_CONTINUOUS_BATCH", "0")
    chat = _chat()
    assert chat._cb is None  # wave-aligned coalescer only
    monkeypatch.setenv("PATHWAY_CONTINUOUS_BATCH", "1")
    chat_on = _chat()
    assert chat_on._cb is not None


def test_sampled_generation_keeps_wave_aligned_path():
    chat = _chat(temperature=0.7)
    assert chat._cb is None  # per-request rng in a shared step: future work


# ------------------------------------------- mid-generation join acceptance


def test_mid_generation_join_refills_slot_without_new_compile():
    """A request admitted while another is mid-generation joins the
    in-flight decode batch: the compile ledger gains NOTHING (the step
    program and the prompt bucket are warm) and the slot counters — on
    the pool and in the metrics registry — record the join/re-fill."""
    obs.enable()
    chat = _chat(max_new_tokens=24, continuous_batching=True, decode_slots=2)
    cb = chat._cb
    assert cb is not None
    # warm both programs and the prompt bucket with one full generation
    cb.submit("warm up prompt").result(timeout=60)
    cb.drain()
    warmed = (dict(cb._step.compile_counts), dict(cb._prefill.compile_counts))
    pool_before = cb.pool.snapshot()

    first = cb.submit("first long running request")
    # wait until the first request is provably mid-generation
    deadline = _time.monotonic() + 30
    while cb.stats["decode_steps"] < 3 and _time.monotonic() < deadline:
        _time.sleep(0.005)
    assert cb.stats["decode_steps"] >= 3, "first request never started decoding"
    second = cb.submit("second joins the flight")
    r1 = first.result(timeout=60)
    r2 = second.result(timeout=60)
    cb.drain()
    # outputs still equal the wave-aligned path (no cross-slot bleed)
    wa = _chat(continuous_batching=False, max_new_tokens=24)
    assert [r1, r2] == wa._generate_batch(
        ["first long running request", "second joins the flight"]
    )
    # zero new compiles for the join
    after = (dict(cb._step.compile_counts), dict(cb._prefill.compile_counts))
    assert after == warmed, f"join recompiled: {warmed} -> {after}"
    # slot counters prove the join: pool-side and registry-side
    pool_after = cb.pool.snapshot()
    assert pool_after["joined_inflight"] > pool_before["joined_inflight"]
    assert pool_after["refills"] > pool_before["refills"]
    plane = obs.PLANE
    assert plane is not None
    assert plane.metrics.counter_value(
        "pathway_serving_joined_inflight_total", {"pool": cb.pool.name}
    ) >= 1
    assert plane.metrics.counter_value(
        "pathway_serving_slot_refills_total", {"pool": cb.pool.name}
    ) >= 1
    assert plane.metrics.counter_value(
        "pathway_serving_decode_steps_total", {"pool": cb.pool.name}
    ) >= 23


def test_queue_overflow_waits_for_free_slot():
    """More requests than slots: the excess queues and lands in freed
    slots (refills), every result still byte-equal to wave-aligned."""
    chat = _chat(continuous_batching=True, decode_slots=2)
    cb = chat._cb
    prompts = [f"prompt number {i}" for i in range(7)]
    futs = [cb.submit(p) for p in prompts]
    got = [f.result(timeout=120) for f in futs]
    cb.drain()
    wa = _chat(continuous_batching=False)
    assert got == wa._generate_batch(prompts)
    snap = cb.pool.snapshot()
    assert snap["refills"] >= 5  # 7 requests over 2 slots
    assert snap["active"] == 0  # fully drained


def test_chat_finalizer_releases_cb_namespace():
    chat = _chat(continuous_batching=True, decode_slots=2)
    cb = chat._cb
    cb.submit("a b").result(timeout=60)
    cb.drain()
    plane = chat._plane
    name = cb.name
    assert f"{name}/prefill" in plane.programs
    assert f"{name}/step" in plane.programs
    assert f"{name}/slots" in plane._slot_pools
    assert any(isinstance(k, tuple) and name in k for k in plane._leases)
    chat._finalizer()  # what gc runs when the instance dies
    assert f"{name}/prefill" not in plane.programs
    assert f"{name}/step" not in plane.programs
    assert f"{name}/slots" not in plane._slot_pools
    assert not any(isinstance(k, tuple) and name in k for k in plane._leases)


def test_cb_chat_through_a_pipeline():
    """JaxLMChat rides the UDF machinery with continuous batching on:
    a table of questions answers identically to the wave-aligned run."""
    import pathway_tpu as pw
    from pathway_tpu.xpacks.llm.llms import prompt_chat_single_qa

    def run_once(cb_on: bool) -> dict:
        chat = _chat(continuous_batching=cb_on, decode_slots=2)
        t = pw.debug.table_from_rows(
            pw.schema_from_types(q=str),
            [("what is a", ), ("what is b", ), ("what is c", )],
        )
        r = t.select(
            q=pw.this.q, a=chat(pw.apply(prompt_chat_single_qa, pw.this.q))
        )
        rows = {}
        pw.io.subscribe(
            r,
            on_change=lambda key, row, time, is_addition: rows.__setitem__(
                row["q"], row["a"]
            ),
        )
        pw.run()
        pw.internals.parse_graph.G.clear()
        return rows

    assert run_once(True) == run_once(False)
