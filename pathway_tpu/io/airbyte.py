"""pw.io.airbyte — API-parity connector (reference: io/airbyte).

Client library gated: see io/_external.py.
"""

from pathway_tpu.io._external import gated_reader, gated_writer

read = gated_reader("airbyte", "requests")
write = gated_writer("airbyte", "requests")
