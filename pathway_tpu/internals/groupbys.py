"""GroupedTable.reduce (reference: internals/groupbys.py:1)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals import universe as univ
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    IdReference,
    ReducerExpression,
    ThisMarker,
    ThisSplat,
    wrap_arg,
)
from pathway_tpu.internals.expression_compiler import collect_reducers
from pathway_tpu.internals.table import OpSpec, Table
from pathway_tpu.internals.type_interpreter import infer_dtype


class GroupedTable:
    def __init__(
        self,
        table: Table,
        gb_exprs: list[ColumnExpression],
        instance: Any = None,
        sort_by: Any = None,
    ):
        self._table = table
        self._gb_exprs = gb_exprs
        self._instance = wrap_arg(instance) if instance is not None else None
        self._sort_by = sort_by

    def reduce(self, *args: Any, **kwargs: Any) -> Table:
        table = self._table
        exprs: dict[str, ColumnExpression] = {}
        for arg in args:
            if isinstance(arg, ThisSplat):
                # *pw.this in reduce => all groupby columns
                for e in self._gb_exprs:
                    if isinstance(e, ColumnReference):
                        exprs[e.name] = e
            elif isinstance(arg, ColumnReference):
                if isinstance(arg.table, ThisMarker):
                    arg = ColumnReference(table, arg.name)
                exprs[arg.name] = arg
            else:
                raise TypeError(f"positional reduce() args must be references: {arg!r}")
        for name, e in kwargs.items():
            exprs[name] = wrap_arg(e)

        # normalize groupby exprs (pw.this -> table)
        gb_exprs: list[ColumnExpression] = []
        for e in self._gb_exprs:
            if isinstance(e, ColumnReference) and isinstance(e.table, ThisMarker):
                e = ColumnReference(table, e.name)
            gb_exprs.append(e)
        if self._instance is not None:
            inst = self._instance
            if isinstance(inst, ColumnReference) and isinstance(inst.table, ThisMarker):
                inst = ColumnReference(table, inst.name)
            if not any(_expr_matches(inst, g) for g in gb_exprs):
                gb_exprs.append(inst)

        reducer_exprs = collect_reducers(list(exprs.values()))

        def ref_dtype(ref: ColumnReference) -> dt.DType:
            tab = ref.table
            if isinstance(tab, ThisMarker):
                tab = table
            if isinstance(ref, IdReference) or ref.name == "id":
                return dt.ANY_POINTER
            if isinstance(tab, Table):
                return tab._dtype_of(ref.name)
            raise KeyError(ref.name)

        columns = {
            name: sch.ColumnSchema(name=name, dtype=infer_dtype(e, ref_dtype))
            for name, e in exprs.items()
        }
        schema = sch.schema_from_columns(columns)
        spec = OpSpec(
            "groupby",
            [table],
            gb_exprs=gb_exprs,
            out_exprs=exprs,
            reducer_exprs=reducer_exprs,
            sort_by=self._sort_by,
        )
        return Table(spec, schema, univ.Universe())

    def windowby_param(self) -> Any:
        return None


def _expr_matches(a: ColumnExpression, b: ColumnExpression) -> bool:
    if isinstance(a, ColumnReference) and isinstance(b, ColumnReference):
        return a.table is b.table and a.name == b.name
    return a is b
