"""Temporal matrix: window kinds x reducers, behaviors x windows
(streamed via __time__ scripts), and the interval/window/asof/asof_now
join mode matrix. Reference test model:
python/pathway/tests/temporal/ (test_windows.py, test_interval_join.py,
test_window_join.py, test_asof_join.py, test_asof_now_join.py,
test_behaviors.py)."""

import sys
from pathlib import Path

import pytest

import pathway_tpu as pw
from pathway_tpu.stdlib import temporal

sys.path.insert(0, str(Path(__file__).parent))
from utils import T, run_capture, stream_of  # noqa: E402


def _vals(table, *cols):
    cap = run_capture(table)
    rows = [tuple(r[i] for i in range(len(cols))) for r in cap.state.rows.values()]
    # None sorts last within its column (outer-join pads mix None & str)
    return sorted(rows, key=lambda r: tuple((v is None, v) for v in r))


EVENTS = """
    k | t  | v
    a | 1  | 1
    b | 5  | 2
    c | 12 | 3
    d | 15 | 4
    e | 21 | 5
    """


# ------------------------------------------------------------- windows


def test_tumbling_window_counts_and_bounds():
    t = T(EVENTS)
    res = t.windowby(t.t, window=temporal.tumbling(duration=10)).reduce(
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        n=pw.reducers.count(),
        s=pw.reducers.sum(pw.this.v),
    )
    assert _vals(res, "start", "end", "n", "s") == [
        (0, 10, 2, 3),
        (10, 20, 2, 7),
        (20, 30, 1, 5),
    ]


def test_tumbling_window_origin_offset():
    t = T(EVENTS)
    res = t.windowby(
        t.t, window=temporal.tumbling(duration=10, origin=5)
    ).reduce(start=pw.this._pw_window_start, n=pw.reducers.count())
    # windows [5,15): {5,12}, [15,25): {15,21}, [-5,5): {1}
    assert _vals(res, "start", "n") == [(-5, 1), (5, 2), (15, 2)]


def test_sliding_window_multi_membership():
    t = T(
        """
        k | t
        a | 12
        """
    )
    res = t.windowby(
        t.t, window=temporal.sliding(hop=5, duration=10)
    ).reduce(start=pw.this._pw_window_start, n=pw.reducers.count())
    # t=12 belongs to [5,15) and [10,20)
    assert _vals(res, "start", "n") == [(5, 1), (10, 1)]


def test_session_window_max_gap():
    t = T(
        """
        k | t
        a | 1
        b | 3
        c | 10
        d | 12
        e | 30
        """
    )
    res = t.windowby(
        t.t, window=temporal.session(max_gap=5)
    ).reduce(
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        n=pw.reducers.count(),
    )
    assert _vals(res, "start", "end", "n") == [(1, 3, 2), (10, 12, 2), (30, 30, 1)]


def test_session_window_predicate():
    t = T(
        """
        k | t
        a | 1
        b | 2
        c | 40
        """
    )
    res = t.windowby(
        t.t,
        window=temporal.session(predicate=lambda a, b: (b - a) <= 10),
    ).reduce(start=pw.this._pw_window_start, n=pw.reducers.count())
    assert _vals(res, "start", "n") == [(1, 2), (40, 1)]


def test_windowby_instance_isolates_keys():
    t = T(
        """
        k | t | grp
        a | 1 | x
        b | 2 | x
        c | 3 | y
        """
    )
    res = t.windowby(
        t.t, window=temporal.tumbling(duration=10), instance=t.grp
    ).reduce(
        grp=pw.this._pw_instance,
        n=pw.reducers.count(),
    )
    assert _vals(res, "grp", "n") == [("x", 2), ("y", 1)]


def test_intervals_over():
    t = T(EVENTS)
    probes = T(
        """
        p
        10
        20
        """
    )
    res = t.windowby(
        t.t,
        window=temporal.intervals_over(
            at=probes.p, lower_bound=-10, upper_bound=0, is_outer=False
        ),
    ).reduce(
        at=pw.this._pw_window_start + 10,
        n=pw.reducers.count(),
    )
    # at=10 covers t in [0,10]: {1,5}; at=20 covers [10,20]: {12,15}
    assert _vals(res, "at", "n") == [(10, 2), (20, 2)]


# -------------------------------------------------- behaviors x windows


def test_common_behavior_delay_buffers_emission():
    """delay=4: the [0,10) window must not emit before engine time
    start+delay — early wave outputs would flap on every row."""
    t = T(
        """
        k | t | __time__
        a | 1 | 2
        b | 2 | 4
        c | 6 | 10
        """
    )
    res = t.windowby(
        t.t,
        window=temporal.tumbling(duration=10),
        behavior=temporal.common_behavior(delay=4),
    ).reduce(n=pw.reducers.count())
    events = stream_of(res)
    assert [(row, d) for (_t, _k, row, d) in events] == [((3,), 1)]


def test_common_behavior_cutoff_freezes_results():
    """cutoff: a row arriving after window end + cutoff is IGNORED but
    the window's result is kept (keep_results=True default)."""
    t = T(
        """
        k | t  | __time__
        a | 1  | 2
        b | 2  | 2
        c | 50 | 4
        d | 3  | 6
        """
    )
    # by engine time 4, the watermark (max t seen = 50) is far past the
    # [0,10) window end + cutoff=5 -> the late t=3 row at engine time 6
    # must not change the frozen count of 2
    res = t.windowby(
        t.t,
        window=temporal.tumbling(duration=10),
        behavior=temporal.common_behavior(cutoff=5),
    ).reduce(start=pw.this._pw_window_start, n=pw.reducers.count())
    assert (0, 2) in _vals(res, "start", "n")


def test_common_behavior_cutoff_drops_results():
    """keep_results=False additionally removes the window output once the
    cutoff passes."""
    t = T(
        """
        k | t  | __time__
        a | 1  | 2
        b | 50 | 4
        """
    )
    res = t.windowby(
        t.t,
        window=temporal.tumbling(duration=10),
        behavior=temporal.common_behavior(cutoff=5, keep_results=False),
    ).reduce(start=pw.this._pw_window_start, n=pw.reducers.count())
    finals = _vals(res, "start", "n")
    assert (0, 1) not in finals  # the [0,10) window was dropped
    assert (50, 1) in finals


def test_exactly_once_behavior_single_emission():
    """Each window emits exactly once (no retract/re-emit chatter), when
    the watermark passes its end."""
    t = T(
        """
        k | t  | __time__
        a | 1  | 2
        b | 2  | 4
        c | 11 | 6
        d | 25 | 8
        """
    )
    res = t.windowby(
        t.t,
        window=temporal.tumbling(duration=10),
        behavior=temporal.exactly_once_behavior(),
    ).reduce(start=pw.this._pw_window_start, n=pw.reducers.count())
    events = stream_of(res)
    insertions = [(row, d) for (_t, _k, row, d) in events if d > 0]
    retractions = [e for e in events if e[3] < 0]
    assert retractions == [], f"exactly-once must never retract: {events}"
    # [0,10) emitted once with BOTH rows; [10,20) emitted once after t=25
    assert ((0, 2), 1) in insertions
    assert ((10, 1), 1) in insertions


# ------------------------------------------------------- interval joins


LEFT = """
    lk | lt | lval
    a  | 2  | 10
    b  | 6  | 20
    c  | 30 | 30
    """
RIGHT = """
    rk | rt | rval
    x  | 1  | 100
    y  | 5  | 200
    z  | 50 | 300
    """


def _ij(how):
    lt, rt = T(LEFT), T(RIGHT)
    res = temporal.interval_join(
        lt, rt, lt.lt, rt.rt, temporal.interval(-2, 1), how=how
    ).select(lt.lk, rt.rk)
    return _vals(res, "lk", "rk")


def test_interval_join_inner():
    # pairs with rt - lt in [-2, 1]: (a,x): -1 ok; (a,y): 3 no;
    # (b,y): -1 ok; (b,x): -5 no; c matches nothing
    assert _ij("inner") == [("a", "x"), ("b", "y")]


def test_interval_join_left():
    assert _ij("left") == [("a", "x"), ("b", "y"), ("c", None)]


def test_interval_join_right():
    assert _ij("right") == [("a", "x"), ("b", "y"), (None, "z")]


def test_interval_join_outer():
    assert _ij("outer") == [("a", "x"), ("b", "y"), ("c", None), (None, "z")]


def test_interval_join_bounds_inclusive():
    lt = T("""
        lk | lt
        a  | 10
        """)
    rt = T("""
        rk | rt
        p  | 8
        q  | 12
        r  | 7
        s  | 13
        """)
    res = temporal.interval_join(
        lt, rt, lt.lt, rt.rt, temporal.interval(-2, 2)
    ).select(lt.lk, rt.rk)
    assert _vals(res, "lk", "rk") == [("a", "p"), ("a", "q")]


def test_interval_join_with_on_equality():
    lt = T("""
        lk | lt | sym
        a  | 2  | AA
        b  | 2  | BB
        """)
    rt = T("""
        rk | rt | sym
        x  | 2  | AA
        y  | 2  | CC
        """)
    res = temporal.interval_join(
        lt, rt, lt.lt, rt.rt, temporal.interval(-1, 1), lt.sym == rt.sym
    ).select(lt.lk, rt.rk)
    assert _vals(res, "lk", "rk") == [("a", "x")]


# --------------------------------------------------------- window joins


def _wj(how):
    lt, rt = T(LEFT), T(RIGHT)
    res = temporal.window_join(
        lt, rt, lt.lt, rt.rt, temporal.tumbling(duration=10), how=how
    ).select(lt.lk, rt.rk)
    return _vals(res, "lk", "rk")


def test_window_join_inner():
    # windows: [0,10): l{a,b} r{x,y}; [30,40): l{c}; [50,60): r{z}
    assert _wj("inner") == [("a", "x"), ("a", "y"), ("b", "x"), ("b", "y")]


def test_window_join_left():
    assert _wj("left") == [
        ("a", "x"), ("a", "y"), ("b", "x"), ("b", "y"), ("c", None)
    ]


def test_window_join_right():
    assert _wj("right") == [
        ("a", "x"), ("a", "y"), ("b", "x"), ("b", "y"), (None, "z")
    ]


def test_window_join_outer():
    assert _wj("outer") == [
        ("a", "x"), ("a", "y"), ("b", "x"), ("b", "y"), ("c", None), (None, "z")
    ]


# ----------------------------------------------------------- asof joins


TRADES = """
    tk | tt
    a  | 3
    b  | 7
    c  | 100
    """
QUOTES = """
    qk | qt | px
    p  | 1  | 10
    q  | 5  | 20
    r  | 90 | 30
    """


def _asof(direction):
    lt, rt = T(TRADES), T(QUOTES)
    res = temporal.asof_join(
        lt, rt, lt.tt, rt.qt, direction=direction
    ).select(lt.tk, rt.px)
    return _vals(res, "tk", "px")


def test_asof_join_backward():
    assert _asof(temporal.Direction.BACKWARD) == [
        ("a", 10), ("b", 20), ("c", 30)
    ]


def test_asof_join_forward():
    assert _asof(temporal.Direction.FORWARD) == [
        ("a", 20), ("b", 30), ("c", None)
    ]


def test_asof_join_nearest():
    # a(3): dist 2 to qt=1, 2 to qt=5 -> implementation tie-break; use
    # unambiguous probes instead
    lt = T("""
        tk | tt
        a  | 2
        b  | 80
        """)
    rt = T(QUOTES)
    res = temporal.asof_join(
        lt, rt, lt.tt, rt.qt, direction=temporal.Direction.NEAREST
    ).select(lt.tk, rt.px)
    assert _vals(res, "tk", "px") == [("a", 10), ("b", 30)]


def test_asof_join_with_on_partitions():
    lt = T("""
        tk | tt | sym
        a  | 4  | AA
        b  | 4  | BB
        """)
    rt = T("""
        qk | qt | sym | px
        p  | 1  | AA  | 10
        q  | 2  | BB  | 20
        r  | 3  | BB  | 30
        """)
    res = temporal.asof_join(
        lt, rt, lt.tt, rt.qt, lt.sym == rt.sym
    ).select(lt.tk, rt.px)
    assert _vals(res, "tk", "px") == [("a", 10), ("b", 30)]


def test_asof_join_right():
    lt, rt = T(TRADES), T(QUOTES)
    res = temporal.asof_join_right(
        rt, lt, rt.qt, lt.tt
    ).select(rt.qk, lt.tk)
    # right-asof flips sides: each TRADE picks its backward quote
    assert ("p", "a") in _vals(res, "qk", "tk")


# ------------------------------------------------------- asof_now join


def test_asof_now_join_results_frozen():
    """Left insertions join the right state AS OF arrival; later right
    updates must NOT retro-update delivered results."""
    queries = T(
        """
        qk | sym | __time__
        q1 | AA  | 4
        """
    )
    prices = T(
        """
        sym | px | __time__ | __diff__
        AA  | 10 | 2        | 1
        AA  | 10 | 6        | -1
        AA  | 99 | 6        | 1
        """
    )
    res = temporal.asof_now_join(
        queries, prices, queries.sym == prices.sym
    ).select(queries.qk, prices.px)
    events = stream_of(res)
    assert [(row, d) for (_t, _k, row, d) in events] == [(("q1", 10), 1)], (
        f"asof_now must freeze at query time: {events}"
    )


def test_asof_now_join_left_pads():
    queries = T(
        """
        qk | sym
        q1 | ZZ
        """
    )
    prices = T(
        """
        sym | px
        AA  | 10
        """
    )
    res = temporal.asof_now_join_left(
        queries, prices, queries.sym == prices.sym
    ).select(queries.qk, prices.px)
    assert _vals(res, "qk", "px") == [("q1", None)]


# ------------------------------------------------- streaming re-windowing


def test_tumbling_window_retracts_on_update():
    """An upstream retraction moves a row across windows; the old window
    must shrink and the new one grow (delta-correctness of windowby)."""
    t = T(
        """
        k | t  | __time__ | __diff__
        a | 1  | 2        | 1
        b | 2  | 2        | 1
        a | 1  | 4        | -1
        a | 12 | 4        | 1
        """
    ).with_id_from(pw.this.k)
    res = t.windowby(t.t, window=temporal.tumbling(duration=10)).reduce(
        start=pw.this._pw_window_start, n=pw.reducers.count()
    )
    assert _vals(res, "start", "n") == [(0, 1), (10, 1)]


def test_intervals_over_is_outer_reference_fixture():
    """is_outer=True (the reference DEFAULT) emits every probe's window;
    empty ones carry one all-None row, so sorted_tuple gives (None,)
    (reference: tests/temporal/test_windows.py is_outer=True fixture)."""
    t = pw.debug.table_from_markdown(
        """
        t  | v
        1  | 10
        2  | 1
        3  | 3
        8  | 2
        9  | 4
        10 | 8
        1  | 9
        2  | 16
        """
    )
    probes = pw.debug.table_from_markdown(
        """
        t
        2
        4
        6
        8
        10
        """
    )
    res = pw.temporal.windowby(
        t, t.t,
        window=pw.temporal.intervals_over(
            at=probes.t, lower_bound=-2, upper_bound=1, is_outer=True
        ),
    ).reduce(
        pw.this._pw_window_location, v=pw.reducers.sorted_tuple(pw.this.v)
    )
    _ids, cols = pw.debug.table_to_dicts(res)
    got = sorted(
        (cols["_pw_window_location"][k], cols["v"][k]) for k in cols["v"]
    )
    assert got == [
        (2, (1, 3, 9, 10, 16)),
        (4, (1, 3, 16)),
        (6, (None,)),
        (8, (2, 4)),
        (10, (2, 4, 8)),
    ]
