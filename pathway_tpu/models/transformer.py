"""TPU-native transformer: embedder (bi-directional + mean pool) and causal LM.

This is the flagship compute model of the framework — the engine behind the
local `JaxEmbedder` / reranker / on-TPU generation in `xpacks.llm`, replacing
the reference's torch `SentenceTransformerEmbedder`
(`/root/reference/python/pathway/xpacks/llm/embedders.py:270`) and
`HFPipelineChat` (`llms.py:441`) with batched XLA programs.

Design notes (TPU-first):
- Params are a plain pytree of `jnp` arrays; every leaf has a PartitionSpec
  in `param_specs()` implementing Megatron-style tensor parallelism over the
  mesh's `model` axis (attention heads + ffn hidden sharded), data
  parallelism over `data` (batch sharded), with XLA inserting the
  all-reduces at the row-parallel projections.
- Forward is pure + jit-friendly: static shapes, no Python branching on
  data; attention uses one fused einsum per projection so the MXU sees
  [B*S, D] x [D, D'] matmuls in bf16 with f32 accumulation.
- `remat` wraps each block for the train step: activations are
  rematerialized in backward, trading MXU flops for HBM — the standard
  memory lever on TPU.
- The causal decode path keeps a KV cache laid out [layers, B, S, H, Dh]
  sharded on heads, so generation is also tensor-parallel.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Array = jax.Array
Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32768
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_len: int = 512
    causal: bool = False  # False: bi-directional encoder; True: decoder LM
    pool: str = "mean"  # encoder pooling: mean | cls | last
    dtype: Any = jnp.bfloat16
    embed_dim: int | None = None  # projection head dim (None = d_model)
    # Use the fused Pallas attention kernel (ops/attention.py) on TPU for
    # the non-causal path. MUST be False when params are tensor-parallel
    # over a mesh's `model` axis: pallas_call has no partitioning rule, so
    # a 'model'-sharded qkv operand cannot be auto-partitioned — use
    # `dataclasses.replace(cfg, fused_attention=False)`
    # (TransformerLM.shard does this for you).
    fused_attention: bool = True
    # Sequence/context parallelism: name of the mesh axis the sequence is
    # sharded over. When set, forward/encode must run INSIDE shard_map
    # with [b, s_local, ...] blocks; attention runs as ring attention
    # (ops/attention.py ring_attention — K/V blocks rotate over ICI with
    # streaming-softmax accumulation), and positions/pooling account for
    # the block offset. Long sequences scale with the ring size.
    seq_axis: str | None = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def __post_init__(self) -> None:
        if self.pool not in ("mean", "cls", "last"):
            raise ValueError(f"pool must be mean|cls|last, got {self.pool!r}")
        if self.d_model % self.n_heads != 0:
            raise ValueError("d_model must be divisible by n_heads")


def embedder_config(**kw) -> TransformerConfig:
    """SBERT-class text encoder."""
    return TransformerConfig(causal=False, **kw)


def lm_config(**kw) -> TransformerConfig:
    """Gemma-class causal decoder."""
    kw.setdefault("pool", "last")
    return TransformerConfig(causal=True, **kw)


# ------------------------------------------------------------------ params


def _init_block(rng: Array, cfg: TransformerConfig) -> Params:
    d, f = cfg.d_model, cfg.d_ff
    ks = jax.random.split(rng, 6)
    s = 1.0 / math.sqrt(d)
    return {
        "qkv": jax.random.normal(ks[0], (d, 3 * d), jnp.float32) * s,
        "o": jax.random.normal(ks[1], (d, d), jnp.float32) * s,
        "ff_in": jax.random.normal(ks[2], (d, f), jnp.float32) * s,
        "ff_out": jax.random.normal(ks[3], (f, d), jnp.float32) * (1.0 / math.sqrt(f)),
        "ln1_scale": jnp.ones((d,), jnp.float32),
        "ln2_scale": jnp.ones((d,), jnp.float32),
    }


def init_params(rng: Array, cfg: TransformerConfig) -> Params:
    ks = jax.random.split(rng, cfg.n_layers + 3)
    e = cfg.embed_dim or cfg.d_model
    params: Params = {
        "tok_embed": jax.random.normal(
            ks[0], (cfg.vocab_size, cfg.d_model), jnp.float32
        )
        * 0.02,
        "pos_embed": jax.random.normal(ks[1], (cfg.max_len, cfg.d_model), jnp.float32)
        * 0.02,
        "ln_f_scale": jnp.ones((cfg.d_model,), jnp.float32),
        "head": jax.random.normal(ks[2], (cfg.d_model, e), jnp.float32)
        * (1.0 / math.sqrt(cfg.d_model)),
        "blocks": [_init_block(ks[3 + i], cfg) for i in range(cfg.n_layers)],
    }
    return params


def param_specs(cfg: TransformerConfig) -> Params:
    """PartitionSpecs: tensor-parallel over the `model` mesh axis.

    qkv/ff_in are column-parallel (output dim sharded); o/ff_out are
    row-parallel (input dim sharded) so XLA places one psum per block half.
    Embeddings shard the vocab/feature dim; norms are replicated.
    """
    block = {
        "qkv": P(None, "model"),
        "o": P("model", None),
        "ff_in": P(None, "model"),
        "ff_out": P("model", None),
        "ln1_scale": P(None),
        "ln2_scale": P(None),
    }
    return {
        "tok_embed": P("model", None),
        "pos_embed": P(None, None),
        "ln_f_scale": P(None),
        "head": P(None, "model"),
        "blocks": [dict(block) for _ in range(cfg.n_layers)],
    }


def shard_params(params: Params, mesh: Mesh, cfg: TransformerConfig) -> Params:
    specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def count_params(params: Params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


def cast_params(params: Params, dtype: Any = jnp.bfloat16) -> Params:
    """bf16-resident inference params: cast once instead of per matmul.

    Training keeps the f32 master copy; serving paths (encode/generate)
    run on the cast tree so weight reads from HBM are half-width and no
    cast ops appear inside the jitted program.
    """
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        params,
    )


# ----------------------------------------------------------------- forward


def _rmsnorm(x: Array, scale: Array) -> Array:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + 1e-6) * scale).astype(x.dtype)


_FUSED_ATTN_ENV: bool | None = None


def _use_fused_attention() -> bool:
    # the kill switch is read ONCE per process: _attention runs inside
    # jit traces, and an env read per trace is the hot-path bug class
    # the repo lint bans (PR 9(h))
    global _FUSED_ATTN_ENV
    if _FUSED_ATTN_ENV is None:
        import os

        _FUSED_ATTN_ENV = (
            os.environ.get("PATHWAY_TPU_FUSED_ATTN", "1") != "0"
        )
    return _FUSED_ATTN_ENV and jax.default_backend() == "tpu"


def _attention(
    x: Array,
    block: Params,
    cfg: TransformerConfig,
    mask: Array,
    token_mask: Array,
) -> Array:
    # The qkv projection output feeds the fused Pallas attention kernel
    # directly (ops/attention.py): head split, scores, masked softmax and
    # the value contraction all stay in VMEM, so the only HBM traffic is
    # the qkv read and the ctx write. On non-TPU backends (and for the
    # causal LM path) the einsum reference implementation runs instead —
    # XLA's lowering there round-trips [b,h,s,s] scores through HBM,
    # which at flagship shapes is ~5x slower (measured on v5e).
    b, s, d = x.shape
    h, dh = cfg.n_heads, cfg.head_dim
    qkv = jnp.einsum(
        "bsd,de->bse", x, block["qkv"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    ).astype(cfg.dtype)
    if cfg.seq_axis is not None:
        from pathway_tpu.ops.attention import ring_attention

        q, k, v = jnp.split(qkv, 3, axis=-1)
        ctx = ring_attention(
            q.reshape(b, s, h, dh),
            k.reshape(b, s, h, dh),
            v.reshape(b, s, h, dh),
            cfg.seq_axis,
            causal=cfg.causal,
            kv_mask=token_mask,
        ).reshape(b, s, d)
    elif not cfg.causal and cfg.fused_attention and _use_fused_attention():
        from pathway_tpu.ops.attention import fused_qkv_attention

        ctx = fused_qkv_attention(qkv, token_mask, h)
    elif not cfg.causal:
        from pathway_tpu.ops.attention import reference_attention

        ctx = reference_attention(qkv, token_mask, h)
    else:
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, h, dh)
        k = k.reshape(b, s, h, dh)
        v = v.reshape(b, s, h, dh)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        ) / math.sqrt(dh)
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        ctx = jnp.einsum(
            "bhqk,bkhd->bqhd", probs, v, preferred_element_type=jnp.float32
        ).astype(cfg.dtype).reshape(b, s, d)
    return jnp.einsum(
        "bsd,de->bse", ctx, block["o"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    ).astype(cfg.dtype)


def _ffn(x: Array, block: Params, cfg: TransformerConfig) -> Array:
    hline = jnp.einsum(
        "bsd,df->bsf", x, block["ff_in"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    hline = jax.nn.gelu(hline).astype(cfg.dtype)
    return jnp.einsum(
        "bsf,fd->bsd", hline, block["ff_out"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    ).astype(cfg.dtype)


def _block_fwd(
    x: Array, block: Params, cfg: TransformerConfig, mask: Array, token_mask: Array
) -> Array:
    x = x + _attention(_rmsnorm(x, block["ln1_scale"]), block, cfg, mask, token_mask)
    x = x + _ffn(_rmsnorm(x, block["ln2_scale"]), block, cfg)
    return x


def _build_mask(token_mask: Array, causal: bool) -> Array:
    # token_mask: [b, s] 1/0 valid; returns [b, 1, q, k] bool
    b, s = token_mask.shape
    attend = token_mask[:, None, None, :].astype(bool)
    if causal:
        tri = jnp.tril(jnp.ones((s, s), bool))
        attend = attend & tri[None, None, :, :]
    return attend


def forward(
    params: Params, token_ids: Array, token_mask: Array, cfg: TransformerConfig
) -> Array:
    """Hidden states [b, s, d_model]."""
    b, s = token_ids.shape
    x = params["tok_embed"].astype(cfg.dtype)[token_ids]
    if cfg.seq_axis is not None:
        # sequence-parallel block: positions offset by this device's block.
        # The ring size is static, so over-length sequences fail at trace
        # time (dynamic_slice would otherwise clamp and silently repeat
        # the final positions).
        n_blocks = jax.lax.psum(1, cfg.seq_axis)
        if n_blocks * s > cfg.max_len:
            raise ValueError(
                f"sequence-parallel length {n_blocks}x{s} exceeds "
                f"max_len={cfg.max_len}"
            )
        offset = jax.lax.axis_index(cfg.seq_axis) * s
        pos = jax.lax.dynamic_slice_in_dim(
            params["pos_embed"].astype(cfg.dtype), offset, s, axis=0
        )
        x = x + pos[None, :, :]
    else:
        x = x + params["pos_embed"].astype(cfg.dtype)[None, :s, :]
    mask = _build_mask(token_mask, cfg.causal)
    blk = functools.partial(_block_fwd, cfg=cfg, mask=mask, token_mask=token_mask)
    for block in params["blocks"]:
        x = jax.checkpoint(blk)(x, block)
    return _rmsnorm(x, params["ln_f_scale"])


def encode(
    params: Params, token_ids: Array, token_mask: Array, cfg: TransformerConfig
) -> Array:
    """Pooled, L2-normalized embeddings [b, embed_dim] (f32)."""
    h = forward(params, token_ids, token_mask, cfg)
    if cfg.seq_axis is not None and cfg.pool != "mean":
        # 'cls'/'last' would need a block broadcast across the ring
        raise NotImplementedError(
            "sequence-parallel encode supports mean pooling"
        )
    if cfg.pool == "mean":
        # bf16 mask-and-sum (HBM-bound step); divide in f32 for accuracy.
        # Under sequence parallelism the block-local partials combine over
        # the ring before the divide.
        m16 = token_mask.astype(cfg.dtype)[:, :, None]
        part = jnp.sum(h * m16, axis=1).astype(jnp.float32)
        cnt = jnp.sum(token_mask, axis=1)[:, None].astype(jnp.float32)
        if cfg.seq_axis is not None:
            part = jax.lax.psum(part, cfg.seq_axis)
            cnt = jax.lax.psum(cnt, cfg.seq_axis)
        pooled = part / jnp.maximum(cnt, 1.0)
    elif cfg.pool == "cls":
        pooled = h[:, 0, :].astype(jnp.float32)
    else:  # last valid token
        idx = jnp.maximum(jnp.sum(token_mask, axis=1) - 1, 0).astype(jnp.int32)
        pooled = h[jnp.arange(h.shape[0]), idx, :].astype(jnp.float32)
    from pathway_tpu.ops.distances import normalize

    return normalize(pooled @ params["head"].astype(jnp.float32))


def logits(
    params: Params, token_ids: Array, token_mask: Array, cfg: TransformerConfig
) -> Array:
    """LM logits [b, s, vocab] via tied embedding."""
    h = forward(params, token_ids, token_mask, cfg)
    return jnp.einsum(
        "bsd,vd->bsv", h, params["tok_embed"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )


# ------------------------------------------------------------- train step


def lm_loss(
    params: Params, token_ids: Array, token_mask: Array, cfg: TransformerConfig
) -> Array:
    """Next-token cross-entropy. Requires a causal config: with bidirectional
    attention the target token is visible to its own position and the loss
    degenerates to copying."""
    if not cfg.causal:
        raise ValueError("lm_loss requires causal=True (use lm_config)")
    lg = logits(params, token_ids, token_mask, cfg)
    targets = jnp.roll(token_ids, -1, axis=1)
    valid = token_mask.astype(jnp.float32)
    valid = valid * jnp.roll(valid, -1, axis=1)
    valid = valid.at[:, -1].set(0.0)
    logp = jax.nn.log_softmax(lg, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[:, :, None], axis=-1)[:, :, 0]
    return jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1.0)


def make_train_step(cfg: TransformerConfig, learning_rate: float = 1e-3):
    """Returns (init_opt_state, train_step). AdamW via optax."""
    import optax

    tx = optax.adamw(learning_rate, weight_decay=0.01)

    def init_opt(params: Params):
        return tx.init(params)

    def train_step(params: Params, opt_state, token_ids: Array, token_mask: Array):
        loss, grads = jax.value_and_grad(lm_loss)(params, token_ids, token_mask, cfg)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return init_opt, train_step


# ---------------------------------------------------------------- decoding


def init_kv_cache(cfg: TransformerConfig, batch: int) -> Params:
    shape = (cfg.n_layers, batch, cfg.max_len, cfg.n_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def decode_step(
    params: Params,
    cache: Params,
    token: Array,  # [b] current token ids
    pos: Array,  # scalar int32 position
    cfg: TransformerConfig,
    pad_len: Array | None = None,  # [b] left-pad lengths (batched serving)
) -> tuple[Array, Params]:
    """One autoregressive step with KV cache; returns ([b, vocab], cache).

    With `pad_len` the batch is LEFT-padded: each row's logical position
    is pos - pad_len (continuing the prefill's mask-cumsum positions) and
    pad cache slots never enter attention — a row's tokens match what an
    unpadded single-prompt run would produce."""
    b = token.shape[0]
    h, dh = cfg.n_heads, cfg.head_dim
    x = params["tok_embed"].astype(cfg.dtype)[token][:, None, :]  # [b,1,d]
    mask_len = cfg.max_len
    if pad_len is None:
        x = x + jax.lax.dynamic_slice_in_dim(
            params["pos_embed"].astype(cfg.dtype), pos, 1, axis=0
        )[None]
        kmask = (jnp.arange(mask_len) <= pos)[None, None, None, :]
    else:
        x = x + params["pos_embed"].astype(cfg.dtype)[pos - pad_len][:, None, :]
        kmask = (
            (jnp.arange(mask_len)[None, :] <= pos)
            & (jnp.arange(mask_len)[None, :] >= pad_len[:, None])
        )[:, None, None, :]
    for li, block in enumerate(params["blocks"]):
        xin = _rmsnorm(x, block["ln1_scale"])
        qkv = jnp.einsum(
            "bsd,de->bse", xin, block["qkv"].astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        ).astype(cfg.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, 1, h, dh)
        k = k.reshape(b, 1, h, dh)
        v = v.reshape(b, 1, h, dh)
        cache["k"] = jax.lax.dynamic_update_slice(
            cache["k"], k[None], (li, 0, pos, 0, 0)
        )
        cache["v"] = jax.lax.dynamic_update_slice(
            cache["v"], v[None], (li, 0, pos, 0, 0)
        )
        keys, vals = cache["k"][li], cache["v"][li]  # [b, S, h, dh]
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, keys, preferred_element_type=jnp.float32
        ) / math.sqrt(dh)
        scores = jnp.where(kmask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        ctx = jnp.einsum(
            "bhqk,bkhd->bqhd", probs, vals, preferred_element_type=jnp.float32
        ).astype(cfg.dtype).reshape(b, 1, cfg.d_model)
        attn_out = jnp.einsum(
            "bsd,de->bse", ctx, block["o"].astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        ).astype(cfg.dtype)
        x = x + attn_out
        x = x + _ffn(_rmsnorm(x, block["ln2_scale"]), block, cfg)
    hline = _rmsnorm(x, params["ln_f_scale"])
    lg = jnp.einsum(
        "bsd,vd->bsv", hline, params["tok_embed"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    return lg[:, 0, :], cache


def prefill(
    params: Params,
    prompt_ids: Array,
    cache: Params,
    cfg: TransformerConfig,
    prompt_mask: Array | None = None,
) -> tuple[Array, Params]:
    """One batched causal forward over the whole prompt, writing every
    layer's K/V into the cache. Returns (last-position logits [b, vocab],
    cache). This is ONE XLA program over [b, p] — prefill cost does not
    serialize over prompt length the way per-token decode would.

    With `prompt_mask` the batch is LEFT-padded (pad tokens first, real
    tokens end at p-1 so the last-position logits are every row's next-
    token logits): real tokens take positions 0..len-1 via the mask
    cumsum and pad keys are masked out, so a padded row's outputs equal
    an unpadded single-prompt run.
    """
    b, p = prompt_ids.shape
    h, dh = cfg.n_heads, cfg.head_dim
    x = params["tok_embed"].astype(cfg.dtype)[prompt_ids]
    if prompt_mask is None:
        x = x + params["pos_embed"].astype(cfg.dtype)[None, :p, :]
        mask = _build_mask(jnp.ones((b, p), jnp.int32), causal=True)
    else:
        pos_idx = jnp.clip(jnp.cumsum(prompt_mask, axis=1) - 1, 0, None)
        x = x + params["pos_embed"].astype(cfg.dtype)[pos_idx]
        mask = _build_mask(prompt_mask, causal=True)
    for li, block in enumerate(params["blocks"]):
        xin = _rmsnorm(x, block["ln1_scale"])
        qkv = jnp.einsum(
            "bsd,de->bse", xin, block["qkv"].astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        ).astype(cfg.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, p, h, dh)
        k = k.reshape(b, p, h, dh)
        v = v.reshape(b, p, h, dh)
        cache["k"] = jax.lax.dynamic_update_slice(
            cache["k"], k[None], (li, 0, 0, 0, 0)
        )
        cache["v"] = jax.lax.dynamic_update_slice(
            cache["v"], v[None], (li, 0, 0, 0, 0)
        )
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        ) / math.sqrt(dh)
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        ctx = jnp.einsum(
            "bhqk,bkhd->bqhd", probs, v, preferred_element_type=jnp.float32
        ).astype(cfg.dtype).reshape(b, p, cfg.d_model)
        attn_out = jnp.einsum(
            "bsd,de->bse", ctx, block["o"].astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        ).astype(cfg.dtype)
        x = x + attn_out
        x = x + _ffn(_rmsnorm(x, block["ln2_scale"]), block, cfg)
    hlast = _rmsnorm(x[:, -1:, :], params["ln_f_scale"])
    lg = jnp.einsum(
        "bsd,vd->bsv", hlast, params["tok_embed"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    return lg[:, 0, :], cache


def generate(
    params: Params,
    prompt_ids: Array,  # [b, p]
    n_steps: int,
    cfg: TransformerConfig,
    temperature: float = 0.0,
    rng: Array | None = None,
    prompt_mask: Array | None = None,  # [b, p] 1/0, LEFT-padded batches
) -> Array:
    """Batched prefill + `lax.scan` decode. Returns [b, p + n_steps].

    `prompt_mask` enables serving-style batching of heterogeneous
    prompts: left-pad every prompt to a common length, pass the validity
    mask, and each row generates exactly what an unpadded single-prompt
    run would (mask-cumsum positions; pad slots never attend)."""
    toks, _cache = generate_serving(
        params, prompt_ids, init_kv_cache(cfg, prompt_ids.shape[0]),
        n_steps, cfg, temperature=temperature, rng=rng,
        prompt_mask=prompt_mask,
    )
    return toks


def generate_serving(
    params: Params,
    prompt_ids: Array,  # [b, p]
    cache: Params,  # KV cache for batch b (init_kv_cache shape)
    n_steps: int,
    cfg: TransformerConfig,
    temperature: float = 0.0,
    rng: Array | None = None,
    prompt_mask: Array | None = None,
) -> tuple[Array, Params]:
    """`generate` for the serving loop: the KV cache is an ARGUMENT and
    is returned, so a dispatch site can keep one persistent cache buffer
    per batch bucket and jit with `donate_argnums` on it — XLA then
    reuses the (hundreds of MB at Gemma shapes) allocation in place
    across dispatches instead of re-allocating per call. Stale cache
    contents from a previous wave are harmless: prefill rewrites
    positions 0..p-1, decode writes p..p+n-1, and the attention masks
    never read past the current position."""
    b, p = prompt_ids.shape
    if p + n_steps > cfg.max_len:
        raise ValueError(
            f"prompt ({p}) + n_steps ({n_steps}) exceeds max_len ({cfg.max_len})"
        )
    if temperature > 0.0 and rng is None:
        raise ValueError("sampled generation (temperature > 0) requires rng")
    first_logits, cache = prefill(params, prompt_ids, cache, cfg, prompt_mask)
    pad_len = (
        None
        if prompt_mask is None
        else (p - jnp.sum(prompt_mask, axis=1)).astype(jnp.int32)
    )

    def pick(lg: Array, key):
        if temperature > 0.0:
            key, sub = jax.random.split(key)
            return jax.random.categorical(sub, lg / temperature).astype(jnp.int32), key
        return jnp.argmax(lg, -1).astype(jnp.int32), key

    key = rng
    first_tok, key = pick(first_logits, key)

    def body(carry, i):
        cache, tok, key = carry
        lg, cache = decode_step(params, cache, tok, p + i, cfg, pad_len=pad_len)
        nxt, key = pick(lg, key)
        # emit the token being consumed this step; the carry holds the next
        return (cache, nxt, key), tok

    (cache, _last_tok, _), toks = jax.lax.scan(
        body, (cache, first_tok, key), jnp.arange(n_steps)
    )
    return jnp.concatenate([prompt_ids, toks.T], axis=1), cache


def prefill_into_slot(
    params: Params,
    prompt_ids: Array,  # [1, P] LEFT-padded (pad_left_rows convention)
    prompt_mask: Array,  # [1, P] 1/0
    cache: Params,  # multi-slot serving cache (init_kv_cache shape)
    slot: Array,  # scalar int32 — which cache row this request owns
    cfg: TransformerConfig,
) -> tuple[Array, Params]:
    """Prefill ONE request into row `slot` of a multi-slot serving cache
    (continuous batching). Runs the standard b=1 left-padded prefill into
    a scratch single-row cache and scatters that row into `cache` at the
    slot. `slot` is a traced scalar, so one compiled program serves every
    slot of the bucket — a request joining an in-flight batch costs zero
    new XLA compilations once its prompt bucket is warm. Returns (first
    decoded token [1] int32, cache); argmax decoding, matching the
    temperature-0 `generate_serving` path bit for bit per row."""
    lg, mini = prefill(params, prompt_ids, init_kv_cache(cfg, 1), cfg, prompt_mask)
    cache["k"] = jax.lax.dynamic_update_slice(
        cache["k"], mini["k"], (0, slot, 0, 0, 0)
    )
    cache["v"] = jax.lax.dynamic_update_slice(
        cache["v"], mini["v"], (0, slot, 0, 0, 0)
    )
    return jnp.argmax(lg, -1).astype(jnp.int32), cache


def decode_step_slots(
    params: Params,
    cache: Params,
    token: Array,  # [b] int32 — the token each slot consumes this step
    pos: Array,  # [b] int32 — per-slot physical write position
    pad_len: Array,  # [b] int32 — per-slot left-pad length
    cfg: TransformerConfig,
) -> tuple[Array, Params]:
    """One decode step where every batch row is an INDEPENDENT request at
    its own sequence position (continuous batching). Unlike
    :func:`decode_step`, which advances a wave-aligned batch at one shared
    scalar position, here `token`/`pos`/`pad_len` are per-row vectors: row
    i consumes ``token[i]``, writes its K/V at physical position
    ``pos[i]`` of its own cache slot, and attends over
    ``[pad_len[i], pos[i]]`` — its left-padded prompt plus the tokens it
    has decoded so far. Rows never read each other's slots, so a freshly
    prefilled request is correct from its first step even though its
    neighbours are mid-generation. Returns (next token [b] int32, cache);
    argmax decoding, bit-identical per row to the wave-aligned path."""
    b = token.shape[0]
    h, dh = cfg.n_heads, cfg.head_dim
    x = params["tok_embed"].astype(cfg.dtype)[token][:, None, :]
    x = x + params["pos_embed"].astype(cfg.dtype)[pos - pad_len][:, None, :]
    mask_len = cfg.max_len
    kmask = (
        (jnp.arange(mask_len)[None, :] <= pos[:, None])
        & (jnp.arange(mask_len)[None, :] >= pad_len[:, None])
    )[:, None, None, :]
    rows = jnp.arange(b)
    for li, block in enumerate(params["blocks"]):
        xin = _rmsnorm(x, block["ln1_scale"])
        qkv = jnp.einsum(
            "bsd,de->bse", xin, block["qkv"].astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        ).astype(cfg.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, 1, h, dh)
        k = k.reshape(b, h, dh)
        v = v.reshape(b, h, dh)
        cache["k"] = cache["k"].at[li, rows, pos].set(k)
        cache["v"] = cache["v"].at[li, rows, pos].set(v)
        keys, vals = cache["k"][li], cache["v"][li]  # [b, S, h, dh]
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, keys, preferred_element_type=jnp.float32
        ) / math.sqrt(dh)
        scores = jnp.where(kmask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        ctx = jnp.einsum(
            "bhqk,bkhd->bqhd", probs, vals, preferred_element_type=jnp.float32
        ).astype(cfg.dtype).reshape(b, 1, cfg.d_model)
        attn_out = jnp.einsum(
            "bsd,de->bse", ctx, block["o"].astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        ).astype(cfg.dtype)
        x = x + attn_out
        x = x + _ffn(_rmsnorm(x, block["ln2_scale"]), block, cfg)
    hline = _rmsnorm(x, params["ln_f_scale"])
    lg = jnp.einsum(
        "bsd,vd->bsv", hline, params["tok_embed"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    return jnp.argmax(lg[:, 0, :], -1).astype(jnp.int32), cache


class TransformerLM:
    """Convenience OO wrapper over the functional model."""

    def __init__(self, cfg: TransformerConfig, rng_seed: int = 0):
        self.cfg = cfg
        self.params = init_params(jax.random.PRNGKey(rng_seed), cfg)
        self._encode = jax.jit(functools.partial(encode, cfg=cfg))
        self._logits = jax.jit(functools.partial(logits, cfg=cfg))

    def encode(self, token_ids: Array, token_mask: Array) -> Array:
        return self._encode(self.params, token_ids, token_mask)

    def logits(self, token_ids: Array, token_mask: Array) -> Array:
        return self._logits(self.params, token_ids, token_mask)

    def shard(self, mesh: Mesh) -> None:
        # tensor-parallel params: switch off the fused attention kernel
        # (no partitioning rule for pallas_call — see TransformerConfig)
        self.cfg = dataclasses.replace(self.cfg, fused_attention=False)
        self.params = shard_params(self.params, mesh, self.cfg)
        self._encode = jax.jit(functools.partial(encode, cfg=self.cfg))
        self._logits = jax.jit(functools.partial(logits, cfg=self.cfg))
