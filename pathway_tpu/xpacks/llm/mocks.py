"""Fake chats/embedders for tests (reference: xpacks/llm/tests/mocks.py)."""

from __future__ import annotations

import hashlib
from typing import Any

import numpy as np

import pathway_tpu as pw
from pathway_tpu.internals.json import Json


class IdentityMockChat(pw.UDF):
    """Returns 'model: last user message'."""

    def __wrapped__(self, messages: Any, model: str = "mock", **kwargs: Any) -> str:
        msgs = messages.value if isinstance(messages, Json) else messages
        if isinstance(msgs, list):
            content = msgs[-1]["content"]
        else:
            content = str(msgs)
        return f"{model}: {content}"


class FakeChatModel(pw.UDF):
    """Always answers 'Text'."""

    def __wrapped__(self, messages: Any, **kwargs: Any) -> str:
        return "Text"


class EchoChat(pw.UDF):
    """Returns the last user message verbatim."""

    def __wrapped__(self, messages: Any, **kwargs: Any) -> str:
        msgs = messages.value if isinstance(messages, Json) else messages
        return msgs[-1]["content"] if isinstance(msgs, list) else str(msgs)


def fake_embeddings_model(x: str, dim: int = 8) -> np.ndarray:
    """Deterministic pseudo-embedding: hash of each token folded into dim
    buckets, L2-normalized; similar token sets -> similar vectors."""
    vec = np.zeros(dim, np.float32)
    for tok in str(x).lower().split():
        h = int(hashlib.md5(tok.encode()).hexdigest(), 16)
        vec[h % dim] += 1.0
    n = np.linalg.norm(vec)
    return vec / n if n > 0 else vec + 1.0 / np.sqrt(dim)


class FakeEmbedder(pw.UDF):
    def __init__(self, dim: int = 8):
        super().__init__(deterministic=True)
        self.dim = dim

    def __wrapped__(self, text: str, **kwargs: Any) -> np.ndarray:
        return fake_embeddings_model(text, self.dim)

    def get_embedding_dimension(self, **kwargs: Any) -> int:
        return self.dim
