"""Tests for stdlib.indexing: KNN / BM25 / hybrid / filters / DataIndex."""

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.stdlib.indexing import (
    BruteForceKnn,
    BruteForceKnnFactory,
    DataIndex,
    HybridIndex,
    LshKnn,
    TantivyBM25,
    TantivyBM25Factory,
    UsearchKnn,
)


def _docs():
    return pw.debug.table_from_rows(
        pw.schema_from_types(vec=object, text=str),
        [
            ((1.0, 0.0), "the x axis"),
            ((0.0, 1.0), "the y axis"),
            ((0.7, 0.7), "the diagonal"),
        ],
    )


def _queries():
    return pw.debug.table_from_rows(
        pw.schema_from_types(qvec=object), [((0.9, 0.1),), ((0.1, 0.9),)]
    )


def test_brute_force_knn_collapse():
    docs = _docs()
    queries = _queries()
    index = DataIndex(docs, BruteForceKnn(data_column=docs.vec, dimensions=2))
    res = index.query_as_of_now(queries.qvec, number_of_matches=2)
    df = pw.debug.table_to_pandas(res, include_id=False)
    rows = {r.qvec: r.text for r in df.itertuples()}
    assert rows[(0.9, 0.1)] == ("the x axis", "the diagonal")
    assert rows[(0.1, 0.9)] == ("the y axis", "the diagonal")


def test_brute_force_knn_flat_with_distances():
    docs = _docs()
    queries = _queries()
    index = DataIndex(docs, BruteForceKnn(data_column=docs.vec, dimensions=2))
    res = index.query_as_of_now(
        queries.qvec, number_of_matches=2, collapse_rows=False, with_distances=True
    )
    df = pw.debug.table_to_pandas(res, include_id=False)
    assert len(df) == 4  # 2 queries x 2 matches
    assert set(df.columns) >= {"qvec", "vec", "text", "_pw_dist", "_pw_matched_id"}
    # best match for (0.9, 0.1) is x-axis with near-zero distance
    best = df[df.text == "the x axis"]
    assert (best._pw_dist < 0.05).all()


def test_usearch_knn_same_ranking():
    docs = _docs()
    queries = _queries()
    index = DataIndex(docs, UsearchKnn(data_column=docs.vec, dimensions=2))
    res = index.query_as_of_now(queries.qvec, number_of_matches=2)
    df = pw.debug.table_to_pandas(res, include_id=False)
    rows = {r.qvec: r.text for r in df.itertuples()}
    assert rows[(0.9, 0.1)][0] == "the x axis"


def test_lsh_knn_finds_close_neighbor():
    rng = np.random.default_rng(0)
    base = rng.normal(size=(20, 8))
    docs = pw.debug.table_from_rows(
        pw.schema_from_types(vec=object, name=str),
        [(tuple(map(float, base[i])), f"doc{i}") for i in range(20)],
    )
    queries = pw.debug.table_from_rows(
        pw.schema_from_types(qvec=object),
        [(tuple(map(float, base[7] + 0.001)),)],
    )
    inner = LshKnn(data_column=docs.vec, dimensions=8, n_or=8, n_and=4, bucket_length=4.0)
    res = DataIndex(docs, inner).query_as_of_now(queries.qvec, number_of_matches=1)
    df = pw.debug.table_to_pandas(res, include_id=False)
    assert df.iloc[0]["name"] == ("doc7",)


def test_bm25_ranking():
    docs = pw.debug.table_from_rows(
        pw.schema_from_types(text=str),
        [
            ("the quick brown fox jumps over the lazy dog",),
            ("a fast auburn fox leaps across",),
            ("completely unrelated text about databases",),
        ],
    )
    queries = pw.debug.table_from_rows(
        pw.schema_from_types(q=str), [("quick fox",), ("databases",)]
    )
    index = DataIndex(docs, TantivyBM25(data_column=docs.text))
    res = index.query_as_of_now(queries.q, number_of_matches=1)
    df = pw.debug.table_to_pandas(res, include_id=False)
    rows = {r.q: r.text for r in df.itertuples()}
    assert rows["quick fox"][0].startswith("the quick brown")
    assert rows["databases"][0].endswith("databases")


def test_hybrid_index_rrf():
    docs = pw.debug.table_from_rows(
        pw.schema_from_types(vec=object, text=str),
        [
            ((1.0, 0.0), "alpha beta"),
            ((0.0, 1.0), "gamma delta"),
            ((0.7, 0.7), "alpha delta"),
        ],
    )
    queries = pw.debug.table_from_rows(
        pw.schema_from_types(q=object), [((0.95, 0.05),)]
    )
    hybrid = HybridIndex(
        [
            BruteForceKnn(data_column=docs.vec, dimensions=2),
            BruteForceKnn(data_column=docs.vec, dimensions=2, metric="l2sq"),
        ]
    )
    res = DataIndex(docs, hybrid).query_as_of_now(queries.q, number_of_matches=2)
    df = pw.debug.table_to_pandas(res, include_id=False)
    assert df.iloc[0]["text"][0] == "alpha beta"


def test_metadata_filter():
    docs = pw.debug.table_from_rows(
        pw.schema_from_types(vec=object, text=str, meta=object),
        [
            ((1.0, 0.0), "a", {"owner": "alice", "path": "docs/a.txt"}),
            ((0.99, 0.01), "b", {"owner": "bob", "path": "docs/b.txt"}),
            ((0.98, 0.02), "c", {"owner": "alice", "path": "img/c.png"}),
        ],
    )
    queries = pw.debug.table_from_rows(
        pw.schema_from_types(q=object, flt=str),
        [
            ((1.0, 0.0), "owner == 'bob'"),
            ((1.0, 0.0), "globmatch('docs/*', path)"),
        ],
    )
    inner = BruteForceKnn(data_column=docs.vec, metadata_column=docs.meta, dimensions=2)
    res = DataIndex(docs, inner).query_as_of_now(
        queries.q, number_of_matches=3, metadata_filter=queries.flt
    )
    df = pw.debug.table_to_pandas(res, include_id=False)
    by_flt = {r.flt: r.text for r in df.itertuples()}
    assert by_flt["owner == 'bob'"] == ("b",)
    assert set(by_flt["globmatch('docs/*', path)"]) == {"a", "b"}


def test_query_updates_with_index_changes():
    """Non-asof query results update when better docs arrive later."""
    docs = pw.debug.table_from_markdown(
        """
        vec    | __time__
        first  | 2
        second | 4
        """,
        schema=pw.schema_from_types(vec=str),
    )
    # encode strings as 1-d vectors via apply
    enc = {"first": (1.0, 0.0), "second": (0.9, 0.1), "query": (0.89, 0.11)}
    docs = docs.select(v=pw.apply(lambda s: enc[s], docs.vec), name=docs.vec)
    queries = pw.debug.table_from_markdown(
        """
        q     | __time__
        query | 2
        """,
        schema=pw.schema_from_types(q=str),
    )
    queries = queries.select(qv=pw.apply(lambda s: enc[s], queries.q))
    index = DataIndex(docs, BruteForceKnn(data_column=docs.v, dimensions=2))
    updating = index.query(queries.qv, number_of_matches=1)
    frozen = index.query_as_of_now(queries.qv, number_of_matches=1)
    df_u = pw.debug.table_to_pandas(updating, include_id=False)
    df_f = pw.debug.table_to_pandas(frozen, include_id=False)
    assert df_u.iloc[0]["name"] == ("second",)  # updated to the closer doc
    assert df_f.iloc[0]["name"] == ("first",)  # frozen at time 2


def test_knnindex_facade():
    from pathway_tpu.stdlib.ml.index import KNNIndex

    docs = _docs()
    queries = _queries()
    knn = KNNIndex(docs.vec, docs, n_dimensions=2, distance_type="cosine")
    res = knn.get_nearest_items_asof_now(queries.qvec, k=1, with_distances=True)
    df = pw.debug.table_to_pandas(res, include_id=False)
    # reference shape: only data columns + dist, one row per query
    assert sorted(df.columns) == ["dist", "text", "vec"]
    assert {r.text for r in df.itertuples()} == {("the x axis",), ("the y axis",)}
    assert all(d[0] < 0.1 for d in df.dist)


def test_index_survives_same_wave_doc_update():
    """A (-old, +new) doc update in one commit must not evict the doc."""
    docs = pw.debug.table_from_markdown(
        """
        name | vx   | vy  | __time__ | __diff__
        a    | 1.0  | 0.0 | 2        | 1
        a    | 1.0  | 0.0 | 4        | -1
        a    | 0.0  | 1.0 | 4        | 1
        """,
        schema=pw.schema_from_types(name=str, vx=float, vy=float),
    )
    docs = docs.select(docs.name, v=pw.make_tuple(docs.vx, docs.vy))
    queries = pw.debug.table_from_markdown(
        """
        q | qx  | qy  | __time__
        q | 0.0 | 1.0 | 6
        """,
        schema=pw.schema_from_types(q=str, qx=float, qy=float),
    )
    queries = queries.select(qv=pw.make_tuple(queries.qx, queries.qy))
    index = DataIndex(docs, BruteForceKnn(data_column=docs.v, dimensions=2))
    res = index.query_as_of_now(queries.qv, number_of_matches=1, with_distances=True)
    df = pw.debug.table_to_pandas(res, include_id=False)
    assert len(df) == 1
    assert df.iloc[0]["name"] == ("a",)
    # matched the NEW vector (distance ~0); the old one would be ~1.0
    assert df.iloc[0]["_pw_index_reply_score"][0] < 0.05


def test_inner_index_reply_mode():
    docs = _docs()
    queries = _queries()
    inner = BruteForceKnn(data_column=docs.vec, dimensions=2)
    raw = inner.query_as_of_now(queries.qvec, number_of_matches=2)
    df = pw.debug.table_to_pandas(raw, include_id=False)
    assert list(df.columns) == ["_pw_index_reply"]
    reply = df.iloc[0]["_pw_index_reply"]
    assert len(reply) == 2 and isinstance(reply[0][1], float)


def test_hybrid_with_embedder_and_bm25():
    """Embedder KNN + BM25 hybrid: data embedded once, queries transformed
    per-retriever (regression for the double-embed / raw-query bugs)."""
    from pathway_tpu.xpacks.llm.mocks import FakeEmbedder

    calls = []

    class CountingEmbedder(FakeEmbedder):
        def __wrapped__(self, text, **kwargs):
            calls.append(text)
            return super().__wrapped__(text, **kwargs)

    docs = pw.debug.table_from_rows(
        pw.schema_from_types(text=str),
        [("quick brown fox",), ("lazy dog sleeps",), ("stream of data",)],
    )
    emb = CountingEmbedder(dim=8)
    hybrid = HybridIndex(
        [
            BruteForceKnn(data_column=docs.text, dimensions=8, embedder=emb),
            TantivyBM25(data_column=docs.text),
        ]
    )
    queries = pw.debug.table_from_rows(
        pw.schema_from_types(q=str), [("quick fox",)]
    )
    res = DataIndex(docs, hybrid).query_as_of_now(queries.q, number_of_matches=1)
    df = pw.debug.table_to_pandas(res, include_id=False)
    assert df.iloc[0]["text"] == ("quick brown fox",)
    # 3 docs embedded exactly once each + 1 query
    assert sorted(calls) == sorted(
        ["quick brown fox", "lazy dog sleeps", "stream of data", "quick fox"]
    )


def test_preset_embeds_queries():
    from pathway_tpu.stdlib.indexing import default_vector_document_index
    from pathway_tpu.xpacks.llm.mocks import FakeEmbedder

    docs = pw.debug.table_from_rows(
        pw.schema_from_types(text=str), [("alpha beta",), ("gamma delta",)]
    )
    index = default_vector_document_index(
        docs.text, docs, dimensions=8, embedder=FakeEmbedder(dim=8)
    )
    queries = pw.debug.table_from_rows(pw.schema_from_types(q=str), [("alpha",)])
    df = pw.debug.table_to_pandas(
        index.query_as_of_now(queries.q, number_of_matches=1), include_id=False
    )
    assert df.iloc[0]["text"] == ("alpha beta",)


def test_factories():
    docs = _docs()
    f = BruteForceKnnFactory(dimensions=2)
    idx = f.build_index(docs.vec, docs)
    assert isinstance(idx, DataIndex)
    f2 = TantivyBM25Factory()
    assert isinstance(f2.build_inner_index(docs.text), TantivyBM25)


# ----------------------------------------------------------------- filters


def test_filter_evaluator():
    from pathway_tpu.stdlib.indexing.filters import compile_filter

    f = compile_filter("owner == 'alice' && size > `100`")
    assert f({"owner": "alice", "size": 200})
    assert not f({"owner": "alice", "size": 50})
    assert not f({"owner": "bob", "size": 200})

    f2 = compile_filter("contains(path, 'foo') || modified_at >= `1702840800`")
    assert f2({"path": "a/foo/b", "modified_at": 0})
    assert f2({"path": "x", "modified_at": 1702840801})
    assert not f2({"path": "x", "modified_at": 5})

    f3 = compile_filter("globmatch('**/*.pdf', path)")
    assert f3({"path": "a/b/c.pdf"})
    assert f3({"path": "c.pdf"})
    assert not f3({"path": "a/b/c.txt"})

    f4 = compile_filter("!(owner == 'alice')")
    assert f4({"owner": "bob"})

    # json-string metadata is parsed
    assert compile_filter("owner == 'a'")('{"owner": "a"}')


def test_glob_star_does_not_cross_slash():
    from pathway_tpu.stdlib.indexing.filters import glob_match

    assert glob_match("docs/*.txt", "docs/a.txt")
    assert not glob_match("docs/*.txt", "docs/sub/a.txt")
    assert glob_match("docs/**/*.txt", "docs/sub/a.txt")
    assert glob_match("*.txt", "a.txt")


def test_quantized_knn_recall():
    """int8 scan + bf16 rescore matches exact search ordering (~recall 1.0
    at this scale) and returns exact distances for the winners."""
    import jax.numpy as jnp

    from pathway_tpu.ops.topk import knn_search, knn_search_quantized, quantize_docs

    rng = np.random.default_rng(7)
    docs = rng.normal(size=(5000, 64)).astype(np.float32)
    docs /= np.linalg.norm(docs, axis=1, keepdims=True)
    docs = jnp.asarray(docs, jnp.bfloat16)
    q = rng.normal(size=(8, 64)).astype(np.float32)
    q /= np.linalg.norm(q, axis=1, keepdims=True)
    q = jnp.asarray(q)

    exact = knn_search(q, docs, 10, "cos", normalized=True)
    quant = knn_search_quantized(q, quantize_docs(docs), 10, candidates=64)
    ex, qz = np.asarray(exact.indices), np.asarray(quant.indices)
    recall = np.mean([len(set(ex[i]) & set(qz[i])) / 10 for i in range(8)])
    assert recall >= 0.9, recall
    # distances are the exact bf16 rescored similarities
    np.testing.assert_allclose(
        np.asarray(quant.distances),
        np.asarray(1.0 - jnp.einsum(
            "qd,qkd->qk", q.astype(jnp.float32),
            docs.astype(jnp.float32)[qz])),
        atol=2e-2,
    )


def test_sort_incremental_update_cost():
    """SortNode updates are O(delta log n), not O(n log n) per wave
    (VERDICT r2 item 8): after building a 100k-row instance, a 1-row
    update must re-emit only the 3 affected rows and run orders of
    magnitude faster than a rebuild."""
    import time as _time

    from pathway_tpu.engine.core import Graph, InputNode, SortNode
    from pathway_tpu.internals.keys import Key

    g = Graph()
    inp = InputNode(g)
    node = SortNode(g, inp, lambda key, row: row[0], lambda key, row: 0)

    n = 100_000
    entries = [(Key(i + 1), (i * 2,), 1) for i in range(n)]
    inp.push(entries)
    g.step(2)
    assert node.rows_out == n  # initial emission covers everything

    before = node.rows_out
    t0 = _time.perf_counter()
    waves = 50
    for w in range(waves):
        # insert between two existing sort values -> 3 affected rows each
        inp.push([(Key(n + 10 + w), (2 * w + 100_001,), 1)])
        g.step(4 + 2 * w)
    per_wave = (_time.perf_counter() - t0) / waves
    emitted = node.rows_out - before
    # 1 new row + up to 2 neighbor updates, each a retract+insert pair
    assert emitted <= waves * 5, emitted
    # a full 100k re-sort per wave costs >25ms in this engine; the
    # incremental path is bisect + 3 emissions
    assert per_wave < 0.005, f"per-wave {per_wave*1000:.1f}ms — not incremental"


def test_sort_bulk_load_not_quadratic():
    """A descending-order bulk wave must take the one-sort path, not
    per-row list inserts at position 0 (O(n^2) memmove)."""
    import time as _time

    from pathway_tpu.engine.core import Graph, InputNode, SortNode
    from pathway_tpu.internals.keys import Key

    g = Graph()
    inp = InputNode(g)
    node = SortNode(g, inp, lambda key, row: row[0], lambda key, row: 0)
    n = 100_000
    t0 = _time.perf_counter()
    inp.push([(Key(i + 1), (n - i,), 1) for i in range(n)])
    g.step(2)
    el = _time.perf_counter() - t0
    assert node.rows_out == n
    # the quadratic path takes minutes at this size; the bound only needs
    # to separate O(n log n) from O(n^2), with headroom for loaded CI
    assert el < 8.0, f"descending bulk load took {el:.2f}s"
