"""Batched on-device second-stage reranking.

The reference framework reranks retrieval hits ROW-WISE through torch
cross-encoders (`xpacks/llm/rerankers.py` keeps those adapters,
torch-gated). This module is the device-native seat for that stage:
score every (query, candidate) pair of a wave in ONE bucketed XLA
dispatch — [B, C, d] candidate rows against [B, d] queries — through
the DevicePlane's program/bucket compile ledger, exactly the
discipline LLM decode uses (docs/serving.md), so steady-state serving
never recompiles and the ledger stays flat.

The default scorer is the EXACT f32 metric (cos/dot/l2sq) over the
candidates' full-precision rows. That is deliberately honest: against
an IVF-PQ first stage the quality loss is dominated by probe misses
and ADC quantization, and an exact rescore over a WIDER candidate set
(fetched via the adaptive expansion in
`stdlib/indexing/reranking.py`) is what recovers recall — not a
fancier pair function. A custom jax `scorer(q[B,d], cands[B,C,d]) ->
[B,C]` (e.g. a learned cross-encoder head) drops in through the same
bucketed dispatch.

Degradation: 3-strike to the numpy mirror (`rerank_scores_host`),
permanent on ImportError/NotImplementedError — the same ladder as
every other device op in the repo.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

__all__ = [
    "BatchedReranker",
    "rerank_scores_host",
]


def _rerank_scores_fn(q, cands, valid, *, metric: str = "cos"):
    """[B, d] queries x [B, C, d] candidate rows -> [B, C] f32 scores
    (larger is better; invalid slots pinned to -inf)."""
    import jax.numpy as jnp

    q = q.astype(jnp.float32)
    c = cands.astype(jnp.float32)
    if metric in ("cos", "cosine"):
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=-1, keepdims=True), 1e-12)
        c = c / jnp.maximum(jnp.linalg.norm(c, axis=-1, keepdims=True), 1e-12)
        s = jnp.einsum("bd,bcd->bc", q, c, preferred_element_type=jnp.float32)
    elif metric == "l2sq":
        diff = q[:, None, :] - c
        s = -jnp.sum(diff * diff, axis=-1)
    elif metric == "dot":
        s = jnp.einsum("bd,bcd->bc", q, c, preferred_element_type=jnp.float32)
    else:
        raise NotImplementedError(f"rerank metric {metric!r}")
    return jnp.where(valid, s, -jnp.inf)


def rerank_scores_host(
    q: np.ndarray, cands: np.ndarray, valid: np.ndarray, metric: str = "cos"
) -> np.ndarray:
    """Numpy mirror of `_rerank_scores_fn` (degradation path)."""
    q = np.asarray(q, np.float32)
    c = np.asarray(cands, np.float32)
    if metric in ("cos", "cosine"):
        q = q / np.maximum(np.linalg.norm(q, axis=-1, keepdims=True), 1e-12)
        c = c / np.maximum(np.linalg.norm(c, axis=-1, keepdims=True), 1e-12)
        s = np.einsum("bd,bcd->bc", q, c)
    elif metric == "l2sq":
        diff = q[:, None, :] - c
        s = -np.sum(diff * diff, axis=-1)
    elif metric == "dot":
        s = np.einsum("bd,bcd->bc", q, c)
    else:
        raise NotImplementedError(f"rerank metric {metric!r}")
    return np.where(np.asarray(valid, bool), s, -np.inf).astype(np.float32)


class BatchedReranker:
    """Second-stage pair scorer with bucketed device dispatch.

    `scores(q, cands, valid)` pads B to the plane's row bucket and C to
    the pow2 cap bucket, so distinct wave shapes collapse onto a small
    ladder of compiled programs (one ledger entry per bucket, verified
    flat by the serving tests)."""

    def __init__(
        self,
        metric: str = "cos",
        *,
        device: bool = True,
        scorer: Callable | None = None,
        name: str = "rerank_scores",
    ):
        self.metric = metric if metric != "cosine" else "cos"
        self.name = name
        self._scorer = scorer
        self._use_device = device
        self._failures = 0

    # --------------------------------------------------------------- API

    def scores(
        self, q: np.ndarray, cands: np.ndarray, valid: np.ndarray
    ) -> np.ndarray:
        """[B, d], [B, C, d], [B, C] -> [B, C] f32; -inf on invalid."""
        if self._use_device:
            try:
                out = self._scores_device(q, cands, valid)
                self._failures = 0
                return out
            except (ImportError, NotImplementedError) as e:
                self._use_device = False
                self._log(e, permanent=True)
            except Exception as e:  # noqa: BLE001 — transient (OOM…)
                self._failures += 1
                if self._failures >= 3:
                    self._use_device = False
                self._log(e, permanent=not self._use_device)
        if self._scorer is not None:
            raise RuntimeError(
                "custom rerank scorer has no host mirror and the device "
                "path is unavailable"
            )
        return rerank_scores_host(q, cands, valid, self.metric)

    # ------------------------------------------------------------ device

    def _scores_device(self, q, cands, valid) -> np.ndarray:
        import jax.numpy as jnp

        from pathway_tpu.engine.device_plane import get_device_plane

        plane = get_device_plane()
        B, C = valid.shape
        d = q.shape[1]
        if B > plane.buckets.max_rows:
            Bb = B
        else:
            Bb = plane.buckets.rows_bucket(B)
        Cb = plane.buckets.cap_bucket(max(C, 1))
        qp = np.zeros((Bb, d), np.float32)
        qp[:B] = q
        cp = np.zeros((Bb, Cb, d), np.float32)
        cp[:B, :C] = cands
        vp = np.zeros((Bb, Cb), bool)
        vp[:B, :C] = valid
        prog = plane.program(
            self.name,
            self._scorer or _rerank_scores_fn,
            static_argnames=() if self._scorer else ("metric",),
        )
        kwargs = {} if self._scorer else {"metric": self.metric}
        s = prog(
            jnp.asarray(qp),
            jnp.asarray(cp),
            jnp.asarray(vp),
            bucket=(Bb, Cb, d, self.metric),
            **kwargs,
        )
        return np.asarray(s)[:B, :C]

    @staticmethod
    def _log(e: Exception, permanent: bool) -> None:
        from pathway_tpu.internals.errors import global_error_log

        global_error_log().log(
            f"device rerank failed ({type(e).__name__}: {e}); "
            + ("numpy mirror from now on" if permanent else "retrying")
        )
