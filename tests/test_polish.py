"""VERDICT r2 item 9 polish: pyfilesystem connector, monitoring TUI,
async_transformer depth (failed table, retries, capacity, retractions)."""

from __future__ import annotations

import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def _fresh_graph():
    G.clear()
    yield
    G.clear()


# ------------------------------------------------------------ pyfilesystem


class _FakeInfo:
    def __init__(self, size):
        from datetime import datetime, timezone

        self.size = size
        self.created = datetime.now(timezone.utc)
        self.modified = self.created
        self.accessed = self.created
        self.user = "tester"
        self.name = "f"


class _FakeFS:
    """Duck-typed PyFilesystem source."""

    def __init__(self, files: dict[str, bytes]):
        self.files = dict(files)
        self.mtimes = {p: 1.0 for p in files}

        class _Walk:
            def __init__(self, fsys):
                self.fsys = fsys

            def files(self, path="/"):
                return list(self.fsys.files)

        self.walk = _Walk(self)

    def getmodified(self, p):
        return self.mtimes[p]

    def open(self, p, mode="rb"):
        import io

        return io.BytesIO(self.files[p])

    def getinfo(self, p, namespaces=()):
        return _FakeInfo(len(self.files[p]))


def test_pyfilesystem_static_read():
    src = _FakeFS({"/a.txt": b"alpha", "/b.txt": b"beta"})
    t = pw.io.pyfilesystem.read(src, mode="static", with_metadata=True)
    rows = {}
    pw.io.subscribe(
        t,
        on_change=lambda key, row, time, is_addition: rows.__setitem__(
            row["_metadata"].value["path"], row["data"]
        ),
    )
    pw.run()
    assert rows == {"/a.txt": b"alpha", "/b.txt": b"beta"}


def test_pyfilesystem_streaming_update_and_delete():
    src = _FakeFS({"/a.txt": b"v1"})
    t = pw.io.pyfilesystem.read(src, mode="streaming", refresh_interval=0.05)
    events = []
    pw.io.subscribe(
        t,
        on_change=lambda key, row, time, is_addition: events.append(
            (row["data"], is_addition)
        ),
    )
    th = threading.Thread(target=pw.run, daemon=True)
    th.start()
    deadline = time.time() + 10
    while time.time() < deadline and (b"v1", True) not in events:
        time.sleep(0.02)
    src.files["/a.txt"] = b"v2"
    src.mtimes["/a.txt"] = 2.0
    while time.time() < deadline and (b"v2", True) not in events:
        time.sleep(0.02)
    del src.files["/a.txt"]
    del src.mtimes["/a.txt"]
    while time.time() < deadline and (b"v2", False) not in events:
        time.sleep(0.02)
    assert (b"v1", True) in events
    assert (b"v2", True) in events  # upsert on modification
    assert (b"v2", False) in events  # retraction on deletion


# -------------------------------------------------------------- monitoring


def test_monitoring_tui_renders():
    from pathway_tpu.internals.monitoring import StatsMonitor, rich_renderable

    class _S:
        pass

    t = pw.debug.table_from_rows(pw.schema_from_types(x=int), [(1,), (2,)])
    r = t.reduce(s=pw.reducers.sum(t.x))
    captured = {}

    def on_change(key, row, time, is_addition):
        captured["sum"] = row["s"]

    pw.io.subscribe(r, on_change=on_change)
    # run through the real session so graph stats exist
    from pathway_tpu.internals import run as _run_mod

    pw.run()
    assert captured["sum"] == 3

    # snapshot + renderable over a synthetic session
    from pathway_tpu.internals.lowering import Session

    sess = Session()
    import pathway_tpu.engine.core as core

    inp = core.InputNode(sess.graph)
    mon = StatsMonitor(sess)
    snap = mon.snapshot(wave_time=42)
    assert snap["operators"] == 1 and snap["time"] == 42
    from rich.console import Console

    console = Console(record=True, width=100)
    console.print(rich_renderable(snap))
    text = console.export_text()
    assert "pathway_tpu" in text and "hottest operators" in text


def test_monitor_attaches_with_tui():
    from pathway_tpu.internals.lowering import Session
    from pathway_tpu.internals.monitoring import attach_monitor

    sess = Session()
    attach_monitor(sess, every_n_waves=1, use_tui=False)
    assert sess.monitors
    sess.monitors[0](2)  # no crash on an empty graph


# -------------------------------------------------------- async_transformer


def _stream_table(rows):
    from pathway_tpu.io.python import ConnectorSubject

    class Src(ConnectorSubject):
        def run(self):
            for r in rows:
                self.next(**r)
                time.sleep(0.01)

    return pw.io.python.read(
        Src(), schema=pw.schema_from_types(a=int), name="src"
    )


def test_async_transformer_success_failed_and_retry():
    from pathway_tpu.internals.udfs import FixedDelayRetryStrategy
    from pathway_tpu.stdlib.utils import AsyncTransformer

    attempts = {}

    class Xf(AsyncTransformer):
        output_schema = pw.schema_from_types(doubled=int)

        async def invoke(self, a):
            attempts[a] = attempts.get(a, 0) + 1
            if a == 13:
                raise ValueError("unlucky")
            if a == 7 and attempts[a] < 2:
                raise RuntimeError("flaky once")
            return {"doubled": a * 2}

    t = _stream_table([{"a": 2}, {"a": 7}, {"a": 13}])
    xf = Xf(t).with_options(
        capacity=2, retry_strategy=FixedDelayRetryStrategy(max_retries=2, delay_ms=5)
    )
    ok_rows = {}
    failed = []
    pw.io.subscribe(
        xf.successful,
        on_change=lambda key, row, time, is_addition: ok_rows.__setitem__(
            row["doubled"], is_addition
        ),
    )
    pw.io.subscribe(
        xf.failed,
        on_change=lambda key, row, time, is_addition: failed.append(row),
    )
    th = threading.Thread(target=pw.run, daemon=True)
    th.start()
    deadline = time.time() + 15
    while time.time() < deadline and not (
        {4, 14} <= set(ok_rows) and failed
    ):
        time.sleep(0.02)
    assert {4, 14} <= set(ok_rows), ok_rows
    assert attempts[7] == 2  # the retry strategy re-invoked the flaky row
    assert attempts[13] == 3  # exhausted retries -> failed table
    assert failed and failed[0] == {"doubled": None}
