"""Inactivity detection (reference: stdlib/temporal/time_utils.py:125).

Detects gaps longer than `allowed_inactivity` in an event stream (per
instance): returns (inactivities, resumptions) — event-time based; rows
appear once the resuming event arrives.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import expression as ex
from pathway_tpu.internals.table import Table


def inactivity_detection(
    table: Table,
    time_expr: Any,
    allowed_inactivity: Any,
    instance: Any = None,
    refresh_rate: Any = None,
) -> tuple[Table, Table]:
    t = table.with_columns(_pw_t=time_expr)
    sorted_t = t.sort(key=t._pw_t, instance=instance)
    prev_rows = t.ix(sorted_t.prev, optional=True)
    marked = t.select(
        inactive_since=prev_rows._pw_t,
        resumed_at=t._pw_t,
    ).filter(
        ex.this.inactive_since.is_not_none()
        & ((ex.this.resumed_at - ex.this.inactive_since) > allowed_inactivity)
    )
    inactivities = marked.select(inactive_since=ex.this.inactive_since)
    resumptions = marked.select(resumed_at=ex.this.resumed_at)
    return inactivities, resumptions
