"""Sharded NativeBatch column plane: the engine's key-hash shuffle as ONE
compiled device collective.

The host exchange moves a wave's rows either as per-row Python entries
(pickled over the process mesh) or as `NativeBatch.select` masks (thread
shards). This module lifts the batch's scalar columns — (key_lo, key_hi,
token, diff), each a flat 64-bit array — onto device arrays sharded over
the mesh `data` axis and shuffles the whole column set with a single
`all_to_all` (`parallel/exchange.py exchange_columns_with_respill`):
the bulk bytes of a shuffle ride the interconnect, while routing stays a
HOST decision (`engine/workers.native_shards` — the exact 128-bit
`key % n_shards` / group-key blake2b rule) and frontier/watermark
control traffic stays on the host ring (`parallel/process_mesh.py`).
Intern tokens are process-wide, so a column-plane split inside one
process needs no row blob; cross-process delivery keeps the wire form
(dense ids + unique-row blob, pickle-5 out-of-band buffers).

Donation lifecycle: near-uniform waves take the exchange's donated
single-round path — the padded staging columns are donated to XLA, which
aliases them as the receive buffers, so steady-state waves reuse staging
memory instead of holding send + receive copies live (see
`exchange._exchange_program`).

Mode (PATHWAY_DEVICE_EXCHANGE, shared with the vector payload plane):
"1" forces the column plane on, "0" forces it off, unset = AUTO — on
only on a real multi-device TPU mesh for batches of at least
``auto_min_rows()`` rows (the vector plane's measured 262144-element
crossover divided by the 4 u64 lanes a scalar batch ships; the adaptive
planner retunes it from the `pathway_device_exchange_rows` counters in
BOTH directions — see internals/planner.py).

Degradation: the `mesh.device_wire` fault point models the device wire
dropping a wave. One retry, then the split returns None and the caller
falls back to the host wire — byte-identical by construction, since the
collective preserves per-destination global arrival order exactly like
`batch.select(shards == p)` (the chaos drill's `device_wire` kind pins
this end to end).
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from pathway_tpu.engine import faults
from pathway_tpu.parallel import device_exchange as _dx
from pathway_tpu.parallel.exchange import exchange_columns_with_respill
from pathway_tpu.parallel.mesh import default_mesh
from pathway_tpu.analysis import lockgraph as _lockgraph

__all__ = [
    "ColumnExchanger",
    "engine_column_exchanger",
    "auto_min_rows",
    "stats",
    "reset_stats",
]

# the vector plane's measured crossover is in ELEMENTS; a scalar batch
# ships 4 u64 lanes per row, so rows = elems / 4
_AUTO_LANES = 4


def auto_min_rows() -> int:
    return max(_dx.auto_min_elems() // _AUTO_LANES, 1)


_STATS_LOCK = _lockgraph.register_lock(
    "column_plane.stats", threading.Lock()
)
_STATS = {
    "invocations": 0,  # column-plane collectives dispatched
    "rows": 0,  # rows shuffled over the device wire
    "wire_faults": 0,  # mesh.device_wire shots absorbed (incl. retried)
    "host_degrades": 0,  # splits that fell back to the host wire
}


def stats() -> dict:
    with _STATS_LOCK:
        return dict(_STATS)


def reset_stats() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0


class ColumnExchanger:
    """Splits a NativeBatch across shards through the device collective.

    ``split_batch(batch, shards, n_shards)`` returns the per-shard
    sub-batches (token-valid in this process — thread shards and the
    local half of a process split share one intern table), or None when
    the batch must take the host path. The result is row-for-row
    identical to ``[batch.select(shards == p) for p in range(n_shards)]``.
    """

    MIN_ROWS = 8  # below this the dispatch overhead always dominates

    def __init__(self, mesh=None, axis: str = "data"):
        self.mesh = mesh if mesh is not None else default_mesh((axis,))
        self.axis = axis
        self._auto_ok = _dx.auto_eligible_mesh(self.mesh)
        self._auto_min_rows = auto_min_rows()
        self._auto_min_rows_base = self._auto_min_rows
        # cached like DeviceExchanger._mode: an env read per wave is
        # measurable; the adaptive policy refreshes it at its fences
        self._mode = _dx.mode()

    def split_batch(
        self, batch: Any, shards: np.ndarray, n_shards: int
    ) -> "list | None":
        n = len(batch)
        if n_shards > self.mesh.shape[self.axis]:
            return None
        if self._mode == "off":
            return None
        if self._mode == "auto" and not (
            self._auto_ok
            and n >= max(self._auto_min_rows, self.MIN_ROWS)
        ):
            return None  # below the measured wire crossover
        if n == 0:
            return None  # nothing to ship; empty split is the host's
        cols_per_dest = None
        for attempt in (0, 1):
            try:
                # the injectable wire: a drop retries once (a transient
                # fault recovers in place), a second shot degrades to
                # the host wire byte-identically
                faults.check("mesh.device_wire")
                cols_per_dest, _srcs = exchange_columns_with_respill(
                    [batch.key_lo, batch.key_hi, batch.token, batch.diff],
                    np.asarray(shards, np.int64),
                    self.mesh,
                    self.axis,
                )
                break
            except faults.FaultInjected:
                with _STATS_LOCK:
                    _STATS["wire_faults"] += 1
                if attempt == 0:
                    continue
            except Exception as e:  # noqa: BLE001 — no usable devices
                # mid-run degrades to the host wire; the plan verifier's
                # donation guard is NOT a degradation — swallowing it
                # here would turn an invariant violation into a silent
                # host fallback (the vector plane propagates it loudly)
                from pathway_tpu.internals.verifier import (
                    PlanVerificationError,
                )

                if isinstance(e, PlanVerificationError):
                    raise
            with _STATS_LOCK:
                _STATS["host_degrades"] += 1
            return None
        with _STATS_LOCK:
            _STATS["invocations"] += 1
            _STATS["rows"] += n
        _dx.note_exchange_metrics(n)
        from pathway_tpu.engine.native.dataplane import NativeBatch

        out = []
        for d in range(n_shards):
            lo, hi, tok, diff = cols_per_dest[d]
            out.append(
                NativeBatch(
                    batch.tab, lo, hi, tok, diff,
                    # a split of pairwise-distinct +1 rows stays distinct
                    distinct_hint=batch.distinct_hint,
                )
            )
        return out


_ENGINE_EXCHANGER: ColumnExchanger | None = None


def engine_column_exchanger() -> ColumnExchanger | None:
    """Process-wide column exchanger for the engine's exchange sites,
    when the device plane is enabled and a mesh is constructible."""
    global _ENGINE_EXCHANGER
    if not _dx.enabled():
        return None
    if _ENGINE_EXCHANGER is None:
        try:
            _ENGINE_EXCHANGER = ColumnExchanger()
        except Exception:  # noqa: BLE001 — no usable devices
            return None
    return _ENGINE_EXCHANGER
