"""Key-hash record exchange over the device mesh — the ICI data plane.

Reference parity: timely's exchange pacts route each record to the worker
owning hash(key) % n_workers over shared-memory channels or TCP
(external/timely-dataflow/communication/src/networking.rs). Here the shuffle
of a batch of (key, payload) rows is ONE jit-compiled XLA program: each
shard sorts its rows into per-destination buckets (static capacity, padded)
and a single `all_to_all` moves the buckets across the interconnect. Scalar
control traffic stays on host; bulk numeric payloads ride ICI.

Static-shape design: XLA needs fixed shapes, so each shard sends exactly
`capacity` slots to every destination, padding unused slots with a validity
flag. capacity defaults to the full per-shard row count (worst case: all
rows hash to one destination); callers with balanced keys can pass a
smaller capacity and check `overflowed`.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

Array = jax.Array


class ExchangeResult(NamedTuple):
    keys: Array  # [shards, cap * shards] u32 — received keys per shard slot
    payloads: Array  # [shards, cap * shards, d] — received payloads
    valid: Array  # [shards, cap * shards] bool — slot occupancy
    # some bucket exceeded capacity: the overflowing rows were scattered
    # into the bucket's LAST slot with duplicate indices (XLA duplicate
    # scatter order is unspecified), so the whole result must be treated
    # as invalid when this is set — use exchange_by_key_checked for the
    # host wrapper that retries with doubled capacity instead
    overflowed: Array  # [] bool


def _bucketize(keys: Array, payloads: Array, n_shards: int, cap: int):
    """Sort one shard's rows into n_shards buckets of `cap` slots each."""
    dest = keys % n_shards  # [rows]
    # stable order: rows of destination d, in arrival order
    order = jnp.argsort(dest, stable=True)
    sorted_dest = dest[order]
    # slot within destination bucket = running index among same-destination rows
    same = sorted_dest[:, None] == jnp.arange(n_shards)[None, :]
    within = jnp.cumsum(same, axis=0)[jnp.arange(keys.shape[0]), sorted_dest] - 1
    counts = jnp.sum(same, axis=0)
    overflow = jnp.any(counts > cap)
    slot = sorted_dest * cap + jnp.minimum(within, cap - 1)
    bucket_keys = jnp.zeros((n_shards * cap,), keys.dtype).at[slot].set(keys[order])
    bucket_pay = (
        jnp.zeros((n_shards * cap,) + payloads.shape[1:], payloads.dtype)
        .at[slot]
        .set(payloads[order])
    )
    bucket_valid = (
        jnp.zeros((n_shards * cap,), bool)
        .at[slot]
        .set(within < cap)
    )
    return bucket_keys, bucket_pay, bucket_valid, overflow


def exchange_by_key(
    keys: Array,
    payloads: Array,
    mesh: Mesh,
    axis: str = "data",
    capacity: int | None = None,
) -> ExchangeResult:
    """Shuffle rows so shard s receives every row with key % n_shards == s.

    keys: [n] uint32 (row key hashes), sharded over `axis`.
    payloads: [n, d] numeric payloads, same sharding.
    Output arrays keep the shard dimension explicit: result.keys[s] are the
    rows now owned by shard s.
    """
    n_shards = mesh.shape[axis]
    rows_total = keys.shape[0]
    if rows_total % n_shards != 0:
        raise ValueError(f"row count {rows_total} not divisible by {n_shards}")
    rows_local = rows_total // n_shards
    cap = capacity or rows_local

    def local(k, p):
        bk, bp, bv, overflow = _bucketize(k, p, n_shards, cap)
        # [n_shards*cap] -> split into n_shards chunks -> all_to_all
        bk = bk.reshape(n_shards, cap)
        bp = bp.reshape((n_shards, cap) + p.shape[1:])
        bv = bv.reshape(n_shards, cap)
        rk = jax.lax.all_to_all(bk, axis, 0, 0, tiled=False)
        rp = jax.lax.all_to_all(bp, axis, 0, 0, tiled=False)
        rv = jax.lax.all_to_all(bv, axis, 0, 0, tiled=False)
        ov = jax.lax.pmax(overflow.astype(jnp.int32), axis)
        return (
            rk.reshape(1, n_shards * cap),
            rp.reshape((1, n_shards * cap) + p.shape[1:]),
            rv.reshape(1, n_shards * cap),
            ov.reshape(1),
        )

    fn = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=(P(axis), P(axis), P(axis), P(axis)),
    )
    rk, rp, rv, ov = jax.jit(fn)(keys, payloads)
    return ExchangeResult(
        keys=rk, payloads=rp, valid=rv, overflowed=jnp.any(ov > 0)
    )


def exchange_by_key_checked(
    keys: Array,
    payloads: Array,
    mesh: Mesh,
    axis: str = "data",
    capacity: int | None = None,
    max_retries: int = 3,
) -> ExchangeResult:
    """Host wrapper: retries the exchange with doubled capacity while
    `overflowed` is set (an overflowed result is corrupt — see
    ExchangeResult). Engine integrations must use this, never the raw
    primitive, so skewed batches cannot silently drop rows."""
    n_shards = mesh.shape[axis]
    cap = capacity or keys.shape[0] // n_shards
    for _ in range(max_retries + 1):
        result = exchange_by_key(keys, payloads, mesh, axis, capacity=cap)
        if not bool(result.overflowed):
            return result
        cap *= 2
    raise RuntimeError(
        f"exchange overflowed even at capacity {cap // 2} per bucket "
        f"({max_retries} retries) — key distribution is pathologically "
        "skewed; pre-aggregate or rebalance keys"
    )


@functools.partial(jax.jit, static_argnames=("n_shards",))
def partition_counts(keys: Array, n_shards: int) -> Array:
    """Histogram of destination shards — the host scheduler uses this to
    spot skew before committing to a capacity."""
    dest = keys % n_shards
    return jnp.bincount(dest, length=n_shards)
