"""JAX version compatibility shims.

The codebase targets the current JAX surface (``jax.shard_map`` became a
top-level export, and its replication check was renamed ``check_vma``);
older 0.4.x environments still ship ``shard_map`` under
``jax.experimental.shard_map`` with the ``check_rep`` spelling. Rather
than version-guarding every call site (engine exchange programs, the
sharded KNN merge, the ring-attention tests), one shim resolves the
canonical callable and installs it as ``jax.shard_map`` when the import
runs under an old release — the rest of the tree keeps writing
modern-idiom JAX.

Imported (and ``install()``-ed) from the packages that already import
jax at module scope (``ops``, ``parallel``, ``models``) — NOT from the
top-level ``pathway_tpu`` package, which deliberately keeps jax out of
its import graph so CPU-only engine users never pay the jax import.
"""

from __future__ import annotations

import functools
import inspect

import jax


def _resolve_shard_map():
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn, True
    from jax.experimental.shard_map import shard_map as legacy

    return legacy, False


_shard_map, _is_native = _resolve_shard_map()
_accepts_check_vma = "check_vma" in inspect.signature(_shard_map).parameters


@functools.wraps(_shard_map)
def shard_map(f=None, /, **kwargs):
    """``jax.shard_map`` with the modern keyword surface on any version.

    Accepts ``check_vma`` everywhere; on releases that predate the rename
    it is forwarded as ``check_rep`` (same meaning: skip the replication/
    varying-axes inference the program's collectives would fail)."""
    if not _accepts_check_vma and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    if f is None:
        return functools.partial(shard_map, **kwargs)
    return _shard_map(f, **kwargs)


def install() -> None:
    """Make ``jax.shard_map`` resolvable (idempotent). Old releases get
    the shim; new releases keep their native export untouched unless it
    rejects ``check_vma`` (never the case in practice)."""
    if not _is_native:
        jax.shard_map = shard_map
