"""Telemetry: traces + metrics export.

Reference parity: src/engine/telemetry.rs:436 — an OTLP exporter for run
spans and engine metrics, configured from the monitoring server setting.
Here the OpenTelemetry SDK is used when installed and an endpoint is
configured; otherwise a local JSONL exporter (PATHWAY_TELEMETRY_FILE)
records the same spans/metrics so runs remain observable in any
environment. Span structure mirrors the reference: one `run` root span,
`wave` spans per finalized timestamp (sampled), `checkpoint` spans, and
periodic operator-stats metric flushes.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Any
from pathway_tpu.analysis import lockgraph as _lockgraph

_LOCK = _lockgraph.register_lock("telemetry.registry", threading.Lock())


class _LocalExporter:
    """JSONL spans/metrics when no OTLP stack is available."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a")

    def export(self, record: dict) -> None:
        with _LOCK:
            self._f.write(json.dumps(record) + "\n")
            self._f.flush()

    def shutdown(self) -> None:
        with _LOCK:
            self._f.close()


_OTLP_PROVIDERS: dict[str, Any] = {}


class _OtlpExporter:
    """Real OpenTelemetry export (requires the opentelemetry-sdk +
    exporter packages and a collector endpoint). The TracerProvider is a
    process-wide singleton per endpoint and is NOT installed globally —
    a second pw.run() in the same process keeps exporting (installing
    globally would make later set_tracer_provider calls no-ops against a
    shut-down provider)."""

    def __init__(self, endpoint: str, run_id: str):
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (
            OTLPSpanExporter,
        )
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor

        provider = _OTLP_PROVIDERS.get(endpoint)
        if provider is None:
            resource = Resource.create({"service.name": "pathway-tpu"})
            provider = TracerProvider(resource=resource)
            provider.add_span_processor(
                BatchSpanProcessor(OTLPSpanExporter(endpoint=endpoint))
            )
            _OTLP_PROVIDERS[endpoint] = provider
        self._provider = provider
        self._tracer = provider.get_tracer("pathway_tpu")
        self.run_id = run_id

    def export(self, record: dict) -> None:
        # spans are emitted directly through the tracer; metric records
        # become span events on a short-lived span
        span = self._tracer.start_span(record.get("name", "metric"))
        for k, v in record.items():
            if isinstance(v, (str, int, float, bool)):
                span.set_attribute(k, v)
        span.end()

    def shutdown(self) -> None:
        # flush only: the provider is shared across runs in this process
        self._provider.force_flush()


class Telemetry:
    """Span/metric recorder; construct via Telemetry.create()."""

    def __init__(self, exporter: Any, run_id: str):
        self.exporter = exporter
        self.run_id = run_id

    @classmethod
    def create(cls, endpoint: str | None = None) -> "Telemetry | None":
        """Endpoint resolution: explicit arg > PATHWAY_MONITORING_SERVER
        (OTLP) > PATHWAY_TELEMETRY_FILE (local JSONL) > disabled."""
        run_id = str(uuid.uuid4())
        endpoint = endpoint or os.environ.get("PATHWAY_MONITORING_SERVER")
        if endpoint:
            try:
                return cls(_OtlpExporter(endpoint, run_id), run_id)
            except ImportError:
                pass  # no OTel SDK: fall through to the local exporter
        path = os.environ.get("PATHWAY_TELEMETRY_FILE")
        if path:
            return cls(_LocalExporter(path), run_id)
        return None

    # ----------------------------------------------------------- recording

    def span(self, name: str, **attrs: Any) -> "_Span":
        return _Span(self, name, attrs)

    def metric(self, name: str, value: float, **attrs: Any) -> None:
        self.exporter.export(
            {
                "kind": "metric",
                "name": name,
                "value": value,
                "run_id": self.run_id,
                "ts": time.time(),
                **attrs,
            }
        )

    def operator_stats(self, graph: Any) -> None:
        """Flush per-operator probes (rows in/out, cumulative latency) —
        the reference's OperatorStats export (graph.rs:988-995)."""
        for node in graph.nodes:
            self.exporter.export(
                {
                    "kind": "operator",
                    "operator": type(node).__name__,
                    "label": getattr(node, "label", None) or "",
                    "id": node.node_id,
                    "rows_in": node.rows_in,
                    "rows_out": node.rows_out,
                    "latency_ms": node.time_ns / 1e6,
                    "run_id": self.run_id,
                    "ts": time.time(),
                }
            )

    def export_event(self, event: dict) -> None:
        """Observability-spine subscriber: structured events (faults,
        breaker flips, device quarantines, mesh quiesces) flow out the
        same JSONL/OTLP pipe as spans and metrics. High-volume wave spans
        are ring-only by design (observability.ObservabilityPlane.record
        export=False) — they arrive as histograms instead."""
        self.exporter.export(
            {"kind": "event", "run_id": self.run_id, **event}
        )

    def shutdown(self) -> None:
        self.exporter.shutdown()


class _Span:
    def __init__(self, telemetry: Telemetry, name: str, attrs: dict):
        self.telemetry = telemetry
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0

    def __enter__(self) -> "_Span":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.telemetry.exporter.export(
            {
                "kind": "span",
                "name": self.name,
                "duration_ms": (time.perf_counter() - self.t0) * 1e3,
                "error": bool(exc[0]),
                "run_id": self.telemetry.run_id,
                "ts": time.time(),
                **self.attrs,
            }
        )


def attach_telemetry(session: Any, endpoint: str | None = None) -> Telemetry | None:
    """Wire run telemetry into a session: wave metrics every flush
    interval + operator stats, and a final flush at end of run."""
    telemetry = Telemetry.create(endpoint)
    if telemetry is None:
        return None
    state = {"waves": 0, "last_flush": time.monotonic()}

    def monitor(wave_time: int) -> None:
        state["waves"] += 1
        now = time.monotonic()
        if now - state["last_flush"] >= 1.0:
            state["last_flush"] = now
            telemetry.metric("pathway.waves", state["waves"], time=wave_time)
            telemetry.operator_stats(session.graph)

    session.monitors.append(monitor)
    return telemetry


__all__ = ["Telemetry", "attach_telemetry"]
