"""Embedders — text -> vector UDFs.

Reference parity: xpacks/llm/embedders.py — `BaseEmbedder` (:64),
`OpenAIEmbedder` (:85), `LiteLLMEmbedder` (:180),
`SentenceTransformerEmbedder` (:270, row-wise torch — the bottleneck the
north-star targets), `GeminiEmbedder` (:330).

TPU flagship: `JaxEmbedder` — the framework's own transformer encoder with a
microbatching async front: every concurrently in-flight call in a wave lands
in one device batch, so the engine's async-apply operator (which gathers a
wave's rows into one asyncio.gather) drives the TPU at full batch size
instead of row-at-a-time.
"""

from __future__ import annotations

import asyncio
import weakref
from typing import Any

import numpy as np

import pathway_tpu as pw
from pathway_tpu.internals import udfs
from pathway_tpu.internals.expression import ColumnExpression
from pathway_tpu.xpacks.llm._utils import _coerce_sync


class BaseEmbedder(pw.UDF):
    def get_embedding_dimension(self, **kwargs: Any) -> int:
        return len(_coerce_sync(self.__wrapped__)(".", **kwargs))

    def __call__(self, input: ColumnExpression, *args: Any, **kwargs: Any) -> ColumnExpression:
        return super().__call__(input, *args, **kwargs)


class OpenAIEmbedder(BaseEmbedder):
    """OpenAI embeddings API (reference: embedders.py:85)."""

    def __init__(
        self,
        *,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = "text-embedding-3-small",
        **openai_kwargs: Any,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        try:
            import openai
        except ImportError as e:
            raise ImportError(
                "OpenAIEmbedder requires `openai`; use JaxEmbedder for the "
                "on-TPU path"
            ) from e
        self.kwargs = {"model": model, **openai_kwargs}
        # one client for all rows — connection pooling matters on the
        # hottest path of the pipeline
        self.client = openai.AsyncOpenAI()

    async def __wrapped__(self, input: str, **kwargs: Any) -> np.ndarray:
        merged = {**self.kwargs, **kwargs}
        ret = await self.client.embeddings.create(input=[input or "."], **merged)
        return np.array(ret.data[0].embedding)


class LiteLLMEmbedder(BaseEmbedder):
    """LiteLLM embeddings (reference: embedders.py:180)."""

    def __init__(
        self,
        *,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = None,
        **kwargs: Any,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        try:
            import litellm  # noqa: F401
        except ImportError as e:
            raise ImportError("LiteLLMEmbedder requires `litellm`") from e
        self.kwargs = {"model": model, **kwargs}

    async def __wrapped__(self, input: str, **kwargs: Any) -> np.ndarray:
        import litellm

        merged = {**self.kwargs, **kwargs}
        ret = await litellm.aembedding(input=[input or "."], **merged)
        return np.array(ret.data[0]["embedding"])


class GeminiEmbedder(BaseEmbedder):
    """Google Gemini embeddings (reference: embedders.py:330)."""

    def __init__(
        self,
        *,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = "models/embedding-001",
        **kwargs: Any,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        try:
            import google.generativeai as genai  # noqa: F401
        except ImportError as e:
            raise ImportError("GeminiEmbedder requires `google-generativeai`") from e
        self.kwargs = {"model": model, **kwargs}

    def __wrapped__(self, input: str, **kwargs: Any) -> np.ndarray:
        import google.generativeai as genai

        merged = {**self.kwargs, **kwargs}
        ret = genai.embed_content(content=input or ".", **merged)
        return np.array(ret["embedding"])


class SentenceTransformerEmbedder(BaseEmbedder):
    """Local sentence-transformers torch model, row-wise
    (reference: embedders.py:270). Kept for drop-in compatibility; the TPU
    path is JaxEmbedder."""

    def __init__(
        self,
        model: str,
        call_kwargs: dict = {},
        device: str = "cpu",
        **init_kwargs: Any,
    ):
        super().__init__()
        try:
            from sentence_transformers import SentenceTransformer
        except ImportError as e:
            raise ImportError(
                "SentenceTransformerEmbedder requires `sentence_transformers`; "
                "use JaxEmbedder for the on-TPU path"
            ) from e
        self.model = SentenceTransformer(model, device=device, **init_kwargs)
        self.kwargs = dict(call_kwargs)

    def __wrapped__(self, text: str, **kwargs: Any) -> np.ndarray:
        merged = {**self.kwargs, **kwargs}
        return self.model.encode(text or ".", **merged)


# The wave batcher moved into the device plane: coalescing is a serving
# concern shared by every XLA-backed stage (embed, generate, batched
# UDFs). Kept under its historical name — callers (and the bench's phase
# probes) patch `<udf>._batcher.flush_fn`.
from pathway_tpu.engine.device_plane import (  # noqa: E402
    WaveCoalescer as _MicroBatcher,
    get_device_plane,
)


def bucket_len(longest: int, cap: int) -> int:
    """Power-of-two sequence bucket (>=16) so the jit cache sees few
    distinct shapes as lengths vary — shared by the embedder's right-pad
    and the chat's left-pad batching (the device plane's BucketPolicy)."""
    return get_device_plane().buckets.seq_bucket(longest, cap)


def pad_left_rows(
    rows: list, cap: int, pad_rows_to: int | None = None,
    n_rows: int | None = None,
):
    """Left-pad variable-length token rows into (ids, mask) int32 arrays
    at a bucketed width (generation convention — real tokens end at the
    last column, so last-position logits are every row's next token).
    The batch dimension pads with all-masked rows so arbitrary wave
    sizes hit few jit shapes: to exactly `n_rows` (callers pass the
    device plane's row bucket), to a multiple of `pad_rows_to`, or to
    the plane's power-of-two bucket by default."""
    bucket = bucket_len(max((len(r) for r in rows), default=1) or 1, cap)
    if n_rows is not None:
        n = n_rows
    elif pad_rows_to is not None:
        n = ((len(rows) + pad_rows_to - 1) // pad_rows_to) * pad_rows_to
    else:
        n = get_device_plane().buckets.rows_bucket(len(rows))
    ids = np.zeros((n, bucket), np.int32)
    mask = np.zeros((n, bucket), np.int32)
    for i, r in enumerate(rows):
        r = r[-bucket:]
        ids[i, bucket - len(r):] = r
        mask[i, bucket - len(r):] = 1
    return ids, mask


class JaxEmbedder(BaseEmbedder):
    """The TPU-native embedder: hash tokenizer + the flagship JAX encoder.

    Replaces the reference's per-row torch SentenceTransformer call
    (embedders.py:270) with wave-batched XLA encoding. Pass trained `params`
    for a real model; defaults give a deterministic random-weight encoder
    (useful for pipelines and tests — similarity structure still follows
    token overlap thanks to mean pooling).
    """

    def __init__(
        self,
        config: Any = None,
        params: Any = None,
        tokenizer: Any = None,
        *,
        max_batch: int = 4096,
        cache_strategy: udfs.CacheStrategy | None = None,
    ):
        super().__init__(
            executor=udfs.async_executor(), cache_strategy=cache_strategy
        )
        import functools

        import jax

        from pathway_tpu.models import embedder_config, transformer
        from pathway_tpu.models.tokenizer import HashTokenizer

        self.config = config or embedder_config(
            vocab_size=32768, d_model=256, n_heads=8, n_layers=4, d_ff=1024,
            max_len=128, embed_dim=256,
        )
        if params is None:
            params = transformer.init_params(jax.random.PRNGKey(0), self.config)
        # serving keeps bf16-resident params (half the HBM weight reads;
        # no per-matmul casts inside the jitted program)
        self.params = jax.device_put(
            transformer.cast_params(params, self.config.dtype)
        )
        self.tokenizer = tokenizer or HashTokenizer(
            vocab_size=self.config.vocab_size, max_len=self.config.max_len
        )
        # the device plane owns the dispatch: bucketed shapes, compile
        # ledger, off-loop flushes (a slow generate elsewhere never
        # blocks this embedder's coalescer)
        self._plane = get_device_plane()
        self._encode = self._plane.program(
            self._plane.unique_name("embed_encode"),
            functools.partial(transformer.encode, cfg=self.config),
        )
        self._batcher = self._plane.coalescer(
            self._encode_batch, max_batch=max_batch
        )
        # release the per-instance program when this embedder dies — the
        # plane is process-global and would otherwise pin it forever
        self._finalizer = weakref.finalize(
            self, self._plane.drop_program, self._encode.name
        )

    def _encode_batch(self, texts: list[str]) -> list[np.ndarray]:
        import jax.numpy as jnp

        ids, mask = self.tokenizer.batch([t or "." for t in texts])
        # pad rows + seq up to the plane's power-of-two buckets: ragged
        # live waves hit a bounded set of XLA programs
        (ids, mask), rows = self._plane.pad_rows([ids, mask], ids.shape[0])
        seq = ids.shape[1]
        bucket = bucket_len(seq, self.config.max_len)
        if bucket != seq:
            ids = np.pad(ids, ((0, 0), (0, bucket - seq)))
            mask = np.pad(mask, ((0, 0), (0, bucket - seq)))
        out = np.asarray(
            self._encode(
                self.params, jnp.asarray(ids), jnp.asarray(mask),
                bucket=(rows, bucket),
            )
        )
        return [out[i] for i in range(len(texts))]

    async def __wrapped__(self, input: str, **kwargs: Any) -> np.ndarray:
        return await self._batcher.submit(input)

    def encode_many(self, texts: list[str]) -> list[np.ndarray]:
        """Synchronous bulk encode (used by rerankers and tests)."""
        return self._encode_batch(texts)
