"""Typed binary codec for journals + operator snapshots (codec.py): the
reference's bincode equivalent. Covers the full Value domain, the engine
state containers, crc torn-tail detection, and the explicit pickle
escape for opaque state."""

import struct

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.engine.core import KeyedState, MultisetState
from pathway_tpu.internals.datetime_types import (
    DateTimeNaive,
    DateTimeUtc,
    Duration,
)
from pathway_tpu.internals.errors import ERROR
from pathway_tpu.internals.keys import Key
from pathway_tpu.persistence import codec


def rt(v):
    return codec.decode_value(codec.encode_value(v))


VALUES = [
    None,
    True,
    False,
    0,
    -1,
    2**62,
    -(2**70),  # bigint path
    3.5,
    float("inf"),
    "héllo",
    b"\x00\xff raw",
    Key(2**127 + 17),
    (1, "a", None),
    [1, [2, [3]]],
    {"k": 1, 2: "v", Key(5): (1, 2)},
    {1, 2, 3},
    frozenset({"a"}),
    DateTimeNaive(ns=1_700_000_000_123_456_789),
    DateTimeUtc(ns=42),
    Duration(nanoseconds=-5_000),
    np.arange(6, dtype=np.float32).reshape(2, 3),
    np.array([], dtype=np.int64),
]


@pytest.mark.parametrize("v", VALUES, ids=[repr(v)[:30] for v in VALUES])
def test_roundtrip(v):
    got = rt(v)
    if isinstance(v, np.ndarray):
        assert got.dtype == v.dtype and got.shape == v.shape
        assert np.array_equal(got, v)
    else:
        assert got == v
        assert type(got) is type(v) or isinstance(v, (bool,))


def test_nan_roundtrip():
    got = rt(float("nan"))
    assert got != got


def test_error_singleton():
    assert rt(ERROR) is ERROR
    assert rt((1, ERROR, "x"))[1] is ERROR


def test_json_roundtrip():
    v = pw.Json({"a": [1, 2, {"b": None}], "c": "s"})
    got = rt(v)
    assert isinstance(got, pw.Json)
    assert got.value == v.value


def test_state_containers():
    ks = KeyedState()
    ks.rows[Key(1)] = ("a", 2)
    ks.rows[Key(2)] = (None, ERROR)
    got = rt(ks)
    assert isinstance(got, KeyedState)
    assert got.rows == {Key(1): ("a", 2), Key(2): (None, ERROR)}

    ms = MultisetState()
    ms.update_one(("g",), ((Key(3), ("r",)), 1), 2)
    got = rt(ms)
    assert isinstance(got, MultisetState)
    assert got.groups == ms.groups


def test_defaultdict_factories_survive():
    from collections import defaultdict

    d = defaultdict(int)
    d[Key(9)] += 4
    got = rt(d)
    assert got[Key(9)] == 4
    assert got["missing"] == 0  # factory preserved

    dl = defaultdict(list)
    dl["x"].append(1)
    got = rt(dl)
    assert got["x"] == [1] and got["y"] == []


class _Acc:
    def __init__(self):
        self.total = 7


def test_opaque_pickle_escape():
    got = rt({"acc": _Acc()})
    assert got["acc"].total == 7


def test_record_framing_and_torn_tail():
    recs = [(1, ("a",), 1), (2, ("b",), -1), (3, ("c",), 1)]
    buf = b"".join(codec.encode_record(r) for r in recs)
    assert list(codec.read_records(buf)) == recs
    # truncate mid-payload of the last record: first two survive
    assert list(codec.read_records(buf[:-3])) == recs[:2]
    # flip a payload byte in the last record: crc rejects it
    bad = bytearray(buf)
    bad[-1] ^= 0xFF
    assert list(codec.read_records(bytes(bad))) == recs[:2]
    # truncated header
    assert list(codec.read_records(buf + b"\x01\x02")) == recs


def test_no_pickle_for_plain_rows():
    """The common journal event shape must not touch the pickle escape."""
    payload = codec.encode_value(
        (2**127, ("word", 3, 1.5, None, True, Key(4)), 1)
    )
    assert bytes([0x10]) not in payload.split(b"word")[0]  # no escape tag
    # decode proves self-describing layout
    kv, row, diff = codec.decode_value(payload)
    assert kv == 2**127 and diff == 1
    assert row == ("word", 3, 1.5, None, True, Key(4))


def test_snapshot_store_detects_corruption(tmp_path):
    from pathway_tpu.persistence import OperatorSnapshotStore

    ops = OperatorSnapshotStore(str(tmp_path))
    ops.write("n1", 3, {"x": [1, 2]})
    assert ops.read("n1", 3) == {"x": [1, 2]}
    assert ops.read("n1", 4) is None
    p = ops._path("n1", 3)
    raw = bytearray(open(p, "rb").read())
    raw[-1] ^= 0x55
    open(p, "wb").write(bytes(raw))
    with pytest.raises(ValueError):
        ops.read("n1", 3)


def test_object_dtype_array_roundtrip():
    arr = np.array(["a", 1, None], dtype=object)
    got = rt(arr)
    assert got.dtype == object and list(got) == ["a", 1, None]


def test_legacy_format_fails_loudly(tmp_path):
    """A journal segment in an unknown (e.g. pre-codec pickle) layout
    must raise, not parse as an empty torn tail that silently drops
    journaled history."""
    import pickle

    from pathway_tpu.persistence import SegmentedJournal

    j = SegmentedJournal(str(tmp_path))
    legacy = tmp_path / "src.0.seg"
    with open(legacy, "wb") as f:
        pickle.dump((1, ("a",), 1), f)
        pickle.dump((2, ("b",), 1), f)
    with pytest.raises(ValueError, match="unrecognized"):
        j.load_from("src", 0)
    with pytest.raises(ValueError, match="unrecognized"):
        j.total_events("src")
    # the WRITER must refuse too — appending would bury the legacy data
    with pytest.raises(ValueError, match="refusing to append"):
        j.open_segment("src", 0)


def test_fingerprint_distinguishes_partial_kwargs():
    """Regression: transient-object id reuse must not collapse distinct
    parameter values into one fingerprint."""
    import functools

    from pathway_tpu.internals.fingerprint import fingerprint_spec

    def f(x, y):
        return x * y

    class Spec:
        kind = "rowwise"

        def __init__(self, y):
            self.params = {"fn": functools.partial(f, y=y)}

    assert fingerprint_spec(Spec(2)) != fingerprint_spec(Spec(99))


def test_structured_dtype_roundtrip():
    """Compound dtypes can't rebuild from str(dtype): they must take the
    escape path instead of encoding undecodably."""
    arr = np.zeros(3, dtype=[("a", "<i4"), ("b", "<f8")])
    arr["a"] = [1, 2, 3]
    got = rt(arr)
    assert got.dtype == arr.dtype and list(got["a"]) == [1, 2, 3]


def test_decoded_arrays_are_writeable():
    got = rt(np.arange(4, dtype=np.float64))
    got[0] = 99.0  # replayed rows must stay mutable like fresh ones
    assert got[0] == 99.0


def test_partial_magic_is_torn_not_foreign(tmp_path):
    """A crash can truncate the 6-byte header itself: that's a torn
    (empty) segment, not a foreign format."""
    from pathway_tpu.persistence import SegmentedJournal

    j = SegmentedJournal(str(tmp_path))
    with open(tmp_path / "src.0.seg", "wb") as f:
        f.write(codec.MAGIC[:3])
    assert j.load_from("src", 0) == []
    assert j.total_events("src") == 0
    # reopening the segment repairs the header instead of appending after it
    w = j.open_segment("src", 0)
    w.append(Key(1).value, ("x",), 1)
    w.flush(sync=True)
    w.close()
    assert [r[2] for r in j.load_from("src", 0)] == [("x",)]


def test_reopen_truncates_torn_tail(tmp_path):
    """Crash mid-record, then reopen + append: the torn frame must be
    dropped, not buried under new (then-unreachable) events."""
    from pathway_tpu.persistence import SegmentedJournal

    j = SegmentedJournal(str(tmp_path))
    w = j.open_segment("src", 0)
    w.append(Key(1).value, ("a",), 1)
    w.append(Key(2).value, ("b",), 1)
    w.flush(sync=True)
    w.close()
    p = tmp_path / "src.0.seg"
    raw = open(p, "rb").read()
    open(p, "wb").write(raw[:-4])  # torn tail: second record truncated
    assert j.total_events("src") == 1
    w = j.open_segment("src", 1)
    w.append(Key(3).value, ("c",), 1)
    w.flush(sync=True)
    w.close()
    assert [r[2] for r in j.load_from("src", 0)] == [("a",), ("c",)]
    # torn-FIRST-record case: only MAGIC + garbage frame
    p2 = tmp_path / "two.0.seg"
    open(p2, "wb").write(codec.MAGIC + b"\x99\x00\x00\x00XX")
    w = j.open_segment("two", 0)
    w.append(Key(4).value, ("d",), 1)
    w.flush(sync=True)
    w.close()
    assert [r[2] for r in j.load_from("two", 0)] == [("d",)]


def test_count_records_skips_decode(monkeypatch):
    recs = [(1, ("a",), 1), (2, ("b",), 1)]
    buf = b"".join(codec.encode_record(r) for r in recs)

    def boom(*a, **k):
        raise AssertionError("count_records must not decode payloads")

    monkeypatch.setattr(codec, "decode_value", boom)
    assert codec.count_records(buf) == 2


def test_journal_roundtrip_typed(tmp_path):
    from pathway_tpu.persistence import SegmentedJournal

    j = SegmentedJournal(str(tmp_path))
    w = j.open_segment("src", 0)
    w.append(Key(1).value, ("a", Duration(nanoseconds=9)), 1)
    w.append(Key(2).value, (np.int64(5), 2.5), -1)
    w.flush(sync=True)
    w.close()
    events = j.load_from("src", 0)
    assert [(o, kv) for (o, kv, _r, _d) in events] == [
        (0, Key(1).value), (1, Key(2).value)
    ]
    assert events[0][2] == ("a", Duration(nanoseconds=9))
    assert events[1][2] == (5, 2.5)
    assert j.total_events("src") == 2
