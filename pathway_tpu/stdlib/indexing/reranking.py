"""Two-stage retrieval: ANN first stage + batched on-device rerank.

`RerankedSlabIndex` wraps any `VectorSlabIndex`-family host index (in
practice the tiered `IvfPqIndex`) and recovers the recall the first
stage loses to probe misses with the reference's ADAPTIVE strategy
(`AdaptiveRAGQuestionAnswerer` / `answer_with_geometric_rag_strategy`
in xpacks/llm/question_answering.py), transplanted from the LLM loop
to the index seam:

* round 0 overfetches ``k * expand`` candidates at the base nprobe;
* the batched reranker (`ops/rerank.py`) scores every candidate's
  full-precision row in one bucketed device dispatch;
* if any of the final top-k sits in the TAIL ``1/factor`` fraction of
  the first-stage ranking while the candidate horizon was clipped
  (the first stage returned as many rows as asked), the winners were
  plausibly cut off — re-query geometrically: ``nprobe * factor``,
  ``fetch * factor``, up to ``max_rounds``;
* independently, if the best UNPROBED centroid scores at least as well
  as the current k-th neighbor (the classic IVF early-termination
  bound, inverted), a probe miss is plausible and the re-query fires
  even when the two stages agree rank-for-rank.

Expanding nprobe (not just k) is what actually recovers recall: the
ANN output is already exact-rescored within the probed lists, so a
wider k alone re-ranks the same probe footprint, while a wider nprobe
reaches rows the first stage never saw.

Results keep the host-index contract: ``[(key, dist)]`` ascending by
``(dist, key)`` with the index's own distance convention (cos ->
``1 - sim``, dot/l2sq -> ``-score``) — a reranked index is a drop-in
`host_index_factory` product for `ExternalIndexNode`.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from pathway_tpu.ops.rerank import BatchedReranker
from pathway_tpu.ops import ivf as _ivf
from pathway_tpu.stdlib.indexing.host_indexes import (
    HostIndex,
    Matches,
    _as_vector,
)


class RerankedSlabIndex(HostIndex):
    """Second-stage wrapper over a slab-family host index (module doc)."""

    def __init__(
        self,
        inner,
        *,
        expand: int = 4,
        factor: int = 2,
        max_rounds: int = 3,
        device: bool = True,
        scorer=None,
    ):
        self.inner = inner
        self.expand = max(1, int(expand))
        self.factor = max(2, int(factor))
        self.max_rounds = max(1, int(max_rounds))
        self.reranker = BatchedReranker(
            getattr(inner, "metric", "cos"), device=device, scorer=scorer
        )
        self.counters = {"rerank_rounds": 0, "rerank_expansions": 0}

    # ------------------------------------------------------- delegation

    def add(self, key, data, metadata=None) -> None:
        self.inner.add(key, data, metadata)

    def remove(self, key) -> None:
        self.inner.remove(key)

    def __getattr__(self, name: str):
        # transparent for everything the engine/verifier touches on the
        # wrapped index (vectors, slot_of, stats, index_tiers plumbing…);
        # underscore names stay local so pickling can't recurse
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self.inner, name)

    # ------------------------------------------------------------ search

    def search(self, query, k, metadata_filter=None) -> Matches:
        return self.search_batch([(query, k, metadata_filter)])[0]

    def search_batch(self, items) -> list[Matches]:
        inner = self.inner
        n = len(items)
        results: list[Matches | None] = [None] * n
        pending = list(range(n))
        mult = 1
        for round_no in range(self.max_rounds):
            self.counters["rerank_rounds"] += 1
            fetch = [
                (items[i][0], items[i][1] * self.expand * mult, items[i][2])
                for i in pending
            ]
            # lazy: pathway_tpu.indexing re-exports this package, so a
            # module-level import would be circular
            from pathway_tpu.indexing.ann import IvfPqIndex

            nprobe = None
            if isinstance(inner, IvfPqIndex):
                nprobe = self._nprobe(mult)
                cand_lists = inner.search_batch(fetch, nprobe=nprobe)
            else:
                cand_lists = inner.search_batch(fetch)
            reranked = self._rerank(
                [items[i] for i in pending], cand_lists, nprobe
            )
            still = []
            last_round = round_no == self.max_rounds - 1
            for idx_in_pending, i in enumerate(pending):
                matches, tail_hit, probe_risk = reranked[idx_in_pending]
                requested = items[i][1] * self.expand * mult
                clipped = len(cand_lists[idx_in_pending]) >= requested
                # two independent expansion triggers: a winner near the
                # clipped candidate horizon (the reranker DISAGREES with
                # the first stage — a wider fetch may promote more), or a
                # competitive unprobed centroid (a probe MISS is
                # plausible — only a wider nprobe can reach those rows)
                if ((tail_hit and clipped) or probe_risk) and not last_round:
                    still.append(i)
                else:
                    results[i] = matches
            if not still:
                break
            pending = still
            mult *= self.factor
            self.counters["rerank_expansions"] += len(still)
        return [r if r is not None else [] for r in results]

    def _nprobe(self, mult: int) -> int | None:
        base = self.inner.nprobe
        if base is None:
            gen = getattr(self.inner, "_gen", None)
            if gen is None:
                return None
            base = _ivf.auto_nprobe(gen.n_lists)
        return base * mult

    def _probe_risk(self, qmat: np.ndarray, nprobe, kth_scores) -> np.ndarray:
        """Per-query: could an UNPROBED list hold a better neighbor than
        the current k-th? True when the (nprobe+1)-th closest centroid
        scores at least as well as the k-th reranked hit — the classic
        IVF early-termination bound, inverted into an expansion trigger.
        Queries with -inf kth (fewer than k live candidates) always
        flag. Without a trained IVF generation there is nothing to
        probe wider, so the signal is all-False."""
        gen = getattr(self.inner, "_gen", None)
        if nprobe is None or gen is None or nprobe >= gen.n_lists:
            return np.zeros(len(qmat), bool)
        cents = np.asarray(gen.centroids, np.float32)
        q = qmat
        metric = self.reranker.metric
        if metric == "cos":
            q = q / np.maximum(
                np.linalg.norm(q, axis=1, keepdims=True), 1e-12
            )
            cn = cents / np.maximum(
                np.linalg.norm(cents, axis=1, keepdims=True), 1e-12
            )
            cscore = q @ cn.T
        elif metric == "l2sq":
            cscore = -(
                np.sum(q * q, axis=1, keepdims=True)
                - 2.0 * (q @ cents.T)
                + np.sum(cents * cents, axis=1)[None, :]
            )
        else:  # dot
            cscore = q @ cents.T
        # score of the BEST centroid left unprobed = rank-nprobe entry
        # (0-indexed) of the descending centroid ranking
        part = np.partition(-cscore, nprobe, axis=1)
        best_unprobed = -part[:, nprobe]
        return best_unprobed >= np.asarray(kth_scores, np.float32)

    def _rerank(
        self, pend_items, cand_lists, nprobe=None
    ) -> list[tuple[Matches, bool, bool]]:
        """One batched scoring pass. Returns per query (top-k matches in
        the host-index convention, tail-hit flag, probe-risk flag) for
        the adaptive loop."""
        inner = self.inner
        B = len(pend_items)
        C = max((len(c) for c in cand_lists), default=0)
        if C == 0:
            return [([], False, False) for _ in pend_items]
        d = inner.dim
        qmat = np.zeros((B, d), np.float32)
        cands = np.zeros((B, C, d), np.float32)
        valid = np.zeros((B, C), bool)
        keys: list[list] = []
        for b, ((query, _k, _f), matches) in enumerate(
            zip(pend_items, cand_lists)
        ):
            qmat[b] = _as_vector(query)
            row_keys = []
            for c, (key, _dist) in enumerate(matches):
                slot = inner.slot_of.get(key)
                if slot is None:  # retracted between stages: skip
                    continue
                cands[b, c] = inner.vectors[slot]
                valid[b, c] = True
                row_keys.append((c, key))
            keys.append(row_keys)
        scores = self.reranker.scores(qmat, cands, valid)
        metric = self.reranker.metric
        packed = []
        kth_scores = np.full(B, -np.inf, np.float32)
        for b, (item, row_keys) in enumerate(zip(pend_items, keys)):
            k = item[1]
            scored = [
                (float(scores[b, c]), c, key)
                for c, key in row_keys
                if np.isfinite(scores[b, c])
            ]
            # deterministic: score desc, then key — the same tie rule as
            # the first stage's (dist, key) ascending order
            scored.sort(key=lambda t: (-t[0], t[2].value))
            top = scored[:k]
            if len(top) == k:
                kth_scores[b] = top[-1][0]
            if metric in ("cos", "cosine"):
                matches = [(key, 1.0 - s) for s, _c, key in top]
            else:
                matches = [(key, -s) for s, _c, key in top]
            n_cand = len(row_keys)
            tail_start = n_cand - max(1, n_cand // self.factor)
            tail_hit = any(c >= tail_start for _s, c, _key in top)
            packed.append((matches, tail_hit))
        risk = self._probe_risk(qmat, nprobe, kth_scores)
        return [
            (matches, tail_hit, bool(risk[b]))
            for b, (matches, tail_hit) in enumerate(packed)
        ]
