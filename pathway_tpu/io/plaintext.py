"""pw.io.plaintext (reference: io/plaintext)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import fs


def read(path: Any, *, mode: str = "streaming", **kwargs: Any):
    return fs.read(path, format="plaintext", mode=mode, **kwargs)


def write(table: Any, filename: Any, **kwargs: Any) -> None:
    fs.write(table, filename, format="plaintext", **kwargs)
