"""Tier-1 guards for the incremental IVF-PQ ANN subsystem.

Three contracts (docs/retrieval.md):
* **recall** — recall@10 >= 0.95 vs the exact f32 scan at default
  nprobe on a seeded clustered corpus;
* **zset correctness under churn** — interleaved add / retract /
  retrain must never surface a tombstoned row (no leaks) and never
  lose a live one (no lost inserts);
* **kill switch** — PATHWAY_ANN=0 reproduces exact-search rankings
  byte-identically through the whole InnerIndex/lowering stack.
"""

from __future__ import annotations

import pickle
import threading

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.indexing import IvfPqIndex, ann_enabled
from pathway_tpu.internals.keys import Key
from pathway_tpu.stdlib.indexing import DataIndex, BruteForceKnn, IvfPqKnn
from pathway_tpu.stdlib.indexing.host_indexes import VectorSlabIndex

DIM = 32


def _clustered(n: int, seed: int = 0, n_clusters: int = 40) -> np.ndarray:
    """Mixture-of-gaussians corpus — the geometry real embedding spaces
    have, and the one IVF routing exists for."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(n_clusters, DIM))
    return (
        centers[rng.integers(0, n_clusters, n)]
        + 0.15 * rng.normal(size=(n, DIM))
    ).astype(np.float32)


def _load(index, docs: np.ndarray, start: int = 0) -> list[Key]:
    keys = [Key(start + i) for i in range(len(docs))]
    for key, vec in zip(keys, docs):
        index.add(key, vec)
    return keys


def _exact_reference(docs: np.ndarray) -> VectorSlabIndex:
    # device=False: the reference must be the true f32 ranking, not the
    # bf16 slab mirror (its ~2^-8 rounding scrambles near-ties and would
    # penalize the ANN's f32 rescore for being MORE exact)
    ex = VectorSlabIndex(dimensions=DIM, device=False)
    _load(ex, docs)
    return ex


def _recall_at(res, ref, k: int = 10) -> float:
    vals = []
    for a, b in zip(res, ref):
        got = {key for key, _ in a[:k]}
        want = {key for key, _ in b[:k]}
        vals.append(len(got & want) / max(len(want), 1))
    return float(np.mean(vals))


# ----------------------------------------------------------- recall guard


def test_ann_recall_guard_at_default_nprobe():
    """The tier-1 quality bar: recall@10 >= 0.95 vs exact brute force on
    a seeded corpus, default nprobe, after incremental (not one-shot)
    loading."""
    docs = _clustered(4000, seed=0)
    ann = IvfPqIndex(dimensions=DIM, background_retrain=False, seed=0)
    _load(ann, docs)
    assert ann.stats()["trained"]
    rng = np.random.default_rng(1)
    q = docs[rng.choice(len(docs), 50)] + 0.05 * rng.normal(size=(50, DIM))
    items = [(q[i], 10, None) for i in range(len(q))]
    res = ann.search_batch(items)
    ref = _exact_reference(docs).search_batch(items)
    recall = _recall_at(res, ref)
    assert recall >= 0.95, f"recall@10 {recall} < 0.95 at default nprobe"
    # the self-reported gauge agrees with the external measurement
    assert ann.measured_recall() >= 0.95


def test_ann_nprobe_is_a_per_query_knob():
    """Raising nprobe toward L approaches the exact ranking; the knob is
    per search call, not per index build."""
    docs = _clustered(3000, seed=2)
    ann = IvfPqIndex(dimensions=DIM, background_retrain=False, seed=0)
    _load(ann, docs)
    L = ann.stats()["lists"]
    q = _clustered(20, seed=3)
    items = [(q[i], 10, None) for i in range(len(q))]
    ref = _exact_reference(docs).search_batch(items)
    wide = _recall_at(ann.search_batch(items, nprobe=L), ref)
    narrow = _recall_at(ann.search_batch(items, nprobe=1), ref)
    assert wide >= 0.95
    assert wide >= narrow


# ------------------------------------------------------ churn correctness


def test_ann_adversarial_churn():
    """Interleaved add / retract / re-add / retrain: results are always
    a subset of live rows (no tombstone leaks) and every live row stays
    findable by its own vector (no lost inserts)."""
    rng = np.random.default_rng(42)
    docs = _clustered(2000, seed=4)
    ann = IvfPqIndex(
        dimensions=DIM, background_retrain=False, train_min=256, seed=0
    )
    live: dict[Key, np.ndarray] = {}
    next_id = 0

    def check():
        assert set(ann.key_of.values()) == set(live)
        sample = rng.choice(len(live), min(30, len(live)), replace=False)
        keys = list(live)
        items = [(live[keys[i]], 5, None) for i in sample]
        res = ann.search_batch(items)
        for i, matches in zip(sample, res):
            got = [key for key, _ in matches]
            assert set(got) <= set(live), "tombstoned row surfaced"
            assert keys[i] in got, "live row lost from its own neighborhood"

    for round_ in range(6):
        # adds (fresh ids)
        for _ in range(300):
            vec = docs[next_id % len(docs)]
            key = Key(next_id)
            ann.add(key, vec)
            live[key] = vec
            next_id += 1
        # retracts
        if len(live) > 200:
            for key in rng.choice(list(live), 120, replace=False):
                ann.remove(key)
                del live[key]
        # in-place value updates (zset -old +new on one key) — each a
        # DISTINCT vector (identical vectors tie at distance 0 and the
        # self-query check below would be asserting tie-break luck)
        for key in rng.choice(list(live), 40, replace=False):
            vec = (
                docs[int(rng.integers(0, len(docs)))]
                + 0.03 * rng.normal(size=DIM)
            ).astype(np.float32)
            ann.add(key, vec)
            live[key] = vec
        if round_ % 2 == 1:
            ann.retrain_now()
        check()
    stats = ann.stats()
    assert stats["trained"] and stats["retrains"] >= 3


def test_ann_compaction_drops_tombstones():
    docs = _clustered(2000, seed=5)
    ann = IvfPqIndex(
        dimensions=DIM, background_retrain=False, compact_frac=0.2, seed=0
    )
    keys = _load(ann, docs)
    base = ann.stats()["compactions"]
    for key in keys[: len(keys) // 2]:
        ann.remove(key)
    stats = ann.stats()
    assert stats["compactions"] > base
    assert stats["tombstone_frac"] <= 0.2 + 1e-9
    # post-compaction searches stay correct
    items = [(docs[i], 5, None) for i in range(1500, 1520)]
    live = set(ann.key_of.values())
    for matches in ann.search_batch(items):
        assert {key for key, _ in matches} <= live


def test_ann_spill_then_resplit():
    """Drift the distribution after training: appends spill past their
    preferred lists, the index schedules a retrain (the re-split), and
    the new generation absorbs the drift."""
    ann = IvfPqIndex(
        dimensions=DIM, background_retrain=False, train_min=256, seed=0,
        retrain_factor=100.0,  # isolate the spill trigger from the size one
    )
    _load(ann, _clustered(1500, seed=6))
    spills_before = ann.stats()["spills"]
    retrains_before = ann.stats()["retrains"]
    # a new tight cluster the trained partition knows nothing about
    rng = np.random.default_rng(7)
    point = rng.normal(size=DIM)
    drift = (point + 0.02 * rng.normal(size=(900, DIM))).astype(np.float32)
    _load(ann, drift, start=10_000)
    stats = ann.stats()
    assert stats["spills"] > spills_before
    assert stats["retrains"] > retrains_before, "chronic spill must re-split"
    q = [(drift[i], 10, None) for i in range(10)]
    for matches in ann.search_batch(q):
        assert len(matches) == 10


def test_ann_background_retrain_off_wave_path():
    """Queries keep answering (old generation) while a retrain runs on
    another thread; the swap is atomic and results stay ⊆ live."""
    docs = _clustered(3000, seed=8)
    ann = IvfPqIndex(dimensions=DIM, background_retrain=True, seed=0)
    _load(ann, docs)
    ann.wait_retrain()
    assert ann.stats()["trained"]
    live = set(ann.key_of.values())
    stop = threading.Event()
    errors: list[Exception] = []

    def churn_retrain():
        try:
            while not stop.is_set():
                ann.retrain_now()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    t = threading.Thread(target=churn_retrain, daemon=True)
    t.start()
    try:
        items = [(docs[i], 10, None) for i in range(40)]
        for _ in range(15):
            for matches in ann.search_batch(items):
                assert {key for key, _ in matches} <= live
                assert matches, "queries must not block or blank on retrain"
    finally:
        stop.set()
        t.join(timeout=30)
    assert not errors


# ------------------------------------------------------------ kill switch


def _rankings(index_cls_kwargs: dict, monkeypatch, env: str | None):
    """Build the same dataflow query against an IvfPqKnn retriever and
    return (list of matched texts, list of scores)."""
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    if env is None:
        monkeypatch.delenv("PATHWAY_ANN", raising=False)
    else:
        monkeypatch.setenv("PATHWAY_ANN", env)
    rng = np.random.default_rng(11)
    vecs = rng.normal(size=(30, 4)).round(3)
    docs = pw.debug.table_from_rows(
        pw.schema_from_types(vec=object, name=str),
        [(tuple(vecs[i]), f"doc{i}") for i in range(len(vecs))],
    )
    queries = pw.debug.table_from_rows(
        pw.schema_from_types(qvec=object),
        [(tuple((vecs[i] + 0.01).round(3)),) for i in range(0, 30, 3)],
    )
    inner = IvfPqKnn(data_column=docs.vec, dimensions=4, **index_cls_kwargs)
    res = DataIndex(docs, inner).query_as_of_now(
        queries.qvec, number_of_matches=5, with_distances=True
    )
    df = pw.debug.table_to_pandas(res, include_id=False)
    names = [tuple(r) for r in df["name"]]
    scores = [tuple(r) for r in df["_pw_index_reply_score"]]
    G.clear()
    return names, scores


def test_pathway_ann_0_is_byte_identical_to_exact(monkeypatch):
    """The kill-switch contract: PATHWAY_ANN=0 must reproduce the exact
    brute-force rankings byte for byte (same scores, same tie-break) —
    and on a sub-train_min corpus ANN-on does too (exact serving mode)."""
    from pathway_tpu.internals.parse_graph import G

    ann_on = _rankings({}, monkeypatch, env=None)
    ann_off = _rankings({}, monkeypatch, env="0")
    # reference: the plain BruteForceKnn retriever
    G.clear()
    monkeypatch.delenv("PATHWAY_ANN", raising=False)
    rng = np.random.default_rng(11)
    vecs = rng.normal(size=(30, 4)).round(3)
    docs = pw.debug.table_from_rows(
        pw.schema_from_types(vec=object, name=str),
        [(tuple(vecs[i]), f"doc{i}") for i in range(len(vecs))],
    )
    queries = pw.debug.table_from_rows(
        pw.schema_from_types(qvec=object),
        [(tuple((vecs[i] + 0.01).round(3)),) for i in range(0, 30, 3)],
    )
    res = DataIndex(
        docs, BruteForceKnn(data_column=docs.vec, dimensions=4)
    ).query_as_of_now(queries.qvec, number_of_matches=5, with_distances=True)
    df = pw.debug.table_to_pandas(res, include_id=False)
    brute = (
        [tuple(r) for r in df["name"]],
        [tuple(r) for r in df["_pw_index_reply_score"]],
    )
    assert ann_off == brute
    assert ann_on == brute  # 30 docs < train_min: exact mode either way


def test_ann_enabled_env_contract(monkeypatch):
    monkeypatch.delenv("PATHWAY_ANN", raising=False)
    assert ann_enabled(True) and not ann_enabled(False)
    monkeypatch.setenv("PATHWAY_ANN", "0")
    assert not ann_enabled(True) and not ann_enabled(False)
    monkeypatch.setenv("PATHWAY_ANN", "1")
    assert ann_enabled(True) and ann_enabled(False)


def test_make_knn_searcher_routes_to_ann(monkeypatch):
    import jax.numpy as jnp

    from pathway_tpu.ops import make_knn_searcher

    monkeypatch.delenv("PATHWAY_ANN", raising=False)
    docs = _clustered(2000, seed=12)
    docs /= np.linalg.norm(docs, axis=1, keepdims=True)
    q = jnp.asarray(docs[:8] + 0.01)
    ddev = jnp.asarray(docs)
    exact = make_knn_searcher(10)(q, ddev)
    ann = make_knn_searcher(10, ann=True)(q, ddev)
    overlap = np.mean([
        len(set(np.asarray(ann.indices)[i]) & set(np.asarray(exact.indices)[i]))
        / 10
        for i in range(8)
    ])
    assert overlap >= 0.9
    # kill switch vetoes the explicit ann=True
    monkeypatch.setenv("PATHWAY_ANN", "0")
    off = make_knn_searcher(10, ann=True)(q, ddev)
    assert np.array_equal(np.asarray(off.indices), np.asarray(exact.indices))


# ------------------------------------------------------- plane discipline


def test_ann_device_compile_ledger_stays_flat():
    """Streaming same-bucket searches must not recompile: every
    (ann program, bucket) ledger entry stays at exactly 1."""
    from pathway_tpu.engine.device_plane import get_device_plane

    docs = _clustered(1500, seed=13)
    ann = IvfPqIndex(dimensions=DIM, background_retrain=False, seed=0)
    keys = _load(ann, docs)
    items = [(docs[i], 10, None) for i in range(16)]
    for round_ in range(5):
        ann.search_batch(items)
        # small same-shape churn between searches (delta scatter path)
        ann.remove(keys[round_])
        ann.add(keys[round_], docs[round_])
    counts = {
        bucket: n
        for (prog, bucket), n in get_device_plane().compile_counts().items()
        if prog.startswith("ann_")
    }
    assert counts, "ANN must route through the device plane"
    assert all(n == 1 for n in counts.values()), counts


def test_ann_pickle_roundtrip_preserves_results():
    docs = _clustered(1200, seed=14)
    ann = IvfPqIndex(dimensions=DIM, background_retrain=False, seed=0)
    _load(ann, docs)
    items = [(docs[i], 10, None) for i in range(12)]
    before = ann.search_batch(items)
    ann2 = pickle.loads(pickle.dumps(ann))
    assert ann2.search_batch(items) == before


def test_ann_metrics_published_to_registry():
    from pathway_tpu.internals import observability as obs

    obs.enable()
    try:
        docs = _clustered(1000, seed=15)
        ann = IvfPqIndex(dimensions=DIM, background_retrain=False, seed=0)
        _load(ann, docs)
        ann.search_batch([(docs[0], 10, None)])
        ann.measured_recall(k=10)
        snap = obs.PLANE.metrics.snapshot()
        for name in (
            "pathway_index_size_rows",
            "pathway_index_lists",
            "pathway_index_tombstone_frac",
            "pathway_index_retrain_seconds",
            "pathway_index_recall_at_k",
        ):
            assert name in snap, f"{name} missing from the registry"
            series = snap[name]["series"]
            assert any(s["labels"].get("index") == ann.name for s in series)
        recall_series = snap["pathway_index_recall_at_k"]["series"]
        val = next(
            s["value"] for s in recall_series
            if s["labels"].get("index") == ann.name
        )
        assert 0.0 <= val <= 1.0
        rows = next(
            s["value"] for s in snap["pathway_index_size_rows"]["series"]
            if s["labels"].get("index") == ann.name
        )
        assert rows == len(docs)
    finally:
        obs.disable()


# ----------------------------------------------------- hybrid fusion fix


class _StubIndex:
    """Fixed-ranking sub-index for fusion tests."""

    def __init__(self, ranking: list[Key]):
        self.ranking = ranking

    def add(self, key, data, metadata=None):
        pass

    def remove(self, key):
        pass

    def search(self, query, k, metadata_filter=None):
        return [(key, float(i)) for i, key in enumerate(self.ranking[:k])]


def test_hybrid_fusion_robust_to_short_sublists():
    """Regression (satellite): a sub-index returning fewer than k hits
    must not outrank every other sub's strong matches. With the
    short-list pad, a doc at rank 0+1 across full lists beats a doc
    whose only evidence is one short list's lone hit."""
    from pathway_tpu.stdlib.indexing.hybrid_index import _HybridHostIndex

    a, b, c = Key(1), Key(2), Key(3)
    knn = _StubIndex([a, b, c])  # full list
    bm25 = _StubIndex([c])  # short list: one rare-term hit
    hybrid = _HybridHostIndex([knn, bm25], rrf_k=60.0)
    res = hybrid.search(("q", "q"), k=3)
    ranked = [key for key, _ in res]
    assert len(ranked) == 3
    # c: bm25 rank-0 + knn rank-2; a: knn rank-0 + pad — c's two real
    # signals win, but a (rank-0 vector hit) must beat b (rank-1) and
    # stay well inside the fused top set rather than being starved
    assert ranked.index(a) < ranked.index(b)
    scores = {key: -s for key, s in res}
    assert scores[a] > 1.0 / 61  # pad contributed (not just its knn rank)


def test_hybrid_fusion_deterministic_tie_break():
    from pathway_tpu.stdlib.indexing.hybrid_index import _HybridHostIndex

    a, b = Key(7), Key(9)
    # perfectly symmetric evidence: a and b swap ranks across subs
    s1 = _StubIndex([a, b])
    s2 = _StubIndex([b, a])
    res1 = _HybridHostIndex([s1, s2], rrf_k=60.0).search(("q", "q"), k=2)
    res2 = _HybridHostIndex([s2, s1], rrf_k=60.0).search(("q", "q"), k=2)
    assert res1 == res2  # key tie-break, not dict insertion order
    assert [key for key, _ in res1] == sorted([a, b], key=lambda k: k.value)
