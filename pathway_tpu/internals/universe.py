"""Universes: static reasoning about table key sets.

Reference parity: internals/universe.py + universe_solver.py — the solver
tracks which tables share a key set (equality via union-find), which are
subsets of which (a DAG with transitive closure), and which are PAIRWISE
DISJOINT, so same-universe operations (select across tables,
update_cells, with_universe_of) and overlap-sensitive ones (concat)
validate at graph build time instead of failing — or silently double
counting — at runtime.

Public promises (pw.universes.*): promise_are_equal,
promise_is_subset_of, promise_are_pairwise_disjoint.
"""

from __future__ import annotations

import itertools
from typing import Any

_ids = itertools.count()


class Universe:
    def __init__(self) -> None:
        self.id = next(_ids)
        self._parent: Universe | None = None

    def root(self) -> "Universe":
        u = self
        while u._parent is not None:
            u = u._parent
        # path compression
        v: Universe | None = self
        while v is not None and v._parent is not None and v._parent is not u:
            nxt = v._parent
            v._parent = u
            v = nxt
        return u

    def __repr__(self) -> str:
        return f"Universe({self.root().id})"


class UniverseSolver:
    """Equality (union-find) + subset DAG + disjointness relation."""

    def __init__(self) -> None:
        # root id -> set of root ids it is a DIRECT subset of
        self.subset_of: dict[int, set[int]] = {}
        # unordered root-id pairs promised disjoint
        self.disjoint: set[frozenset[int]] = set()
        # merged-away root id -> surviving root id (edges recorded under
        # or toward an old root resolve through this chain)
        self.redirect: dict[int, int] = {}

    def reset(self) -> None:
        """Drop every recorded relation (tests / long-lived processes;
        each table contributes O(1) entries, so growth is slow but
        unbounded without this)."""
        self.subset_of.clear()
        self.disjoint.clear()
        self.redirect.clear()

    def _resolve(self, uid: int) -> int:
        while uid in self.redirect:
            uid = self.redirect[uid]
        return uid

    # ------------------------------------------------------------ equality

    def register_as_equal(self, *universes: Universe) -> None:
        roots = [u.root() for u in universes]
        target = roots[0]
        for other in roots[1:]:
            if other is target:
                continue
            if frozenset(
                {self._resolve(target.id), self._resolve(other.id)}
            ) in self.disjoint:
                raise ValueError(
                    "universes promised pairwise disjoint cannot be "
                    "promised equal"
                )
            other._parent = target
            self.redirect[other.id] = target.id
            # merge the relation edges onto the surviving root
            self.subset_of.setdefault(target.id, set()).update(
                self.subset_of.pop(other.id, set())
            )
            for pair in [p for p in self.disjoint if other.id in p]:
                self.disjoint.discard(pair)
                rest = next(iter(pair - {other.id}), None)
                if rest is not None:
                    self.disjoint.add(frozenset({target.id, rest}))

    def are_equal(self, a: Universe, b: Universe) -> bool:
        return a.root() is b.root()

    # ------------------------------------------------------------- subsets

    def register_as_subset(self, sub: Universe, sup: Universe) -> None:
        self.subset_of.setdefault(sub.root().id, set()).add(sup.root().id)

    def is_subset(self, sub: Universe, sup: Universe) -> bool:
        if self.are_equal(sub, sup):
            return True
        target = self._resolve(sup.root().id)
        return target in self._ancestors(sub.root().id)

    # --------------------------------------------------------- disjointness

    def register_as_disjoint(self, *universes: Universe) -> None:
        roots = [self._resolve(u.root().id) for u in universes]
        for i, a in enumerate(roots):
            for b in roots[i + 1 :]:
                if a != b:
                    self.disjoint.add(frozenset({a, b}))

    def are_disjoint(self, a: Universe, b: Universe) -> bool:
        """True when a and b are PROVABLY disjoint: promised directly, or
        each is a subset of a pair promised disjoint."""
        ra, rb = self._resolve(a.root().id), self._resolve(b.root().id)
        if ra == rb:
            return False
        ups_a = self._ancestors(ra)
        ups_b = self._ancestors(rb)
        return any(
            x != y and frozenset({x, y}) in self.disjoint
            for x in ups_a
            for y in ups_b
        )

    def _ancestors(self, uid: int) -> set[int]:
        """All root ids `uid` is (transitively) a subset of, with merged
        roots resolved through the redirect chain."""
        seen: set[int] = set()
        frontier = [self._resolve(uid)]
        while frontier:
            u = frontier.pop()
            if u in seen:
                continue
            seen.add(u)
            frontier.extend(
                self._resolve(x) for x in self.subset_of.get(u, ())
            )
        return seen

    # -------------------------------------------------- derived universes

    def register_as_difference(
        self, result: Universe, minuend: Universe, subtrahend: Universe
    ) -> None:
        self.register_as_subset(result, minuend)
        self.register_as_disjoint(result, subtrahend)

    def register_as_intersection(self, result: Universe, *parts: Universe) -> None:
        for p in parts:
            self.register_as_subset(result, p)

    def register_as_union(self, result: Universe, *parts: Universe) -> None:
        for p in parts:
            self.register_as_subset(p, result)


_SOLVER = UniverseSolver()


def get_solver() -> UniverseSolver:
    return _SOLVER


# ------------------------------------------------------ module-level API
# (kept for existing call sites; tables delegate here)


def promise_are_equal(*universes: Any) -> None:
    """Promise the given tables/universes share exactly the same keys."""
    _SOLVER.register_as_equal(*[_u(x) for x in universes])


def promise_is_subset_of(sub: Any, sup: Any) -> None:
    """Promise `sub`'s keys are all present in `sup`."""
    _SOLVER.register_as_subset(_u(sub), _u(sup))


def promise_are_pairwise_disjoint(*universes: Any) -> None:
    """Promise the given tables/universes share NO keys — concat of
    disjoint tables is statically safe."""
    _SOLVER.register_as_disjoint(*[_u(x) for x in universes])


def register_subset(sub: Universe, sup: Universe) -> None:
    _SOLVER.register_as_subset(sub, sup)


def are_equal(a: Universe, b: Universe) -> bool:
    return _SOLVER.are_equal(a, b)


def is_subset(sub: Universe, sup: Universe) -> bool:
    return _SOLVER.is_subset(sub, sup)


def are_disjoint(a: Universe, b: Universe) -> bool:
    return _SOLVER.are_disjoint(a, b)


def _u(x: Any) -> Universe:
    return x._universe if hasattr(x, "_universe") else x


__all__ = [
    "Universe",
    "UniverseSolver",
    "get_solver",
    "promise_are_equal",
    "promise_is_subset_of",
    "promise_are_pairwise_disjoint",
    "register_subset",
    "are_equal",
    "is_subset",
    "are_disjoint",
]
