"""pw.io.mongodb — API-parity connector (reference: io/mongodb).

Client library gated: see io/_external.py.
"""

from pathway_tpu.io._external import gated_reader, gated_writer

read = gated_reader("mongodb", "pymongo")
write = gated_writer("mongodb", "pymongo")
