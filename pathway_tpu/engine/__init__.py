"""TPU-native incremental dataflow engine.

This package is the equivalent of the reference's Rust engine
(src/engine/dataflow.rs + vendored timely/differential fork), re-derived as
a DBSP-style minimal core:

- one total-ordered timestamp domain (even milliseconds, matching
  src/engine/timestamp.rs:20-27) instead of Naiad product timestamps;
- z-set (diff) collections flowing through a DAG of operator nodes;
- frontier-based progress tracking (engine/frontier.py, the timely
  progress/frontier.rs equivalent over a total order): every source
  carries a watermark, and an operator is notified for time t as soon
  as its input frontier passes t — out-of-order across operators,
  in-order at each, with no global wave barrier; the process mesh
  exchanges (time, batch) plus per-wire watermark announcements;
- numeric columns batch onto the XLA plane (engine/vectorize.py), hot
  index/sort/join inner loops go through the C++ kernel
  (pathway_tpu/native) when available;
- device dispatches of the serving stages (embed/generate/KNN, batched
  UDFs) route through the device plane (engine/device_plane.py):
  shape-bucketed batch coalescing, double-buffered host->device
  staging, frontier-driven stage overlap, donated persistent buffers
  (docs/serving.md);
- multi-chip scale-out shards every arrangement by the 128-bit row key;
  the exchange of numeric payloads is an ICI all_to_all
  (pathway_tpu/parallel/exchange.py), host control plane carries the
  frontier ticks.
"""

from pathway_tpu.engine.core import (
    Entry,
    Node,
    Graph,
    CaptureNode,
)
from pathway_tpu.engine.runtime import Runtime

__all__ = ["Entry", "Node", "Graph", "CaptureNode", "Runtime"]
