"""pw.run: lower all registered sinks and execute
(reference: internals/run.py:11 + graph_runner/__init__.py:113)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.config import get_config
from pathway_tpu.internals.lowering import Session
from pathway_tpu.internals.parse_graph import G


def run(
    *,
    debug: bool = False,
    monitoring_level: Any = None,
    with_http_server: bool = False,
    default_logging: bool = True,
    persistence_config: Any = None,
    license_key: str | None = None,
    runtime_typechecking: bool = True,
    terminate_on_error: bool = False,
    autocommit_duration_ms: int | None = None,
    device: str | None = None,
    **kwargs: Any,
) -> None:
    session = Session()
    session.graph.terminate_on_error = terminate_on_error or get_config().terminate_on_error
    if autocommit_duration_ms:
        session.autocommit_ms = autocommit_duration_ms
    for hook in G.pre_run_hooks:
        hook()
    for sink in G.sinks:
        if sink.kind == "subscribe":
            session.subscribe(
                sink.table,
                on_change=sink.params.get("on_change"),
                on_time_end=sink.params.get("on_time_end"),
                on_end=sink.params.get("on_end"),
            )
        elif sink.kind == "output":
            session.output(
                sink.table,
                sink.params["write_batch"],
                sink.params.get("flush"),
                sink.params.get("close"),
                write_native=sink.params.get("write_native"),
            )
        else:
            raise ValueError(f"unknown sink kind {sink.kind}")
    if with_http_server:
        from pathway_tpu.internals.metrics import start_metrics_server

        start_metrics_server(session)
    if monitoring_level not in (None, False, "none"):
        from pathway_tpu.internals.monitoring import attach_monitor

        attach_monitor(session)
    if persistence_config is not None:
        # wrap AFTER lowering: session.connectors only exist once the sinks
        # above have been lowered into engine nodes
        from pathway_tpu.persistence import attach_persistence

        attach_persistence(session, persistence_config)
    # telemetry: OTLP when configured + SDK present, local JSONL via
    # PATHWAY_TELEMETRY_FILE otherwise (reference: telemetry.rs:436)
    from pathway_tpu.internals.telemetry import attach_telemetry

    telemetry = attach_telemetry(session, get_config().monitoring_server)
    try:
        if telemetry is not None:
            with telemetry.span("run"):
                session.execute()
        else:
            session.execute()
    finally:
        # restore the terminal if the monitoring TUI was live
        for m in session.monitors:
            live = getattr(m, "live", None)
            if live is not None:
                try:
                    live.stop()
                except Exception:  # noqa: BLE001
                    pass
        if telemetry is not None:
            telemetry.operator_stats(session.graph)
            telemetry.shutdown()


def run_all(**kwargs: Any) -> None:
    run(**kwargs)
