"""Watermark-tied backpressure: the gateway reads the pipeline's own
progress signal and slows intake when the frontier falls behind.

Admission control (admission.py) bounds *how much* the edge accepts;
backpressure decides *whether the pipeline can afford it right now*. The
signal is the per-source watermark-lag gauge the runtime already
publishes through the observability plane
(``pathway_source_watermark_lag_seconds`` — the pump's throttled
``tick_sources`` writes it every 250 ms): when a straggling cone lets a
source's watermark trail the local clock, the lag gauge grows, and the
gateway reacts *before* the latency shows up at the client:

* lag past ``delay_lag_s`` — admission is **delayed**: the handler
  sleeps (non-blocking, on its event loop) up to ``max_delay_s``,
  pacing intake to the pipeline instead of queueing blindly;
* lag past ``shed_lag_s`` — admission is **shed**: 429 with a
  Retry-After proportional to the observed lag, so a straggler slows
  intake instead of ballooning p99 for everyone already queued.

Reading the gauge is one registry scan per decision window (results are
memoized for ``poll_interval_s``), so the request path stays cheap. With
the observability plane off there is no signal and backpressure is a
no-op — the gateway degrades to plain admission control.
"""

from __future__ import annotations

import threading
import time as _time

from pathway_tpu.internals import observability as _obs
from pathway_tpu.analysis import lockgraph as _lockgraph

__all__ = ["WatermarkBackpressure"]


class WatermarkBackpressure:
    """Shed/delay policy off the max per-source watermark lag."""

    def __init__(
        self,
        *,
        delay_lag_s: float = 1.0,
        shed_lag_s: float = 5.0,
        max_delay_s: float = 0.5,
        poll_interval_s: float = 0.25,
        sources: tuple[str, ...] | None = None,
    ):
        if shed_lag_s < delay_lag_s:
            raise ValueError(
                f"shed_lag_s ({shed_lag_s}) must be >= delay_lag_s "
                f"({delay_lag_s})"
            )
        self.delay_lag_s = delay_lag_s
        self.shed_lag_s = shed_lag_s
        self.max_delay_s = max_delay_s
        self.poll_interval_s = poll_interval_s
        self.sources = sources  # None = every source the plane reports
        self._lock = _lockgraph.register_lock(
            "serving.backpressure", threading.Lock()
        )
        self._cached_lag = 0.0
        self._cached_at = 0.0
        self.stats = {"delayed": 0, "shed": 0, "max_lag_s": 0.0}

    # ------------------------------------------------------------- signal

    def current_lag(self) -> float:
        """Max watermark lag (seconds) across the watched sources, read
        from the metrics registry; memoized for poll_interval_s."""
        now = _time.monotonic()
        with self._lock:
            if now - self._cached_at < self.poll_interval_s:
                return self._cached_lag
        plane = _obs.PLANE
        lag = 0.0
        if plane is not None:
            lag = plane.metrics.max_gauge(
                "pathway_source_watermark_lag_seconds",
                label="source",
                values=self.sources,
            )
        with self._lock:
            self._cached_lag = lag
            self._cached_at = now
            self.stats["max_lag_s"] = max(self.stats["max_lag_s"], lag)
        return lag

    # ----------------------------------------------------------- decisions

    def decide(self) -> tuple[str, float]:
        """One admission-time decision: ("ok"|"delay"|"shed", seconds).
        For "delay" the seconds are how long to pace this request; for
        "shed" they are the Retry-After hint."""
        lag = self.current_lag()
        if lag >= self.shed_lag_s:
            self.stats["shed"] += 1
            # the frontier is `lag` seconds behind: retrying much sooner
            # than it can catch up just sheds again
            return "shed", max(round(lag, 3), 1.0)
        if lag >= self.delay_lag_s:
            self.stats["delayed"] += 1
            # pace proportionally inside the [delay, shed) band
            frac = (lag - self.delay_lag_s) / max(
                self.shed_lag_s - self.delay_lag_s, 1e-9
            )
            return "delay", round(self.max_delay_s * frac, 4)
        return "ok", 0.0
