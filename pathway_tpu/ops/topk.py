"""Fused distance + top-k retrieval kernels, single-chip and mesh-sharded.

Reference parity: this replaces the external CPU indexes the reference links
in (`/root/reference/src/external_integration/usearch_integration.rs` HNSW,
`brute_force_knn_integration.rs:22` exact search). On TPU the exact search IS
the fast path: a [q,d]x[d,n] bf16 matmul hits the MXU at full tilt, and
`lax.top_k` over the score row is bandwidth-bound on HBM — no pointer-chasing
graph traversal to serialize.

Sharded design (the 1M-doc north star): docs are sharded over the mesh's
`data` axis, queries are replicated; each shard computes its local top-k and
an `all_gather` over ICI merges k*n_shards candidates, re-top-k'd locally.
That keeps the per-chip HBM traffic at docs/n_shards and the ICI payload at
O(q * k * shards), tiny next to the matmul.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pathway_tpu.ops.distances import metric_fn

Array = jax.Array


class TopKResult(NamedTuple):
    indices: Array  # [q, k] int32 — indices into the doc matrix
    distances: Array  # [q, k] f32 — metric distances (smaller = closer)


@functools.partial(jax.jit, static_argnames=("k", "metric", "normalized", "approx"))
def knn_search(
    queries: Array,
    docs: Array,
    k: int,
    metric: str = "cos",
    *,
    normalized: bool = False,
    approx: bool = False,
) -> TopKResult:
    """k-NN on one device: fused distance grid + top-k.

    `normalized=True` skips per-call L2 normalization for cosine (store docs
    pre-normalized — this is the serving fast path; re-normalizing 1M docs
    per query costs more than the search). `approx=True` uses the
    TPU-optimized `approx_min_k` (same recall regime as the reference's HNSW
    default, `usearch_integration.rs:20`).
    """
    if metric in ("cos", "cosine", "dot"):
        # similarity form: top-k runs directly on the matmul output and the
        # distance conversion touches only the k winners, not all n docs
        from pathway_tpu.ops.distances import dot_products, normalize

        q = normalize(queries.astype(jnp.float32)) if metric != "dot" else queries
        d_mat = docs if (normalized or metric == "dot") else normalize(
            docs.astype(jnp.float32)
        )
        sims = dot_products(q, d_mat)
        if approx:
            s, idx = jax.lax.approx_max_k(sims, k)
        else:
            s, idx = jax.lax.top_k(sims, k)
        d = (1.0 - s) if metric != "dot" else -s
        return TopKResult(indices=idx.astype(jnp.int32), distances=d)
    dists = metric_fn(metric)(queries, docs)
    if approx:
        d, idx = jax.lax.approx_min_k(dists, k)
    else:
        neg, idx = jax.lax.top_k(-dists, k)
        d = -neg
    return TopKResult(indices=idx.astype(jnp.int32), distances=d)


class QuantizedDocs(NamedTuple):
    """Serving layout for the int8 scan + bf16 rescore KNN path.

    `values` is the per-row symmetric int8 quantization of the doc matrix,
    `scale` the per-row dequant factor (maxabs/127), `full` the original
    rows kept for exact rescoring of the top candidates. Capacity cost is
    1.5x the bf16 index; *bandwidth* per query drops 2x — and HBM
    bandwidth, not capacity, bounds brute-force search latency.
    """

    values: Array  # [n, d] int8
    scale: Array  # [n] f32
    full: Array  # [n, d] bf16 (exact rescore rows)


def quantize_docs(docs: Array) -> QuantizedDocs:
    """Build the int8 serving layout from a (preferably row-normalized)
    doc matrix."""
    d32 = docs.astype(jnp.float32)
    maxabs = jnp.maximum(jnp.max(jnp.abs(d32), axis=1), 1e-12)
    scale = maxabs / 127.0
    q = jnp.clip(jnp.round(d32 / scale[:, None]), -127, 127).astype(jnp.int8)
    return QuantizedDocs(values=q, scale=scale, full=docs.astype(jnp.bfloat16))


@functools.partial(jax.jit, donate_argnums=(0,))
def update_quantized_docs(docs: QuantizedDocs, idx: Array, rows: Array) -> QuantizedDocs:
    """Scatter fresh rows into a PERSISTENT quantized doc shard in place.

    All three serving buffers (int8 scan matrix, dequant scales, bf16
    rescore rows) are donated: XLA reuses the shard's allocation across
    streaming refreshes instead of rebuilding the layout per update —
    the device-plane donation lifecycle (docs/serving.md) applied to the
    quantized KNN path. `rows` are the raw (row-normalized) vectors for
    slots `idx`; quantization of the delta happens on-device. Duplicate
    indices padded with a repeated real (idx, row) pair are idempotent,
    so callers can pad update batches to a shape bucket.
    """
    r32 = rows.astype(jnp.float32)
    maxabs = jnp.maximum(jnp.max(jnp.abs(r32), axis=1), 1e-12)
    scale = maxabs / 127.0
    q = jnp.clip(jnp.round(r32 / scale[:, None]), -127, 127).astype(jnp.int8)
    return QuantizedDocs(
        values=docs.values.at[idx].set(q),
        scale=docs.scale.at[idx].set(scale),
        full=docs.full.at[idx].set(rows.astype(jnp.bfloat16)),
    )


@functools.partial(jax.jit, static_argnames=("k", "candidates"))
def knn_search_quantized(
    queries: Array,
    docs: QuantizedDocs,
    k: int,
    *,
    candidates: int = 64,
) -> TopKResult:
    """Cosine k-NN: int8 MXU scan -> approx top-`candidates` -> exact bf16
    rescore -> top-k. ~2x lower HBM traffic than the bf16 scan; the rescore
    restores exact ordering *within* the candidate set, so residual error
    comes only from candidate selection (int8 scores + approx_max_k).
    Measured recall@10 vs exact search: 0.994 at 1M random normalized
    docs with the default candidates=64; the small-scale invariant is
    pinned by tests/test_indexing.py::test_quantized_knn_recall.

    Replaces the reference's HNSW+i8 usearch serving config
    (/root/reference/src/external_integration/usearch_integration.rs:20)
    with a layout the MXU actually likes: dense int8 matmul + top-k.
    Queries are L2-normalized internally (same contract as
    `knn_search(metric='cos')`), so returned distances are true cosine
    distances.
    """
    from pathway_tpu.ops.distances import normalize

    queries = normalize(queries.astype(jnp.float32))
    qn = queries
    qmax = jnp.maximum(jnp.max(jnp.abs(qn), axis=1), 1e-12)
    qscale = qmax / 127.0
    qi = jnp.clip(jnp.round(qn / qscale[:, None]), -127, 127).astype(jnp.int8)
    sims_i32 = jax.lax.dot_general(
        qi, docs.values, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    # candidate selection only needs ordering; keep it bf16 to halve the
    # [q, n] round-trip through HBM
    sims = (sims_i32.astype(jnp.float32) * docs.scale[None, :]).astype(
        jnp.bfloat16
    )
    c = min(candidates, docs.values.shape[0])
    _, cand_idx = jax.lax.approx_max_k(sims, c)
    # exact rescore: gather candidate rows (tiny — c*d per query) in bf16
    cand_rows = docs.full[cand_idx]  # [q, c, d]
    exact = jnp.einsum(
        "qd,qcd->qc", queries.astype(jnp.bfloat16), cand_rows,
        preferred_element_type=jnp.float32,
    )
    s, pos = jax.lax.top_k(exact, k)
    idx = jnp.take_along_axis(cand_idx, pos, axis=1)
    return TopKResult(indices=idx.astype(jnp.int32), distances=1.0 - s)


def knn_search_masked(
    queries: Array, docs: Array, valid: Array, k: int, metric: str = "cos"
) -> TopKResult:
    """Exact k-NN with a validity mask over doc slots (for tombstoned rows)."""
    dists = metric_fn(metric)(queries, docs)
    dists = jnp.where(valid[None, :], dists, jnp.inf)
    neg, idx = jax.lax.top_k(-dists, k)
    return TopKResult(indices=idx.astype(jnp.int32), distances=-neg)


knn_search_masked = jax.jit(knn_search_masked, static_argnames=("k", "metric"))


def knn_search_sharded(
    queries: Array,
    docs: Array,
    k: int,
    metric: str = "cos",
    mesh: Mesh | None = None,
    axis: str = "data",
) -> TopKResult:
    """Exact k-NN with docs sharded over `axis` of `mesh`.

    Per-shard top-k then cross-shard merge. Returns global doc indices
    (row offsets into the unsharded doc matrix).
    """
    if mesh is None:
        return knn_search(queries, docs, k, metric)
    n_shards = mesh.shape[axis]
    n_docs = docs.shape[0]
    if n_docs % n_shards != 0:
        raise ValueError(f"doc count {n_docs} not divisible by {n_shards} shards")
    shard_rows = n_docs // n_shards
    dist = metric_fn(metric)
    kk = min(k, shard_rows)

    def local(q, d_shard):
        # d_shard: [n/s, d]; local top-k, then gather candidates across shards
        dists = dist(q, d_shard)
        neg, idx = jax.lax.top_k(-dists, kk)
        shard_id = jax.lax.axis_index(axis)
        global_idx = idx.astype(jnp.int32) + shard_id * shard_rows
        # [shards, q, kk] on every shard after the gather
        all_neg = jax.lax.all_gather(neg, axis)
        all_idx = jax.lax.all_gather(global_idx, axis)
        q_n = q.shape[0]
        cand_neg = jnp.transpose(all_neg, (1, 0, 2)).reshape(q_n, n_shards * kk)
        cand_idx = jnp.transpose(all_idx, (1, 0, 2)).reshape(q_n, n_shards * kk)
        mneg, midx = jax.lax.top_k(cand_neg, min(k, n_shards * kk))
        merged_idx = jnp.take_along_axis(cand_idx, midx, axis=1)
        return merged_idx, -mneg

    fn = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(axis, None)),
        out_specs=(P(), P()),
        # after the all_gather every shard holds identical merged candidates,
        # which the varying-axes inference cannot prove
        check_vma=False,
    )
    idx, dists = jax.jit(fn)(queries, docs)
    return TopKResult(indices=idx, distances=dists)


def make_knn_searcher(
    k: int,
    metric: str = "cos",
    mesh: Mesh | None = None,
    axis: str = "data",
    *,
    ann: bool | None = None,
    nprobe: int | None = None,
) -> Callable[[Array, Array], TopKResult]:
    """Pre-configured searcher closure (stable jit cache across calls).

    `ann=True` routes through the IVF-PQ index (`ops/ivf.py`): the first
    search against a given doc matrix trains and caches the index, later
    searches probe `nprobe` lists instead of scanning every row. The
    `PATHWAY_ANN` env var overrides either way — `0` forces the exact
    scan (the kill-switch discipline), `1` opts unlabeled call sites in.
    With a mesh, the ANN tier shards by ROUTING LIST across the mesh's
    `axis`: each chip scans only the probed fraction of its own lists and
    the cross-shard top-k merge ships O(q·k·shards) over the interconnect
    (`ops/ivf.py shard_ivf_pq` / `ivf_pq_search_sharded`;
    docs/retrieval.md).
    """
    from pathway_tpu.indexing import ann_enabled

    # ann=False is an explicit exact-search request — the env can veto an
    # ANN opt-in (PATHWAY_ANN=0) but must not override an explicit False
    use_ann = (
        ann is not False
        and ann_enabled(default=bool(ann))
        and metric in ("cos", "cosine", "dot", "l2sq")
    )
    if not use_ann:
        def search(queries: Array, docs: Array) -> TopKResult:
            return knn_search_sharded(queries, docs, k, metric, mesh, axis)

        return search

    import os
    import weakref
    from collections import OrderedDict

    import numpy as np

    from pathway_tpu.ops import ivf as _ivf

    # Bounded LRU of resident indexes, keyed by matrix id() but only
    # served through a LIVE weakref check (a freed array's address can
    # be recycled by a new same-shape matrix — the id alone must never
    # validate a hit). Multiple entries keep alternating doc matrices
    # (A/B snapshot swaps in serving) warm without retraining per call;
    # the bound keeps the cache from growing monotonically per distinct
    # matrix across a long-lived searcher.
    cache: "OrderedDict[int, tuple]" = OrderedDict()
    cache_cap = max(1, int(os.environ.get("PATHWAY_KNN_CACHE", "4") or 4))

    def search_ann(queries: Array, docs: Array) -> TopKResult:
        key = id(docs)
        index = None
        ent = cache.get(key)
        if ent is not None:
            ref, shape, cached = ent
            if ref() is docs and shape == tuple(docs.shape):
                index = cached
                cache.move_to_end(key)
            else:  # recycled id: the entry is stale, drop it
                del cache[key]
        if index is None:
            # prune entries whose matrix has been freed, THEN evict LRU
            for stale in [
                kk for kk, (r, _s, _i) in cache.items() if r() is None
            ]:
                del cache[stale]
            index = _ivf.build_ivf_pq(np.asarray(docs), metric=metric)
            if mesh is not None:
                # one placement per trained index: lists sharded over the
                # mesh axis, rescore rows re-laid list-local per shard
                index = _ivf.shard_ivf_pq(index, mesh, axis)
            try:
                ref = weakref.ref(docs)
            except TypeError:  # unweakreferenceable: pin it (still correct)
                ref = (lambda d=docs: d)
            cache[key] = (ref, tuple(docs.shape), index)
            while len(cache) > cache_cap:
                cache.popitem(last=False)
        if mesh is not None:
            slots, dists = _ivf.ivf_pq_search_sharded(
                queries, index, k, nprobe=nprobe, metric=metric
            )
        else:
            slots, dists = _ivf.ivf_pq_search(
                queries, index, k, nprobe=nprobe, metric=metric
            )
        return TopKResult(indices=slots, distances=dists)

    search_ann._cache = cache  # introspection seam (tests, debugging)
    return search_ann
