"""Prompt templates for the RAG pipelines.

Reference parity: xpacks/llm/prompts.py (447 LoC of template text +
`RAGPromptTemplate`/`prompt_qa` style helpers). Text is original.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import pathway_tpu as pw


@dataclass
class RAGPromptTemplate:
    """A template with {context} and {query} slots."""

    template: str

    def format(self, context: str, query: str) -> str:
        return self.template.format(context=context, query=query)


DEFAULT_QA_TEMPLATE = RAGPromptTemplate(
    template=(
        "Answer the question based only on the context below. If the context "
        "does not contain the answer, reply exactly: No information found.\n\n"
        "Context:\n{context}\n\nQuestion: {query}\nAnswer:"
    )
)

DEFAULT_SUMMARY_TEMPLATE = (
    "Summarize the following texts into a single short summary:\n\n{text}\n\nSummary:"
)


@pw.udf
def prompt_qa(query: str, docs: tuple) -> str:
    """Build a QA prompt from retrieved doc texts (reference: prompts.py
    prompt_qa / prompt_short_qa family)."""
    context = "\n\n".join(str(d) for d in docs)
    return DEFAULT_QA_TEMPLATE.format(context=context, query=query)


@pw.udf
def prompt_qa_geometric_rag(query: str, docs: tuple) -> str:
    context = "\n\n".join(str(d) for d in docs)
    return DEFAULT_QA_TEMPLATE.format(context=context, query=query)


@pw.udf
def prompt_summarize(texts: tuple) -> str:
    return DEFAULT_SUMMARY_TEMPLATE.format(text="\n\n".join(str(t) for t in texts))


@pw.udf
def prompt_citing_qa(query: str, docs: tuple) -> str:
    context = "\n\n".join(
        f"[{i + 1}] {d}" for i, d in enumerate(str(d) for d in docs)
    )
    return (
        "Answer using only the numbered context passages and cite them as "
        f"[n].\n\nContext:\n{context}\n\nQuestion: {query}\nAnswer:"
    )
