"""pw.io.pubsub — Google Cloud Pub/Sub source/sink.

Reference parity: python/pathway/io/pubsub/__init__.py. Implemented
against google.cloud.pubsub_v1; raises a clear ImportError when it is
not installed.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import schema as sch
from pathway_tpu.io._external import require_module


def read(
    subscription: str,
    *,
    project_id: str | None = None,
    schema: Any = None,
    format: str = "raw",  # noqa: A002
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    **kwargs: Any,
) -> Any:
    """Streams messages from a Pub/Sub subscription ('raw' bytes or
    'json' rows per `schema`)."""
    pubsub_v1 = require_module("google.cloud.pubsub_v1", "pubsub")

    import json as _json

    from pathway_tpu.io.python import ConnectorSubject
    from pathway_tpu.io.python import read as python_read

    if format == "json":
        if schema is None:
            raise ValueError("pw.io.pubsub.read(format='json') requires a schema")
    else:
        schema = sch.schema_from_types(data=bytes)
    columns = list(schema.__columns__)

    class PubSubSubject(ConnectorSubject):
        def run(self) -> None:
            subscriber = pubsub_v1.SubscriberClient()
            path = (
                subscription
                if subscription.startswith("projects/")
                else subscriber.subscription_path(project_id, subscription)
            )

            def callback(message: Any) -> None:
                if format == "raw":
                    self.next(data=bytes(message.data))
                else:
                    try:
                        doc = _json.loads(message.data)
                        self.next(**{c: doc.get(c) for c in columns})
                    except ValueError:
                        pass
                message.ack()

            future = subscriber.subscribe(path, callback=callback)
            future.result()  # blocks for the life of the stream

    return python_read(
        PubSubSubject(),
        schema=schema,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"pubsub:{subscription}",
        replay_style="live",
    )


def write(table: Any, publisher: Any, project_id: str, topic_id: str) -> None:
    """Publishes the table's updates to a Pub/Sub topic with pathway_time
    / pathway_diff attributes (reference API: caller-made PublisherClient)."""
    require_module("google.cloud.pubsub_v1", "pubsub")
    from pathway_tpu.internals.json import Json
    from pathway_tpu.internals.parse_graph import G

    names = table._column_names()
    topic_path = publisher.topic_path(project_id, topic_id)

    def write_batch(time: int, entries: list) -> None:
        futures = []
        for _key, row, diff in entries:
            payload = Json.dumps(dict(zip(names, row))).encode()
            futures.append(
                publisher.publish(
                    topic_path, payload,
                    pathway_time=str(time), pathway_diff=str(diff),
                )
            )
        for f in futures:
            f.result(timeout=30)

    G.add_sink("output", table, write_batch=write_batch)


__all__ = ["read", "write"]
