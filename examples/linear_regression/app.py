"""Realtime linear regression over a streaming source.

(x, y) events stream in; the least-squares coefficients a, b of
y = a + b*x stream out, updating incrementally with every commit wave —
the reference's kafka-linear-regression project
(examples/projects/kafka-linear-regression/realtime_regression.py), with
the kafka source swapped for a watched directory so it runs anywhere.

Run:
    python app.py ./inbox ./regression.csv
Feed it:
    python -c "import json,random
for i in range(100):
    x = random.uniform(0, 10)
    print(json.dumps({'x': x, 'y': 2*x - 1 + random.gauss(0, .1)}))" \
        >> ./inbox/points.jsonl
"""

import argparse

import pathway_tpu as pw


class PointSchema(pw.Schema):
    x: float
    y: float


def build(points: pw.Table) -> pw.Table:
    t = points.select(
        *pw.this, x_square=points.x * points.x, x_y=points.x * points.y
    )
    stats = t.reduce(
        count=pw.reducers.count(),
        sum_x=pw.reducers.sum(t.x),
        sum_y=pw.reducers.sum(t.y),
        sum_x_y=pw.reducers.sum(t.x_y),
        sum_x_square=pw.reducers.sum(t.x_square),
    )

    def compute_a(sum_x, sum_y, sum_x_square, sum_x_y, count):
        d = count * sum_x_square - sum_x * sum_x
        return 0.0 if d == 0 else (sum_y * sum_x_square - sum_x * sum_x_y) / d

    def compute_b(sum_x, sum_y, sum_x_square, sum_x_y, count):
        d = count * sum_x_square - sum_x * sum_x
        return 0.0 if d == 0 else (count * sum_x_y - sum_x * sum_y) / d

    return stats.select(
        a=pw.apply(compute_a, **stats), b=pw.apply(compute_b, **stats)
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("inbox")
    ap.add_argument("output")
    ap.add_argument("--once", action="store_true")
    args = ap.parse_args()

    points = pw.io.fs.read(
        args.inbox,
        format="json",
        schema=PointSchema,
        mode="streaming",
        autocommit_duration_ms=100,
        _single_pass=args.once,
    )
    pw.io.csv.write(build(points), args.output)
    pw.run()


if __name__ == "__main__":
    main()
