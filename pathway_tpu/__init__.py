"""pathway_tpu — a TPU-native live-dataflow framework.

A brand-new implementation of the capabilities of the reference streaming
framework (Tables + expressions DSL, incremental engine, connectors,
temporal windows, indexes, LLM/RAG xpack), architected for TPU:
JAX/XLA/Pallas numeric plane, device-mesh scale-out, host C++ kernel for
the irregular hot loops.

Import convention, same as the reference: `import pathway_tpu as pw`.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as _dtype
from pathway_tpu.internals import reducers
from pathway_tpu.internals import universe as _universe_mod
from pathway_tpu.internals import udfs
from pathway_tpu.internals.common import (
    apply,
    apply_async,
    apply_with_type,
    assert_table_has_schema,
    cast,
    coalesce,
    declare_type,
    fill_error,
    if_else,
    iterate,
    make_tuple,
    require,
    table_transformer,
    unwrap,
)
from pathway_tpu.internals.config import (
    PathwayConfig,
    get_config,
    set_license_key,
    set_monitoring_config,
)
from pathway_tpu.internals.datetime_types import DateTimeNaive, DateTimeUtc, Duration
from pathway_tpu.internals.errors import global_error_log
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    left,
    right,
    this,
)
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import Key as Pointer
from pathway_tpu.internals.interactive import (
    LiveTable,
    enable_interactive_mode,
)
from pathway_tpu.internals.row_transformer import (
    ClassArg,
    input_attribute,
    input_method,
    method,
    output_attribute,
    transformer,
)
from pathway_tpu.internals.run import run, run_all
from pathway_tpu.internals.schema import (
    ColumnDefinition,
    Schema,
    column_definition,
    schema_builder,
    schema_from_dict,
    schema_from_pandas,
    schema_from_types,
)
from pathway_tpu.internals.table import JoinMode, Table
from pathway_tpu.internals.udfs import (
    UDF,
    AsyncRetryStrategy,
    CacheStrategy,
    DiskCache,
    ExponentialBackoffRetryStrategy,
    FixedDelayRetryStrategy,
    InMemoryCache,
    NoRetryStrategy,
    async_executor,
    auto_executor,
    fully_async_executor,
    sync_executor,
    udf,
)
from pathway_tpu.internals.parse_graph import G as parse_graph_G  # noqa: N811

# subpackages (import order matters: io/stdlib pull from internals)
from pathway_tpu import debug  # noqa: E402
from pathway_tpu import demo  # noqa: E402
from pathway_tpu import io  # noqa: E402
from pathway_tpu import persistence  # noqa: E402
from pathway_tpu import serving  # noqa: E402
from pathway_tpu.stdlib import graphs, indexing, ml, ordered, stateful, statistical, temporal, utils, viz  # noqa: E402
from pathway_tpu.internals.sql import sql  # noqa: E402
from pathway_tpu.internals.yaml_loader import load_yaml  # noqa: E402
from pathway_tpu.internals.custom_reducers import BaseCustomAccumulator  # noqa: E402

# dtype namespace parity (pw.Json handled above)
Pointer_dtype = _dtype.ANY_POINTER
universes = _universe_mod


class __module_shortcuts__:
    pass


# reference exposes reducers also at pw.reducers; xpacks lazily
from pathway_tpu import xpacks  # noqa: E402

# ---- reference top-level surface parity ----
from pathway_tpu.internals.table_slice import TableSlice  # noqa: E402
from pathway_tpu.internals.wrappers import PyObjectWrapper, wrap_py_object  # noqa: E402
from pathway_tpu.internals.monitoring import MonitoringLevel  # noqa: E402
from pathway_tpu.internals.joins import JoinResult  # noqa: E402
from pathway_tpu.internals.groupbys import GroupedTable  # noqa: E402
from pathway_tpu.stdlib.temporal import (  # noqa: E402
    AsofJoinResult,
    IntervalJoinResult,
    WindowJoinResult,
)
from pathway_tpu.stdlib.utils.async_transformer import AsyncTransformer  # noqa: E402


class PersistenceMode:
    """Persistence-mode names (reference: engine PersistenceMode enum);
    pass as `persistence_mode=` on pw.persistence.Config."""

    BATCH = "BATCH"
    PERSISTING = "PERSISTING"
    SELECTIVE_PERSISTING = "SELECTIVE_PERSISTING"
    UDF_CACHING = "UDF_CACHING"
    OPERATOR_PERSISTING = "OPERATOR_PERSISTING"


# legacy aliases the reference keeps exporting
Joinable = Table
TableLike = Table
UDFSync = UDF
UDFAsync = UDF


def join(left_table: Table, other: Table, *on, **kwargs):  # noqa: A002
    """Free-function form of Table.join (reference exports both)."""
    return left_table.join(other, *on, **kwargs)


def join_inner(left_table: Table, other: Table, *on, **kwargs):
    return left_table.join_inner(other, *on, **kwargs)


def join_left(left_table: Table, other: Table, *on, **kwargs):
    return left_table.join_left(other, *on, **kwargs)


def join_right(left_table: Table, other: Table, *on, **kwargs):
    return left_table.join_right(other, *on, **kwargs)


def join_outer(left_table: Table, other: Table, *on, **kwargs):
    return left_table.join_outer(other, *on, **kwargs)


def groupby(table: Table, *args, **kwargs):
    return table.groupby(*args, **kwargs)


# module aliases (reference: pw.csv is pw.io.csv, etc.)
csv = io.csv
jsonlines = io.jsonlines
http = io.http
kafka = io.kafka
debezium = io.debezium
elasticsearch = io.elasticsearch

__version__ = "0.1.0"

__all__ = [
    "Table", "Schema", "Json", "Pointer", "DateTimeNaive", "DateTimeUtc",
    "Duration", "JoinMode", "ColumnExpression", "ColumnReference",
    "this", "left", "right", "run", "run_all", "iterate",
    "apply", "apply_async", "apply_with_type", "cast", "declare_type",
    "coalesce", "require", "if_else", "make_tuple", "unwrap", "fill_error",
    "assert_table_has_schema", "table_transformer",
    "transformer", "ClassArg", "input_attribute", "output_attribute",
    "method", "input_method", "LiveTable", "enable_interactive_mode",
    "udf", "UDF", "udfs", "reducers",
    "column_definition", "ColumnDefinition", "schema_from_types",
    "schema_from_dict", "schema_from_pandas", "schema_builder",
    "io", "debug", "demo", "persistence", "serving", "temporal", "indexing", "ml",
    "graphs", "stateful", "statistical", "ordered", "utils", "viz", "universes",
    "sql", "load_yaml", "BaseCustomAccumulator", "xpacks",
    "get_config", "PathwayConfig", "set_license_key", "set_monitoring_config",
    "global_error_log",
    # reference top-level surface parity
    "TableSlice", "PyObjectWrapper", "wrap_py_object", "MonitoringLevel",
    "PersistenceMode", "JoinResult", "GroupedTable", "AsofJoinResult",
    "IntervalJoinResult", "WindowJoinResult", "AsyncTransformer",
    "Joinable", "TableLike", "UDFSync", "UDFAsync",
    "join", "join_inner", "join_left", "join_right", "join_outer", "groupby",
    "csv", "jsonlines", "http", "kafka", "debezium", "elasticsearch",
]
