"""pw.indexing — the index layer: KNN / BM25 / hybrid retrieval + sorting.

Reference parity: python/pathway/stdlib/indexing/__init__.py. The vector
backends are TPU-native (HBM-resident bf16 slab + fused matmul/top-k XLA
programs) instead of usearch/tantivy CPU libraries; see host_indexes.py.
"""

from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25, TantivyBM25Factory
from pathway_tpu.stdlib.indexing.colnames import (
    _INDEX_REPLY,
    _INDEX_REPLY_ID,
    _INDEX_REPLY_SCORE,
    _MATCHED_ID,
    _SCORE,
)
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.full_text_document_index import (
    default_full_text_document_index,
)
from pathway_tpu.stdlib.indexing.hybrid_index import HybridIndex, HybridIndexFactory
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    BruteForceKnnFactory,
    BruteForceKnnMetricKind,
    IvfPqKnn,
    IvfPqKnnFactory,
    LshKnn,
    LshKnnFactory,
    USearchMetricKind,
    UsearchKnn,
    UsearchKnnFactory,
)
from pathway_tpu.stdlib.indexing.reranking import RerankedSlabIndex
from pathway_tpu.stdlib.indexing.retrievers import (
    InnerIndex,
    InnerIndexFactory,
    build_index_query,
)
from pathway_tpu.stdlib.indexing.sorting import (
    build_sorted_index,
    retrieve_prev_next_values,
    sort_from_index,
)
from pathway_tpu.stdlib.indexing.vector_document_index import (
    VectorDocumentIndex,
    default_brute_force_knn_document_index,
    default_ivf_pq_knn_document_index,
    default_lsh_knn_document_index,
    default_usearch_knn_document_index,
    default_vector_document_index,
)

# reference-compat alias (reference class is named USearchKnn)
USearchKnn = UsearchKnn

__all__ = [
    "DataIndex",
    "InnerIndex",
    "InnerIndexFactory",
    "BruteForceKnn",
    "BruteForceKnnFactory",
    "BruteForceKnnMetricKind",
    "UsearchKnn",
    "USearchKnn",
    "UsearchKnnFactory",
    "USearchMetricKind",
    "IvfPqKnn",
    "IvfPqKnnFactory",
    "RerankedSlabIndex",
    "LshKnn",
    "LshKnnFactory",
    "TantivyBM25",
    "TantivyBM25Factory",
    "HybridIndex",
    "HybridIndexFactory",
    "VectorDocumentIndex",
    "default_vector_document_index",
    "default_brute_force_knn_document_index",
    "default_ivf_pq_knn_document_index",
    "default_usearch_knn_document_index",
    "default_lsh_knn_document_index",
    "default_full_text_document_index",
    "build_index_query",
    "build_sorted_index",
    "sort_from_index",
    "retrieve_prev_next_values",
]
