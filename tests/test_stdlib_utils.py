"""stdlib.utils.col (whole-table applies, json unpacking, majority,
flatten-with-origin) and stdlib.viz (notebook views). Reference:
stdlib/utils/col.py, stdlib/viz/."""

import sys
from pathlib import Path

import pytest

import pathway_tpu as pw

sys.path.insert(0, str(Path(__file__).parent))
from utils import run_capture  # noqa: E402


def _vals(table):
    cap = run_capture(table)
    return sorted(tuple(r) for r in cap.state.rows.values())


def _nums():
    return pw.debug.table_from_markdown(
        """
        colA | colB
        1    | 10
        2    | 20
        3    | 30
        """
    )


def test_apply_all_rows():
    t = _nums()

    def add_total_sum(col1, col2):
        s = sum(col1) + sum(col2)
        return [x + s for x in col1]

    res = pw.utils.col.apply_all_rows(
        t.colA, t.colB, fun=add_total_sum, result_col_name="res"
    )
    assert _vals(res) == [(67,), (68,), (69,)]
    # output keeps the ORIGINAL row ids (reference contract)
    joined = t.join(res, t.id == res.id).select(t.colA, res.res)
    assert _vals(joined) == [(1, 67), (2, 68), (3, 69)]


def test_multiapply_all_rows():
    t = _nums()

    def add2(col1, col2):
        s = sum(col1) + sum(col2)
        return [x + s for x in col1], [x + s for x in col2]

    res = pw.utils.col.multiapply_all_rows(
        t.colA, t.colB, fun=add2, result_col_names=["r1", "r2"]
    )
    assert _vals(res) == [(67, 76), (68, 86), (69, 96)]


def test_unpack_col_dict():
    t = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=pw.Json),
        rows=[
            ({"field_a": 13, "field_b": "foo", "field_c": False},),
            ({"field_a": 17, "field_c": True, "field_d": 3.4},),
        ],
    )

    class DS(pw.Schema):
        field_a: int
        field_b: str | None
        field_c: bool
        field_d: float | None

    res = pw.utils.col.unpack_col_dict(t.data, schema=DS)
    assert res.column_names() == ["field_a", "field_b", "field_c", "field_d"]
    assert _vals(res) == [(13, "foo", False, None), (17, None, True, 3.4)]


def test_groupby_reduce_majority():
    g = pw.debug.table_from_markdown(
        """
        g | v
        a | x
        a | x
        a | y
        b | z
        """
    )
    res = pw.utils.col.groupby_reduce_majority(g.g, g.v)
    assert _vals(res) == [("a", "x"), ("b", "z")]


def test_flatten_column_keeps_origin():
    fl = pw.debug.table_from_rows(
        pw.schema_from_types(items=tuple), [((1, 2),), ((3,),)]
    )
    flat = pw.utils.col.flatten_column(fl.items)
    assert flat.column_names() == ["items", "origin_id"]
    cap = run_capture(flat)
    items = sorted(r[0] for r in cap.state.rows.values())
    assert items == [1, 2, 3]
    origins = {r[1] for r in cap.state.rows.values()}
    assert len(origins) == 2  # two source rows


def test_unpack_col():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(pair=tuple), [((1, "a"),), ((2, "b"),)]
    )
    res = pw.utils.col.unpack_col(t.pair, "num", "tag")
    assert res.column_names() == ["num", "tag"]
    assert _vals(res) == [(1, "a"), (2, "b")]


# ------------------------------------------------------------------- viz


def test_show_static_html():
    t = _nums()
    view = t.show()
    h = view._repr_html_()
    assert "<table>" in h and "colA" in h and "30" in h
    assert "TableView(3 rows" in repr(view)
    # pw.Table grows a notebook repr
    assert "<table>" in t._repr_html_()


def test_show_live_view():
    import time

    t = pw.demo.range_stream(nb_rows=5, input_rate=200)
    view = t.show(snapshot=False)
    try:
        deadline = time.time() + 15
        while time.time() < deadline:
            if "live view" in view._repr_html_() and view._snapshot()[1]:
                break
            time.sleep(0.1)
        assert view._snapshot()[1], "live view never saw data"
    finally:
        view.stop()


def test_plot_requires_bokeh():
    t = _nums()
    with pytest.raises(ImportError, match="bokeh"):
        t.plot(lambda src: src)


def test_streaming_table_show_never_blocks():
    """show()/._repr_html_ on a connector-backed table must not compute
    synchronously (an unbounded stream would block forever)."""
    t = pw.demo.range_stream(nb_rows=3, input_rate=100)
    assert "streaming table" in t._repr_html_()  # placeholder, no run
    view = t.show()  # snapshot=True STILL routes to the live view
    try:
        assert view._static is None
    finally:
        view.stop()


def test_multiapply_rejects_misaligned_output():
    t = _nums()
    res = pw.utils.col.apply_all_rows(
        t.colA, fun=lambda col: [1], result_col_name="r"
    )
    from pathway_tpu.internals.lowering import Session

    before = len(pw.global_error_log().entries)
    s = Session()
    s.capture(res)
    s.execute()
    errs = pw.global_error_log().entries[before:]
    assert any("one-to-one" in str(e) for e in errs), errs


def test_unpack_col_dict_missing_required_field_poisons():
    t = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=pw.Json),
        rows=[({"b": "x"},)],
    )
    schema = pw.schema_from_types(a=int)
    res = pw.utils.col.unpack_col_dict(t.data, schema=schema)
    before = len(pw.global_error_log().entries)
    cap = run_capture(res)
    from pathway_tpu.internals.errors import ERROR

    (row,) = cap.state.rows.values()
    assert row[0] is ERROR
    assert any(
        "required field" in str(e)
        for e in pw.global_error_log().entries[before:]
    )


def test_flatten_origin_id_on_table():
    fl = pw.debug.table_from_rows(
        pw.schema_from_types(items=tuple, tag=str), [((1, 2), "t1")]
    )
    flat = fl.flatten(fl.items, origin_id="src")
    assert sorted(flat.column_names()) == ["items", "src", "tag"]
    cap = run_capture(flat)
    rows = list(cap.state.rows.values())
    assert sorted(r[0] for r in rows) == [1, 2]
    assert all(r[2] is not None for r in rows)
