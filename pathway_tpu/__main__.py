from pathway_tpu.cli import main

raise SystemExit(main())
