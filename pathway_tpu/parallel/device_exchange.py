"""Engine hook for the ICI data plane: batches whose rows carry numeric
vector columns (embeddings etc.) move those payloads across the worker
shards through the device-mesh `all_to_all` (parallel/exchange.py) instead
of the host object plane; only per-row control metadata (key, scalar
columns, diff) stays host-side.

Reference parity: SURVEY §5's TPU-native replacement for timely's TCP
exchange (external/timely-dataflow/communication/src/networking.rs) — the
bulk bytes of a shuffle ride the interconnect, the progress/control plane
stays on sockets. In a multi-host deployment each engine process drives
its slice of one global mesh and this same program spans hosts over
ICI/DCN; single-host it runs across the local (or virtual) devices, which
is what the multichip dryrun validates.

Mode (PATHWAY_DEVICE_EXCHANGE): "1" forces the device plane on, "0"
forces it off, unset = AUTO. Auto enables per batch only when all of:
  * the mesh is real multi-device TPU (on a CPU/virtual mesh the
    "device" hop is just extra copies — measured always slower), and
  * the vector payload is at least PATHWAY_DEVICE_EXCHANGE_MIN_ELEMS
    elements (default 262144 = the measured crossover against the
    pickled TCP wire on the bench host; see docs/parallelism.md for the
    full rows x width table — in-process reference-passing is always
    cheaper, so the payoff exists only where rows would otherwise
    serialize).
Payload dtypes: float32 natively; int32 rides bit-exactly as f32 views.
float64 stays host-side (casting would round row bytes and break
retraction identity) and bf16 host arrays don't exist in numpy.
"""

from __future__ import annotations

import os
from typing import Any, Callable

import numpy as np

from pathway_tpu.parallel.exchange import exchange_with_respill
from pathway_tpu.parallel.mesh import default_mesh

AUTO_MIN_ELEMS = 262_144  # measured wire crossover (docs/parallelism.md)


def mode() -> str:
    v = os.environ.get("PATHWAY_DEVICE_EXCHANGE")
    if v == "1":
        return "force"
    if v == "0":
        return "off"
    return "auto"


def enabled() -> bool:
    return mode() != "off"


def auto_min_elems() -> int:
    raw = os.environ.get("PATHWAY_DEVICE_EXCHANGE_MIN_ELEMS")
    if raw is None:
        return AUTO_MIN_ELEMS
    try:
        return int(float(raw))
    except ValueError:
        return AUTO_MIN_ELEMS  # malformed override: keep the measured default


def auto_eligible_mesh(mesh) -> bool:
    """Auto mode only pays on a real multi-device TPU mesh."""
    try:
        devs = list(mesh.devices.flat)
    except Exception:  # noqa: BLE001
        return False
    return len(devs) > 1 and getattr(devs[0], "platform", "") == "tpu"


def note_exchange_metrics(rows: int) -> None:
    """Wire-cost visibility for the adaptive planner (and /metrics),
    shared by the vector-payload and scalar-column planes: one pair of
    counters governs the AUTO crossover retune at the next epoch fence
    (internals/planner.py `_retune_exchange`)."""
    from pathway_tpu.internals import observability as _obs

    if _obs.PLANE is not None:
        m = _obs.PLANE.metrics
        m.counter(
            "pathway_device_exchange_invocations",
            help="device-mesh batch exchanges dispatched",
        )
        m.counter(
            "pathway_device_exchange_rows", inc=rows,
            help="rows moved over the device-mesh exchange",
        )


class DeviceExchanger:
    """Routes the ndarray columns of an entry batch over the device mesh.

    Per batch: rows' float ndarray columns (uniform dtype/shape across the
    batch) are stacked into one [n, d] matrix and shuffled to their
    destination shard via bucketize + all_to_all with host-exact routing;
    every other column travels as control metadata. Rows are reassembled
    at the destination in deterministic (src-major, arrival) order.
    """

    MIN_ROWS = 8  # below this the dispatch overhead always dominates

    def __init__(self, mesh=None, axis: str = "data"):
        self.mesh = mesh if mesh is not None else default_mesh((axis,))
        self.axis = axis
        self.invocations = 0
        self.rows_exchanged = 0
        self._auto_ok = auto_eligible_mesh(self.mesh)
        self._auto_min = auto_min_elems()  # parsed once, not per batch
        # _auto_min_base anchors the adaptive planner's retuning: each
        # run's policy restores it and bounds its doublings RELATIVE to
        # it, so tuning can never ratchet across pw.run invocations of
        # this process-wide exchanger (internals/planner.py).
        self._auto_min_base = self._auto_min
        # mode cached at construction too: try_exchange runs per batch
        # and an env read per batch is measurable on the wave path.
        # enabled() still reads the env per engine_exchanger() call, so
        # flipping PATHWAY_DEVICE_EXCHANGE=0 between runs is honored;
        # auto<->force flips refresh at the adaptive policy's fences.
        self._mode = mode()

    # ------------------------------------------------------------ detection

    @staticmethod
    def _vector_columns(row: tuple) -> list[int]:
        # f32 rides natively; i32 rides as a bit-exact f32 view. f64
        # would come back rounded — silently different row bytes break
        # downstream retraction matching — so it stays host-side.
        return [
            i
            for i, v in enumerate(row)
            if isinstance(v, np.ndarray)
            and v.dtype in (np.float32, np.int32)
            and v.ndim >= 1
        ]

    def try_exchange(
        self,
        entries: list,
        shard_of_entry: Callable[[Any, tuple], int],
        n_shards: int,
    ) -> list[list] | None:
        """Returns per-shard entry lists, or None when the batch isn't
        eligible (no/irregular vector columns, too small, mesh mismatch).
        shard_of_entry(key, row) must be the operator's exact host
        routing rule — device routing follows it bit-for-bit."""
        if len(entries) < self.MIN_ROWS:
            return None
        if n_shards > self.mesh.shape[self.axis]:
            return None
        first_row = entries[0][1]
        vcols = self._vector_columns(first_row)
        if not vcols:
            return None
        shapes = [first_row[c].shape for c in vcols]
        dtypes = [first_row[c].dtype for c in vcols]
        n = len(entries)
        if self._mode == "off":
            return None
        if self._mode == "auto":
            n_elems = n * sum(
                int(np.prod(s)) for s in shapes
            )
            if not (self._auto_ok and n_elems >= self._auto_min):
                return None  # below the measured wire crossover
        dests = np.empty(n, np.int64)
        mats = []
        try:
            for j, c in enumerate(vcols):
                mat = np.stack([e[1][c] for e in entries])
                if mat.dtype != dtypes[j]:
                    # some LATER row changed dtype: casting would change
                    # row bytes silently (see _vector_columns) — host path
                    return None
                if mat.dtype == np.int32:
                    mat = mat.view(np.float32)  # bit-exact transport form
                mats.append(mat.reshape(n, -1))
            for i, (key, row, _diff) in enumerate(entries):
                dests[i] = shard_of_entry(key, row)
        except Exception:  # noqa: BLE001 — ragged rows / failing routes
            return None
        widths = [m.shape[1] for m in mats]
        payload = np.concatenate(mats, axis=1) if len(mats) > 1 else mats[0]
        # u32 ids are only for debugging; reassembly uses src indices
        ids = (np.arange(n) & 0xFFFFFFFF).astype(np.uint32)
        _keys, pays, srcs = exchange_with_respill(
            ids, payload, dests, self.mesh, self.axis
        )
        self.invocations += 1
        self.rows_exchanged += n
        note_exchange_metrics(n)
        out: list[list] = [[] for _ in range(n_shards)]
        for d in range(n_shards):
            for vec_row, i in zip(pays[d], srcs[d]):
                key, row, diff = entries[int(i)]
                parts = np.split(vec_row, np.cumsum(widths)[:-1]) if len(mats) > 1 else [vec_row]
                new_row = list(row)
                for j, c in enumerate(vcols):
                    p = np.ascontiguousarray(parts[j], np.float32)
                    if dtypes[j] == np.int32:
                        p = p.view(np.int32)  # undo the bit-exact view
                    new_row[c] = p.reshape(shapes[j])
                out[d].append((key, tuple(new_row), diff))
        return out


_ENGINE_EXCHANGER: DeviceExchanger | None = None


def engine_exchanger() -> DeviceExchanger | None:
    """Process-wide exchanger for ShardedNode, when enabled and a device
    mesh is constructible."""
    global _ENGINE_EXCHANGER
    if not enabled():
        return None
    if _ENGINE_EXCHANGER is None:
        try:
            _ENGINE_EXCHANGER = DeviceExchanger()
        except Exception:  # noqa: BLE001 — no usable devices
            return None
    return _ENGINE_EXCHANGER
