"""Engine runtime: the per-worker pump loop.

Reference parity: run_with_new_dataflow_graph (src/engine/dataflow.rs:5506)
— connector pollers feeding input sessions, commit timestamps on an
even-millisecond total order (src/engine/timestamp.rs:20-27), a pump that
finalizes one timestamp per wave, and end-of-stream flush.
"""

from __future__ import annotations

import asyncio
import os
import queue
import threading
import time as _time
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from pathway_tpu.engine import faults
from pathway_tpu.internals import observability as _obs
from pathway_tpu.engine.core import (
    CaptureNode,
    Entry,
    Graph,
    InputNode,
    KeyedState,
    Node,
    _kv_cols,
    _kvs_of,
    _tok_plane,
    _wave_arrays,
    consolidate,
    freeze_row,
    iterate_native_on,
    nks_decode,
    nks_encode,
)
from pathway_tpu.internals.errors import ERROR
from pathway_tpu.internals.keys import Key, key_for_values, sequential_key
from pathway_tpu.analysis import lockgraph as _lockgraph


class OffsetMark:
    """In-stream frontier marker (reference: OffsetAntichain,
    src/persistence/frontier.rs): every event staged BEFORE this mark is
    covered by `frontier` — a {partition: position} dict whose shape the
    source owns (file -> byte position / ('done', mtime, size); kafka
    topic:partition -> next offset). The persistence layer checkpoints the
    frontier instead of journaling seekable sources' events; plain runs
    drop marks at poll time."""

    __slots__ = ("frontier",)

    def __init__(self, frontier: dict):
        self.frontier = frontier


class InputSession:
    """Thread-safe staging buffer feeding an InputNode.

    Mirrors the reference's input session + upsert session
    (src/connectors/adaptors.rs:23): `upsert` overwrites by key, `insert`/
    `remove` are plain z-set deltas.
    """

    def __init__(self, node: InputNode, upsert: bool = False):
        self.node = node
        self.upsert_mode = upsert
        self._lock = _lockgraph.register_lock(
            "runtime.input_session", threading.Lock()
        )
        self._staged: list[Entry] = []
        self._current: dict[Key, tuple] = {}  # for upsert sessions
        self.closed = False
        self.has_marks = False
        # persistence sets this before the reader starts: sources seek
        # past everything a committed checkpoint already covers
        self.resume_frontier: dict | None = None

    def mark_frontier(self, frontier: dict) -> None:
        """Stage an offset-frontier mark covering everything staged so
        far (offset-aware sources call this at record-aligned positions)."""
        self.has_marks = True
        with self._lock:
            self._staged.append(OffsetMark(dict(frontier)))

    def insert(self, key: Key, row: tuple) -> None:
        with self._lock:
            if self.upsert_mode:
                old = self._current.get(key)
                if old is not None:
                    self._staged.append((key, old, -1))
                self._current[key] = row
            self._staged.append((key, row, 1))

    def insert_batch(self, nbatch) -> None:
        """Stage a token-resident NativeBatch segment whole (plain insert
        sessions only — upsert bookkeeping is inherently per-row)."""
        assert not self.upsert_mode
        with self._lock:
            self._staged.append(nbatch)

    def remove(self, key: Key, row: tuple | None = None) -> None:
        with self._lock:
            if self.upsert_mode:
                old = self._current.pop(key, None)
                if old is not None:
                    self._staged.append((key, old, -1))
            elif row is not None:
                self._staged.append((key, row, -1))

    def drain(self) -> list[Entry]:
        with self._lock:
            staged, self._staged = self._staged, []
        return staged

    def close(self) -> None:
        self.closed = True


class Connector:
    """A data source with its own reader thread (reference:
    src/connectors/mod.rs:427 Connector::run — one thread per input
    connector, poller drained by the main pump).

    `replay_style` drives persistence resume (reference: seekable vs
    non-seekable sources in src/persistence/frontier.rs offset logic):
      * 'seekable' — the source re-reads deterministically from the start
        on every run (files, scripted subjects); resume skips the first N
        live events already journaled.
      * 'live' — the source only ever delivers new events (message
        queues); nothing is skipped, the journal supplies history.
    """

    replay_style = "seekable"

    def __init__(self, name: str, session: InputSession):
        self.name = name
        self.session = session
        self.thread: threading.Thread | None = None
        self.finished = threading.Event()

    def start(self) -> None:
        pass

    def poll(self) -> list[Entry]:
        staged = self.session.drain()
        if self.session.has_marks:
            # frontier marks matter only under persistence (the
            # PersistentConnector drains the session itself); plain runs
            # drop them here so they never reach the engine
            staged = [s for s in staged if type(s) is not OffsetMark]
        return staged

    @property
    def done(self) -> bool:
        return self.finished.is_set() and not self.session._staged


class ThreadConnector(Connector):
    """Runs a read function on a dedicated thread."""

    def __init__(self, name: str, session: InputSession, read_fn: Callable[[InputSession], None]):
        super().__init__(name, session)
        self.read_fn = read_fn

    def start(self) -> None:
        def run() -> None:
            try:
                self.read_fn(self.session)
            finally:
                self.finished.set()

        self.thread = threading.Thread(target=run, daemon=True, name=f"pw-connector-{self.name}")
        self.thread.start()


class Runtime:
    """Per-worker pump. Timestamps are even milliseconds from run start.

    Streaming and mesh execution are frontier-driven (engine/frontier.py):
    every source owns a watermark, waves carry (time, batch), and an
    operator fires for time t as soon as its input frontier passes t —
    there is no global wave barrier. ``run_static`` keeps the exact
    deterministic batch pump for debug computations.
    """

    # hard ceiling on one checkpoint-fence/end quiesce (_mesh_quiesce):
    # a genuinely livelocked mesh fails loudly with a state dump instead
    # of hanging forever; generous because a legitimate wave mid-fence
    # may be arbitrarily slow (first-touch XLA compile)
    _QUIESCE_TIMEOUT_S = 120.0

    def __init__(self, graph: Graph, autocommit_ms: int = 2):
        self.graph = graph
        self.autocommit_ms = max(2, autocommit_ms - autocommit_ms % 2)
        self.time = 0
        self.connectors: list[Connector] = []
        self.monitors: list[Callable[[int], None]] = []
        # checkpoint/resume orchestration (persistence.CheckpointManager)
        self.checkpointer: Any = None
        # cooperative stop: ends the pump at the next wave boundary
        self.stop_event: Any = None
        # inter-process data plane (parallel/process_mesh.py)
        self.mesh: Any = None
        # session sequence for namespacing mesh control tags
        self.session_seq = 0
        # the live FrontierScheduler (set by run/run_mesh; tests inspect)
        self.scheduler: Any = None

    def next_time(self) -> int:
        self.time += 2  # even-ms granule, reference timestamp.rs:20-27
        return self.time

    def add_connector(self, connector: Connector) -> None:
        self.connectors.append(connector)

    # ------------------------------------------------------ frontier pumps

    def _make_scheduler(self):
        from pathway_tpu.engine.frontier import FrontierScheduler

        if getattr(self.graph, "_cones", None):
            # the frontier scheduler fires finish_time per node and
            # stashes emissions per slot — an installed cone would never
            # fire there. Dissolve loudly (plan report + flight event)
            # so the fallback to per-node dispatch is visible, never
            # silent (engine/cone.py).
            from pathway_tpu.engine.cone import dissolve_cones

            dissolve_cones(self.graph, "frontier-scheduler")
        sched = FrontierScheduler(self.graph, monitors=self.monitors)
        self.scheduler = sched
        self.graph.scheduler = sched
        return sched

    def _kick_sources(self, sched) -> dict:
        """Register kick sources for capability-holding operators
        (iterate scopes with truncated convergence): the pump schedules
        empty waves through their cones until they drop the capability."""
        return {
            node: sched.add_kick_source(node)
            for node in self.graph.nodes
            if hasattr(node, "_pending_convergence")
        }

    def _stage_kicks(self, sched, kicks: dict) -> None:
        for node, tok in kicks.items():
            if node._pending_convergence:
                sched.stage(tok, self.next_time())

    def run(self) -> None:
        """Streaming pump: poll until all connectors are done, then
        flush + end.

        Each connector is its own SOURCE: a poll that yields data
        becomes a wave at a fresh timestamp of that source alone, and
        only that source's downstream cone fires. A slow source
        therefore delays nothing outside its own cone — operators
        downstream of other sources keep processing newer timestamps
        while the straggler catches up (frontier semantics; previously
        every wave stepped the whole graph at one shared timestamp).
        """
        try:
            self._run_streaming()
        except BaseException as e:
            if _obs.PLANE is not None:
                _obs.PLANE.record(
                    "runtime.error", error=f"{type(e).__name__}: {e}"[:500]
                )
                _obs.dump_flight("error")
            raise

    def _run_streaming(self) -> None:
        for c in self.connectors:
            c.start()
        if not self.connectors:
            t = self.next_time()
            self.graph.step(t)
            self.graph.end(t)
            return
        sched = self._make_scheduler()
        sched.allow_async = True  # deferred device waves pipeline here
        src = {c: sched.add_source(c.session.node) for c in self.connectors}
        kicks = self._kick_sources(sched)
        closed: set = set()
        ckpt_dirty = False
        # metrics-fed re-planning at safe epoch fences (fully-drained
        # scheduler): needs the observability plane for its signal and
        # the optimizer enabled (docs/planner.md)
        policy = None
        from pathway_tpu.internals import planner as _planner

        if (
            _obs.PLANE is not None
            and _planner.fuse_enabled()
            and _planner.adaptive_enabled()
        ):
            policy = _planner.AdaptivePolicy(
                self.graph, getattr(self.graph, "plan_report", None)
            )
        while True:
            plane = _obs.PLANE
            if plane is None:
                _time.sleep(self.autocommit_ms / 1000.0)
                for c in self.connectors:
                    entries = c.poll()
                    if entries:
                        sched.stage(src[c], self.next_time(), entries)
            else:
                t0 = _time.perf_counter()
                _time.sleep(self.autocommit_ms / 1000.0)
                t1 = _time.perf_counter()
                plane.stage_seconds("idle", t1 - t0)
                for c in self.connectors:
                    entries = c.poll()
                    if entries:
                        sched.stage(src[c], self.next_time(), entries)
                plane.stage_seconds("poll", _time.perf_counter() - t1)
            stopped = self.stop_event is not None and self.stop_event.is_set()
            for c in self.connectors:
                if (stopped or c.done) and src[c] not in closed:
                    closed.add(src[c])
                    sched.close(src[c])
            self._stage_kicks(sched, kicks)
            sched.advance_local(self.time)
            if sched.pump():
                ckpt_dirty = True
                # chaos drills: die hard right after a wave retired, with
                # its input offsets consumed but no checkpoint cut yet
                faults.crash("runtime.wave")
            if plane is not None:
                plane.tick_sources(
                    self.time,
                    lambda: [
                        (c.name, sched.watermark(src[c]))
                        for c in self.connectors
                    ],
                    sched.global_frontier,
                )
            # checkpoint on cadence whenever there is anything new to
            # commit — retired waves OR offset-frontier advances (a
            # quiet stream whose source finished a file still needs its
            # frontier made durable). The cut is at the global frontier:
            # after a pump every staged wave at or below it has retired.
            if (
                self.checkpointer is not None
                and self.checkpointer.due()
                and (ckpt_dirty or self.checkpointer.frontier_advanced())
                # never cut while a deferred device wave is in flight:
                # its input offsets are consumed but its results exist
                # only in the (non-persisted) in-flight future — a crash
                # after this cut would drop the wave. Holds resolve
                # within a dispatch, so the cut lands next cadence.
                and not sched.has_async()
            ):
                if plane is None:
                    self.checkpointer.checkpoint(self.time)
                else:
                    t0 = _time.perf_counter()
                    self.checkpointer.checkpoint(self.time)
                    plane.stage_seconds(
                        "checkpoint", _time.perf_counter() - t0
                    )
                ckpt_dirty = False
            # adaptive re-planning: only at a true epoch fence (nothing
            # in flight, nothing deferred) so a rewired cone can never
            # strand a staged wave on a replaced node
            if (
                policy is not None
                and sched.fully_drained()
                and not sched.has_async()
            ):
                # refresh pathway_spill_{runs,bytes} gauges at the fence
                # (seal/compact publish too, but an idle store's gauges
                # would otherwise go stale after restore)
                from pathway_tpu.engine import spill as _spill

                _spill.publish_metrics()
                policy.maybe_replan(sched)
            if len(closed) == len(self.connectors):
                # final drain: anything staged between the last poll and
                # the connector finishing
                final = False
                for c in self.connectors:
                    entries = c.poll()
                    if entries:
                        sched.stage(src[c], self.next_time(), entries)
                        final = True
                if final:
                    sched.advance_local(self.time)
                    sched.pump()
                # deferred device waves may still be computing: pump
                # until every hold resolves before ending the stream
                self._drain(sched, "streaming drain")
                t = self.next_time()
                self.graph.end(t)
                if self.checkpointer is not None:
                    self.checkpointer.checkpoint(t)
                    self.checkpointer.close()
                break

    # ---------------------------------------------------------- mesh pump

    def _drain_mesh(self, sched, mesh, remote_tokens) -> bool:
        """Pull watermark announcements + data buckets from the mesh
        into the scheduler. The watermark snapshot is taken atomically
        with (and logically before) the inbox drain, so a wire watermark
        of W is never acted on before every bucket at or below W from
        that peer has been staged (TCP frames from one peer arrive in
        send order)."""
        wm, buckets = mesh.take_frontier_updates()
        staged = False
        for (wire, time, peer, payload) in buckets:
            if not isinstance(time, (int, float)):
                # a peer already at the END BARRIER tags buckets with
                # ('end', t): they belong to the keyed blocking
                # exchange this process will run at its own graph.end
                mesh.restore_bucket(wire, time, peer, payload)
                continue
            tok = remote_tokens.get((wire, peer))
            if tok is None:
                # another session's wire on the shared process-wide
                # mesh: put it back for that session to claim (its
                # enable_frontier_inbox sweep recovers keyed buckets)
                mesh.restore_bucket(wire, time, peer, payload)
                continue
            sched.stage(tok, time, payload)
            staged = True
            if time > self.time:
                # keep the local clock ahead of every observed remote
                # time so fresh local waves never sort behind them
                self.time = time + (time % 2)
        for (wire, peer), value in wm.items():
            tok = remote_tokens.get((wire, peer))
            if tok is not None:
                sched.advance(tok, value)
        return staged

    def _pump_mesh(self, sched, mesh, xnodes, sent: dict) -> bool:
        """Fire until stable, in small chunks: after every few
        notifications, announce each wire's advanced frontier (min over
        the sources reaching its exchange node, bounded by in-flight
        waves — nothing at or below it will ever be sent on the wire
        again) and drain newly-arrived remote buckets/watermarks. The
        chunking keeps this process's outgoing frontiers moving even
        through a long grind of slow operator waves — peers gated on
        these wires progress concurrently instead of freezing until
        the grind ends."""
        fired_any = False
        while True:
            fired = sched.pump(budget=8)
            if fired:
                fired_any = True
                # chaos drills: one worker dies right after waves retired
                # — whether this pump serves the main loop or a fence
                # quiesce round. Peers observe the death on their wires
                # and abort with WorkerLost for the supervisor to restart.
                faults.crash("runtime.mesh.wave")
            moved = False
            for x in xnodes:
                f = sched.frontier_of_node(x)
                if f > sent[x.wire_id]:
                    sent[x.wire_id] = f
                    mesh.send_wm(x.wire_id, f)
                    moved = True
            if self._drain_mesh(sched, mesh, self._remote_tokens):
                moved = True
            if not moved and not fired:
                return fired_any

    def _mesh_quiesce(self, sched, mesh, xnodes, sent, tag: str, rounds: int):
        """Barrier-drain rounds until the mesh is PROVABLY quiescent.

        Each round: advance the local clock over everything staged so
        far (a remote bucket above the step-1 watermark must become
        admissible, or it would sit stashed forever), allgather
        (local_time, fully_drained, data_frames_sent), sync the local
        clock to the mesh-wide max (announcements are capped by the
        local clock, and nothing advances it inside a fence — without
        the sync a peer's wave stashed above a slow process's clock
        livelocks the mesh), then drain+pump.
        The loop ends — identically on every process, because the
        decision reads only the allgathered view — once

          * at least ``rounds`` (= 2*exchange_depth+2) rounds ran, AND
          * every process entered the round fully drained, AND
          * no process's data-frame counter moved since the previous
            round (frames sent before a peer's barrier frame are
            ordered before it, so an unchanged counter means nothing
            is in flight anywhere).

        A fixed round count alone is NOT enough: a wave can lawfully
        stay stashed across many rounds while watermarks catch up, and
        a checkpoint cut with a stashed wave commits its input offsets
        without its effects — the recovered run silently loses it (the
        chaos drill's supervised-mesh case caught exactly this).
        Returns the final allgather view {proc: local_time}."""
        prev_sent: dict | None = None
        r = 0
        q0 = _time.perf_counter()
        deadline = _time.monotonic() + self._QUIESCE_TIMEOUT_S
        while True:
            sched.advance_local(self.time)
            view = mesh.allgather(
                f"{tag}-r{r}",
                (self.time, sched.fully_drained(), mesh.data_frames_sent),
            )
            # clock sync: my wire announcements are capped by my local-
            # source watermark = my clock, and with no connector polls
            # inside the fence the clock is FROZEN. A peer wave stashed
            # above it (its clock ran ahead and its bucket routed only
            # to itself) would wait on my announcement forever — the
            # mesh livelocks. Jumping to the mesh-wide max is safe for
            # the same reason _drain_mesh's bump on observed bucket
            # times is: every future local wave is stamped via
            # next_time() strictly above self.time.
            tmax = max(v[0] for v in view.values())
            if tmax > self.time:
                self.time = tmax
            self._drain_mesh(sched, mesh, self._remote_tokens)
            sched.advance_local(self.time)  # drained buckets moved the clock
            self._pump_mesh(sched, mesh, xnodes, sent)
            drained = all(v[1] for v in view.values())
            sent_now = {p: v[2] for p, v in view.items()}
            if r + 1 >= rounds and drained and sent_now == prev_sent:
                if _obs.PLANE is not None:
                    # metric only: waves fired inside the fence window are
                    # already attributed per-operator by the scheduler's
                    # span hook — feeding the window to the profiler too
                    # would count that wall-clock twice
                    _obs.PLANE.stage_seconds(
                        "quiesce", _time.perf_counter() - q0, profile=False
                    )
                    _obs.PLANE.record(
                        "mesh.quiesce", export=False, tag=tag, rounds=r + 1,
                        time=self.time,
                    )
                return {p: v[0] for p, v in view.items()}
            prev_sent = sent_now
            r += 1
            if _time.monotonic() > deadline:
                # wall-clock, not round-count: rounds are cheap on a
                # localhost mesh, and a legitimately slow wave (huge
                # first-touch compile) must not trip a spurious failure
                pend = {
                    slot: sorted(times)[:4]
                    for slot, times in sched._pending.items()
                    if times
                }
                # poison the wires BEFORE raising: peers are blocked in
                # the next round's allgather (which has no deadline of
                # its own) — closing our sockets flips us to dead on
                # their side, so they abort with WorkerLost instead of
                # hanging if this process survives the error
                try:
                    mesh.close()
                except Exception:  # noqa: BLE001 — best-effort poison
                    pass
                raise RuntimeError(
                    f"mesh quiesce {tag!r} failed to converge after "
                    f"{self._QUIESCE_TIMEOUT_S:.0f}s ({r} rounds): "
                    f"time={self.time} pending={pend} "
                    f"async={sorted(sched._async_waves)} view={view}"
                )

    def _mesh_rebalance_exit(self, mesh: Any, sid: int) -> None:
        """End this generation at a membership fence. Every process just
        committed the same epoch; an rb-ack flag barrier proves it mesh-
        wide (a process must not exit — killing its wires — while a peer
        is still quiescing toward that fence). Process 0, the only one
        holding the lowered graph, then re-homes the persisted shards
        before exiting. Never returns: raises SystemExit(REBALANCE_EXIT),
        which the supervisor treats as a planned generation boundary."""
        from pathway_tpu.parallel import membership as _mb
        from pathway_tpu.parallel.process_mesh import WorkerLost

        mesh.send_flag(("rb-ack", sid), 1)
        mesh.set_flag(("rb-ack", sid), 1)
        deadline = _time.monotonic() + 120.0
        while not all(
            mesh.flag_of(("rb-ack", sid), p, 0) for p in mesh.peers
        ):
            if mesh._dead:
                raise WorkerLost(
                    f"process {mesh.process_id}: peer(s) "
                    f"{sorted(mesh._dead)} died during the rebalance "
                    "quiesce; resume from the last committed checkpoint"
                )
            if _time.monotonic() > deadline:
                raise RuntimeError(
                    f"process {mesh.process_id}: rebalance quiesce ack "
                    "timed out"
                )
            mesh.wait_frames(0.05)
        if self.checkpointer is not None:
            self.checkpointer.close()
        if mesh.process_id == 0:
            _mb.rebalance_at_fence(self)
        _obs.record("runtime.rebalance_exit", process=mesh.process_id)
        raise SystemExit(_mb.REBALANCE_EXIT)

    def run_mesh(
        self, static_batches: list[tuple[int, InputNode, list[Entry]]] | None = None
    ) -> None:
        """Multi-process frontier pump: replaces the lockstep BSP wave
        barrier (``run_lockstep``) with asynchronous progress tracking.

        Every process pumps its OWN sources at its own pace; exchange
        channels carry (time, batch) plus per-wire watermark
        announcements, and a downstream operator fires for time t as
        soon as its input frontier — local sources AND incoming wires —
        passes t. A straggling process therefore delays only the
        operators that causally consume its data; causally-independent
        cones on every peer keep processing at full speed (reference:
        timely's distributed progress protocol, progress/frontier.rs).

        Checkpoints cut at globally fully-retired times: the cadence
        owner (process 0) raises a FENCE; every process stops admitting
        input, the mesh drains to quiescence over barrier rounds, and
        all processes snapshot the same epoch — mutually consistent by
        construction (no wave is half-absorbed anywhere).
        """
        try:
            self._run_mesh(static_batches)
        except BaseException as e:
            if isinstance(e, SystemExit) and e.code == 75:
                # planned rebalance exit (parallel/membership.py), not a
                # crash: no postmortem
                raise
            # postmortem before the supervisor restarts the generation:
            # the recorder holds the last waves/frames/faults this worker
            # saw, which is exactly what "why did the mesh die" needs
            if _obs.PLANE is not None:
                _obs.PLANE.record(
                    "runtime.error", error=f"{type(e).__name__}: {e}"[:500]
                )
                _obs.dump_flight("error")
            raise

    def _run_mesh(
        self, static_batches: list[tuple[int, InputNode, list[Entry]]] | None = None
    ) -> None:
        from pathway_tpu.engine.frontier import DONE
        from pathway_tpu.engine.workers import ProcessExchangeNode
        from pathway_tpu.parallel import membership as _mb
        from pathway_tpu.parallel.process_mesh import WorkerLost

        mesh = self.mesh
        assert mesh is not None
        sched = self._make_scheduler()
        sid = self.session_seq
        for c in self.connectors:
            c.start()
        src = {c: sched.add_source(c.session.node) for c in self.connectors}
        statics_by_node: dict[int, Any] = {}
        for t, node, entries in sorted(
            static_batches or [], key=lambda b: b[0]
        ):
            tok = statics_by_node.get(node.node_id)
            if tok is None:
                tok = statics_by_node[node.node_id] = sched.add_source(node)
            sched.stage(tok, t, entries)
            self.time = max(self.time, t + (t % 2))
        for tok in statics_by_node.values():
            sched.close(tok)
        kicks = self._kick_sources(sched)
        xnodes = [
            n for n in self.graph.nodes if isinstance(n, ProcessExchangeNode)
        ]
        self._remote_tokens: dict[tuple[int, int], Any] = {}
        for x in xnodes:
            x.frontier_mode = True
            for p in mesh.peers:
                self._remote_tokens[(x.wire_id, p)] = sched.add_remote_source(
                    x, p
                )
        mesh.enable_frontier_inbox()
        wm_sent = {x.wire_id: -1 for x in xnodes}
        rounds = 2 * sched.reach.exchange_depth() + 2
        fences_handled = 0
        fences_raised = 0
        closed: set = set()
        done_sent = False
        ckpt_dirty = False
        # elastic membership (parallel/membership.py): process 0 watches
        # for quiesce requests under the SHARED persistence root; every
        # process stops admitting input once the quiesce flag is seen and
        # exits REBALANCE_EXIT after the final fence commits
        shared_root: str | None = None
        if self.checkpointer is not None:
            shared_root = os.path.dirname(
                os.path.abspath(self.checkpointer.config.backend.path)
            )
        elastic = shared_root is not None and _mb.elastic_enabled()
        if elastic:
            _mb.write_source_map(
                self.checkpointer.config.backend.path, self.connectors
            )
        try:
            while True:
                quiescing = elastic and bool(
                    mesh.flag_value(("quiesce", sid), default=0)
                )
                if mesh._dead:
                    # supervised recovery: abort THIS wave cleanly (no
                    # partial checkpoint — the last committed epoch stays
                    # the resume point) and surface a typed error the
                    # supervisor restarts the whole mesh on. Every peer
                    # observes the death on its own wires, so the mesh
                    # drains instead of hanging on a barrier.
                    raise WorkerLost(
                        f"process {mesh.process_id}: peer(s) "
                        f"{sorted(mesh._dead)} died mid-run; resume from "
                        "the last committed checkpoint"
                    )
                # 1. local ingestion: one fresh wave per source per poll
                # (suspended during a rebalance quiesce: anything consumed
                # after the final fence would be lost to the next
                # generation, which resumes from that fence's offsets)
                if not quiescing:
                    for c in self.connectors:
                        entries = c.poll()
                        if entries:
                            sched.stage(src[c], self.next_time(), entries)
                            ckpt_dirty = True
                stopped = (
                    self.stop_event is not None and self.stop_event.is_set()
                )
                for c in self.connectors:
                    if (stopped or c.done) and src[c] not in closed:
                        closed.add(src[c])
                        sched.close(src[c])
                self._stage_kicks(sched, kicks)
                sched.advance_local(self.time)
                # 2. remote ingestion + watermark announcements
                self._drain_mesh(sched, mesh, self._remote_tokens)
                # 3. fire everything the frontier allows; announce wires
                # (the runtime.mesh.wave crash point probes inside
                # _pump_mesh, so fence-quiesce waves count too)
                if self._pump_mesh(sched, mesh, xnodes, wm_sent):
                    ckpt_dirty = True
                if _obs.PLANE is not None:
                    _obs.PLANE.tick_sources(
                        self.time,
                        lambda: [
                            (c.name, sched.watermark(src[c]))
                            for c in self.connectors
                        ],
                        sched.global_frontier,
                    )
                # 4. checkpoint fences (cadence owned by process 0)
                if (
                    elastic
                    and mesh.process_id == 0
                    and not done_sent
                    and not quiescing
                    and _mb.quiesce_requested(shared_root)
                ):
                    # membership change pending: broadcast the quiesce
                    # (flag value = the fence number that seals this
                    # generation) BEFORE raising that fence — per-peer
                    # frame ordering makes every process see the quiesce
                    # no later than the fence itself
                    quiescing = True
                    fences_raised += 1
                    mesh.send_flag(("quiesce", sid), fences_raised)
                    mesh.set_flag(("quiesce", sid), fences_raised)
                    mesh.send_flag(("fence", sid), fences_raised)
                    mesh.set_flag(("fence", sid), fences_raised)
                elif (
                    mesh.process_id == 0
                    and not done_sent
                    and not quiescing
                    and self.checkpointer is not None
                    and self.checkpointer.due()
                    and (ckpt_dirty or self.checkpointer.frontier_advanced())
                ):
                    fences_raised += 1
                    mesh.send_flag(("fence", sid), fences_raised)
                    mesh.set_flag(("fence", sid), fences_raised)
                pending_fence = mesh.flag_value(("fence", sid), default=0)
                while fences_handled < pending_fence:
                    fences_handled += 1
                    self._mesh_quiesce(
                        sched, mesh, xnodes, wm_sent,
                        f"s{sid}-fence-{fences_handled}", rounds,
                    )
                    if not sched.fully_drained():
                        # committing here would persist input offsets for
                        # waves whose effects are still in flight — the
                        # recovered run would silently drop them
                        raise RuntimeError(
                            f"process {mesh.process_id}: checkpoint fence "
                            f"{fences_handled} reached with undrained waves"
                        )
                    if self.checkpointer is not None:
                        self.checkpointer.checkpoint(self.time)
                        ckpt_dirty = False
                    pending_fence = mesh.flag_value(("fence", sid), default=0)
                # 4b. rebalance exit: the quiesce flag names the fence
                # that seals this generation; once THAT fence's epoch is
                # committed everywhere, acknowledge and hand the roots to
                # the rebalancer (process 0) / exit (peers)
                quiesce_fence = (
                    mesh.flag_value(("quiesce", sid), default=0)
                    if elastic
                    else 0
                )
                if quiesce_fence and fences_handled >= quiesce_fence:
                    self._mesh_rebalance_exit(mesh, sid)  # never returns
                # 5. termination: local done -> announce; global done ->
                # drain to quiescence and end together
                local_done = len(closed) == len(self.connectors)
                if local_done and not done_sent:
                    final = False
                    for c in self.connectors:
                        entries = c.poll()
                        if entries:
                            sched.stage(src[c], self.next_time(), entries)
                            final = True
                    if final:
                        sched.advance_local(self.time)
                        self._pump_mesh(sched, mesh, xnodes, wm_sent)
                    for tok in kicks.values():
                        sched.close(tok)
                    sched.advance_local(DONE)
                    self._pump_mesh(sched, mesh, xnodes, wm_sent)
                    done_sent = True
                    mesh.send_flag(("done", sid), 1)
                    mesh.set_flag(("done", sid), 1)
                if done_sent and all(
                    mesh.flag_of(("done", sid), p) for p in mesh.peers
                ):
                    # a fence raised just before a peer announced done is
                    # ordered before its done flag: handle it first
                    pending_fence = mesh.flag_value(("fence", sid), default=0)
                    if fences_handled < pending_fence:
                        continue
                    vals = self._mesh_quiesce(
                        sched, mesh, xnodes, wm_sent, f"s{sid}-end", rounds
                    )
                    t_end = max(max(vals.values()), self.time) + 2
                    self.time = t_end
                    mesh.frontier_inbox = False
                    for x in xnodes:
                        x.end_barrier = True
                    self.graph.end(t_end)
                    if self.checkpointer is not None:
                        self.checkpointer.checkpoint(t_end)
                        self.checkpointer.close()
                    break
                if _obs.PLANE is None:
                    mesh.wait_frames(self.autocommit_ms / 1000.0)
                else:
                    t0 = _time.perf_counter()
                    mesh.wait_frames(self.autocommit_ms / 1000.0)
                    _obs.PLANE.stage_seconds(
                        "idle", _time.perf_counter() - t0
                    )
        finally:
            mesh.frontier_inbox = False

    def run_lockstep(
        self, static_batches: list[tuple[int, InputNode, list[Entry]]] | None = None
    ) -> None:
        """DEPRECATED lockstep BSP pump (PATHWAY_MESH_BSP=1 fallback and
        the measured baseline for docs/parallelism.md): every process
        executes the same wave sequence in lockstep, so one slow worker
        bounds the whole mesh's wave rate. Superseded by ``run_mesh``'s
        frontier-based progress tracking. A per-round control exchange
        gives each process the identical (any_data, all_done) view so
        wave times and termination agree everywhere."""
        mesh = self.mesh
        assert mesh is not None
        for c in self.connectors:
            c.start()
        statics = sorted(static_batches or [], key=lambda b: b[0])
        # checkpoint cadence must be a deterministic function of the
        # SHARED round count — per-process wall clocks would snapshot at
        # different waves, leaving exchange rounds (and therefore resume)
        # mutually inconsistent
        ckpt_every = 1
        if self.checkpointer is not None:
            interval = self.checkpointer.config.snapshot_interval_ms
            ckpt_every = max(1, interval // max(self.autocommit_ms, 1))
        rnd = 0
        waves = 0
        while True:
            has_data = False
            t_hint = 0
            if statics:  # feed one scripted timestamp per wave
                t_hint = statics[0][0]
                while statics and statics[0][0] == t_hint:
                    _t, node, entries = statics.pop(0)
                    node.push(
                        list(entries) if type(entries) is list else entries
                    )
                    has_data = True
            for c in self.connectors:
                entries = c.poll()
                if entries:
                    c.session.node.push(entries)
                    has_data = True
            stopped = self.stop_event is not None and self.stop_event.is_set()
            local_done = (
                not statics
                and (stopped or all(c.done for c in self.connectors))
            )
            any_data, all_done, t_max = mesh.control_round(
                rnd, has_data, local_done, t_hint
            )
            rnd += 1
            if any_data:
                # scripted timestamps win (identical everywhere via the
                # control exchange); live waves use the even-ms counter
                self.time = max(self.time + 2, t_max)
                t = self.time
                self.graph.step(t)
                waves += 1
                for m in self.monitors:
                    m(t)
                if self.checkpointer is not None and waves % ckpt_every == 0:
                    self.checkpointer.checkpoint(t)
            elif not all_done:
                _time.sleep(self.autocommit_ms / 1000.0)
            if all_done and not any_data:
                t = self.next_time()
                self.graph.end(t)
                if self.checkpointer is not None:
                    self.checkpointer.checkpoint(t)
                    self.checkpointer.close()
                break

    def run_static(self, batches: list[tuple[int, InputNode, list[Entry]]]) -> None:
        """Batch mode: feed pre-timed batches, run each wave, then end.

        `batches` are (time, node, entries); times must use the even-ms
        domain. Pipelines with deferrable device stages (async-apply
        under stage overlap) run through the frontier scheduler so waves
        at distinct timestamps pipeline across operators; everything
        else keeps the exact deterministic lockstep pump.
        """
        if self._wants_stage_overlap():
            return self._run_static_frontier(batches)
        by_time: dict[int, list[tuple[InputNode, list[Entry]]]] = {}
        for t, node, entries in batches:
            by_time.setdefault(t, []).append((node, entries))
        last_t = 0
        for t in sorted(by_time):
            for node, entries in by_time[t]:
                node.push(entries)
            self.graph.step(t)
            last_t = t
        self.graph.end(last_t + 2)

    # the longest a single deferred device wave may reasonably take
    # (a cold 2B-decoder compile on a tunneled chip is minutes); past
    # this the drain raises instead of hanging silently
    _ASYNC_STALL_S = 900.0

    def _drain(self, sched, what: str) -> None:
        """Pump until fully drained; loud failure on both stall modes
        (pending-but-inadmissible forever, and an async hold whose
        future never resolves)."""
        stalls = 0
        last_progress = _time.monotonic()
        while not sched.fully_drained():
            if sched.pump():
                stalls = 0
                last_progress = _time.monotonic()
            elif sched.has_async():
                if _time.monotonic() - last_progress > self._ASYNC_STALL_S:
                    raise RuntimeError(
                        f"{what}: deferred device wave unresolved after "
                        f"{self._ASYNC_STALL_S:.0f}s"
                    )
                _time.sleep(0.0005)
                if _obs.PLANE is not None:
                    _obs.PLANE.stage_seconds("idle", 0.0005)
            else:
                stalls += 1
                if stalls > 10_000:
                    raise RuntimeError(f"{what} stalled with undrained waves")

    def _wants_stage_overlap(self) -> bool:
        if os.environ.get("PATHWAY_STAGE_OVERLAP", "1") == "0":
            return False
        return any(
            isinstance(n, AsyncApplyNode) and n.is_async and n.overlap
            for n in self.graph.nodes
        )

    def _run_static_frontier(
        self, batches: list[tuple[int, InputNode, list[Entry]]]
    ) -> None:
        """Static batches through the frontier scheduler: each (time,
        node) wave is staged on its source and operators fire per-
        timestamp, so a deferred device dispatch of wave t (embed,
        generate) overlaps the staging and compute of wave t+1 — the
        serving pipeline the device plane is built around. Results are
        identical to the lockstep pump (same per-operator time order);
        only the interleaving differs.
        """
        sched = self._make_scheduler()
        sched.allow_async = True
        kicks = self._kick_sources(sched)
        tokens: dict[int, Any] = {}
        for t, node, entries in sorted(batches, key=lambda b: b[0]):
            tok = tokens.get(node.node_id)
            if tok is None:
                tok = tokens[node.node_id] = sched.add_source(node)
            sched.stage(tok, t, entries)
            if t > self.time:
                self.time = t + (t % 2)
        for tok in tokens.values():
            sched.close(tok)
        stalls = 0
        last_progress = _time.monotonic()
        while True:
            fired = sched.pump()
            self._stage_kicks(sched, kicks)
            sched.advance_local(self.time)
            if sched.fully_drained():
                if any(n._pending_convergence for n in kicks):
                    continue  # truncated convergence: keep kicking
                break
            if fired:
                stalls = 0
                last_progress = _time.monotonic()
            elif sched.has_async():
                if _time.monotonic() - last_progress > self._ASYNC_STALL_S:
                    raise RuntimeError(
                        "static frontier pump: deferred device wave "
                        f"unresolved after {self._ASYNC_STALL_S:.0f}s"
                    )
                _time.sleep(0.0005)  # a deferred wave is still computing
                if _obs.PLANE is not None:
                    _obs.PLANE.stage_seconds("idle", 0.0005)
            else:
                stalls += 1
                if stalls > 10_000:
                    raise RuntimeError(
                        "static frontier pump stalled with undrained waves"
                    )
        self.graph.end(self.next_time())


class IterateNode(Node):
    """Incremental fixpoint iteration (reference: iterate dataflow.rs:3737,
    which runs the loop body in a nested product-timestamp scope).

    One PERSISTENT body graph lives across outer timestamps and
    iterations; every stateful operator inside it keeps its arrangement,
    so each round processes only deltas:

      * outer input deltas are pushed into the body's placeholder inputs;
      * per round, the feedback delta into an iterated placeholder is
        (capture's wave delta) ⊖ (what was pushed into that placeholder
        this round) — an O(changes) identity: with P the placeholder's
        accumulated collection and C = F(P) the capture state, the desired
        push is C ⊖ P, and after each previous push P equaled C, so the
        difference is exactly the new wave delta minus this round's push;
      * the loop stops when the feedback consolidates to nothing (P = C,
        the fixpoint) or `iteration_limit` rounds elapse.

    An input update therefore re-converges from the previous fixpoint in
    O(affected) work — e.g. one edge insert into pagerank touches only the
    vertices whose ranks actually move. The body is expected to be a
    convergent fixpoint (the reference's iterate contract); with a warm
    start, `iteration_limit` bounds the re-convergence rounds per update.
    """

    def __init__(
        self,
        graph: Graph,
        inputs: Sequence[Node],
        input_names: list[str],
        iterated_names: list[str],
        output_names: list[str],
        sub_graph: Graph,
        placeholder_nodes: dict[str, InputNode],
        captures: dict[str, "CaptureNode"],
        static_batches: list[tuple[int, InputNode, list[Entry]]],
        iteration_limit: int | None = None,
    ):
        super().__init__(graph, inputs)
        self.persist_signature = lambda: (  # type: ignore[method-assign]
            f"IterateNode/{input_names}/{iterated_names}/{output_names}"
            f"/{iteration_limit}/"
            + ",".join(n.persist_signature() for n in sub_graph.nodes)
        )
        self.input_names = input_names
        self.iterated_names = iterated_names
        self.output_names = output_names
        self.sub_graph = sub_graph
        self.placeholder_nodes = placeholder_nodes
        self.captures = captures
        self.static_batches = static_batches
        self.iteration_limit = iteration_limit
        self.out_nodes: dict[str, InputNode] = {}
        self.inner_t = 0
        # body-closure static batches not yet released (outer-time gated)
        self._pending_statics = sorted(static_batches, key=lambda b: b[0])
        # the sub-scope's frontier (engine/frontier.py ScopeFrontier):
        # outer times released into the body + the inner round watermark.
        # A non-quiescent scope holds its feedback capability — a limit-
        # truncated convergence left deltas queued in the placeholders —
        # and the runtime keeps scheduling waves through this node's
        # cone (kick source) until the capability drops.
        from pathway_tpu.engine.frontier import ScopeFrontier

        self.scope = ScopeFrontier()
        self._ended = False
        # capture-stream read positions (per output name)
        self._read_pos = {name: 0 for name in output_names}
        # mirror of each iterated placeholder's accumulated collection:
        # outer deltas arrive against the INPUT rows but the placeholder
        # holds the CONVERGED rows, so updates/retractions must be
        # translated onto the current iterate value per key (iterate
        # bodies are key-preserving — the reference requires the returned
        # iterated table to keep the input universe)
        self._fed = {name: KeyedState() for name in iterated_names}
        # Token plane (docs/iterate.md): the whole feedback loop —
        # translate, capture wave deltas, the C ⊖ P subtraction
        # (zs_difference) and per-round consolidation — runs on NativeBatch
        # flat arrays, matching the reference's typed nested-scope iterate
        # (dataflow.rs:3737). PATHWAY_ITERATE_NATIVE=0 kill switch keeps
        # today's object plumbing for bit-identical A/B; the object code
        # below doubles as the permanent demotion fallback (exotic rows).
        self._tok = iterate_native_on()
        self._ext: dict | None = None
        self._out_start: dict | None = None
        # boundary round-trip audit (tests/test_iterate_native.py): rows
        # this node's own plumbing interned/materialized, sampled from the
        # InternTable counter hooks, plus rows the WHOLE scope (body
        # operators included) decoded back to Python per round
        self.plane_stats = {
            "boundary_intern_rows": 0,
            "boundary_materialize_rows": 0,
            "scope_materialize_rows": 0,
            "rounds": 0,
        }
        if self._tok:
            from pathway_tpu.engine import native as _nat

            self._nat = _nat
            self._dp = _tok_plane()
            self._tab = self._dp.default_table()
            self._fed_tok: dict | None = {
                name: _nat.NativeKeyedState() for name in iterated_names
            }
            for cap in captures.values():
                cap.on_demote = self._capture_demoted
        else:
            self._fed_tok = None

    def set_output_node(self, name: str, node: InputNode) -> None:
        self.out_nodes[name] = node

    # The feedback capability, expressed as scope-frontier state: True
    # while a truncated convergence still holds deltas to push around
    # the loop. Kept as a (settable) property so operator snapshots and
    # the runtime's kick machinery read/write one source of truth.
    @property
    def _pending_convergence(self) -> bool:
        return not self.scope.quiescent

    @_pending_convergence.setter
    def _pending_convergence(self, value: bool) -> None:
        if value:
            self.scope.hold()
        else:
            self.scope.drop()


    # --------------------------------------------------- plane transitions

    def _capture_demoted(self, cap: "CaptureNode", bounds: list[int]) -> None:
        """A capture fell off the token plane mid-run (body emitted a
        plane-unrepresentable row): remap this scope's read positions
        through the materialization bounds and demote the whole scope —
        mixed-plane feedback bookkeeping is not worth its complexity."""
        for name, c in self.captures.items():
            if c is cap:
                self._remap_positions(name, bounds)
        self._demote_scope()

    def _remap_positions(self, name: str, bounds: list[int]) -> None:
        last = len(bounds) - 1
        pos = self._read_pos.get(name, 0)
        self._read_pos[name] = bounds[min(pos, last)]
        if self._out_start is not None and name in self._out_start:
            self._out_start[name] = bounds[min(self._out_start[name], last)]

    def _demote_scope(self) -> None:
        """One-way switch of the whole iterate scope to the object
        plumbing: captures materialize their logs (positions remapped),
        the fed mirrors decode, and any mid-wave external batches fall
        back to entry lists. Correctness never depends on the plane."""
        if not self._tok:
            return
        self._tok = False
        for name, cap in self.captures.items():
            if getattr(cap, "_tok", False):
                cap.on_demote = None
                self._remap_positions(name, cap.demote())
        if self._fed_tok is not None:
            for name, st in self._fed_tok.items():
                self._fed[name] = nks_decode(st, self._tab)
            self._fed_tok = None
        if self._ext:
            for name, v in list(self._ext.items()):
                if v is not None and type(v) is not list:
                    self._ext[name] = v.materialize()

    def _boundary(self, fn):
        """Run one piece of this node's own boundary plumbing with the
        InternTable round-trip counters sampled around it (the audit the
        acceptance test reads: zero on an all-native pipeline)."""
        tab = self._tab
        i0 = tab.stat_intern_rows
        m0 = tab.stat_materialize_rows
        try:
            return fn()
        finally:
            st = self.plane_stats
            st["boundary_intern_rows"] += tab.stat_intern_rows - i0
            st["boundary_materialize_rows"] += tab.stat_materialize_rows - m0

    # ------------------------------------------------- operator snapshots

    def persist_state(self) -> dict:
        # snapshots always export the OBJECT form (portable across the
        # kill switch and process restarts): fed mirrors decode, and read
        # positions are mapped onto each capture log's object form — the
        # same expansion CaptureNode.persist_state performs, so the pair
        # stays consistent.
        if self._tok:
            read_pos = dict(self._read_pos)
            fed = {}
            for name, cap in self.captures.items():
                if getattr(cap, "_tok", False):
                    _stream, bounds = cap._log_object_form()
                    last = len(bounds) - 1
                    if name in read_pos:
                        read_pos[name] = bounds[min(read_pos[name], last)]
            for name, st in (self._fed_tok or {}).items():
                fed[name] = nks_decode(st, self._tab)
        else:
            read_pos = self._read_pos
            fed = self._fed
        return {
            "inner_t": self.inner_t,
            "pending_statics": self._pending_statics_state(),
            "pending_convergence": self._pending_convergence,
            "read_pos": read_pos,
            "fed": fed,
            "sub": [n.persist_state() for n in self.sub_graph.nodes],
        }

    def _pending_statics_state(self) -> list:
        # static batch entries pickle in object form; node identity maps
        # by index (NativeBatch closures materialize — they are rare and
        # only survive until their scripted release time)
        idx = {id(n): i for i, n in enumerate(self.sub_graph.nodes)}
        return [
            (
                t,
                idx[id(node)],
                entries if type(entries) is list else entries.materialize(),
            )
            for (t, node, entries) in self._pending_statics
        ]

    def restore_state(self, st: dict) -> None:
        self.inner_t = st["inner_t"]
        self._pending_convergence = st["pending_convergence"]
        self._pending_statics = [
            (t, self.sub_graph.nodes[i], entries)
            for (t, i, entries) in st["pending_statics"]
        ]
        self._read_pos = st["read_pos"]
        self._fed = st["fed"]
        if self._tok and not self._encode_fed(st["fed"]):
            self._fed_tok = None
            self._demote_scope()
        for node, sub_st in zip(self.sub_graph.nodes, st["sub"]):
            if sub_st is not None:
                node.restore_state(sub_st)
        if self._tok and any(
            not getattr(c, "_tok", False) for c in self.captures.values()
        ):
            # a capture could not re-encode its snapshot: whole scope
            # follows it down (positions are already object-form here)
            self._demote_scope()

    def _encode_fed(self, fed: dict) -> bool:
        """Re-encode restored object-form fed mirrors into the C keyed
        stores; False when a row is not plane-representable."""
        new = {}
        for name in self.iterated_names:
            st = nks_encode(fed[name].rows, self._tab)
            if st is None:
                return False
            new[name] = st
        self._fed_tok = new
        return True

    # ------------------------------------------------------------- pumping

    def _translate(self, name: str, batch: list[Entry]) -> list[Entry]:
        """Map outer input deltas onto the iterated collection's current
        rows: an update restarts key k's iteration from its new input
        value; a retraction removes key k's converged row."""
        fed = self._fed[name]
        per_key: dict[Key, tuple | None] = {}
        for key, row, diff in batch:
            if diff > 0:
                per_key[key] = row
            else:
                per_key.setdefault(key, None)
        out: list[Entry] = []
        for key, new_row in per_key.items():
            cur = fed.get(key)
            if cur is not None:
                out.append((key, cur, -1))
            if new_row is not None:
                out.append((key, new_row, 1))
        out = consolidate(out)
        fed.update(out)
        return out

    def _translate_tok(self, name: str, nb):
        """Token twin of ``_translate``: per-key resolution over flat
        (key128, token) columns with the fed mirror queried in one C
        call — no row ever decodes to a tuple."""
        fed = self._fed_tok[name]
        kvs = _kvs_of(nb.key_lo, nb.key_hi)
        toks = nb.token.tolist()
        dfs = nb.diff.tolist()
        per: dict[int, int | None] = {}
        for i, kv in enumerate(kvs):
            if dfs[i] > 0:
                per[kv] = toks[i]
            else:
                per.setdefault(kv, None)
        u_kvs = list(per.keys())
        lo_u, hi_u = _kv_cols(u_kvs)
        old = fed.get(lo_u, hi_u).tolist()
        absent = (1 << 64) - 1
        o_kv: list[int] = []
        o_tok: list[int] = []
        o_diff: list[int] = []
        for j, kv in enumerate(u_kvs):
            cur = old[j] if old[j] != absent else None
            new = per[kv]
            if cur == new:
                continue  # unchanged row: the object plane consolidates
            if cur is not None:
                o_kv.append(kv)
                o_tok.append(cur)
                o_diff.append(-1)
            if new is not None:
                o_kv.append(kv)
                o_tok.append(new)
                o_diff.append(1)
        n = len(o_kv)
        lo, hi = _kv_cols(o_kv)
        out = self._dp.NativeBatch(
            self._tab, lo, hi,
            np.fromiter(o_tok, np.uint64, n),
            np.fromiter(o_diff, np.int64, n),
        )
        fed.update(out.key_lo, out.key_hi, out.token, out.diff)
        return out

    def _wave_delta(self, name: str) -> list[Entry]:
        """Capture-stream entries appended since the last read."""
        cap = self.captures[name]
        pos = self._read_pos.get(name, 0)
        new = cap.stream[pos:]
        self._read_pos[name] = len(cap.stream)
        return [(k, row, d) for (_t, k, row, d) in new]

    def _read_log(self, cap: "CaptureNode", pos: int):
        """Log items appended since `pos`, split by plane (order within
        each kind preserved — z-set math is commutative across them).
        Does NOT advance any read position."""
        batches: list = []
        entries: list[Entry] = []
        for item in cap.stream[pos:]:
            if len(item) == 4:
                _t, k, row, d = item
                entries.append((k, row, d))
            else:
                batches.append(item[1])
        return batches, entries

    def _wave_quad(self, cap: "CaptureNode", pos: int):
        """Log items since `pos` as one (lo, hi, tok, diff) array quad, or
        None when an object item is not plane-representable (caller
        demotes the scope). Boundary-audited."""
        batches, entries = self._read_log(cap, pos)
        if not batches and not entries:
            return np.empty(0, np.uint64), np.empty(0, np.uint64), \
                np.empty(0, np.uint64), np.empty(0, np.int64)
        return self._boundary(
            lambda: _wave_arrays(self._tab, batches, entries)
        )

    def _feedback_delta(self, name: str, external: dict):
        """One round's feedback for an iterated placeholder: the capture's
        new wave delta ⊖ this round's external push (the C ⊖ P identity
        from the class docstring). Returns a NativeBatch (token plane), an
        entry list (object plane), or None when the feedback is empty.
        Advances the capture read position and updates the fed mirror."""
        if self._tok:
            cap = self.captures[name]
            pos = self._read_pos.get(name, 0)
            ext = external.get(name)
            # convert a (rare) object-form external first: the demotion
            # paths below then run with the external dict intact
            e_quad = None
            if type(ext) is list and ext:
                e_quad = self._boundary(
                    lambda: _wave_arrays(self._tab, [], ext)
                )
                if e_quad is None:
                    self._demote_scope()
                    return self._feedback_obj(name, external)
            elif ext is not None and type(ext) is not list and len(ext):
                e_quad = (ext.key_lo, ext.key_hi, ext.token, ext.diff)
            quad = self._wave_quad(cap, pos)
            if quad is None:
                self._demote_scope()  # read position remapped, not consumed
                return self._feedback_obj(name, external)
            self._read_pos[name] = len(cap.stream)
            external[name] = []
            if e_quad is None:
                lo, hi, tok, diff = (a.copy() for a in quad)
                m = self._nat.consolidate_tokens(lo, hi, tok, diff)
            else:
                lo, hi, tok, diff = self._nat.difference_tokens(quad, e_quad)
                m = len(lo)
            if m == 0:
                return None
            fb = self._dp.NativeBatch(
                self._tab, lo[:m], hi[:m], tok[:m], diff[:m]
            )
            self._fed_tok[name].update(
                fb.key_lo, fb.key_hi, fb.token, fb.diff
            )
            return fb
        return self._feedback_obj(name, external)

    def _feedback_obj(self, name: str, external: dict):
        delta = self._wave_delta(name)
        ext = external.pop(name, [])
        if type(ext) is not list:  # demoted mid-wave with a token external
            ext = ext.materialize()
        external[name] = []
        feedback = consolidate(
            delta + [(k, row, -d) for (k, row, d) in ext]
        )
        if not feedback:
            return None
        self._fed[name].update(feedback)
        return feedback

    def _release_statics(self, time: int) -> bool:
        """Push body-closure static batches whose scripted time has come
        (outer and scripted times share the even-ms domain for static
        runs; streaming wall-clock times release everything at once).
        Advances the sub-scope frontier's outer coordinate: releases are
        keyed off the wave time, never past the node's input frontier —
        data for an earlier outer time can no longer arrive once the
        frontier passed it, so the release point is exactly the scope's
        input frontier restricted to the scripted domain."""
        released = False
        while self._pending_statics and self._pending_statics[0][0] <= time:
            _t, node, entries = self._pending_statics.pop(0)
            node.push(list(entries) if type(entries) is list else entries)
            released = True
        self.scope.release(time)
        return released

    def finish_time(self, time: int) -> None:
        raws = [self.take_segments(i) for i in range(len(self.input_names))]
        released = self._release_statics(time)
        has_input = any(b or e for b, e in raws)
        if not has_input and not released and not self._pending_convergence:
            return
        self._pending_convergence = False
        # External (outer) pushes put the placeholder out of sync with the
        # capture; they are compensated exactly once, in the first round's
        # feedback. Feedback pushes re-establish P = C, so from round 2 on
        # the feedback is the wave delta alone.
        external: dict[str, Any] = {name: [] for name in self.iterated_names}
        self._ext = external
        if self._tok and not self._push_inputs_tok(raws, external):
            self._demote_scope()  # outer rows not plane-representable
        if not self._tok:
            self._push_inputs_obj(raws, external)
        out_start = {name: self._read_pos[name] for name in self.output_names}
        self._out_start = out_start
        tab = self._tab if self._tok else None
        m0 = tab.stat_materialize_rows if tab is not None else 0
        rounds = 0
        while True:
            self.inner_t += 2
            self.scope.advance_round(self.inner_t)
            self.sub_graph.step(self.inner_t)
            rounds += 1
            quiescent = True
            for name in self.iterated_names:
                feedback = self._feedback_delta(name, external)
                if feedback is not None:
                    quiescent = False
                    self.placeholder_nodes[name].push(feedback)
            if quiescent:
                break
            if self.iteration_limit is not None and rounds >= self.iteration_limit:
                # the final feedback is already queued in the placeholders
                # (so P tracks C — the loop invariant survives truncation);
                # convergence resumes on the next wave
                self._pending_convergence = True
                break
        self.plane_stats["rounds"] += rounds
        if tab is not None:
            # whole-scope decode audit: rows ANY body operator pulled back
            # to Python during the fixpoint loop (zero = every round ran
            # on the token plane end to end; the acceptance gate)
            self.plane_stats["scope_materialize_rows"] += (
                tab.stat_materialize_rows - m0
            )
        # emit each output's net change over this outer timestamp
        self._emit_outputs(time, out_start)
        self._out_start = None
        self._ext = None
        # consumed capture prefixes are dead: truncate so memory and
        # checkpoint size track the live collection, not total history
        for name in self.output_names:
            cap = self.captures[name]
            if self._read_pos[name] == len(cap.stream):
                cap.stream.clear()
                self._read_pos[name] = 0

    def _push_inputs_obj(self, raws: list, external: dict) -> None:
        from pathway_tpu.engine.core import _flatten_segments

        for i, name in enumerate(self.input_names):
            b, e = raws[i]
            batch = _flatten_segments(b, e)
            if not batch:
                continue
            batch = consolidate(batch)
            if name in external:
                batch = self._translate(name, batch)
                external[name] = batch
            if batch:
                self.placeholder_nodes[name].push(batch)

    def _push_inputs_tok(self, raws: list, external: dict) -> bool:
        """Batch-first outer push: every input wave becomes ONE
        consolidated NativeBatch; iterated inputs translate through the C
        fed mirror. False (nothing pushed) when a wave holds a
        plane-unrepresentable row — the caller demotes and replays."""
        converted: list[tuple[str, Any]] = []
        for i, name in enumerate(self.input_names):
            b, e = raws[i]
            if not b and not e:
                continue
            quad = self._boundary(lambda b=b, e=e: _wave_arrays(self._tab, b, e))
            if quad is None:
                return False
            nb = self._dp.NativeBatch(
                self._tab,
                np.ascontiguousarray(quad[0]),
                np.ascontiguousarray(quad[1]),
                np.ascontiguousarray(quad[2]),
                np.ascontiguousarray(quad[3]),
            )
            if not nb.is_distinct_insert():
                nb = nb.consolidate()
            converted.append((name, nb))
        for name, nb in converted:
            if name in external:
                nb = self._boundary(lambda n=name, x=nb: self._translate_tok(n, x))
                external[name] = nb
            if nb is not None and len(nb):
                self.placeholder_nodes[name].push(nb)
        return True

    def _emit_outputs(self, time: int, out_start: dict) -> None:
        for name in self.output_names:
            cap = self.captures[name]
            out_node = self.out_nodes.get(name)
            if self._tok:
                quad = self._wave_quad(cap, out_start[name])
                if quad is None:
                    self._demote_scope()  # positions remapped; fall through
                else:
                    self._read_pos[name] = len(cap.stream)
                    if out_node is None or not len(quad[0]):
                        continue
                    lo, hi, tok, diff = (a.copy() for a in quad)
                    m = self._nat.consolidate_tokens(lo, hi, tok, diff)
                    if not m:
                        continue
                    out_node.push(
                        self._dp.NativeBatch(
                            self._tab, lo[:m], hi[:m], tok[:m], diff[:m]
                        )
                    )
                    # downstream of out_node runs later in topo order
                    # within this same wave (out_node was created after
                    # self)
                    out_node.finish_time(time)
                    continue
            delta = consolidate(
                [
                    (k, row, d)
                    for (_t, k, row, d) in cap.stream[out_start[name]:]
                ]
            )
            self._read_pos[name] = len(cap.stream)
            if out_node is not None and delta:
                out_node.push(delta)
                out_node.finish_time(time)

    def on_end(self, time: int) -> None:
        """End-of-stream: release any remaining closure statics, flush the
        body graph's own on_end behavior (buffers etc.), and run the loop
        to quiescence one final time. The emission happens in the
        finish_time that Graph.end calls right after this."""
        if self._ended:
            return
        self._ended = True
        released = False
        while self._pending_statics:
            _t, node, entries = self._pending_statics.pop(0)
            node.push(list(entries) if type(entries) is list else entries)
            released = True
        self.inner_t += 2
        self.scope.advance_round(self.inner_t)
        for node in self.sub_graph.nodes:
            node.on_end(self.inner_t)
        # did end-flushing produce anything to process?
        flushed = any(
            any(buf for buf in node.buffers) for node in self.sub_graph.nodes
        ) or any(n.pending for n in self.placeholder_nodes.values())
        if released or flushed:
            self._pending_convergence = True


class AsyncApplyNode(Node):
    """Async UDF application (reference: async_apply_table dataflow.rs:1442,
    MapWithConsistentDeletions operators.rs:308).

    Insertions run the (async) function — concurrently within a wave via an
    event loop; results are memoized per key so retractions retract exactly
    the value the insertion produced, even for non-deterministic functions.

    Stage overlap: under a frontier pump that allows it, an async wave is
    DEFERRED — the batch is submitted to the loop and the node returns
    without blocking, holding its outgoing watermark at the wave's time
    via ``FrontierScheduler.hold_async``. The pump keeps firing other
    admissible work (including this node's own later waves: that is the
    double buffer — wave t+1 stages/tokenizes while wave t computes on
    the device), and when the batch resolves the node fires again at the
    held time to emit. Opt out with PATHWAY_STAGE_OVERLAP=0.
    """

    _state_routing = {"memo": "keytup"}  # memo keys are (key.value, row)

    def __init__(
        self,
        graph: Graph,
        inp: Node,
        fn: Callable[[Key, tuple], Any],
        is_async: bool,
        deterministic: bool = False,
    ):
        super().__init__(graph, [inp])
        self._persist_attrs = ("memo",)
        self.fn = fn
        self.is_async = is_async
        self.deterministic = deterministic
        self.memo: dict[tuple, Any] = {}
        self.overlap = os.environ.get("PATHWAY_STAGE_OVERLAP", "1") != "0"
        # time -> (entries, concurrent Future[results dict]) for deferred
        # waves; never persisted — checkpoints cut at the global frontier,
        # which a hold keeps below any half-done wave
        self._inflight: dict[float, tuple[list, Any]] = {}

    def finish_time(self, time: int) -> None:
        held = self._inflight.pop(time, None)
        if held is not None:
            # completion pass: the deferred batch resolved (the scheduler
            # only re-fires a held time once its future is done)
            entries, fut = held
            try:
                results = fut.result()
            except Exception as e:  # noqa: BLE001 — per-row errors are
                # already caught inside the batch; this is a belt for
                # loop teardown races
                self.log_error(f"async apply: {type(e).__name__}: {e}")
                results = {}
            self._emit_resolved(time, entries, results)
            return
        entries = self.take_input()
        if not entries:
            return
        insertions = [(k, r) for k, r, d in entries if d > 0]
        sched = self.graph.scheduler
        if (
            self.is_async
            and self.overlap
            and sched is not None
            and getattr(sched, "allow_async", False)
            # a retraction-only wave behind an in-flight one must chain
            # through the same hold queue: its tokens may be exactly the
            # values the earlier wave is still computing (emitting ERROR
            # for them would poison downstream arrangements)
            and (insertions or self._inflight)
        ):
            fut = _submit_async_batch(self.fn, insertions, self.graph)
            self._inflight[time] = (entries, fut)
            sched.hold_async(self, time, lambda t=time: self._hold_done(t))
            return
        results: dict[tuple, Any] = {}
        if insertions:
            if self.is_async:
                results = _run_async_batch(self.fn, insertions, self.graph)
            else:
                for k, r in insertions:
                    try:
                        results[(k.value, freeze_row(r))] = self.fn(k, r)
                    except Exception as e:  # noqa: BLE001
                        self.log_error(f"apply: {type(e).__name__}: {e}")
                        results[(k.value, freeze_row(r))] = ERROR
        self._emit_resolved(time, entries, results)

    def _hold_done(self, time: float) -> bool:
        """A deferred wave releases only when its batch resolved AND it
        is the EARLIEST in-flight wave: computes overlap freely, but
        emissions (and with them the memo the retraction path reads)
        stay in per-operator time order."""
        held = self._inflight.get(time)
        if held is None:
            return True
        return held[1].done() and min(self._inflight) >= time

    def _emit_resolved(
        self, time: int, entries: list[Entry], results: dict[tuple, Any]
    ) -> None:
        out: list[Entry] = []
        for key, row, diff in entries:
            token = (key.value, freeze_row(row))
            if diff > 0:
                value = results.get(token, self.memo.get(token, ERROR))
                if not self.deterministic:
                    self.memo[token] = value
            else:
                if token in self.memo:
                    value = self.memo.pop(token)
                elif token in results:
                    value = results[token]
                elif self.deterministic:
                    # recompute for retraction — allowed for deterministic fns;
                    # async fns (every batched=True UDF) must go through the
                    # loop or the "value" would be a bare coroutine object
                    if self.is_async:
                        value = _run_async_batch(
                            self.fn, [(key, row)], self.graph
                        ).get(token, ERROR)
                    else:
                        try:
                            value = self.fn(key, row)
                        except Exception as e:  # noqa: BLE001
                            self.log_error(f"apply: {type(e).__name__}: {e}")
                            value = ERROR
                else:
                    value = ERROR
            out.append((key, row + (value,), diff))
        self.emit(time, consolidate(out))


_async_loop: asyncio.AbstractEventLoop | None = None
_async_loop_lock = _lockgraph.register_lock(
    "runtime.async_loop", threading.Lock()
)


def _get_async_loop() -> asyncio.AbstractEventLoop:
    """Dedicated event-loop thread (reference: graph_runner/async_utils.py)."""
    global _async_loop
    with _async_loop_lock:
        if _async_loop is None or _async_loop.is_closed():
            loop = asyncio.new_event_loop()

            def run() -> None:
                asyncio.set_event_loop(loop)
                loop.run_forever()

            threading.Thread(target=run, daemon=True, name="pw-async-loop").start()
            _async_loop = loop
    return _async_loop


def _submit_async_batch(
    fn: Callable, insertions: list[tuple[Key, tuple]], graph: Graph
):
    """Start a wave's row coroutines on the loop; returns a concurrent
    Future resolving to {(key, row): value}. The caller decides whether
    to block (`_run_async_batch`) or defer (stage overlap)."""
    loop = _get_async_loop()

    async def one(k: Key, r: tuple) -> Any:
        try:
            res = fn(k, r)
            if asyncio.iscoroutine(res):
                res = await res
            return res
        except Exception as e:  # noqa: BLE001
            graph.log_error(f"async apply: {type(e).__name__}: {e}")
            return ERROR

    async def batch() -> dict[tuple, Any]:
        values = await asyncio.gather(*[one(k, r) for k, r in insertions])
        return {
            (k.value, freeze_row(r)): v
            for (k, r), v in zip(insertions, values)
        }

    return asyncio.run_coroutine_threadsafe(batch(), loop)


def _run_async_batch(
    fn: Callable, insertions: list[tuple[Key, tuple]], graph: Graph
) -> dict[tuple, Any]:
    return _submit_async_batch(fn, insertions, graph).result()


class OutputNode(Node):
    """Sink: formats consolidated batches and hands them to a writer callback
    with retries (reference: output_table dataflow.rs:3542, OUTPUT_RETRIES=5).

    The retry loop rides the unified ``pw.io.RetryPolicy`` (same default
    timings as the old hand-rolled loop: 5 attempts, 10 ms apart), which
    makes every sink fault-injectable at ``io.retry.sink``.

    Exactly-once mode (persistence attached + PATHWAY_EXACTLY_ONCE!=0):
    ``attach_outbox`` reroutes every wave into a per-sink transactional
    outbox WAL (io/outbox.py) — writes happen at checkpoint fences,
    after the epoch's metadata commit sealed them, and the writer close
    waits for the final ack. ``write_keyed`` (optional) is the
    idempotent delivery surface: like ``write_batch`` plus a per-record
    content-key list for consumer-side dedup of replays; ``txn``
    (optional) carries a sink's atomic-commit hooks (the fs writer's
    offset-named temp+fsync+rename segments)."""

    RETRIES = 5

    def __init__(
        self,
        graph: Graph,
        inp: Node,
        write_batch: Callable[[int, list[Entry]], None],
        flush: Callable[[], None] | None = None,
        close: Callable[[], None] | None = None,
        write_native: Callable[[int, Any], None] | None = None,
        retry_policy: Any = None,
        write_keyed: Callable[[int, list[Entry], list], None] | None = None,
        txn: dict | None = None,
    ):
        super().__init__(graph, [inp])
        self.write_batch = write_batch
        self.flush = flush
        self.close = close
        # optional token-resident fast path: write_native(time, NativeBatch)
        # formats whole batches in C (e.g. the csv writer); sinks without
        # it get materialized entries as before
        self.write_native = write_native
        self.write_keyed = write_keyed
        self.txn = txn
        self._outbox: Any = None
        self._closed = False
        if retry_policy is None:
            # lazy import: pathway_tpu.io's package init imports modules
            # that import this one
            from pathway_tpu.io._retry import RetryPolicy

            retry_policy = RetryPolicy(
                "sink",
                max_attempts=self.RETRIES,
                initial_delay_ms=10,
                backoff_factor=1.0,
                jitter_ms=0,
                breaker_threshold=None,
            )
        self.retry_policy = retry_policy

    def _write_retrying(self, fn, time: int, payload) -> None:
        def attempt() -> None:
            fn(time, payload)
            if self.flush is not None:
                self.flush()

        try:
            self.retry_policy.call(attempt)
        except Exception as e:  # noqa: BLE001 — a sink must not kill the pump
            self.log_error(
                f"output failed after "
                f"{self.retry_policy.max_attempts} retries: {e}"
            )

    def attach_outbox(self, outbox: Any) -> None:
        """Switch to transactional staging: waves journal to the outbox
        WAL; delivery happens at epoch fences (io/outbox.py)."""
        self._outbox = outbox
        if self.txn and self.txn.get("enable") is not None:
            self.txn["enable"]()

    def finish_time(self, time: int) -> None:
        if self._outbox is not None:
            # exactly-once: stage in object form (the WAL's codec
            # domain); the native formatting fast path is a direct-write
            # optimization and does not apply to journaled delivery
            entries = self.take_input()
            if entries:
                self._outbox.stage(time, consolidate(entries))
            return
        if self.write_native is not None:
            batches, entries = self.take_segments()
            for b in batches:
                if not b.is_distinct_insert():
                    b = b.consolidate()
                self._write_retrying(self.write_native, time, b)
            if entries:
                self._write_retrying(self.write_batch, time, consolidate(entries))
            return
        entries = self.take_input()
        if not entries:
            return
        self._write_retrying(self.write_batch, time, consolidate(entries))

    def on_end(self, time: int) -> None:
        if self._outbox is not None:
            # the final wave is staged but not yet sealed: the runtime's
            # end-of-stream checkpoint delivers it, and the outbox closes
            # the writer after that ack (CheckpointManager.close)
            return
        if not self._closed and self.close is not None:
            self._closed = True
            self.close()
