"""Azure Blob persistence backend: the same staged-sync layer as S3,
reached through Backend.azure — via the directory fake
(PATHWAY_AZURE_FAKE_DIR), an injected S3-shaped client, and the
ContainerClient adapter over a duck-typed blob client. Reference:
src/persistence/backends/ object-store family."""

from __future__ import annotations

import io
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_azure_requires_credentials_or_client():
    import pathway_tpu as pw

    os.environ.pop("PATHWAY_AZURE_FAKE_DIR", None)
    with pytest.raises(ValueError, match="connection_string"):
        pw.persistence.Backend.azure("root")
    # container validated BEFORE sdk client construction (clear error)
    with pytest.raises(ValueError, match="container"):
        pw.persistence.Backend.azure("root", connection_string="cs")


def test_azure_container_adapter_roundtrip():
    """The adapter maps the four staged-sync calls onto a duck-typed
    ContainerClient (upload/download/delete/list)."""
    from pathway_tpu.persistence import _AzureContainerAdapter

    class Blob:
        def __init__(self, name, size):
            self.name = name
            self.size = size

    class FakeCC:
        def __init__(self):
            self.blobs: dict[str, bytes] = {}

        def upload_blob(self, name, data, overwrite=False):
            assert overwrite
            self.blobs[name] = bytes(data)

        def download_blob(self, name):
            data = self.blobs[name]

            class R:
                def readall(self_inner):
                    return data

            return R()

        def delete_blob(self, name):
            del self.blobs[name]

        def list_blobs(self, name_starts_with=""):
            return [
                Blob(n, len(b))
                for n, b in sorted(self.blobs.items())
                if n.startswith(name_starts_with)
            ]

    cc = FakeCC()
    ad = _AzureContainerAdapter(cc)
    ad.put_object(Bucket="x", Key="a/b.txt", Body=b"hello")
    ad.put_object(Bucket="x", Key="a/c.txt", Body=b"world")
    assert ad.get_object(Bucket="x", Key="a/b.txt")["Body"].read() == b"hello"
    listed = ad.list_objects_v2(Bucket="x", Prefix="a/")
    assert [c["Key"] for c in listed["Contents"]] == ["a/b.txt", "a/c.txt"]
    ad.delete_object(Bucket="x", Key="a/b.txt")
    ad.delete_object(Bucket="x", Key="a/b.txt")  # idempotent
    assert "a/b.txt" not in cc.blobs


def test_azure_backend_accepts_ducktyped_container_client():
    import pathway_tpu as pw

    class FakeCC:
        def upload_blob(self, *a, **k):
            pass

        def download_blob(self, *a, **k):
            raise KeyError

        def delete_blob(self, *a, **k):
            pass

        def list_blobs(self, **k):
            return []

    b = pw.persistence.Backend.azure("root/path", client=FakeCC())
    assert b.kind == "s3"  # staged-sync family
    assert hasattr(b.s3_client, "put_object")


SCRIPT = textwrap.dedent(
    """
    import sys, time
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw
    from pathway_tpu.io.python import ConnectorSubject

    OUT, N = sys.argv[1], int(sys.argv[2])

    class Words(ConnectorSubject):
        def run(self):
            for i in range(N):
                self.next(word=f"w{{i % 5}}")
                time.sleep(0.002)

    t = pw.io.python.read(Words(), schema=pw.schema_from_types(word=str), name="words")
    counts = t.groupby(t.word).reduce(t.word, count=pw.reducers.count())
    sink = open(OUT, "a")
    def on_change(key, row, time, is_addition):
        sink.write(__import__("json").dumps(
            {{"word": row["word"], "count": row["count"], "add": is_addition}}
        ) + "\\n")
        sink.flush()
    pw.io.subscribe(counts, on_change=on_change)
    pw.run(persistence_config=pw.persistence.Config(
        pw.persistence.Backend.azure("ckpt/root"),
        snapshot_interval_ms=50))
    """
)


def test_azure_backend_end_to_end_restart(tmp_path):
    """Two runs against the azure fake container: the second resumes from
    blob state alone — its (deterministically re-read) words are
    count-skipped against the journal, so counts stay exact and nothing
    re-emits (mirror of the S3 restart test's semantics)."""
    import json

    fake = str(tmp_path / "container")
    out = str(tmp_path / "events.jsonl")
    env = dict(os.environ)
    env["PATHWAY_AZURE_FAKE_DIR"] = fake
    env["JAX_PLATFORMS"] = "cpu"

    def run(n):
        r = subprocess.run(
            [sys.executable, "-c", SCRIPT.format(repo=REPO), out, str(n)],
            capture_output=True, text=True, timeout=120, env=env,
        )
        assert r.returncode == 0, r.stderr[-1500:]

    def consolidated():
        cur: dict[str, int] = {}
        with open(out) as f:
            for line in f:
                e = json.loads(line)
                if e["add"]:
                    cur[e["word"]] = e["count"]
                elif cur.get(e["word"]) == e["count"]:
                    del cur[e["word"]]
        return cur

    run(25)
    expected = {f"w{k}": 5 for k in range(5)}
    assert consolidated() == expected
    assert any("metadata.json" in f for f in os.listdir(fake))
    run(25)
    assert consolidated() == expected  # resumed, nothing re-emitted
