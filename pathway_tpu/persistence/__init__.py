"""Persistence: operator snapshots + frontier metadata + input journals.

Reference parity: src/persistence/ —
  * operator snapshots with compaction (operator_snapshot.rs:1) →
    `OperatorSnapshotStore` (per-node typed-binary state with crc
    framing — codec.py, the bincode equivalent — one file per epoch,
    old epochs deleted after the metadata commit),
  * metadata / finalized-frontier store (state.rs:35 MetadataAccessor) →
    `MetadataStore` (per-connector committed offsets + epoch, written
    fsync-then-atomic-rename so a crash never yields a torn commit),
  * per-source offset frontiers (frontier.rs OffsetAntichain) →
    per-connector event offsets in segmented journals (`*.N.seg`,
    N = first event offset in the segment),
plus python/pathway/persistence/__init__.py (Backend :27, Config :88) for
the user-facing API.

Recovery order (reference: worker.rs bootstrap): metadata → operator
state → journal tail. The journal head covered by the snapshot epoch is
deleted at checkpoint time (compaction), so resume replays only the tail
— O(new events), not O(history).

Modes:
  * pipeline signature matches + snapshot epoch valid → restore operator
    states, replay journal events at offsets ≥ committed, seek live
    sources past everything journaled.
  * signature mismatch (pipeline changed / PATHWAY_THREADS changed /
    native kernel toggled) → fall back to FULL journal replay if the head
    still exists; otherwise fail with a clear error instead of silently
    recomputing wrong state.
"""

from __future__ import annotations

import hashlib
import json as _json
import os
import time as _time
from typing import Any

from pathway_tpu.engine import faults
from pathway_tpu.internals.keys import Key
from pathway_tpu.persistence import codec


class Backend:
    kind = "mock"

    def __init__(self, path: str | None = None):
        self.path = path

    @classmethod
    def filesystem(cls, path: str) -> "Backend":
        b = cls(os.fspath(path))
        b.kind = "filesystem"
        return b

    @classmethod
    def s3(
        cls,
        root_path: str,
        bucket_settings: Any = None,
        *,
        bucket: str | None = None,
        client: Any = None,
    ) -> "Backend":
        """Object-store persistence (reference: src/persistence/backends/
        s3.rs). The run stages through a local directory; every checkpoint
        syncs changed journal segments / operator snapshots up and commits
        `metadata.json` LAST (the S3 linearization point), and attach
        rebuilds the staging directory from the bucket, so a fresh host
        resumes from object storage alone.

        `bucket_settings`: pw.io.s3.AwsS3Settings (boto3-gated);
        `client`: injected boto3-compatible client (tests / custom auth);
        PATHWAY_S3_FAKE_DIR routes to the built-in directory-backed fake
        (dev machines without S3)."""
        b = cls(root_path.strip("/"))
        b.kind = "s3"
        fake_dir = os.environ.get("PATHWAY_S3_FAKE_DIR")
        if client is None and fake_dir:
            client = _DirS3Client(fake_dir)
            # bucket id doubles as the staging-dir key: make it unique
            # per fake directory so concurrent test runs never share one
            bucket = bucket or fake_dir
        if client is None:
            if bucket_settings is None:
                raise ValueError(
                    "Backend.s3 needs bucket_settings (pw.io.s3."
                    "AwsS3Settings) or an injected client"
                )
            client = bucket_settings.client()
            bucket = bucket or bucket_settings.bucket_name
        if not bucket:
            raise ValueError(
                "Backend.s3 with an injected client needs bucket=..."
            )
        b.s3_client = client
        b.s3_bucket = bucket
        return b

    @classmethod
    def azure(
        cls,
        root_path: str,
        account_settings: Any = None,
        *,
        container: str | None = None,
        connection_string: str | None = None,
        client: Any = None,
    ) -> "Backend":
        """Azure Blob persistence (reference: src/persistence/backends/
        — object-store family). Same staged-sync design as Backend.s3:
        checkpoints upload changed files with metadata.json LAST, attach
        pulls the container state. `client` may be an injected
        S3-shaped client (put/get/list/delete — tests, custom auth) or an
        azure.storage.blob ContainerClient (adapted); PATHWAY_AZURE_FAKE_DIR
        routes to the directory-backed fake on dev machines."""
        b = cls(root_path.strip("/"))
        b.kind = "s3"  # the staged-sync path is object-store-generic
        fake_dir = os.environ.get("PATHWAY_AZURE_FAKE_DIR")
        if client is None and fake_dir:
            client = _DirS3Client(fake_dir)
            container = container or fake_dir
        if client is None:
            if connection_string is None and account_settings is None:
                raise ValueError(
                    "Backend.azure needs a connection_string, "
                    "account_settings, or an injected client"
                )
            if not container:
                # guard BEFORE client construction: the SDK's own error
                # for a missing container name is opaque
                raise ValueError("Backend.azure needs container=...")
            try:
                from azure.storage.blob import ContainerClient
            except ImportError as e:
                raise ImportError(
                    "Backend.azure needs azure-storage-blob: "
                    "`pip install azure-storage-blob`"
                ) from e
            if connection_string is not None:
                cc = ContainerClient.from_connection_string(
                    connection_string, container_name=container
                )
            else:
                cc = account_settings.container_client(container)
            client = _AzureContainerAdapter(cc)
        elif not hasattr(client, "put_object") and hasattr(
            client, "upload_blob"
        ):
            client = _AzureContainerAdapter(client)
            container = container or "azure"
        if not container:
            raise ValueError(
                "Backend.azure with an injected client needs container=..."
            )
        b.s3_client = client
        b.s3_bucket = container
        return b

    @classmethod
    def mock(cls, events: Any = None) -> "Backend":
        return cls(None)


class Config:
    def __init__(
        self,
        backend: Backend | None = None,
        *,
        snapshot_interval_ms: int = 0,
        persistence_mode: str = "PERSISTING",
        snapshot_access: Any = None,
        continue_after_replay: bool = True,
        operator_snapshots: bool = True,
    ):
        self.backend = backend or Backend.mock()
        self.snapshot_interval_ms = snapshot_interval_ms
        self.persistence_mode = persistence_mode
        self.continue_after_replay = continue_after_replay
        # UDF-cache-only mode (serving processes) skips input journaling
        # and operator snapshots entirely
        self.operator_snapshots = operator_snapshots and persistence_mode not in (
            "UDF_CACHING",
            "udf_caching",
        )

    def with_backend(self, backend: Backend) -> "Config":
        """Same settings against another backend (mesh per-process roots,
        S3 staging redirection)."""
        return Config(
            backend,
            snapshot_interval_ms=self.snapshot_interval_ms,
            persistence_mode=self.persistence_mode,
            continue_after_replay=self.continue_after_replay,
            operator_snapshots=self.operator_snapshots,
        )

    @classmethod
    def simple_config(cls, backend: Backend, **kwargs: Any) -> "Config":
        return cls(backend, **kwargs)

    @classmethod
    def udf_caching(cls, backend: Backend) -> "Config":
        """Cache-only persistence for serving processes: UDF results are
        cached under the backend, but no input journaling / replay /
        operator snapshots are attached (reference: udf caching mode)."""
        return cls(backend, persistence_mode="UDF_CACHING")


class _DirS3Client:
    """Directory-backed stand-in for the boto3 S3 surface the sync layer
    uses (put/get/list/delete) — the mocked-S3 test target and a dev
    shim; enable via PATHWAY_S3_FAKE_DIR."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _p(self, key: str) -> str:
        return os.path.join(self.root, key.replace("/", "\x01"))

    def put_object(self, Bucket: str, Key: str, Body: bytes) -> None:  # noqa: N803
        _fsync_write(self._p(Key), Body)

    def get_object(self, Bucket: str, Key: str) -> dict:  # noqa: N803
        import io as _io

        p = self._p(Key)
        if not os.path.exists(p):
            raise KeyError(Key)
        with open(p, "rb") as f:
            return {"Body": _io.BytesIO(f.read())}

    def delete_object(self, Bucket: str, Key: str) -> None:  # noqa: N803
        try:
            os.unlink(self._p(Key))
        except OSError:
            pass

    def list_objects_v2(self, Bucket: str, Prefix: str = "", **kw: Any) -> dict:  # noqa: N803
        out = []
        for fn in sorted(os.listdir(self.root)):
            key = fn.replace("\x01", "/")
            if key.startswith(Prefix):
                out.append({"Key": key, "Size": os.path.getsize(os.path.join(self.root, fn))})
        return {"Contents": out} if out else {}


class _AzureContainerAdapter:
    """azure.storage.blob ContainerClient -> the S3-shaped client surface
    the staged sync uses (put/get/list/delete). The Bucket parameter is
    ignored: a ContainerClient is already bound to its container."""

    def __init__(self, container_client: Any):
        self._cc = container_client

    def put_object(self, Bucket: str, Key: str, Body: bytes) -> None:  # noqa: N803
        self._cc.upload_blob(Key, Body, overwrite=True)

    def get_object(self, Bucket: str, Key: str) -> dict:  # noqa: N803
        import io as _io

        data = self._cc.download_blob(Key).readall()
        return {"Body": _io.BytesIO(data)}

    def delete_object(self, Bucket: str, Key: str) -> None:  # noqa: N803
        try:
            self._cc.delete_blob(Key)
        except Exception as e:  # noqa: BLE001
            # only blob-not-found is ignorable (idempotent deletes);
            # auth/network failures must surface, else compaction
            # silently stops freeing the container. Name-matched so the
            # azure sdk stays an optional dependency.
            if type(e).__name__ not in ("ResourceNotFoundError", "KeyError"):
                raise

    def list_objects_v2(self, Bucket: str, Prefix: str = "", **kw: Any) -> dict:  # noqa: N803
        out = [
            {"Key": b.name, "Size": getattr(b, "size", 0)}
            for b in self._cc.list_blobs(name_starts_with=Prefix)
        ]
        return {"Contents": out} if out else {}


class _S3Sync:
    """Staging-directory <-> object-store synchronizer.

    Layout: every file under the local root maps to `{root_path}/{rel}`.
    `pull` resets the staging dir from the bucket (S3 is the source of
    truth on attach); `push` uploads new/changed files and deletes
    removed ones, with metadata.json strictly LAST so a crash mid-push
    leaves the previous epoch intact and readable.
    """

    def __init__(self, client: Any, bucket: str, root_path: str, local: str):
        self.client = client
        self.bucket = bucket
        self.prefix = root_path.strip("/") + "/"
        self.local = local
        self._pushed: dict[str, tuple[float, int]] = {}

    def _keys(self) -> list[str]:
        resp = self.client.list_objects_v2(Bucket=self.bucket, Prefix=self.prefix)
        return [c["Key"] for c in resp.get("Contents", [])]

    def pull(self) -> None:
        import shutil

        if os.path.exists(self.local):
            shutil.rmtree(self.local)
        os.makedirs(self.local, exist_ok=True)
        self._pushed.clear()
        for key in self._keys():
            rel = key[len(self.prefix):]
            dst = os.path.join(self.local, rel)
            os.makedirs(os.path.dirname(dst) or self.local, exist_ok=True)
            body = self.client.get_object(Bucket=self.bucket, Key=key)["Body"].read()
            with open(dst, "wb") as f:
                f.write(body)
            st = os.stat(dst)
            self._pushed[rel] = (st.st_mtime, st.st_size)

    def push(self) -> None:
        current: dict[str, tuple[float, int]] = {}
        meta_rel = None
        for dirpath, _dirs, files in os.walk(self.local):
            for fn in files:
                if fn.endswith(".tmp"):
                    continue
                p = os.path.join(dirpath, fn)
                rel = os.path.relpath(p, self.local)
                st = os.stat(p)
                current[rel] = (st.st_mtime, st.st_size)
        ordered = sorted(current)
        for rel in ordered:
            if rel == MetadataStore.FILE:
                meta_rel = rel
                continue
            if self._pushed.get(rel) != current[rel]:
                with open(os.path.join(self.local, rel), "rb") as f:
                    self.client.put_object(
                        Bucket=self.bucket, Key=self.prefix + rel, Body=f.read()
                    )
        # deletions (compacted segments / old snapshots)
        for rel in list(self._pushed):
            if rel not in current:
                self.client.delete_object(
                    Bucket=self.bucket, Key=self.prefix + rel
                )
        # the commit point: metadata.json goes up last, and ALWAYS — an
        # (mtime, size) quick-check could skip a same-size rewrite on
        # coarse-timestamp filesystems and strand the bucket one epoch
        # behind; the file is tiny
        if meta_rel is not None:
            with open(os.path.join(self.local, meta_rel), "rb") as f:
                self.client.put_object(
                    Bucket=self.bucket, Key=self.prefix + meta_rel, Body=f.read()
                )
        self._pushed = current


def _fsync_write(path: str, data: bytes) -> None:
    """Write atomically: tmp file, fsync, rename, fsync dir."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dirfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)


def _safe(name: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_" for c in name)


class SegmentedJournal:
    """Per-connector append-only event log in offset-addressed segments.

    Events are globally numbered per connector; segment `{name}.{N}.seg`
    holds events starting at offset N. At each checkpoint the current
    segment rolls over and fully-committed older segments are deleted
    (compaction) once the operator snapshot covering them is durable.
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _segments(self, name: str) -> list[tuple[int, str]]:
        pre = _safe(name) + "."
        out = []
        for fn in os.listdir(self.root):
            if fn.startswith(pre) and fn.endswith(".seg"):
                try:
                    start = int(fn[len(pre):-4])
                except ValueError:
                    continue
                out.append((start, os.path.join(self.root, fn)))
        return sorted(out)

    def load_from(self, name: str, offset: int) -> list[tuple[int, Any, tuple, int]]:
        """All journaled events with global offset >= `offset`, as
        (offset, key_value, row, diff). Records are typed-binary with
        per-record crc (codec.py); a torn tail stops the read."""
        out: list[tuple[int, Any, tuple, int]] = []
        for start, path in self._segments(name):
            pos = start
            with open(path, "rb") as f:
                buf = f.read()
            for (kv, row, diff) in codec.read_records(buf, with_magic=True):
                if pos >= offset:
                    out.append((pos, kv, row, diff))
                pos += 1
        return out

    def head_offset(self, name: str) -> int:
        """Offset of the first surviving journal event (>0 after compaction)."""
        segs = self._segments(name)
        return segs[0][0] if segs else 0

    def total_events(self, name: str) -> int:
        segs = self._segments(name)
        if not segs:
            return 0
        last_start, last_path = segs[-1]
        with open(last_path, "rb") as f:
            buf = f.read()
        return last_start + codec.count_records(buf, with_magic=True)

    def open_segment(self, name: str, start: int):
        return _SegmentWriter(
            os.path.join(self.root, f"{_safe(name)}.{start}.seg"), start
        )

    def truncate_to(self, name: str, offset: int) -> None:
        """Remove journaled events at or past `offset`: whole segments
        unlink, the covering segment rewrites in place (atomic) keeping
        its prefix byte-exactly. The outbox uses this to discard a
        staged-but-unsealed WAL tail on recovery (io/outbox.py)."""
        for start, path in self._segments(name):
            if start >= offset:
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            with open(path, "rb") as f:
                buf = f.read()
            recs = list(codec.read_records(buf, with_magic=True))
            if start + len(recs) <= offset:
                continue
            keep = recs[: offset - start]
            blob = codec.MAGIC + b"".join(
                codec.encode_record(r) for r in keep
            )
            _fsync_write(path, blob)

    def size_bytes(self, name: str) -> int:
        """On-disk bytes held by this connector's surviving segments."""
        total = 0
        for _start, path in self._segments(name):
            try:
                total += os.path.getsize(path)
            except OSError:
                pass
        return total

    def compact(self, name: str, committed: int) -> int:
        """Delete segments whose every event is < committed (covered by a
        durable operator snapshot). Returns number of segments removed."""
        segs = self._segments(name)
        removed = 0
        for i, (start, path) in enumerate(segs):
            end = segs[i + 1][0] if i + 1 < len(segs) else None
            if end is not None and end <= committed:
                try:
                    os.unlink(path)
                    removed += 1
                except OSError:
                    pass
        return removed


class _SegmentWriter:
    def __init__(self, path: str, start: int):
        self.path = path
        self.start = start
        self.count = 0
        self._f = open(path, "ab")
        pos = self._f.tell()
        if pos >= len(codec.MAGIC):
            with open(path, "rb") as rf:
                head = rf.read(len(codec.MAGIC))
            if head != codec.MAGIC:
                # foreign/legacy layout: refuse, exactly like the reader —
                # truncating would destroy data the read path protects
                self._f.close()
                raise ValueError(
                    f"journal segment {path} is not in the typed-binary "
                    "layout; refusing to append"
                )
        if pos > 0:
            # reopening after a crash: drop any torn tail (partial MAGIC
            # or a torn trailing frame) BEFORE appending — new events
            # written beyond the torn point would sit past where every
            # reader stops, silently unreadable
            with open(path, "rb") as rf:
                good = codec.valid_prefix_len(rf.read(), with_magic=True)
            if good < pos:
                self._f.close()
                with open(path, "r+b") as tf:
                    tf.truncate(good)
                self._f = open(path, "ab")
                pos = good
        if pos == 0:
            self._f.write(codec.MAGIC)  # format header on fresh segments

    @property
    def next_offset(self) -> int:
        return self.start + self.count

    def append(self, key_value: int, row: tuple, diff: int) -> None:
        self._f.write(codec.encode_record((key_value, row, diff)))
        self.count += 1
        if faults.fire("persistence.journal.torn"):
            # an OS-level crash that loses the tail of a flushed-but-not-
            # fsynced segment: leave a partial trailing frame and die.
            # Reopen drops the torn tail (valid_prefix_len) and seekable
            # sources re-journal the lost events from their own re-read.
            self._f.flush()
            pos = self._f.tell()
            self._f.close()
            with open(self.path, "r+b") as tf:
                tf.truncate(max(pos - 7, len(codec.MAGIC)))
            faults.hard_crash()

    def flush(self, sync: bool = False) -> None:
        self._f.flush()
        if sync:
            os.fsync(self._f.fileno())

    def close(self) -> None:
        self._f.close()


class MetadataStore:
    """The finalized-frontier record: which epoch of operator snapshots is
    durable and which journal offset each connector is committed to."""

    FILE = "metadata.json"

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.path = os.path.join(root, self.FILE)

    def load(self) -> dict | None:
        if not os.path.exists(self.path):
            return None
        try:
            with open(self.path) as f:
                return _json.load(f)
        except OSError:
            return None
        except ValueError as e:
            # the commit path is fsync-then-atomic-rename, so a crash never
            # leaves this file torn; unparsable content means external
            # corruption — silently treating it as "no checkpoint" would
            # cold-start and drop committed state. Fail loudly instead.
            raise RuntimeError(
                f"persistence metadata {self.path} is corrupt ({e}); "
                "restore it from a copy or clear the persistence "
                "directory to cold-start"
            ) from e

    _UNSET = object()

    def commit(
        self,
        epoch: int,
        offsets: dict[str, int],
        signature: str,
        finalized_time: int,
        prev: "dict | None | object" = _UNSET,
        frontiers: dict | None = None,
        op_snapshots: list[str] | None = None,
        outbox: dict[str, int] | None = None,
    ) -> None:
        record = {
            "epoch": epoch,
            "offsets": offsets,
            "signature": signature,
            "finalized_time": finalized_time,
            # per-source offset frontiers (seekable sources: the source
            # seeks here on resume instead of journaling every event —
            # reference: src/persistence/frontier.rs OffsetAntichain)
            "frontiers": frontiers or {},
            "committed_at": _time.time(),
        }
        if outbox is not None:
            # per-sink SEALED outbox offsets: this commit is the
            # transactional-sink linearization point — staged output at
            # or below these offsets WILL be delivered exactly once
            # (io/outbox.py), anything past them is discarded on restart
            record["outbox"] = outbox
        if op_snapshots is not None:
            # manifest of operator snapshots this epoch WROTE: restore
            # distinguishes "stateless node" (absent here) from "snapshot
            # file lost" (listed but unreadable -> fall back an epoch)
            record["op_snapshots"] = op_snapshots
        # keep the PREVIOUS epoch's record: multi-process recovery may
        # need to roll back one epoch when peers crashed between each
        # other's commits (coordinated-recovery min-epoch negotiation).
        # Callers that already hold the previous record pass it in (one
        # consistent snapshot, one read); prev=None means "no history"
        # (rollback rewrite).
        if prev is MetadataStore._UNSET:
            prev = self.load()
        if prev is not None:
            record["history"] = [
                {k: prev[k] for k in
                 ("epoch", "offsets", "signature", "finalized_time",
                  "frontiers", "op_snapshots", "outbox")
                 if k in prev}
            ]
        blob = _json.dumps(record).encode()
        if faults.fire("persistence.metadata.torn"):
            # the crash the atomic rename protects against: half the
            # record reaches the tmp file and the process dies before the
            # rename — recovery must find the PREVIOUS record intact
            with open(self.path + ".tmp", "wb") as f:
                f.write(blob[: len(blob) // 2])
            faults.hard_crash()
        _fsync_write(self.path, blob)

    def clear(self) -> None:
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass

    def record_for(self, epoch: int) -> dict | None:
        meta = self.load()
        if meta is None:
            return None
        if int(meta.get("epoch", -1)) == epoch:
            return meta
        for rec in meta.get("history", []):
            if int(rec.get("epoch", -1)) == epoch:
                return rec
        return None


class OperatorSnapshotStore:
    """Typed-binary per-operator state, one file per (node, epoch), with
    a crc frame so a corrupt snapshot is detected at read time (phase 1
    of restore falls back to journal replay)."""

    def __init__(self, root: str):
        self.root = os.path.join(root, "operator")
        os.makedirs(self.root, exist_ok=True)

    def _path(self, pid: str, epoch: int) -> str:
        return os.path.join(self.root, f"{_safe(pid)}.{epoch}.state")

    def write(self, pid: str, epoch: int, state: dict) -> None:
        _fsync_write(
            self._path(pid, epoch), codec.encode_record(state, with_magic=True)
        )

    def read(self, pid: str, epoch: int) -> dict | None:
        p = self._path(pid, epoch)
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            buf = f.read()
        for state in codec.read_records(buf, with_magic=True):
            return state
        raise ValueError(f"operator snapshot {p} is corrupt or torn")

    def compact(self, keep_epochs: set[int]) -> None:
        keep = set(keep_epochs)
        for fn in os.listdir(self.root):
            if not fn.endswith(".state"):
                continue
            try:
                epoch = int(fn.rsplit(".", 2)[-2])
            except (ValueError, IndexError):
                continue
            if epoch not in keep:
                try:
                    os.unlink(os.path.join(self.root, fn))
                except OSError:
                    pass


def _pipeline_signature(graph: Any, exchange_n: int | None = None) -> str:
    """Stable id of the lowered pipeline: node order + each operator's
    semantic signature (class, mode, reducer set, widths, …) + native
    kernel availability. A change means persisted operator state cannot
    be mapped back onto the graph. Deliberately NOT included: the worker
    count — snapshots re-partition across PATHWAY_THREADS changes (see
    engine/core.py shard-rescale protocol; the reference pins `-w`).

    ``exchange_n`` substitutes a different process count into the
    ProcessExchangeNode signatures: elastic rebalance (parallel/
    membership.py) stages metadata that the NEXT generation — lowered at
    the new mesh size — must accept, so it computes the signature that
    generation will compute rather than its own."""
    from pathway_tpu.engine import native
    from pathway_tpu.engine.workers import ProcessExchangeNode

    from pathway_tpu.internals.fingerprint import fingerprint_spec

    parts = [f"native={native.available()}"]
    for node in graph.nodes:
        fp = getattr(node, "state_fingerprint", None)
        if fp is None:
            spec = getattr(node, "_fingerprint_spec", None)
            fp = fingerprint_spec(spec) if spec is not None else ""
            node.state_fingerprint = fp  # cache for repeat signatures
        sig = node.persist_signature()
        if exchange_n is not None and isinstance(node, ProcessExchangeNode):
            sig = f"ProcessExchange/{exchange_n}/{int(node.route is None)}"
        parts.append(f"{node.node_id}:{sig}:{fp}")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def _persistent_id(node: Any) -> str:
    # a ShardedNode is named after its inner operator so snapshots match
    # across worker counts (THREADS=1 builds the inner node directly)
    replicas = getattr(node, "replicas", None)
    inner = replicas[0] if replicas else node
    return f"n{node.node_id}-{type(inner).__name__}"


def _adapt_shard_state(node: Any, st: dict) -> dict:
    from pathway_tpu.engine.workers import adapt_shard_state

    return adapt_shard_state(node, st)


def _validate_spill_manifests(st: Any, pid: str) -> None:
    """Phase-1 validation of every spill-run manifest embedded in a
    decoded snapshot. Semantic tamper (run missing from the listing,
    bad record totals) raises PlanVerificationError — restore REFUSES;
    file-level damage (missing/torn run segments) raises RuntimeError —
    restore falls back one epoch like any other unreadable snapshot."""
    from pathway_tpu.engine import spill as _spill

    if _spill.is_manifest(st):
        _spill.verify_manifest(st, pid)
        _spill.validate_manifest_files(st)
        return
    if isinstance(st, dict):
        for v in st.values():
            _validate_spill_manifests(v, pid)
    elif isinstance(st, (list, tuple)):
        for v in st:
            _validate_spill_manifests(v, pid)


class CheckpointManager:
    """Orchestrates checkpoints: journal fsync → operator snapshots →
    metadata commit → compaction. Restores in the opposite order."""

    def __init__(self, session: Any, config: Config):
        self.session = session
        self.config = config
        root = config.backend.path
        assert root is not None
        self.journal = SegmentedJournal(root)
        self.metadata = MetadataStore(root)
        self.ops = OperatorSnapshotStore(root)
        # spilled arrangements (engine/spill.py) keep their runs under
        # the same persistence root so checkpoint manifests stay valid
        # across restarts; without persistence the runs live in a
        # per-process tempdir instead
        from pathway_tpu.engine import spill as _spill

        _spill.set_root(root, persistent=True)
        self.signature = _pipeline_signature(session.graph)
        self.epoch = 0
        self._last_checkpoint = _time.monotonic()
        self._writers: dict[str, _SegmentWriter] = {}
        self._restored_offsets: dict[str, int] = {}
        # per-connector offset frontiers from the restored epoch (seekable
        # sources seek here instead of journal replay)
        self.restored_frontiers: dict[str, dict] = {}
        self.restored = False
        # transactional sinks (io/outbox.py): set by attach_persistence
        # when exactly-once mode is on; sealed offsets of the restored
        # epoch drive the replay negotiation
        self.outboxes: Any = None
        self.restored_outbox: dict[str, int] = {}

    # ------------------------------------------------------------ restore

    def latest_epoch(self) -> int:
        meta = self.metadata.load()
        return int(meta["epoch"]) if meta is not None else 0

    def restore(self, epoch: int | None = None) -> dict[str, int]:
        """Returns per-connector replay offsets ({} = cold start). Loads
        operator snapshots when the pipeline signature matches. `epoch`
        selects a specific committed epoch (multi-process recovery rolls
        back to the minimum epoch every process holds); default latest."""
        if epoch == 0:
            # agreed cold start (a peer has no checkpoint): ignore local
            # snapshots; the full journal replays — only sound if its head
            # survives. The stale metadata is wiped so the next commit
            # starts a fresh epoch chain consistent with the peers.
            meta0 = self.metadata.load()
            names = list(meta0["offsets"]) if meta0 else []
            for name in names:
                if self.journal.head_offset(name) > 0:
                    raise RuntimeError(
                        f"cold recovery needs the full journal for "
                        f"{name!r} but it was compacted; clear the "
                        "persistence directories to restart"
                    )
            self.metadata.clear()
            self.epoch = 0
            return {name: 0 for name in names}
        meta = (
            self.metadata.load()
            if epoch is None
            else self.metadata.record_for(epoch)
        )
        if meta is None:
            if epoch:
                raise RuntimeError(
                    f"checkpoint epoch {epoch} is not available locally; "
                    "clear the persistence directory to cold-start"
                )
            return {}
        offsets: dict[str, int] = {k: int(v) for k, v in meta["offsets"].items()}
        # the journal must still cover every offset this epoch needs —
        # silently-skipped missing head segments would drop events
        for name, off in offsets.items():
            head = self.journal.head_offset(name)
            if head > off:
                raise RuntimeError(
                    f"journal for {name!r} was compacted to offset {head}, "
                    f"past epoch {meta.get('epoch')}'s offset {off}; cannot "
                    "resume from this epoch. Clear the persistence "
                    "directories to restart."
                )
        # Candidate epochs, newest first. A negotiated epoch (multi-process
        # rollback) is exact — peers agreed on it, no deeper fallback; the
        # single-process default may fall back one epoch when the newest
        # snapshots turn out lost/corrupt (compaction keeps TWO epochs of
        # snapshots and journal back to the previous epoch's offsets for
        # exactly this degradation rung).
        candidates = [meta]
        if epoch is None:
            candidates += list(meta.get("history", []))
        if self.config.operator_snapshots:
            for i, rec in enumerate(candidates):
                if rec.get("signature") != self.signature:
                    continue
                offs = {k: int(v) for k, v in rec["offsets"].items()}
                if i > 0 and any(
                    self.journal.head_offset(n) > o for n, o in offs.items()
                ):
                    continue  # journal no longer covers this epoch
                restored = self._read_epoch_snapshots(rec)
                if restored is None:
                    continue
                if i > 0:
                    # logged only now that this epoch's snapshots READ —
                    # claiming a fallback that then fails its own phase-1
                    # validation would mislead recovery forensics
                    self.session.graph.log_error(
                        f"epoch {meta.get('epoch')} snapshots unusable; "
                        f"falling back to epoch {rec.get('epoch')}"
                    )
                # Phase 2 — apply. A failure here leaves earlier nodes
                # mutated; falling back to journal replay would double-
                # count their state, so fail loudly instead.
                applied = 0
                try:
                    for node, st in restored:
                        node.restore_state(st)
                        applied += 1
                except Exception as e:  # noqa: BLE001
                    raise RuntimeError(
                        f"operator state restore failed after {applied} of "
                        f"{len(restored)} operators ({e}); persisted state is "
                        "incompatible with this pipeline. Clear the "
                        "persistence directory or revert the change."
                    ) from e
                self.epoch = int(rec["epoch"])
                self.restored = True
                self._restored_offsets = offs
                self.restored_frontiers = dict(rec.get("frontiers", {}))
                self.restored_outbox = {
                    k: int(v) for k, v in rec.get("outbox", {}).items()
                }
                if epoch is not None or i > 0:
                    # rollback OR history fallback: rewrite the on-disk
                    # record to the epoch actually restored NOW, else the
                    # next commit would chain its history and journal-
                    # compaction floor off the stale newer record
                    # (unrecoverable on a second crash)
                    self.metadata.commit(
                        self.epoch,
                        offs,
                        str(rec.get("signature")),
                        int(rec.get("finalized_time", 0)),
                        prev=None,
                        frontiers=self.restored_frontiers,
                        op_snapshots=rec.get("op_snapshots"),
                        outbox=rec.get("outbox"),
                    )
                return offs
        # fall back to full journal replay — only sound if the head exists
        for name in offsets:
            head = self.journal.head_offset(name)
            if head > 0:
                raise RuntimeError(
                    f"persisted state for {name!r} was compacted up to offset "
                    f"{head} but the pipeline changed (signature mismatch); "
                    "cannot resume. Clear the persistence directory or revert "
                    "the pipeline/worker configuration."
                )
        if epoch is not None:
            # negotiated-epoch resume via full replay (snapshots disabled
            # or unusable): restart the epoch chain at 0 so every peer's
            # next commit agrees — leaving the stale record would desync
            # chains on the next crash
            self.metadata.clear()
            self.epoch = 0
        return {name: 0 for name in offsets}

    def _read_epoch_snapshots(
        self, rec: dict
    ) -> list[tuple[Any, dict]] | None:
        """Phase 1 of restore: read + validate every snapshot of `rec`'s
        epoch before touching any node, so failure falls back cleanly
        (nothing has been mutated). Returns None when the epoch is
        unusable: a snapshot is corrupt, un-adaptable, or listed in the
        epoch's manifest but missing on disk."""
        from pathway_tpu.internals.verifier import PlanVerificationError

        epoch = int(rec["epoch"])
        manifest = rec.get("op_snapshots")
        restored: list[tuple[Any, dict]] = []
        try:
            for node in self.session.graph.nodes:
                pid = _persistent_id(node)
                st = self.ops.read(pid, epoch)
                if st is None:
                    if manifest is not None and pid in manifest:
                        raise RuntimeError(
                            f"operator snapshot {pid}.{epoch} is listed in "
                            "the epoch manifest but missing on disk"
                        )
                    continue  # stateless node: never snapshotted
                _validate_spill_manifests(st, pid)
                # worker-count changes re-partition here, BEFORE any node
                # mutates — RescaleUnsupported falls back cleanly
                restored.append((node, _adapt_shard_state(node, st)))
        except PlanVerificationError:
            # a TAMPERED spill manifest (keys in two tiers, runs missing
            # from the listing) is a contract violation, not a degraded
            # disk: refuse loudly before any data flows rather than
            # silently serving an older epoch
            raise
        except Exception as e:  # noqa: BLE001
            self.session.graph.log_error(
                f"operator snapshot unreadable (epoch {epoch}): {e}"
            )
            return None
        return restored

    # --------------------------------------------------------- journaling

    def open_writer(self, name: str, start: int) -> None:
        self._writers[name] = self.journal.open_segment(name, start)

    def append(self, name: str, key_value: int, row: tuple, diff: int) -> None:
        # always via the manager: checkpoints roll segments underneath
        self._writers[name].append(key_value, row, diff)

    def flush_journal(self, name: str) -> None:
        self._writers[name].flush()

    # --------------------------------------------------------- checkpoint

    def due(self) -> bool:
        interval = self.config.snapshot_interval_ms / 1000.0
        return (_time.monotonic() - self._last_checkpoint) >= interval

    def frontier_advanced(self) -> bool:
        """True when some offset-aware connector's frontier moved past
        what the last checkpoint committed (the pump checkpoints even on
        data-quiet streams then)."""
        committed = getattr(self, "_committed_frontiers", {})
        for c in getattr(self.session, "connectors", []):
            fr = getattr(c, "current_frontier", None)
            if fr is not None and fr != committed.get(c.name):
                return True
        return False

    def checkpoint(self, finalized_time: int) -> None:
        """Durable commit of everything consumed so far."""
        self._last_checkpoint = _time.monotonic()
        # 1. journal segments durable + offset frontiers of seekable
        # sources (their events are never journaled; the frontier IS the
        # durable input record)
        offsets: dict[str, int] = {}
        for name, w in self._writers.items():
            w.flush(sync=True)
            offsets[name] = w.next_offset
        frontiers: dict[str, dict] = {}
        for c in getattr(self.session, "connectors", []):
            fr = getattr(c, "current_frontier", None)
            if fr is not None:
                frontiers[c.name] = dict(fr)
        self._committed_frontiers = frontiers
        # 1b. transactional sinks: fsync the staged outbox WAL and take
        # the per-sink sealed offsets the metadata commit will pin
        outbox_offsets = None
        if self.outboxes is not None:
            outbox_offsets = self.outboxes.seal_all()
            # crash window: output staged + durable but NOT sealed — the
            # committed metadata still points at the previous offsets, so
            # recovery discards this tail and the replayed inputs
            # regenerate it (their offsets were not committed either)
            faults.crash("sink.outbox.pre_seal")
        # 2. operator snapshots for the next epoch
        epoch = self.epoch + 1
        wrote_ops = False
        op_manifest: list[str] = []
        if self.config.operator_snapshots:
            wrote_ops = True
            for node in self.session.graph.nodes:
                st = node.persist_state()
                if st is not None:
                    pid = _persistent_id(node)
                    op_manifest.append(pid)
                    if faults.fire("persistence.snapshot.skip"):
                        # injected lost-snapshot: the file never lands but
                        # the manifest still lists it — restore must
                        # detect the hole and fall back an epoch
                        continue
                    self.ops.write(pid, epoch, st)
        # crash window A: snapshots written, metadata not committed —
        # recovery must resume from the PREVIOUS epoch untouched
        faults.crash("persistence.checkpoint.pre_commit")
        # 3. metadata commit (the linearization point)
        prev_record = self.metadata.load()
        self.metadata.commit(
            epoch, offsets, self.signature, finalized_time, prev=prev_record,
            frontiers=frontiers, op_snapshots=sorted(op_manifest),
            outbox=outbox_offsets,
        )
        self.epoch = epoch
        # crash window B: committed but not compacted — recovery resumes
        # from THIS epoch; stale epoch-(N-1) artifacts are inert
        faults.crash("persistence.checkpoint.post_commit")
        # 3b. the epoch's sink output is now SEALED: flush it through the
        # writers, ack, and GC fully-acked outbox segments (io/outbox.py)
        if self.outboxes is not None:
            # crash window: sealed but nothing delivered — restart
            # replays the whole sealed-unacked range from the outbox
            faults.crash("sink.outbox.post_seal")
            self.outboxes.deliver_all(epoch)
        # 4. compaction — keep TWO epochs of snapshots and the journal
        # back to the previous epoch's offsets, so multi-process recovery
        # can roll back one epoch when peers crashed between commits
        if wrote_ops:
            self.ops.compact({epoch - 1, epoch})
            # spill runs retired by compaction stay on disk until they
            # have survived enough checkpoints that no restorable epoch's
            # manifest can still reference them
            from pathway_tpu.engine import spill as _spill

            _spill.collect_garbage()
            prev_offsets = (
                prev_record.get("offsets", {}) if prev_record else {}
            )
            for name, committed in offsets.items():
                # no previous record -> floor 0: the pre-existing journal
                # may still serve an agreed-epoch-0 recovery (a genuine
                # first run has nothing to compact anyway)
                safe = min(int(prev_offsets.get(name, 0)), committed)
                self.journal.compact(name, safe)
                # roll the segment so future compactions can free it
                w = self._writers[name]
                if w.count:
                    w.close()
                    self._writers[name] = self.journal.open_segment(
                        name, w.next_offset
                    )

    def close(self) -> None:
        for w in self._writers.values():
            w.close()
        if self.outboxes is not None:
            # writers close only now, after the final checkpoint's
            # delivery + ack (OutputNode.on_end defers to the outbox)
            self.outboxes.close()


def attach_persistence(session: Any, config: Config) -> None:
    """Wire journaling + operator snapshots + replay into a session."""
    if config.persistence_mode in ("UDF_CACHING", "udf_caching"):
        return  # cache-only mode: UDF caches use the backend directly
    s3_sync = None
    if config.backend.kind == "s3":
        import tempfile

        root_path = config.backend.path or "pathway"
        if getattr(session, "mesh", None) is not None:
            root_path = f"{root_path}/proc-{session.mesh.process_id}"
        # per-run private staging dir: a fixed shared path would let a
        # second attach rmtree a live run's tree (pull() resets from the
        # bucket anyway, so nothing needs to survive locally)
        local = tempfile.mkdtemp(prefix="pathway-s3-stage-")
        s3_sync = _S3Sync(
            config.backend.s3_client, config.backend.s3_bucket, root_path, local
        )
        s3_sync.pull()  # the bucket is the source of truth on attach
        config = config.with_backend(Backend.filesystem(local))
    elif config.backend.kind != "filesystem" or not config.backend.path:
        return
    elif getattr(session, "mesh", None) is not None:
        # each cooperating process owns its shard of operator state and
        # its own sources: persistence roots are per-process
        config = config.with_backend(
            Backend.filesystem(
                os.path.join(
                    config.backend.path, f"proc-{session.mesh.process_id}"
                )
            )
        )
    manager = CheckpointManager(session, config)
    if s3_sync is not None:
        # every durable commit ships to the bucket; metadata.json last
        # (see _S3Sync.push) so a crash mid-upload keeps the prior epoch
        _orig_ckpt = manager.checkpoint
        _orig_close = manager.close

        def _ckpt_and_push(t: int) -> None:
            _orig_ckpt(t)
            s3_sync.push()

        def _close_and_push() -> None:
            import shutil

            _orig_close()
            s3_sync.push()
            shutil.rmtree(s3_sync.local, ignore_errors=True)

        manager.checkpoint = _ckpt_and_push  # type: ignore[method-assign]
        manager.close = _close_and_push  # type: ignore[method-assign]
    if getattr(session, "mesh", None) is not None:
        # coordinated recovery: a crash can land BETWEEN two processes'
        # commits of the same epoch, so resume from the MINIMUM epoch all
        # processes hold — each keeps two epochs for exactly this
        epochs = session.mesh.allgather("ckpt-epoch", manager.latest_epoch())
        agreed = min(epochs.values())
        replay_offsets = manager.restore(epoch=agreed)
    else:
        replay_offsets = manager.restore()

    from pathway_tpu.engine.runtime import Connector, OffsetMark

    class PersistentConnector(Connector):
        """Durability wrapper, per the source's replay style:

        * 'offset' — the source emits OffsetMark frontiers (fs byte
          positions, kafka offsets). NOTHING is journaled: events are
          delivered only up to the last mark (the rest is held one poll),
          the checkpoint records the frontier, and on restart the source
          SEEKS past it (reference: frontier.rs OffsetAntichain +
          data_storage.rs:303-320 seek). Token-resident batches flow
          through untouched — full native ingest speed under persistence.
        * 'seekable' — deterministic re-readers without offsets: journal
          everything, count-skip the re-read on resume.
        * 'live' — message queues: journal everything; the journal
          supplies history, the source only ever delivers new events.
        """

        def __init__(self, inner: Connector, name: str):
            super().__init__(name, inner.session)
            self.inner = inner
            # global lowering ordinal rides the wrapper: elastic
            # rebalance routes this source's journal by ordinal % n
            if hasattr(inner, "ordinal"):
                self.ordinal = inner.ordinal
            self.style = (
                "offset" if inner.replay_style == "offset" else
                "seekable" if inner.replay_style == "seekable" else "live"
            )
            if self.style == "offset":
                self.frontier: dict = dict(
                    manager.restored_frontiers.get(name, {})
                )
                inner.session.resume_frontier = dict(self.frontier)
                self._held: list = []
                self.tail = []
                self.skip = 0
                return
            self.committed = replay_offsets.get(name, 0)
            self.tail = manager.journal.load_from(name, self.committed)
            total = manager.journal.total_events(name)
            # seekable sources re-read from the start: skip events the
            # journal already has. Live sources (message queues) only
            # deliver new events — skip nothing.
            self.skip = total if self.style == "seekable" else 0
            manager.open_writer(name, total)
            self._replay_done = False
            self._seen = 0

        def start(self) -> None:
            self.inner.start()

        @property
        def current_frontier(self) -> dict | None:
            """Checkpointed by the manager: covers exactly the events
            delivered so far (held events are re-read after resume)."""
            return self.frontier if self.style == "offset" else None

        def _poll_offset(self) -> list:
            staged = self.session.drain()
            out: list = []
            for seg in staged:
                if type(seg) is OffsetMark:
                    out.extend(self._held)
                    self._held.clear()
                    self.frontier.update(seg.frontier)
                else:
                    self._held.append(seg)
            if self.inner.finished.is_set() and not self.session._staged:
                out.extend(self._held)
                self._held.clear()
            return out

        def poll(self) -> list:
            if self.style == "offset":
                return self._poll_offset()
            out = []
            if not self._replay_done:
                self._replay_done = True
                for (_off, kv, row, diff) in self.tail:
                    out.append((Key(kv), row, diff))
                self.tail = []
            live = self.inner.poll()
            # token-resident segments journal via the object plane (the
            # per-event journal format); offset-style sources keep native
            # speed because they never journal
            if any(type(seg) is not tuple for seg in live):
                flat: list = []
                for seg in live:
                    if type(seg) is tuple:
                        flat.append(seg)
                    else:
                        flat.extend(
                            (k, row, d) for (k, row, d) in seg.materialize()
                        )
                live = flat
            wrote = False
            for (key, row, diff) in live:
                self._seen += 1
                if self._seen <= self.skip:
                    continue  # journaled in a previous run; replayed above
                manager.append(self.name, key.value, row, diff)
                wrote = True
                out.append((key, row, diff))
            if wrote:
                manager.flush_journal(self.name)
            return out

        @property
        def done(self) -> bool:
            if self.style == "offset":
                return self.inner.done and not self._held
            return self.inner.done

    fresh_start = manager.metadata.load() is None and all(
        manager.journal.total_events(c.name) == 0 for c in session.connectors
    )
    session.connectors = [
        PersistentConnector(c, c.name) for c in session.connectors
    ]
    session.checkpointer = manager
    # end-to-end exactly-once: thread every output sink through the
    # transactional outbox (io/outbox.py) — stage to a WAL, seal at the
    # metadata commit, deliver + ack after it, replay on restart.
    # PATHWAY_EXACTLY_ONCE=0 keeps the direct per-wave writes (today's
    # at-least-once) byte-identically; static pipelines (no streaming
    # connectors) never cut checkpoints, so they also write directly.
    from pathway_tpu.io.outbox import exactly_once_enabled

    if session.connectors and exactly_once_enabled():
        from pathway_tpu.engine.runtime import OutputNode
        from pathway_tpu.io.outbox import OutboxManager

        out_nodes = [
            n for n in session.graph.nodes if isinstance(n, OutputNode)
        ]
        if out_nodes:
            obm = OutboxManager(manager.journal.root)
            for i, node in enumerate(out_nodes):
                obm.register(f"sink{i:02d}", node)
            manager.outboxes = obm
            # replay negotiation: discard the unsealed WAL tail, then
            # re-deliver anything sealed by the restored epoch but not
            # yet acked by a writer flush
            obm.recover(manager.restored_outbox, manager.epoch)
    if fresh_start:
        # bootstrap commit: a fresh run records epoch 1 (empty operator
        # state, zero offsets) BEFORE any event flows, so a crash at any
        # point leaves a committed metadata record to resume from — the
        # reference likewise initializes its metadata storage at startup
        # (state.rs MetadataAccessor::new). Only safe on a fresh start:
        # with a journal tail pending replay, the writers' offsets would
        # overstate what the (restored) operator state has consumed.
        manager.checkpoint(0)


# Backwards-compatible alias used by earlier tests/tools.
class SnapshotJournal(SegmentedJournal):
    def load(self, name: str) -> list:
        return [(kv, row, diff) for (_o, kv, row, diff) in self.load_from(name, 0)]


__all__ = [
    "Backend",
    "Config",
    "attach_persistence",
    "CheckpointManager",
    "MetadataStore",
    "OperatorSnapshotStore",
    "SegmentedJournal",
    "SnapshotJournal",
]
