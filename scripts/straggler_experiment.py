#!/usr/bin/env python
"""Straggler isolation experiment (docs/parallelism.md).

Two cooperating processes, two causally-independent branches:

  * branch A — 3000 fast rows -> groupby -> subscribe;
  * branch B — 300 rows -> UDF that sleeps D ms per row ON WORKER 1
    only (the straggler) -> groupby -> subscribe.

Measured per mode (lockstep BSP via PATHWAY_MESH_BSP=1 vs the default
frontier runtime) and per injected delay D: the wall-clock time until
branch A's LAST delivery anywhere in the mesh, and the total run wall.

Under lockstep BSP every process advances one wave at a time, so branch
A's deliveries trail the straggler's wave rate: its completion time
grows with D even though no A-row ever waits on B data. Under
frontier-based progress tracking, A's operators fire as soon as their
own input frontier passes — the straggler delays only the B branch.

Usage: python scripts/straggler_experiment.py [--quick]
Prints a markdown table (the one embedded in docs/parallelism.md).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCRIPT = textwrap.dedent(
    """
    import json, os, sys, time
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw
    from pathway_tpu.io.python import ConnectorSubject

    OUT = sys.argv[1]
    DELAY_MS = float(sys.argv[2])
    N_A, N_B = {n_a}, {n_b}
    PID = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class Fast(ConnectorSubject):
        def run(self):
            # light and paced: the fast branch must not be CPU-bound,
            # so any inflation of its completion time is COUPLING, not
            # contention
            for i in range(N_A):
                self.next(g=f"a{{i % 10}}", v=i)
                time.sleep(0.005)

    class Small(ConnectorSubject):
        def run(self):
            for i in range(N_B):
                self.next(g=f"b{{i % 10}}", v=i)
                time.sleep(0.002)  # straggler waves spread over the run

    # sources partition by ordinal: Fast on process 0, Small on process 1
    a = pw.io.python.read(Fast(), schema=pw.schema_from_types(g=str, v=int),
                          name="fast")
    b = pw.io.python.read(Small(), schema=pw.schema_from_types(g=str, v=int),
                          name="small")

    def slow_id(v):
        # the straggler: worker 1's UDF is delayed per row
        if PID == 1 and DELAY_MS > 0:
            time.sleep(DELAY_MS / 1000.0)
        return v

    b2 = b.select(g=b.g, v=pw.apply(slow_id, b.v))
    agg_a = a.groupby(a.g).reduce(a.g, n=pw.reducers.count())
    agg_b = b2.groupby(b2.g).reduce(b2.g, n=pw.reducers.count())

    t0 = time.perf_counter()
    last = {{"a": 0.0, "b": 0.0, "first_a": None, "rows_a": 0, "rows_b": 0}}
    a_times = []
    import time as _clock
    def track(tag):
        def on_change(key, row, time, is_addition):
            now = _clock.perf_counter() - t0
            last[tag] = now
            if tag == "a":
                if last["first_a"] is None:
                    last["first_a"] = now
                a_times.append(now)
            last["rows_" + tag] += 1
        return on_change
    pw.io.subscribe(agg_a, on_change=track("a"))
    pw.io.subscribe(agg_b, on_change=track("b"))
    pw.run()
    last["total"] = time.perf_counter() - t0
    # delivery cadence of the fast branch: distinct update waves and the
    # worst gap between consecutive updates (freshness under skew)
    waves = sorted(set(round(x, 4) for x in a_times))
    gaps = [b - a for a, b in zip(waves, waves[1:])]
    last["a_waves"] = len(waves)
    last["a_max_gap"] = max(gaps) if gaps else 0.0
    with open(OUT + f".{{PID}}", "w") as f:
        json.dump(last, f)
    # with PATHWAY_OBSERVABILITY=1 (+ PATHWAY_FLIGHT_DIR) in the caller's
    # env, each worker leaves a flight dump whose wave events carry the
    # (operator, time, queue/exec) timeline — the skew experiment becomes
    # reconstructable from one dump per process instead of rerunning
    from pathway_tpu.internals import observability as _sobs
    if _sobs.PLANE is not None:
        _sobs.dump_flight("straggler-end")
    """
)


def _free_port_base() -> int:
    socks, ports = [], []
    for _ in range(6):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return max(ports) + 1


def run_once(mode: str, delay_ms: float, n_a: int, n_b: int) -> dict:
    out = f"/tmp/straggler_{os.getpid()}_{mode}_{delay_ms}"
    base = _free_port_base()
    procs = []
    for pid in range(2):
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PATHWAY_PROCESSES": "2",
            "PATHWAY_PROCESS_ID": str(pid),
            "PATHWAY_FIRST_PORT": str(base),
        }
        if mode == "bsp":
            env["PATHWAY_MESH_BSP"] = "1"
        else:
            env.pop("PATHWAY_MESH_BSP", None)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-c",
                    SCRIPT.format(repo=REPO, n_a=n_a, n_b=n_b),
                    out, str(delay_ms),
                ],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    for p in procs:
        _o, err = p.communicate(timeout=600)
        if p.returncode != 0:
            raise RuntimeError(f"{mode} d={delay_ms}: {err[-2000:]}")
    merged = {"a": 0.0, "b": 0.0, "total": 0.0, "rows_a": 0, "rows_b": 0}
    for pid in range(2):
        with open(out + f".{pid}") as f:
            r = json.load(f)
        if pid == 0:
            # worker 0's own branch-A shard: the pure isolation metric —
            # these operators never consume straggler data, and their
            # pump thread never runs the delayed UDF. Span from first to
            # last delivery excludes mesh-connect / lowering startup.
            merged["a_w0"] = r["a"] - (r["first_a"] or 0.0)
            merged["a_waves"] = r["a_waves"]
            merged["a_max_gap"] = r["a_max_gap"]
        merged["a"] = max(merged["a"], r["a"])
        merged["b"] = max(merged["b"], r["b"])
        merged["total"] = max(merged["total"], r["total"])
        merged["rows_a"] += r["rows_a"]
        merged["rows_b"] += r["rows_b"]
        os.unlink(out + f".{pid}")
    assert merged["rows_a"] > 0 and merged["rows_b"] > 0, merged
    return merged


def main() -> None:
    quick = "--quick" in sys.argv
    n_a, n_b = (150, 100) if quick else (300, 200)
    delays = [0.0, 5.0] if quick else [0.0, 5.0, 20.0]
    rows = []
    for delay in delays:
        for mode in ("bsp", "frontier"):
            best = None
            for _trial in range(1 if quick else 2):
                r = run_once(mode, delay, n_a, n_b)
                if best is None or r["total"] < best["total"]:
                    best = r
            rows.append((delay, mode, best))
            print(
                f"# {mode:9s} d={delay:4.0f}ms  A@w0 {best['a_w0']:6.2f}s  "
                f"A-waves {best['a_waves']:4d}  A-max-gap "
                f"{best['a_max_gap'] * 1000:6.0f}ms  "
                f"branchB {best['b']:6.2f}s  total {best['total']:6.2f}s",
                file=sys.stderr,
            )
    print("| per-row delay on worker 1 | mode | branch-A span (worker 0) | "
          "branch-A update waves | branch-A worst gap | branch-B done | "
          "total wall |")
    print("|---|---|---|---|---|---|---|")
    for delay, mode, r in rows:
        print(
            f"| {delay:.0f} ms | {mode} | {r['a_w0']:.2f} s | {r['a_waves']} "
            f"| {r['a_max_gap'] * 1000:.0f} ms | {r['b']:.2f} s "
            f"| {r['total']:.2f} s |"
        )


if __name__ == "__main__":
    main()
