"""pw.ordered: diff over sorted order (reference: stdlib/ordered/diff.py:123)."""

from __future__ import annotations

from typing import Any

import pathway_tpu.internals.expression as ex
from pathway_tpu.internals.table import Table


def diff(
    table: Table,
    timestamp: ex.ColumnExpression,
    *values: ex.ColumnReference,
    instance: Any = None,
) -> Table:
    """For each row, subtract the previous row's `values` (ordered by
    `timestamp`): diff_<col> = col - prev(col)."""
    sorted_t = table.sort(key=timestamp, instance=instance)
    prev_rows = table.ix(sorted_t.prev, optional=True)
    kwargs = {}
    for v in values:
        name = v.name
        # the first row (no predecessor) gets None, not an arithmetic
        # error — reference: stdlib/ordered/diff.py "the value of the
        # first row is None"
        kwargs["diff_" + name] = ex.IfElseExpression(
            ex.IsNoneExpression(prev_rows[name]),
            None,
            table[name] - prev_rows[name],
        )
    return table.select(*table, **kwargs)
