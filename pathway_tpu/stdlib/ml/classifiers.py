"""kNN-LSH classifiers.

Reference parity: stdlib/ml/classifiers/_knn_lsh.py
(knn_lsh_classifier_train :64, knn_lsh_generic_classifier_train :135,
knn_lsh_euclidean_classifier_train :293, knn_lsh_classify :306) and
_lsh.py's euclidean/cosine bucketers. The reference expresses LSH
bucketing as dataflow (band columns + join on bucket); here the LSH
tables live in the engine's external-index operator (host LshIndex,
stdlib/indexing/host_indexes.py — the same OR-AND random-projection
scheme), so one query wave is answered in a single batched index call.
API and semantics (train -> model(queries, k) -> majority-vote classify)
match the reference.
"""

from __future__ import annotations

from typing import Any, Callable, Literal

from pathway_tpu.internals.reducers import ArgMaxReducer, ReducerExpression
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.colnames import _INDEX_REPLY_ID
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import LshKnn

DistanceTypes = Literal["euclidean", "cosine"]

KnnModel = Callable[[Table, Any], Table]


def knn_lsh_classifier_train(
    data: Table,
    L: int,
    type: DistanceTypes = "euclidean",  # noqa: A002
    **kwargs: Any,
) -> KnnModel:
    """Build the LSH index over `data` (column ``data``: vectors).

    L is the number of repetitions of the LSH scheme (OR-tables). Extra
    kwargs: d (dimension), M (projections per table), A (bucket width).
    Returns a model: (queries, k) -> Table(query_id, knns_ids).
    """
    if type == "euclidean":
        return knn_lsh_euclidean_classifier_train(
            data,
            d=kwargs.get("d"),
            M=kwargs.get("M", 10),
            L=L,
            A=kwargs.get("A", 1.0),
        )
    if type == "cosine":
        inner = LshKnn(
            data_column=data.data,
            metadata_column=None,
            dimensions=kwargs.get("d"),
            n_or=L,
            n_and=kwargs.get("M", 10),
            bucket_length=kwargs.get("A", 1.0),
            distance_type="cos",
        )
        return _model_from_inner(data, inner)
    raise ValueError(f"unsupported LSH distance type {type!r}")


knn_lsh_train = knn_lsh_classifier_train


def knn_lsh_euclidean_classifier_train(
    data: Table, d: int | None, M: int, L: int, A: float
) -> KnnModel:
    """Euclidean LSH: M random projections per table, bucket width A,
    L OR-tables (reference :293)."""
    inner = LshKnn(
        data_column=data.data,
        metadata_column=None,
        dimensions=d,
        n_or=L,
        n_and=M,
        bucket_length=A,
        distance_type="l2",
    )
    return _model_from_inner(data, inner)


def knn_lsh_generic_classifier_train(
    data: Table,
    lsh_projection: Any = None,
    distance_function: str | Callable = "euclidean",
    L: int = 10,
    **kwargs: Any,
) -> KnnModel:
    """Generic variant (reference :135): `lsh_projection` is a callable
    vec -> sequence of per-table bucket ids (one per OR-table) and
    `distance_function` either a metric name ('euclidean' / 'cosine') or
    a callable (query_vec, doc_vec) -> float used to rescore bucket
    candidates."""
    if isinstance(distance_function, str):
        try:
            metric = {"euclidean": "l2", "cosine": "cos"}[distance_function]
        except KeyError:
            raise ValueError(
                f"unsupported LSH distance type {distance_function!r}"
            ) from None
    else:
        metric = "l2"  # unused: the callable rescorer takes over
    inner = LshKnn(
        data_column=data.data,
        metadata_column=None,
        # d/M/A keep the classifier-train spelling and defaults
        dimensions=kwargs.get("d"),
        n_or=L,
        n_and=kwargs.get("M", 10),
        bucket_length=kwargs.get("A", 1.0),
        distance_type=metric,
        projection=lsh_projection,
        distance=(
            distance_function if callable(distance_function) else None
        ),
    )
    return _model_from_inner(data, inner)


def _model_from_inner(data: Table, inner: LshKnn) -> KnnModel:
    index = DataIndex(data_table=data, inner_index=inner)

    def model(queries: Table, k: Any) -> Table:
        # rename the query vector column: the index layer requires query
        # and data column names to be disjoint
        q = queries.select(_pw_query_vec=queries.data)
        result = index.query(
            q._pw_query_vec, number_of_matches=k, collapse_rows=True,
            with_distances=False,
        )
        return result.select(
            query_id=result.id, knns_ids=result[_INDEX_REPLY_ID]
        )

    return model


def knn_lsh_classify(
    knn_model: KnnModel, data_labels: Table, queries: Table, k: Any
) -> Table:
    """Label each query by majority vote over its k nearest neighbors'
    labels (reference :306). Output: Table(predicted_label) keyed by the
    query id; queries with no neighbors are absent from the result."""
    import pathway_tpu as pw

    knns = knn_model(queries, k)
    flat = knns.flatten(pw.this.knns_ids)
    labeled = flat.select(
        flat.query_id,
        label=data_labels.ix(flat.knns_ids).label,
    )
    votes = labeled.groupby(labeled.query_id, labeled.label).reduce(
        labeled.query_id,
        labeled.label,
        votes=pw.reducers.count(),
    )
    winner = votes.groupby(votes.query_id).reduce(
        votes.query_id,
        predicted_label=ReducerExpression(
            ArgMaxReducer(), votes.votes, votes.label
        ),
    )
    final = winner.with_id(winner.query_id)
    return final.select(predicted_label=final.predicted_label)


__all__ = [
    "DistanceTypes",
    "knn_lsh_classifier_train",
    "knn_lsh_train",
    "knn_lsh_classify",
    "knn_lsh_generic_classifier_train",
    "knn_lsh_euclidean_classifier_train",
]
