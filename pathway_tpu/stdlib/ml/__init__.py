"""pw.ml (reference: stdlib/ml/) — filled in by the index/classifier work."""

from pathway_tpu.stdlib.ml import classifiers, index, smart_table_ops, utils

__all__ = ["classifiers", "index", "smart_table_ops", "utils"]
