"""Deterministic hashing tokenizer.

A dependency-free tokenizer for the local TPU embedder: words are hashed into
a fixed vocab (feature-hashing, the same trick as hashing vectorizers). This
keeps tokenization O(len) on host with zero model files; swap in a real BPE
via `transformers` when a pretrained checkpoint is used (the `JaxEmbedder`
accepts any `tokenize_fn`).
"""

from __future__ import annotations

import re

import numpy as np

_WORD_RE = re.compile(r"[a-z0-9]+", re.IGNORECASE)

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK = 0xFFFFFFFFFFFFFFFF


def _fnv1a(data: bytes) -> int:
    h = _FNV_OFFSET
    for byte in data:
        h = ((h ^ byte) * _FNV_PRIME) & _MASK
    return h


class HashTokenizer:
    def __init__(self, vocab_size: int = 32768, max_len: int = 128):
        # ids 0 = pad, 1 = cls; words map into [2, vocab)
        self.vocab_size = vocab_size
        self.max_len = max_len

    def tokenize(self, text: str) -> list[int]:
        ids = [1]
        for m in _WORD_RE.finditer(text.lower()):
            ids.append(2 + _fnv1a(m.group(0).encode()) % (self.vocab_size - 2))
            if len(ids) >= self.max_len:
                break
        return ids

    def batch(self, texts: list[str], pad_to: int | None = None):
        """Returns (ids [b, L] int32, mask [b, L] int32) padded numpy arrays."""
        tokenized = [self.tokenize(t) for t in texts]
        longest = max((len(t) for t in tokenized), default=1)
        length = pad_to or min(self.max_len, max(longest, 1))
        ids = np.zeros((len(texts), length), np.int32)
        mask = np.zeros((len(texts), length), np.int32)
        for i, toks in enumerate(tokenized):
            toks = toks[:length]
            ids[i, : len(toks)] = toks
            mask[i, : len(toks)] = 1
        return ids, mask
