"""pw.io.redpanda — API-parity connector (reference: io/redpanda).

Client library gated: see io/_external.py.
"""

from pathway_tpu.io._external import gated_reader, gated_writer

read = gated_reader("redpanda", "confluent_kafka")
write = gated_writer("redpanda", "confluent_kafka")
