"""pw.xpacks — extension packs (llm)."""
from pathway_tpu.xpacks import llm

__all__ = ["llm"]
