"""Tests for pw.parallel: mesh helpers + key-hash ICI exchange."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pathway_tpu.parallel import (
    exchange_by_key,
    make_mesh,
    partition_counts,
    shard_rows,
)

N_DEV = len(jax.devices())


def test_make_mesh_shapes():
    mesh = make_mesh((N_DEV,), ("data",))
    assert mesh.shape["data"] == N_DEV
    mesh2 = make_mesh((N_DEV // 2, 2), ("data", "model"))
    assert mesh2.shape["model"] == 2
    with pytest.raises(ValueError, match="devices"):
        make_mesh((N_DEV * 2,), ("data",))


def test_exchange_routes_by_key_hash():
    mesh = make_mesh((N_DEV,), ("data",))
    rng = np.random.default_rng(0)
    n = N_DEV * 16
    keys = jnp.asarray(rng.integers(0, 2**31, n), jnp.uint32)
    pay = jnp.asarray(rng.normal(size=(n, 4)), jnp.float32)
    res = exchange_by_key(shard_rows(keys, mesh), shard_rows(pay, mesh), mesh)
    assert not bool(res.overflowed)
    k = np.asarray(res.keys)
    v = np.asarray(res.valid)
    p = np.asarray(res.payloads)
    # routing: shard s received exactly the keys with key % N_DEV == s
    for s in range(N_DEV):
        for kk, vv in zip(k[s], v[s]):
            if vv:
                assert int(kk) % N_DEV == s
    # conservation: every row delivered exactly once, payload intact
    assert int(v.sum()) == n
    sent = {int(kk): tuple(np.round(pp, 5)) for kk, pp in zip(np.asarray(keys), np.asarray(pay))}
    for s in range(N_DEV):
        for kk, vv, pp in zip(k[s], v[s], p[s]):
            if vv:
                assert tuple(np.round(pp, 5)) == sent[int(kk)]


def test_exchange_overflow_flag():
    mesh = make_mesh((N_DEV,), ("data",))
    n = N_DEV * 8
    # all keys hash to shard 0 -> per-dest bucket needs n slots; cap of 8
    # per destination overflows
    keys = jnp.asarray(np.zeros(n), jnp.uint32) * np.uint32(N_DEV)
    pay = jnp.ones((n, 2), jnp.float32)
    res = exchange_by_key(
        shard_rows(keys, mesh), shard_rows(pay, mesh), mesh, capacity=4
    )
    assert bool(res.overflowed)


def test_partition_counts():
    keys = jnp.asarray([0, 1, 2, 3, 4, 8, 12], jnp.uint32)
    counts = np.asarray(partition_counts(keys, 4))
    assert counts.tolist() == [4, 1, 1, 1]


def test_exchange_with_respill_skewed():
    """All rows to one destination at tiny capacity: respill rounds ship
    everything, nothing dropped, arrival order preserved."""
    from pathway_tpu.parallel.exchange import exchange_with_respill

    mesh = make_mesh((N_DEV,), ("data",))
    n = N_DEV * 8
    ids = np.arange(n, dtype=np.uint32)
    pay = np.arange(n, dtype=np.float32)[:, None] * np.ones((1, 3), np.float32)
    dests = np.full(n, 2 % N_DEV, np.int64)  # pathological skew
    keys, pays, srcs = exchange_with_respill(
        ids, pay, dests, mesh, capacity=2
    )
    d = 2 % N_DEV
    assert sum(len(k) for k in keys) == n
    assert len(keys[d]) == n
    # GLOBAL ARRIVAL ORDER across respill rounds: a retraction shipped in
    # round 2 must not overtake its insert from round 1
    assert [int(i) for i in srcs[d]] == list(range(n))
    for j, i in enumerate(srcs[d]):
        assert pays[d][j][0] == float(i)


def test_exchange_dests_route_128bit():
    """dests computed from the full 128-bit key space override the u32
    identity routing."""
    from pathway_tpu.parallel.exchange import exchange_with_respill, route128

    mesh = make_mesh((N_DEV,), ("data",))
    rng = np.random.default_rng(3)
    n = N_DEV * 4
    lo = rng.integers(0, 2**63, n, dtype=np.uint64)
    hi = rng.integers(0, 2**63, n, dtype=np.uint64)
    dests = route128(lo, hi, N_DEV)
    for i in range(n):
        assert dests[i] == ((int(hi[i]) << 64) | int(lo[i])) % N_DEV
    ids = np.arange(n, dtype=np.uint32)
    pay = rng.normal(size=(n, 2)).astype(np.float32)
    _keys, pays, srcs = exchange_with_respill(ids, pay, dests, mesh)
    for d in range(N_DEV):
        for j, i in enumerate(srcs[d]):
            assert dests[int(i)] == d
            np.testing.assert_array_equal(pays[d][j], pay[int(i)])


def test_engine_groupby_routes_vectors_through_device_exchange(monkeypatch):
    """A thread-sharded groupby whose rows carry f32 embedding columns
    moves the vectors through the device-mesh exchange (the VERDICT's
    'assert on the code path' test) and produces identical results."""
    import pathway_tpu as pw
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.parallel import device_exchange as dx

    def build_and_run():
        G.clear()
        rows = [
            (f"cat{i % 5}", np.full(16, float(i), np.float32)) for i in range(64)
        ]
        t = pw.Table.from_rows(
            pw.schema_from_types(cat=str, emb=np.ndarray), rows
        )
        res = t.groupby(t.cat).reduce(t.cat, n=pw.reducers.count())
        return sorted(map(tuple, pw.debug.table_to_pandas(res).values.tolist()))

    monkeypatch.setenv("PATHWAY_THREADS", "4")
    base = build_and_run()

    monkeypatch.setenv("PATHWAY_DEVICE_EXCHANGE", "1")
    dx._ENGINE_EXCHANGER = None  # fresh counters under the new env
    got = build_and_run()
    ex = dx._ENGINE_EXCHANGER
    assert ex is not None and ex.invocations > 0, "device exchange not taken"
    assert ex.rows_exchanged >= 64
    assert got == base == [(f"cat{i}", 13 if i < 4 else 12) for i in range(5)]
    dx._ENGINE_EXCHANGER = None


def test_device_exchange_auto_mode_policy(monkeypatch):
    """Auto mode (env unset) enables the device plane only on a real
    multi-device TPU mesh AND above the measured payload crossover;
    PATHWAY_DEVICE_EXCHANGE=1/0 force/disable it regardless."""
    import numpy as np

    from pathway_tpu.internals.keys import key_for_values
    from pathway_tpu.parallel import device_exchange as dx

    monkeypatch.delenv("PATHWAY_DEVICE_EXCHANGE", raising=False)
    assert dx.mode() == "auto"
    monkeypatch.setenv("PATHWAY_DEVICE_EXCHANGE", "1")
    assert dx.mode() == "force" and dx.enabled()
    monkeypatch.setenv("PATHWAY_DEVICE_EXCHANGE", "0")
    assert dx.mode() == "off" and not dx.enabled()

    # the virtual CPU mesh is never auto-eligible (measured always-lose:
    # in-process routing passes references; the device hop only copies).
    # The mode is CACHED at construction (one env read per exchanger,
    # not per batch) — build under auto, then prove a later env flip
    # does not leak into the running exchanger.
    monkeypatch.delenv("PATHWAY_DEVICE_EXCHANGE", raising=False)
    ex = dx.DeviceExchanger()
    assert not ex._auto_ok and ex._mode == "auto"
    entries = [
        (key_for_values(i), (i, np.ones(1024, np.float32)), 1)
        for i in range(1024)
    ]
    assert ex.try_exchange(entries, lambda k, r: k.value % 2, 2) is None
    monkeypatch.setenv("PATHWAY_DEVICE_EXCHANGE", "1")
    assert ex._mode == "auto"  # construction-time cache, not per batch
    assert ex.try_exchange(entries, lambda k, r: k.value % 2, 2) is None
    monkeypatch.delenv("PATHWAY_DEVICE_EXCHANGE", raising=False)
    # an auto-eligible mesh above the crossover would engage: simulate
    # eligibility; 1024 rows x 1024 dims = 1M elems >= 262144
    ex._auto_ok = True
    routed = ex.try_exchange(entries, lambda k, r: k.value % 2, 2)
    assert routed is not None and sum(len(r) for r in routed) == 1024
    # below the crossover auto stays off even on an eligible mesh
    small = entries[:64]
    assert ex.try_exchange(small, lambda k, r: k.value % 2, 2) is None


def test_device_exchange_int32_bit_exact(monkeypatch):
    """int32 vector columns ride the exchange as f32 views and come back
    bit-identical (incl. values whose f32 cast would round)."""
    import numpy as np

    from pathway_tpu.internals.keys import key_for_values
    from pathway_tpu.parallel import device_exchange as dx

    monkeypatch.setenv("PATHWAY_DEVICE_EXCHANGE", "1")
    ex = dx.DeviceExchanger()
    rng = np.random.default_rng(3)
    vals = [
        rng.integers(-(2**31) + 1, 2**31 - 1, 16, dtype=np.int32)
        for _ in range(32)
    ]
    entries = [
        (key_for_values(i), (i, v), 1) for i, v in enumerate(vals)
    ]
    routed = ex.try_exchange(entries, lambda k, r: k.value % 2, 2)
    assert routed is not None
    got = {row[0]: row[1] for shard in routed for _k, row, _d in shard}
    for i, v in enumerate(vals):
        assert got[i].dtype == np.int32
        assert np.array_equal(got[i], v), i


def _ingest_work():
    from pathway_tpu.engine.native import dataplane as dp

    blob = (
        "\n".join(
            '{"k": %d, "v": %d}' % (i % 1000, i) for i in range(400_000)
        )
        + "\n"
    ).encode()

    def work():
        tab = dp.InternTable()
        dp.ingest_jsonl(tab, blob, ["k", "v"], [], 7, 0, [2, 2])

    return work


def test_native_kernel_gil_release():
    """The recorded artifact on EVERY host (no cpu_count gate): the C
    dataplane is called through ctypes.CDLL, which must release the GIL
    for the duration of every call. Proven by work-overlap: a
    pure-Python counter thread keeps ticking at a comparable RATE while
    native ingest calls execute — a GIL-holding call path (e.g. PyDLL)
    would freeze the counter for each call's full duration, collapsing
    the concurrent rate to the few switch-interval slices between calls
    (<5% of solo), on any core count."""
    import threading
    import time

    from pathway_tpu.engine.native import dataplane as dp

    if not dp.available():
        pytest.skip("native dataplane unavailable")
    work = _ingest_work()
    work()  # warm (lib load, allocator)

    t0 = time.perf_counter()
    solo = 0
    while time.perf_counter() - t0 < 0.2:
        solo += 1
    solo_rate = solo / (time.perf_counter() - t0)

    done = threading.Event()

    def native_loop():
        for _ in range(3):
            work()
        done.set()

    th = threading.Thread(target=native_loop)
    ticks = 0
    th.start()
    t0 = time.perf_counter()
    while not done.is_set():
        ticks += 1  # needs the GIL every iteration
    elapsed = time.perf_counter() - t0
    th.join()
    during_rate = ticks / max(elapsed, 1e-9)
    assert during_rate > 0.10 * solo_rate, (
        f"python thread starved during native calls "
        f"({during_rate:.0f}/s vs solo {solo_rate:.0f}/s) — is the GIL "
        "held across dataplane calls?"
    )


@pytest.mark.slow
def test_native_kernel_overlap_wallclock():
    """Core-level parallelism (the stronger claim, multi-core hosts,
    marked slow: wall-clock ratios are co-tenant-sensitive and belong
    in a quiet run, not the tier-1 sweep — the GIL-release proof above
    is the always-recorded invariant): two threads running ingest
    kernels finish faster than serialized."""
    import os
    import threading
    import time

    from pathway_tpu.engine.native import dataplane as dp

    if not dp.available():
        pytest.skip("native dataplane unavailable")
    if (os.cpu_count() or 1) < 2:
        pytest.skip("wall-clock overlap needs >= 2 cores")
    work = _ingest_work()
    work()  # warm

    serial = float("inf")
    for _ in range(3):  # best-of-3 both sides: robust to co-tenant load
        t0 = time.perf_counter()
        work()
        work()
        serial = min(serial, time.perf_counter() - t0)

    best_parallel = float("inf")
    for _ in range(3):
        th2 = [threading.Thread(target=work) for _ in range(2)]
        t0 = time.perf_counter()
        for t in th2:
            t.start()
        for t in th2:
            t.join()
        best_parallel = min(best_parallel, time.perf_counter() - t0)

    overlap = serial / best_parallel
    # genuine core-level overlap sits clearly above the no-overlap 1.0x;
    # ingest is bounded below ideal 2x by the shared intern-table write
    # lock (measured 1.36x on the 2-core CI box, ~1.8x on wider hosts)
    assert overlap >= 1.2, (
        f"native kernels did not overlap across threads: serial {serial:.3f}s"
        f" vs parallel {best_parallel:.3f}s (x{overlap:.2f}) — is the GIL"
        " held across dataplane calls?"
    )
