"""Engine runtime: the per-worker pump loop.

Reference parity: run_with_new_dataflow_graph (src/engine/dataflow.rs:5506)
— connector pollers feeding input sessions, commit timestamps on an
even-millisecond total order (src/engine/timestamp.rs:20-27), a pump that
finalizes one timestamp per wave, and end-of-stream flush.
"""

from __future__ import annotations

import asyncio
import queue
import threading
import time as _time
from typing import Any, Callable, Iterable, Sequence

from pathway_tpu.engine.core import (
    CaptureNode,
    Entry,
    Graph,
    InputNode,
    KeyedState,
    Node,
    consolidate,
    freeze_row,
)
from pathway_tpu.internals.errors import ERROR
from pathway_tpu.internals.keys import Key, key_for_values, sequential_key


class InputSession:
    """Thread-safe staging buffer feeding an InputNode.

    Mirrors the reference's input session + upsert session
    (src/connectors/adaptors.rs:23): `upsert` overwrites by key, `insert`/
    `remove` are plain z-set deltas.
    """

    def __init__(self, node: InputNode, upsert: bool = False):
        self.node = node
        self.upsert_mode = upsert
        self._lock = threading.Lock()
        self._staged: list[Entry] = []
        self._current: dict[Key, tuple] = {}  # for upsert sessions
        self.closed = False

    def insert(self, key: Key, row: tuple) -> None:
        with self._lock:
            if self.upsert_mode:
                old = self._current.get(key)
                if old is not None:
                    self._staged.append((key, old, -1))
                self._current[key] = row
            self._staged.append((key, row, 1))

    def remove(self, key: Key, row: tuple | None = None) -> None:
        with self._lock:
            if self.upsert_mode:
                old = self._current.pop(key, None)
                if old is not None:
                    self._staged.append((key, old, -1))
            elif row is not None:
                self._staged.append((key, row, -1))

    def drain(self) -> list[Entry]:
        with self._lock:
            staged, self._staged = self._staged, []
        return staged

    def close(self) -> None:
        self.closed = True


class Connector:
    """A data source with its own reader thread (reference:
    src/connectors/mod.rs:427 Connector::run — one thread per input
    connector, poller drained by the main pump).

    `replay_style` drives persistence resume (reference: seekable vs
    non-seekable sources in src/persistence/frontier.rs offset logic):
      * 'seekable' — the source re-reads deterministically from the start
        on every run (files, scripted subjects); resume skips the first N
        live events already journaled.
      * 'live' — the source only ever delivers new events (message
        queues); nothing is skipped, the journal supplies history.
    """

    replay_style = "seekable"

    def __init__(self, name: str, session: InputSession):
        self.name = name
        self.session = session
        self.thread: threading.Thread | None = None
        self.finished = threading.Event()

    def start(self) -> None:
        pass

    def poll(self) -> list[Entry]:
        return self.session.drain()

    @property
    def done(self) -> bool:
        return self.finished.is_set() and not self.session._staged


class ThreadConnector(Connector):
    """Runs a read function on a dedicated thread."""

    def __init__(self, name: str, session: InputSession, read_fn: Callable[[InputSession], None]):
        super().__init__(name, session)
        self.read_fn = read_fn

    def start(self) -> None:
        def run() -> None:
            try:
                self.read_fn(self.session)
            finally:
                self.finished.set()

        self.thread = threading.Thread(target=run, daemon=True, name=f"pw-connector-{self.name}")
        self.thread.start()


class Runtime:
    """Single-worker pump. Timestamps are even milliseconds from run start."""

    def __init__(self, graph: Graph, autocommit_ms: int = 2):
        self.graph = graph
        self.autocommit_ms = max(2, autocommit_ms - autocommit_ms % 2)
        self.time = 0
        self.connectors: list[Connector] = []
        self.monitors: list[Callable[[int], None]] = []
        # checkpoint/resume orchestration (persistence.CheckpointManager)
        self.checkpointer: Any = None

    def next_time(self) -> int:
        self.time += 2  # even-ms granule, reference timestamp.rs:20-27
        return self.time

    def add_connector(self, connector: Connector) -> None:
        self.connectors.append(connector)

    def run(self) -> None:
        """Pump until all connectors are done; then flush + end."""
        for c in self.connectors:
            c.start()
        if not self.connectors:
            t = self.next_time()
            self.graph.step(t)
            self.graph.end(t)
            return
        while True:
            _time.sleep(self.autocommit_ms / 1000.0)
            any_data = False
            for c in self.connectors:
                entries = c.poll()
                if entries:
                    any_data = True
                    c.session.node.push(entries)
            if any_data:
                t = self.next_time()
                self.graph.step(t)
                for m in self.monitors:
                    m(t)
                if self.checkpointer is not None and self.checkpointer.due():
                    self.checkpointer.checkpoint(t)
            if all(c.done for c in self.connectors):
                # final drain
                final: bool = False
                for c in self.connectors:
                    entries = c.poll()
                    if entries:
                        c.session.node.push(entries)
                        final = True
                t = self.next_time()
                if final:
                    self.graph.step(t)
                self.graph.end(t)
                if self.checkpointer is not None:
                    self.checkpointer.checkpoint(t)
                    self.checkpointer.close()
                break

    def run_static(self, batches: list[tuple[int, InputNode, list[Entry]]]) -> None:
        """Batch mode: feed pre-timed batches, run each wave, then end.

        `batches` are (time, node, entries); times must use the even-ms
        domain. All nodes step at every distinct time in order.
        """
        by_time: dict[int, list[tuple[InputNode, list[Entry]]]] = {}
        for t, node, entries in batches:
            by_time.setdefault(t, []).append((node, entries))
        last_t = 0
        for t in sorted(by_time):
            for node, entries in by_time[t]:
                node.push(entries)
            self.graph.step(t)
            last_t = t
        self.graph.end(last_t + 2)


class IterateNode(Node):
    """Fixpoint iteration (reference: iterate dataflow.rs:3737).

    v0 strategy: per outer timestamp, re-run the loop body over the full
    accumulated input collections until the iterated collections stop
    changing, then emit the diff of the outputs versus what was previously
    emitted. Incremental-within-loop is a later optimization; the semantics
    (per-time fixpoint, diff-based output) match.
    """

    def __init__(
        self,
        graph: Graph,
        inputs: Sequence[Node],
        input_names: list[str],
        iterated_names: list[str],
        output_names: list[str],
        step_fn: Callable[[dict[str, list[Entry]]], dict[str, list[Entry]]],
        iteration_limit: int | None = None,
    ):
        super().__init__(graph, inputs)
        self._persist_attrs = ("states", "emitted")
        self.persist_signature = lambda: (  # type: ignore[method-assign]
            f"IterateNode/{input_names}/{iterated_names}"
            f"/{output_names}/{iteration_limit}"
        )
        self.input_names = input_names
        self.iterated_names = iterated_names
        self.output_names = output_names
        self.step_fn = step_fn
        self.iteration_limit = iteration_limit
        self.states = {name: KeyedState() for name in input_names}
        self.emitted: dict[str, dict[Key, tuple]] = {name: {} for name in output_names}
        self.out_nodes: dict[str, InputNode] = {}

    def set_output_node(self, name: str, node: InputNode) -> None:
        self.out_nodes[name] = node

    def finish_time(self, time: int) -> None:
        any_change = False
        for i, name in enumerate(self.input_names):
            batch = self.take_input(i)
            if batch:
                any_change = True
                self.states[name].update(batch)
        if not any_change:
            return
        cur = {name: self.states[name].as_entries() for name in self.input_names}
        n = 0
        while True:
            outs = self.step_fn(cur)
            n += 1
            changed = False
            for name in self.iterated_names:
                if name in outs and _collections_differ(cur[name], outs[name]):
                    changed = True
                cur[name] = outs.get(name, cur[name])
            if not changed:
                break
            if self.iteration_limit is not None and n >= self.iteration_limit:
                break
        for name in self.output_names:
            result = outs.get(name, cur.get(name, []))
            new_state: dict[Key, tuple] = {}
            for key, row, diff in consolidate(result):
                if diff > 0:
                    new_state[key] = row
            old_state = self.emitted[name]
            delta: list[Entry] = []
            for key, row in old_state.items():
                nrow = new_state.get(key)
                if nrow is None or freeze_row(nrow) != freeze_row(row):
                    delta.append((key, row, -1))
            for key, row in new_state.items():
                orow = old_state.get(key)
                if orow is None or freeze_row(orow) != freeze_row(row):
                    delta.append((key, row, 1))
            self.emitted[name] = new_state
            out_node = self.out_nodes.get(name)
            if out_node is not None and delta:
                out_node.push(delta)
                # downstream of out_node runs later in topo order within
                # this same wave because out_node was created after self
                out_node.finish_time(time)


def _collections_differ(a: list[Entry], b: list[Entry]) -> bool:
    def norm(entries: list[Entry]) -> set:
        return {
            (key.value, freeze_row(row), diff) for key, row, diff in consolidate(entries)
        }

    return norm(a) != norm(b)


class AsyncApplyNode(Node):
    """Async UDF application (reference: async_apply_table dataflow.rs:1442,
    MapWithConsistentDeletions operators.rs:308).

    Insertions run the (async) function — concurrently within a wave via an
    event loop; results are memoized per key so retractions retract exactly
    the value the insertion produced, even for non-deterministic functions.
    """

    def __init__(
        self,
        graph: Graph,
        inp: Node,
        fn: Callable[[Key, tuple], Any],
        is_async: bool,
        deterministic: bool = False,
    ):
        super().__init__(graph, [inp])
        self._persist_attrs = ("memo",)
        self.fn = fn
        self.is_async = is_async
        self.deterministic = deterministic
        self.memo: dict[tuple, Any] = {}

    def finish_time(self, time: int) -> None:
        entries = self.take_input()
        if not entries:
            return
        insertions = [(k, r) for k, r, d in entries if d > 0]
        results: dict[tuple, Any] = {}
        if insertions:
            if self.is_async:
                results = _run_async_batch(self.fn, insertions, self.graph)
            else:
                for k, r in insertions:
                    try:
                        results[(k.value, freeze_row(r))] = self.fn(k, r)
                    except Exception as e:  # noqa: BLE001
                        self.graph.log_error(f"apply: {type(e).__name__}: {e}")
                        results[(k.value, freeze_row(r))] = ERROR
        out: list[Entry] = []
        for key, row, diff in entries:
            token = (key.value, freeze_row(row))
            if diff > 0:
                value = results.get(token, self.memo.get(token, ERROR))
                if not self.deterministic:
                    self.memo[token] = value
            else:
                if token in self.memo:
                    value = self.memo.pop(token)
                elif token in results:
                    value = results[token]
                elif self.deterministic:
                    # recompute for retraction — allowed for deterministic fns
                    try:
                        value = self.fn(key, row)
                    except Exception as e:  # noqa: BLE001
                        self.graph.log_error(f"apply: {type(e).__name__}: {e}")
                        value = ERROR
                else:
                    value = ERROR
            out.append((key, row + (value,), diff))
        self.emit(time, consolidate(out))


_async_loop: asyncio.AbstractEventLoop | None = None
_async_loop_lock = threading.Lock()


def _get_async_loop() -> asyncio.AbstractEventLoop:
    """Dedicated event-loop thread (reference: graph_runner/async_utils.py)."""
    global _async_loop
    with _async_loop_lock:
        if _async_loop is None or _async_loop.is_closed():
            loop = asyncio.new_event_loop()

            def run() -> None:
                asyncio.set_event_loop(loop)
                loop.run_forever()

            threading.Thread(target=run, daemon=True, name="pw-async-loop").start()
            _async_loop = loop
    return _async_loop


def _run_async_batch(
    fn: Callable, insertions: list[tuple[Key, tuple]], graph: Graph
) -> dict[tuple, Any]:
    loop = _get_async_loop()

    async def one(k: Key, r: tuple) -> Any:
        try:
            res = fn(k, r)
            if asyncio.iscoroutine(res):
                res = await res
            return res
        except Exception as e:  # noqa: BLE001
            graph.log_error(f"async apply: {type(e).__name__}: {e}")
            return ERROR

    async def batch() -> list[Any]:
        return await asyncio.gather(*[one(k, r) for k, r in insertions])

    fut = asyncio.run_coroutine_threadsafe(batch(), loop)
    values = fut.result()
    return {
        (k.value, freeze_row(r)): v for (k, r), v in zip(insertions, values)
    }


class OutputNode(Node):
    """Sink: formats consolidated batches and hands them to a writer callback
    with retries (reference: output_table dataflow.rs:3542, OUTPUT_RETRIES=5)."""

    RETRIES = 5

    def __init__(
        self,
        graph: Graph,
        inp: Node,
        write_batch: Callable[[int, list[Entry]], None],
        flush: Callable[[], None] | None = None,
        close: Callable[[], None] | None = None,
    ):
        super().__init__(graph, [inp])
        self.write_batch = write_batch
        self.flush = flush
        self.close = close
        self._closed = False

    def finish_time(self, time: int) -> None:
        entries = self.take_input()
        if not entries:
            return
        batch = consolidate(entries)
        last_err: Exception | None = None
        for _attempt in range(self.RETRIES):
            try:
                self.write_batch(time, batch)
                if self.flush is not None:
                    self.flush()
                return
            except Exception as e:  # noqa: BLE001
                last_err = e
                _time.sleep(0.01)
        self.graph.log_error(f"output failed after {self.RETRIES} retries: {last_err}")

    def on_end(self, time: int) -> None:
        if not self._closed and self.close is not None:
            self._closed = True
            self.close()
