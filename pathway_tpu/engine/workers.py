"""Multi-worker execution: sharded operators + the wave-boundary exchange.

Reference parity: the reference runs N timely workers, each building the
same dataflow, with records hash-exchanged between workers on every
stateful operator's key (docs 10.worker-architecture.md:37-43,
src/engine/dataflow/shard.rs `Shard` impls; the exchange pact comes from
vendored timely). Here the same model is expressed per-operator: a
`ShardedNode` owns N replicas ("workers") of a stateful node, each holding
the shard of that node's state for the keys routed to it. At every wave
boundary the node's input batches are exchanged — partitioned by the
operator's shard key (record key for keyed nodes, join key for joins,
group key for reductions) — and the replicas run concurrently on the
worker pool. Worker-count invariance holds because routing partitions
exactly along each operator's state key: every group/jk/key sees all its
entries in one replica, in arrival order.

Threads, not processes, execute the replicas (PATHWAY_THREADS=N): pure
Python sections serialize on the GIL, but the native kernel hot paths
(zs_agg groupby aggregation, tokenizers — ctypes calls release the GIL)
and any numeric-plane JAX dispatches genuinely parallelize. The
TPU-mesh exchange primitive for numeric columns is
`pathway_tpu.parallel.exchange` (an `all_to_all` over ICI); this module is
the host-side control-plane equivalent for arbitrary Python rows.
"""

from __future__ import annotations

import hashlib
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Sequence

from pathway_tpu.engine.core import Entry, Graph, InputNode, Node
from pathway_tpu.engine import morsel as _morsel
from pathway_tpu.analysis import lockgraph as _lockgraph

# Route functions map (key, row) -> an int or hashable token; the shard is
# token % n_shards (ints, e.g. Key.value) or hash(token) % n_shards.
RouteFn = Callable[[Any, tuple], Any]

_POOL: ThreadPoolExecutor | None = None
_POOL_LOCK = _lockgraph.register_lock("workers.pool", threading.Lock())


def worker_threads() -> int:
    """PATHWAY_THREADS, read per-session so tests can flip it in-process."""
    try:
        return max(1, int(os.environ.get("PATHWAY_THREADS", "1")))
    except ValueError:
        return 1


def _pool() -> ThreadPoolExecutor:
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = ThreadPoolExecutor(
                max_workers=max(4, (os.cpu_count() or 1)),
                thread_name_prefix="pw-worker",
            )
    return _POOL


class _FinishTask:
    """One replica-wave morsel: ``replica.finish_time(t)`` as a repeat-
    free callable (a bound closure per replica would pin `time` fine
    too; a named task keeps steal traces readable)."""

    __slots__ = ("replica", "time")

    def __init__(self, replica: Node, time: int):
        self.replica = replica
        self.time = time

    def __call__(self) -> None:
        self.replica.finish_time(self.time)


class _Collector:
    """Duck-typed downstream sink capturing one replica's emits (entry
    lists or NativeBatch segments, kept as segments)."""

    __slots__ = ("segments",)

    def __init__(self) -> None:
        self.segments: list = []

    def accept(self, input_idx: int, entries) -> None:
        if type(entries) is list:
            if entries:
                self.segments.append(entries)
        else:
            self.segments.append(entries)

    def take(self) -> list:
        out, self.segments = self.segments, []
        return out


def _canon(v: Any) -> Any:
    """Normalize a shard token so routing agrees with Python equality:
    1 == 1.0 == True must route identically (a group key mixing int and
    float forms is ONE group to the operator's dict state)."""
    if isinstance(v, tuple):
        return tuple(_canon(x) for x in v)
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, float) and v.is_integer():
        return int(v)  # also folds -0.0 -> 0
    return v


def native_shards(batch: Any, plan: Any, n: int):
    """Shard array for a NativeBatch under a route plan (('key',) |
    ('group', cols) | ('ptr_col', col)), or None when the plan can't
    judge the batch. The SINGLE dispatch point for thread- AND
    process-level native routing — both must agree byte-for-byte with
    _shard_of."""
    if plan is None:
        return None
    from pathway_tpu.engine.native import dataplane as dp

    if plan[0] == "key":
        return dp.route_key(batch.key_lo, batch.key_hi, n)
    if plan[0] == "ptr_col":
        # route by the pointer column's key128 (ix colocation); batches
        # holding a non-Key pointer fall back to the object route
        res = dp.decode_key_col(batch.tab, batch.token, plan[1])
        if res is None or (res[2] != 0).any():
            return None
        return dp.route_key(res[0], res[1], n)
    res = dp.project_group(batch.tab, batch.token, plan[1], n_shards=n)
    return None if res is None else res[1]


def _shard_of(token: Any, n: int) -> int:
    """Process-stable shard assignment. Python's hash() is salted per
    process (PYTHONHASHSEED), which would route a group to a different
    worker after restart — operator snapshots store per-shard state, so
    routing must be a pure function of the token's content.

    Non-int tokens hash via blake2b of the token's canonical value
    serialization — the same bytes the native data plane computes in C++
    (dataplane.cpp dp_project_group), so a batch routed natively and a
    row routed here always land on the same shard."""
    if isinstance(token, bool):
        return int(token) % n
    if isinstance(token, int):
        return token % n
    from pathway_tpu.internals.keys import _serialize_value

    out: list[bytes] = []
    try:
        _serialize_value(_canon(token), out)
        payload = b"".join(out)
    except Exception:  # noqa: BLE001 — exotic token: stable repr fallback
        payload = repr(_canon(token)).encode()
    digest = hashlib.blake2b(payload, digest_size=16).digest()
    return int.from_bytes(digest[:8], "little") % n


class ShardedNode(Node):
    """N replicas of a stateful node, each owning one key-range shard.

    `factory(graph, input_nodes) -> Node` builds one replica; replicas are
    constructed against a private throwaway graph (never stepped) with
    dummy inputs, and their emits are captured by per-replica collectors.
    `route_fns[i]` gives the shard key for entries arriving on input i.
    """

    def __init__(
        self,
        graph: Graph,
        inputs: Sequence[Node],
        factory: Callable[[Graph, list[Node]], Node],
        route_fns: Sequence[RouteFn],
        n_shards: int,
        native_routes: Sequence[Any] | None = None,
    ):
        super().__init__(graph, inputs)
        assert len(route_fns) == len(inputs)
        self.route_fns = list(route_fns)
        # per input: None, ('key',) — record-key routing — or
        # ('group', [col_idx...]) — group-key routing; lets NativeBatch
        # segments split across replicas without materializing (the C
        # routing is byte-identical to _shard_of, see dataplane.cpp)
        self.native_routes = list(native_routes or [None] * len(inputs))
        self.n_shards = n_shards
        self.replicas: list[Node] = []
        self.collectors: list[_Collector] = []
        for _ in range(n_shards):
            shadow = Graph()
            shadow.terminate_on_error = graph.terminate_on_error
            dummies = [InputNode(shadow) for _ in inputs]
            replica = factory(shadow, list(dummies))
            collector = _Collector()
            replica.downstream = [(collector, 0)]  # type: ignore[list-item]
            self.replicas.append(replica)
            self.collectors.append(collector)

    # -------------------------------------------------------------- exchange

    def _exchange(self, input_idx: int, entries: list[Entry]) -> list[int]:
        """Partition one input batch across replicas by the shard key.

        Returns the list of replica ids that received data. Entries whose
        route function fails go to shard 0 (the replica re-evaluates the
        same expression and logs the error through the normal path).
        """
        n = self.n_shards
        route = self.route_fns[input_idx]
        # ICI data plane: vector-carrying rows move their numeric payload
        # over the device mesh (PATHWAY_DEVICE_EXCHANGE=1); control
        # metadata stays host-side. Routing is the same _shard_of rule.
        from pathway_tpu.parallel.device_exchange import engine_exchanger

        dev = engine_exchanger()
        if dev is not None:

            def shard_of_entry(key: Any, row: tuple) -> int:
                return _shard_of(route(key, row), n)

            routed = dev.try_exchange(entries, shard_of_entry, n)
            if routed is not None:
                touched = []
                for s, ents in enumerate(routed):
                    if ents:
                        self.replicas[s].accept(input_idx, ents)
                        touched.append(s)
                return touched
        buckets: list[list[Entry]] = [[] for _ in range(n)]
        for entry in entries:
            key, row, _diff = entry
            try:
                s = _shard_of(route(key, row), n)
            except Exception:  # noqa: BLE001 - replica will log it
                s = 0
            buckets[s].append(entry)
        touched = []
        for s in range(n):
            if buckets[s]:
                self.replicas[s].accept(input_idx, buckets[s])
                touched.append(s)
        return touched

    def _exchange_native(self, input_idx: int, batch: Any) -> list[int]:
        """Split a NativeBatch across replicas without materializing.
        Falls back to the object plane when this input has no native
        route plan or the C routing rejects the batch."""
        plan = self.native_routes[input_idx]
        if plan is not None:
            import numpy as np

            shards = native_shards(batch, plan, self.n_shards)
            if shards is not None:
                # sharded column plane: the batch's scalar columns cross
                # as ONE device collective along the host-exact routing
                # (PATHWAY_DEVICE_EXCHANGE; row order identical to the
                # select path below)
                from pathway_tpu.parallel.column_plane import (
                    engine_column_exchanger,
                )

                ce = engine_column_exchanger()
                if ce is not None:
                    subs = ce.split_batch(batch, shards, self.n_shards)
                    if subs is not None:
                        touched = []
                        for s, sub in enumerate(subs):
                            if len(sub):
                                self.replicas[s].accept(input_idx, sub)
                                touched.append(s)
                        return touched
                touched = []
                for s in np.unique(shards):
                    sub = batch.select(shards == s)
                    self.replicas[int(s)].accept(input_idx, sub)
                    touched.append(int(s))
                return touched
        return self._exchange(input_idx, batch.materialize())

    def finish_time(self, time: int) -> None:
        active: set[int] = set()
        for i in range(len(self.inputs)):
            batches, entries = self.take_segments(i)
            for b in batches:
                active.update(self._exchange_native(i, b))
            if entries:
                active.update(self._exchange(i, entries))
        if not active:
            return
        ordered = sorted(active)
        if len(ordered) == 1:
            self.replicas[ordered[0]].finish_time(time)
        elif _morsel.enabled_cached():
            # per-replica morsel queues drained with work stealing: the
            # frontier/static pump no longer pins a replica to the pool
            # thread that happened to receive its future — idle threads
            # drain a straggler's queue instead of blocking the barrier
            # (emission stays on this thread, in replica order, below)
            _morsel.run_stealing(
                [[_FinishTask(self.replicas[s], time)] for s in ordered]
            )
        else:
            futures = [
                _pool().submit(self.replicas[s].finish_time, time)
                for s in ordered
            ]
            for f in futures:
                f.result()  # wave barrier; re-raises replica errors
        self._emit_collected(time, ordered)

    def _emit_collected(self, time: int, shards: Iterable[int]) -> None:
        out: list[Entry] = []
        for s in shards:
            for seg in self.collectors[s].take():
                if type(seg) is list:
                    out.extend(seg)
                else:
                    if out:
                        self.emit(time, out)
                        out = []
                    self.emit(time, seg)
        if out:
            self.emit(time, out)

    def on_end(self, time: int) -> None:
        # Graph.end runs on_end then finish_time per node in topo order, so
        # emitting here still reaches downstream buffers before they close.
        # (No sharded node type currently implements on_end; this keeps the
        # wrapper correct for any future one.)
        for s in range(self.n_shards):
            self.replicas[s].on_end(time)
        self._emit_collected(time, range(self.n_shards))

    # ----------------------------------------------- operator snapshots

    def persist_signature(self) -> str:
        # worker-count independent: a snapshot taken at PATHWAY_THREADS=N
        # restores at M by re-partitioning along the shard key (the
        # checkpoint manager adapts the state before restore_state runs)
        return self.replicas[0].persist_signature()

    def persist_state(self) -> dict | None:
        shards = [r.persist_state() for r in self.replicas]
        if all(s is None for s in shards):
            return None
        return {"n_shards": self.n_shards, "shards": shards}

    def restore_state(self, state: dict) -> None:
        if state.get("n_shards") != self.n_shards:
            # the checkpoint manager rescales before applying; reaching
            # here means a caller skipped adaptation
            raise RuntimeError(
                f"snapshot has {state.get('n_shards')} worker shards, "
                f"session has {self.n_shards} (rescale adaptation missing)"
            )
        for replica, st in zip(self.replicas, state["shards"]):
            if st is not None:
                replica.restore_state(st)

    def rescale_state(self, state: dict) -> dict:
        """Re-partition a snapshot taken at a different worker count onto
        this node's shards (raises RescaleUnsupported when the inner node
        type cannot express its routing)."""
        template = self.replicas[0]
        shards = (
            [s for s in state["shards"] if s is not None]
            if "n_shards" in state
            else [state]
        )
        merged = template.merge_shard_states(shards)
        n = self.n_shards
        parts = template.split_shard_state(
            merged, n, lambda tok: _shard_of(tok, n)
        )
        return {"n_shards": n, "shards": parts}

    # Aggregate observability over replicas (rows_in counted at exchange).
    @property
    def shard_rows(self) -> list[tuple[int, int]]:
        return [(r.rows_in, r.rows_out) for r in self.replicas]


def adapt_shard_state(node: Any, st: dict) -> dict:
    """Re-shape a snapshot for the node's current worker layout: rescales
    ShardedNode states across PATHWAY_THREADS changes, merges multi-shard
    snapshots into unsharded sessions, and recurses into nodes embedding a
    sub-graph (IterateNode) whose states carry per-node `sub` lists.
    Raises RescaleUnsupported when an operator cannot re-partition — the
    checkpoint manager catches it in its read phase and falls back to
    journal replay before any node has mutated."""
    if isinstance(node, ShardedNode):
        if st.get("n_shards") == node.n_shards:
            return st
        return node.rescale_state(st)
    sub_graph = getattr(node, "sub_graph", None)
    if sub_graph is not None and isinstance(st, dict) and "sub" in st:
        st = dict(st)
        st["sub"] = [
            None if s is None else adapt_shard_state(n2, s)
            for n2, s in zip(sub_graph.nodes, st["sub"])
        ]
        return st
    if "n_shards" in st and "shards" in st:
        # snapshot from a multi-worker run restoring into an unsharded
        # session: merge the shard states
        return node.merge_shard_states(
            [s for s in st["shards"] if s is not None]
        )
    return st


class ProcessExchangeNode(Node):
    """Inter-process exchange boundary: one per stateful-operator input.

    The wave's batch partitions by the operator's shard key across
    processes (bucket p goes to process p over the TCP mesh); the
    downstream operator (optionally thread-sharded on top) owns its
    shard exclusively: every key lives on exactly one process.

    Two delivery protocols share the split logic:

      * frontier mode (default, ``Runtime.run_mesh``): ``finish_time``
        only SENDS — buckets cross the wire tagged with their
        timestamp, and the receiving pump injects them below the peer's
        replica of this node (``inject_remote``) once its input
        frontier passes that time. No blocking, no per-wave barrier: a
        slow peer delays only the operators consuming its wire.
      * lockstep BSP (deprecated fallback, ``run_lockstep``): the node
        BLOCKS until every peer's bucket for this (node, round)
        arrives — the old global wave barrier.

    `route` maps (key, row) -> shard token; None routes everything to
    process 0 (operators with global state: buffers, gradual broadcast,
    external indexes, iterate).
    """

    def __init__(
        self,
        graph: Graph,
        inp: Node,
        mesh: Any,
        route: RouteFn | None,
        wire_id: int,
        native_route: Any = None,
    ):
        super().__init__(graph, [inp])
        self.mesh = mesh
        self.route = route
        # plan-node label: exchange boundaries are not spec-built, so the
        # wire id is their identity in metrics/monitors
        self.label = f"exchange:w{wire_id}"
        # token-resident route plan (('key',) | ('group', cols)): native
        # batches split in C and cross the mesh in wire form — unique-row
        # blob + flat arrays — instead of per-row pickled tuples
        self.native_route = native_route
        # wire identity: must match across processes (same program, same
        # creation order) and be unique across sessions sharing one
        # process-wide mesh — the lowering allocates it
        self.wire_id = wire_id
        self.round = 0
        # frontier protocol switches (set by Runtime.run_mesh)
        self.frontier_mode = False
        self.end_barrier = False

    def persist_signature(self) -> str:
        return f"ProcessExchange/{self.mesh.n}/{int(self.route is None)}"

    def persist_state(self) -> dict:
        return {"round": self.round}

    def restore_state(self, st: dict) -> None:
        self.round = st["round"]

    def _split_native(self, batch: Any, n: int):
        """Per-process sub-batches of a NativeBatch, or None (no plan /
        plan rejected the batch -> object-plane fallback)."""
        shards = native_shards(batch, self.native_route, n)
        if shards is None:
            return None
        # device column plane: the wave's bulk columns split through the
        # mesh collective (host routing, identical order); buckets still
        # leave this process in wire form — dense ids + unique-row blob
        # as out-of-band buffers, never per-row pickles
        from pathway_tpu.parallel.column_plane import engine_column_exchanger

        ce = engine_column_exchanger()
        if ce is not None:
            subs = ce.split_batch(batch, shards, n)
            if subs is not None:
                return subs
        return [batch.select(shards == p) for p in range(n)]

    def _split_wave(self, batches, entries):
        """Partition one drained wave into per-process (entry, native)
        buckets along the operator's shard key."""
        n = self.mesh.n
        buckets: list[list[Entry]] = [[] for _ in range(n)]
        nb_buckets: list[list] = [[] for _ in range(n)]
        for b in batches:
            subs = self._split_native(b, n) if self.route is not None else None
            if subs is None:
                if self.route is None:
                    nb_buckets[0].append(b)
                else:
                    entries = b.materialize() + entries
                continue
            for p, sub in enumerate(subs):
                if len(sub):
                    nb_buckets[p].append(sub)
        if self.route is None:
            buckets[0].extend(entries)
        else:
            route = self.route
            shard_of = _shard_of
            # route tokens repeat heavily within a wave (group keys):
            # memoize token -> shard so the blake2b serialization runs
            # once per DISTINCT token, not once per row. The cache key
            # includes the token's TYPE: _shard_of routes a bare int via
            # the % fast path but an equal float via the blake path, and
            # dict equality (5 == 5.0) must not fold them — routing has
            # to stay a pure function of the token, never of which form
            # happened to arrive first in the wave.
            shard_cache: dict = {}
            route_errors = 0
            first_error: BaseException | None = None
            for entry in entries:
                key, row, _diff = entry
                try:
                    tok = route(key, row)
                except Exception as e:  # noqa: BLE001 — owner re-evaluates
                    # + logs through its normal path; shard 0 is the
                    # deterministic overflow bucket
                    route_errors += 1
                    if first_error is None:
                        first_error = e
                    buckets[0].append(entry)
                    continue
                try:
                    ck = (tok.__class__, tok)
                    p = shard_cache.get(ck)
                    if p is None:
                        p = shard_cache[ck] = shard_of(tok, n)
                except TypeError:
                    # unhashable token: no memo, route it directly
                    # (_shard_of's stable-repr fallback still applies)
                    p = shard_of(tok, n)
                buckets[p].append(entry)
            if route_errors:
                import logging

                logging.getLogger("pathway_tpu.workers").warning(
                    "exchange wire %d (node %d): %d row(s) failed shard "
                    "routing, sent to process 0 (first error: %s: %s)",
                    self.wire_id, self.node_id, route_errors,
                    type(first_error).__name__, first_error,
                )
        return buckets, nb_buckets

    def inject_remote(self, time: int, payload: Any) -> None:
        """Deliver a peer's bucket below this node (frontier mode): the
        pump calls this once the wire's watermark admits `time`."""
        if isinstance(payload, tuple):
            ents, wires = payload
            if wires:
                from pathway_tpu.engine.native import dataplane as dp

                for w in wires:
                    self.emit(time, dp.NativeBatch.from_wire(w))
            if ents:
                self.emit(time, ents)
        elif payload:  # legacy plain-entry frame
            self.emit(time, payload)

    def finish_time(self, time: int) -> None:
        batches, entries = self.take_segments()
        if self.frontier_mode and not self.end_barrier:
            # frontier protocol: no blocking. Peer buckets cross the
            # mesh tagged with their time and are injected below the
            # peer's replica of this node once its operators' frontiers
            # admit them; the local bucket emits downstream directly —
            # the per-node scheduler stashes it at any operator whose
            # frontier (which includes this wire's peers) still lags.
            if not batches and not entries:
                return
            buckets, nb_buckets = self._split_wave(batches, entries)
            me = self.mesh.process_id
            for p in self.mesh.peers:
                if buckets[p] or nb_buckets[p]:
                    wires = [b.to_wire() for b in nb_buckets[p]]
                    self.mesh.send_bucket(
                        p, self.wire_id, time, (buckets[p], wires)
                    )
            for b in nb_buckets[me]:
                self.emit(time, b)
            if buckets[me]:
                self.emit(time, buckets[me])
            return
        buckets, nb_buckets = self._split_wave(batches, entries)
        me = self.mesh.process_id
        # end barrier (frontier mode) reuses the blocking exchange once,
        # at the negotiated end time every process steps together
        rnd = ("end", time) if self.end_barrier else self.round
        for p in self.mesh.peers:
            wires = [b.to_wire() for b in nb_buckets[p]]
            self.mesh.send_bucket(
                p, self.wire_id, rnd, (buckets[p], wires)
            )
        merged = list(buckets[me])
        local_batches = list(nb_buckets[me])
        for p in self.mesh.peers:
            payload = self.mesh.recv_bucket(p, self.wire_id, rnd)
            if isinstance(payload, tuple):
                ents, wires = payload
                merged.extend(ents)
                if wires:
                    from pathway_tpu.engine.native import dataplane as dp

                    local_batches.extend(
                        dp.NativeBatch.from_wire(w) for w in wires
                    )
            else:  # legacy plain-entry frame
                merged.extend(payload)
        self.round += 1
        for b in local_batches:
            self.emit(time, b)
        if merged:
            self.emit(time, merged)
