"""REST servers exposing RAG services.

Reference parity: xpacks/llm/servers.py — `BaseRestServer` (:16) registering
(route, schema, handler) over `rest_connector`, `QARestServer` (:92),
`QASummaryRestServer` (:140), `DocumentStoreServer` (:193),
`serve_callable` (:227).
"""

from __future__ import annotations

import threading
from typing import Any, Callable

import pathway_tpu as pw
from pathway_tpu.internals.table import Table


class BaseRestServer:
    def __init__(self, host: str, port: int, gateway: Any = None, **kwargs: Any):
        from pathway_tpu.io.http import PathwayWebserver

        self.host = host
        self.port = port
        self.webserver = PathwayWebserver(host=host, port=port)
        # one ServingGateway fronts every route of this server
        # (admission control + watermark backpressure, docs/serving.md §6)
        self.gateway = gateway

    def serve(
        self,
        route: str,
        schema: Any,
        handler: Callable[[Table], Table],
        **kwargs: Any,
    ) -> None:
        queries, writer = pw.io.http.rest_connector(
            webserver=self.webserver,
            route=route,
            schema=schema,
            delete_completed_queries=kwargs.pop(
                "delete_completed_queries", False
            ),
            gateway=self.gateway,
        )
        writer(handler(queries))

    def run(
        self,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend: Any = None,
        **kwargs: Any,
    ):
        """Start serving (runs pw.run; `threaded=True` returns the thread).

        `with_cache`+`cache_backend` wire UDF caching through the
        persistence layer in cache-only mode — no input journaling /
        replay attaches to a serving process (reference: servers.py run
        with_cache, default Backend.filesystem('./Cache'))."""
        if with_cache:
            if cache_backend is None:
                cache_backend = pw.persistence.Backend.filesystem("./Cache")
            kwargs.setdefault(
                "persistence_config",
                pw.persistence.Config.udf_caching(cache_backend),
            )
        if threaded:
            t = threading.Thread(target=pw.run, kwargs=kwargs, daemon=True)
            t.start()
            return t
        return pw.run(**kwargs)


class QARestServer(BaseRestServer):
    """Routes of the QA pipeline (reference: servers.py:92):
    /v1/retrieve, /v1/statistics, /v1/pw_list_documents, /v1/pw_ai_answer,
    /v2/answer, /v2/list_documents."""

    def __init__(self, host: str, port: int, rag_question_answerer: Any, **kwargs: Any):
        super().__init__(host, port, **kwargs)
        self.serve(
            "/v1/retrieve",
            rag_question_answerer.RetrieveQuerySchema,
            rag_question_answerer.retrieve,
        )
        self.serve(
            "/v1/statistics",
            rag_question_answerer.StatisticsQuerySchema,
            rag_question_answerer.statistics,
        )
        self.serve(
            "/v1/pw_list_documents",
            rag_question_answerer.InputsQuerySchema,
            rag_question_answerer.list_documents,
        )
        self.serve(
            "/v1/pw_ai_answer",
            rag_question_answerer.AnswerQuerySchema,
            rag_question_answerer.answer_query,
        )
        self.serve(
            "/v2/answer",
            rag_question_answerer.AnswerQuerySchema,
            rag_question_answerer.answer_query,
        )
        self.serve(
            "/v2/list_documents",
            rag_question_answerer.InputsQuerySchema,
            rag_question_answerer.list_documents,
        )


class QASummaryRestServer(QARestServer):
    """Adds /v1/pw_ai_summary (reference: servers.py:140)."""

    def __init__(self, host: str, port: int, rag_question_answerer: Any, **kwargs: Any):
        super().__init__(host, port, rag_question_answerer, **kwargs)
        self.serve(
            "/v1/pw_ai_summary",
            rag_question_answerer.SummarizeQuerySchema,
            rag_question_answerer.summarize_query,
        )
        self.serve(
            "/v2/summarize",
            rag_question_answerer.SummarizeQuerySchema,
            rag_question_answerer.summarize_query,
        )


class DocumentStoreServer(BaseRestServer):
    """Standalone DocumentStore REST surface (reference: servers.py:193):
    /v1/retrieve, /v1/statistics, /v1/inputs."""

    def __init__(self, host: str, port: int, document_store: Any, **kwargs: Any):
        super().__init__(host, port, **kwargs)
        self.serve(
            "/v1/retrieve",
            document_store.RetrieveQuerySchema,
            document_store.retrieve_query,
        )
        self.serve(
            "/v1/statistics",
            document_store.StatisticsQuerySchema,
            document_store.statistics_query,
        )
        self.serve(
            "/v1/inputs",
            document_store.InputsQuerySchema,
            document_store.inputs_query,
        )


def serve_callable(
    route: str,
    schema: Any,
    host: str = "0.0.0.0",
    port: int = 8000,
    **rest_kwargs: Any,
):
    """Decorator: expose an async callable as a REST endpoint through the
    dataflow (reference: servers.py:227)."""

    def decorator(callable_fn: Callable) -> Callable:
        server = BaseRestServer(host, port)

        def handler(queries: Table) -> Table:
            args = [queries[n] for n in queries._column_names()]
            return queries.select(result=pw.apply_async(callable_fn, *args))

        server.serve(route, schema, handler)
        callable_fn._pw_server = server  # type: ignore[attr-defined]
        return callable_fn

    return decorator
