"""pw.io.sqlite — real connector over the stdlib sqlite3
(reference: SqliteReader src/connectors/data_storage.rs:1415)."""

from __future__ import annotations

import sqlite3
import time as _time
from typing import Any

from pathway_tpu.engine.runtime import InputSession, ThreadConnector
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals import universe as univ
from pathway_tpu.internals.keys import key_for_values, sequential_key
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import OpSpec, Table


def read(
    path: str,
    table_name: str,
    schema: Any,
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int = 1000,
    **kwargs: Any,
) -> Table:
    names = list(schema.__columns__)
    pk = schema.primary_key_columns()
    cols = ", ".join(names)

    if mode == "static":
        conn = sqlite3.connect(path)
        try:
            rows = [tuple(r) for r in conn.execute(f"SELECT {cols} FROM {table_name}")]  # noqa: S608
        finally:
            conn.close()
        keys = None
        if pk:
            keys = [key_for_values(*[r[names.index(c)] for c in pk]) for r in rows]
        return Table.from_rows(schema, rows, keys=keys)

    def factory(session: InputSession) -> ThreadConnector:
        def run_fn(sess: InputSession) -> None:
            conn = sqlite3.connect(path)
            last_rowid = 0
            try:
                while True:
                    cur = conn.execute(
                        f"SELECT rowid, {cols} FROM {table_name} WHERE rowid > ?",  # noqa: S608
                        (last_rowid,),
                    )
                    for rec in cur:
                        last_rowid = max(last_rowid, rec[0])
                        row = tuple(rec[1:])
                        key = (
                            key_for_values(*[row[names.index(c)] for c in pk])
                            if pk
                            else sequential_key()
                        )
                        sess.insert(key, row)
                    _time.sleep(autocommit_duration_ms / 1000.0)
            finally:
                conn.close()

        return ThreadConnector(f"sqlite:{path}", session, run_fn)

    spec = OpSpec("connector", [], factory=factory, upsert=pk is not None)
    return Table(spec, schema, univ.Universe())


def write(table: Table, path: str, table_name: str, **kwargs: Any) -> None:
    names = table._column_names()
    placeholders = ", ".join("?" for _ in range(len(names) + 2))
    collist = ", ".join([*names, "time", "diff"])

    state: dict[str, Any] = {"conn": None}

    def ensure() -> sqlite3.Connection:
        if state["conn"] is None:
            conn = sqlite3.connect(path, check_same_thread=False)
            coldefs = ", ".join([f"{n}" for n in names] + ["time INTEGER", "diff INTEGER"])
            conn.execute(f"CREATE TABLE IF NOT EXISTS {table_name} ({coldefs})")
            state["conn"] = conn
        return state["conn"]

    def write_batch(time: int, entries: list) -> None:
        conn = ensure()
        conn.executemany(
            f"INSERT INTO {table_name} ({collist}) VALUES ({placeholders})",  # noqa: S608
            [tuple(row) + (time, diff) for _k, row, diff in entries],
        )
        conn.commit()

    G.add_sink("output", table, write_batch=write_batch)
