"""AsyncTransformer: fully-decoupled async row->row processing.

Reference: stdlib/utils/async_transformer.py:282 — results loop back
through a Python connector, arriving at fresh engine timestamps so slow
async work never backpressures the upstream dataflow, with:
  * retraction handling — a retracted input row retracts its result;
  * `.successful` / `.failed` result tables (failures keyed by the input
    row, output columns None);
  * `with_options(capacity=…, retry_strategy=…, cache_strategy=…)` using
    the shared UDF machinery (internals/udfs.py);
  * open()/close() lifecycle hooks around the worker.
"""

from __future__ import annotations

import asyncio
import logging
import queue
import threading
from typing import Any

from pathway_tpu.engine.runtime import Connector, InputSession, _get_async_loop
from pathway_tpu.internals import universe as univ
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.analysis import lockgraph as _lockgraph
from pathway_tpu.internals.table import OpSpec, Table
from pathway_tpu.io._retry import log_degradation

logger = logging.getLogger("pathway_tpu.stdlib.async_transformer")


class AsyncTransformer:
    """Subclass and implement `async def invoke(self, **kwargs) -> dict`.

    `output_schema` declares the result columns. `.successful` is the
    result table (keyed by the input row's key); `.failed` holds the rows
    whose invocation raised (after retries), with all output columns None.
    """

    output_schema: Any = None

    def __init__(self, input_table: Table, *, instance: Any = None, **kwargs: Any):
        assert self.output_schema is not None, "set output_schema"
        self._input_table = input_table
        self._queue: queue.Queue = queue.Queue()
        self._capacity: int | None = None
        self._retry_strategy: Any = None
        self._cached_fn: Any = None
        names = list(self.output_schema.__columns__)
        in_names = input_table._column_names()

        def on_change(key: Any, row: tuple, time: int, is_addition: bool) -> None:
            self._queue.put((key, dict(zip(in_names, row)), is_addition))

        def on_end() -> None:
            self._queue.put(None)

        G.add_sink("subscribe", input_table, on_change=on_change, on_end=on_end)

        transformer = self

        # Loopback workers: the subscribed input deltas drive async
        # invocations (bounded by `capacity`, wrapped in the retry
        # strategy); results insert into FRESH input sessions — the
        # decoupling the reference gets from its output-connector +
        # loopback pair. A side's session exists only if its table is
        # consumed by the pipeline (results for an unused side drop).
        ok_holder: dict[str, InputSession] = {}
        fail_holder: dict[str, InputSession] = {}

        def start_worker() -> None:
            loop = _get_async_loop()
            sem = (
                asyncio.Semaphore(self._capacity)
                if self._capacity
                else None
            )
            transformer.open()

            def run() -> None:
                pending: set = set()
                results: dict[Any, tuple] = {}  # key -> last emitted row
                # key -> generation: an in-flight invoke only publishes if
                # its generation is still live (a retraction or a newer
                # insert invalidates it — otherwise a slow invoke would
                # resurrect a retracted row)
                gens: dict[Any, int] = {}
                publish_lock = _lockgraph.register_lock(
                    "stdlib.async_transformer", threading.Lock()
                )
                while True:
                    item = transformer._queue.get()
                    if item is None:
                        break
                    key, row_dict, is_addition = item
                    if not is_addition:
                        with publish_lock:
                            gens.pop(key, None)
                            old = results.pop(key, None)
                            if old is not None:
                                side, out_row = old
                                sess = (ok_holder if side else fail_holder).get("s")
                                if sess is not None:
                                    sess.remove(key, out_row)
                        continue
                    with publish_lock:
                        gen = gens[key] = gens.get(key, 0) + 1

                    async def invoke_one(k=key, rd=row_dict, g=gen) -> None:
                        if sem is not None:
                            await sem.acquire()
                        try:
                            call = transformer._invoke
                            if transformer._retry_strategy is not None:
                                result = await transformer._retry_strategy.invoke(
                                    lambda: call(rd)
                                )
                            else:
                                result = await call(rd)
                            side, out_row = True, tuple(
                                result.get(n) for n in names
                            )
                        except Exception:  # noqa: BLE001 — failed side
                            side, out_row = False, tuple(None for _ in names)
                        finally:
                            if sem is not None:
                                sem.release()
                        with publish_lock:
                            if gens.get(k) != g:
                                return  # retracted/superseded while in flight
                            results[k] = (side, out_row)
                            sess = (ok_holder if side else fail_holder).get("s")
                            if sess is not None:
                                sess.insert(k, out_row)

                    fut = asyncio.run_coroutine_threadsafe(invoke_one(), loop)
                    pending.add(fut)
                    pending = {f for f in pending if not f.done()}
                for f in pending:
                    try:
                        f.result(timeout=60)
                    except Exception as e:  # noqa: BLE001 — per-row
                        # errors were already routed to the failure
                        # table inside invoke_one; this drain only
                        # absorbs teardown races, visibly
                        log_degradation(
                            logger, "async_transformer.drain", e
                        )
                transformer.close()

            t = threading.Thread(target=run, daemon=True, name="pw-async-xform")
            t.start()
            _worker_holder["t"] = t

        _worker_holder: dict[str, Any] = {}
        started = threading.Event()

        class _LoopbackConnector(Connector):
            """One per consumed side; the FIRST to start launches the
            shared worker (the other side's session may never exist if
            its table isn't used — results for it are dropped)."""

            holder: dict[str, InputSession]

            def start(self) -> None:
                self.holder["s"] = self.session
                if not started.is_set():
                    started.set()
                    start_worker()

            @property
            def done(self) -> bool:
                t = _worker_holder.get("t")
                return (
                    t is not None and not t.is_alive()
                    and not self.session._staged
                )

        class _OkConnector(_LoopbackConnector):
            holder = ok_holder

        class _FailConnector(_LoopbackConnector):
            holder = fail_holder

        ok_spec = OpSpec(
            "connector", [],
            factory=lambda s: _OkConnector("async-transformer", s),
            upsert=True,
        )
        fail_spec = OpSpec(
            "connector", [],
            factory=lambda s: _FailConnector("async-transformer-failed", s),
            upsert=True,
        )
        self._result = Table(ok_spec, self.output_schema, univ.Universe())
        self._failed = Table(fail_spec, self.output_schema, univ.Universe())

    # ------------------------------------------------------------- invoke

    async def _invoke(self, row_dict: dict) -> dict:
        if self._cached_fn is not None:
            return await self._cached_fn(**row_dict)
        return await self.invoke(**row_dict)

    async def invoke(self, **kwargs: Any) -> dict:
        raise NotImplementedError

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass

    # ------------------------------------------------------------- surface

    @property
    def successful(self) -> Table:
        return self._result

    @property
    def failed(self) -> Table:
        return self._failed

    @property
    def output_table(self) -> Table:
        return self._result

    def with_options(
        self,
        capacity: int | None = None,
        retry_strategy: Any = None,
        cache_strategy: Any = None,
    ) -> "AsyncTransformer":
        """Reference surface: bound concurrent invocations, wrap each in
        an AsyncRetryStrategy, and memoize through the given CacheStrategy
        (internals/udfs.py — InMemoryCache, DiskCache, …)."""
        if capacity is not None:
            self._capacity = capacity
        if retry_strategy is not None:
            self._retry_strategy = retry_strategy
        if cache_strategy is not None:
            async def _raw(**kwargs: Any) -> dict:
                return await self.invoke(**kwargs)

            self._cached_fn = cache_strategy.wrap(_raw)
        return self
