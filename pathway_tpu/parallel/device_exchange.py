"""Engine hook for the ICI data plane: batches whose rows carry numeric
vector columns (embeddings etc.) move those payloads across the worker
shards through the device-mesh `all_to_all` (parallel/exchange.py) instead
of the host object plane; only per-row control metadata (key, scalar
columns, diff) stays host-side.

Reference parity: SURVEY §5's TPU-native replacement for timely's TCP
exchange (external/timely-dataflow/communication/src/networking.rs) — the
bulk bytes of a shuffle ride the interconnect, the progress/control plane
stays on sockets. In a multi-host deployment each engine process drives
its slice of one global mesh and this same program spans hosts over
ICI/DCN; single-host it runs across the local (or virtual) devices, which
is what the multichip dryrun validates.

Enabled with PATHWAY_DEVICE_EXCHANGE=1 (off by default: for small host
batches the device round-trip costs more than it saves; it pays off when
vector payloads dominate, e.g. DocumentStore embedding shuffles).
"""

from __future__ import annotations

import os
from typing import Any, Callable

import numpy as np

from pathway_tpu.parallel.exchange import exchange_with_respill
from pathway_tpu.parallel.mesh import default_mesh


def enabled() -> bool:
    return os.environ.get("PATHWAY_DEVICE_EXCHANGE", "0") == "1"


class DeviceExchanger:
    """Routes the ndarray columns of an entry batch over the device mesh.

    Per batch: rows' float ndarray columns (uniform dtype/shape across the
    batch) are stacked into one [n, d] matrix and shuffled to their
    destination shard via bucketize + all_to_all with host-exact routing;
    every other column travels as control metadata. Rows are reassembled
    at the destination in deterministic (src-major, arrival) order.
    """

    MIN_ROWS = 8  # below this the dispatch overhead always dominates

    def __init__(self, mesh=None, axis: str = "data"):
        self.mesh = mesh if mesh is not None else default_mesh((axis,))
        self.axis = axis
        self.invocations = 0
        self.rows_exchanged = 0

    # ------------------------------------------------------------ detection

    @staticmethod
    def _vector_columns(row: tuple) -> list[int]:
        # float32 only: the exchange carries f32, and a float64 column
        # would come back rounded — silently different row bytes break
        # downstream retraction matching
        return [
            i
            for i, v in enumerate(row)
            if isinstance(v, np.ndarray)
            and v.dtype == np.float32
            and v.ndim >= 1
        ]

    def try_exchange(
        self,
        entries: list,
        shard_of_entry: Callable[[Any, tuple], int],
        n_shards: int,
    ) -> list[list] | None:
        """Returns per-shard entry lists, or None when the batch isn't
        eligible (no/irregular vector columns, too small, mesh mismatch).
        shard_of_entry(key, row) must be the operator's exact host
        routing rule — device routing follows it bit-for-bit."""
        if len(entries) < self.MIN_ROWS:
            return None
        if n_shards > self.mesh.shape[self.axis]:
            return None
        first_row = entries[0][1]
        vcols = self._vector_columns(first_row)
        if not vcols:
            return None
        shapes = [first_row[c].shape for c in vcols]
        dtypes = [first_row[c].dtype for c in vcols]
        n = len(entries)
        dests = np.empty(n, np.int64)
        mats = []
        try:
            for j, c in enumerate(vcols):
                mat = np.stack([e[1][c] for e in entries])
                if mat.dtype != np.float32:
                    # some LATER row wasn't f32: casting would change row
                    # bytes silently (see _vector_columns) — host path
                    return None
                mats.append(mat.reshape(n, -1))
            for i, (key, row, _diff) in enumerate(entries):
                dests[i] = shard_of_entry(key, row)
        except Exception:  # noqa: BLE001 — ragged rows / failing routes
            return None
        widths = [m.shape[1] for m in mats]
        payload = np.concatenate(mats, axis=1) if len(mats) > 1 else mats[0]
        # u32 ids are only for debugging; reassembly uses src indices
        ids = (np.arange(n) & 0xFFFFFFFF).astype(np.uint32)
        _keys, pays, srcs = exchange_with_respill(
            ids, payload, dests, self.mesh, self.axis
        )
        self.invocations += 1
        self.rows_exchanged += n
        out: list[list] = [[] for _ in range(n_shards)]
        for d in range(n_shards):
            for vec_row, i in zip(pays[d], srcs[d]):
                key, row, diff = entries[int(i)]
                parts = np.split(vec_row, np.cumsum(widths)[:-1]) if len(mats) > 1 else [vec_row]
                new_row = list(row)
                for j, c in enumerate(vcols):
                    new_row[c] = parts[j].reshape(shapes[j]).astype(dtypes[j])
                out[d].append((key, tuple(new_row), diff))
        return out


_ENGINE_EXCHANGER: DeviceExchanger | None = None


def engine_exchanger() -> DeviceExchanger | None:
    """Process-wide exchanger for ShardedNode, when enabled and a device
    mesh is constructible."""
    global _ENGINE_EXCHANGER
    if not enabled():
        return None
    if _ENGINE_EXCHANGER is None:
        try:
            _ENGINE_EXCHANGER = DeviceExchanger()
        except Exception:  # noqa: BLE001 — no usable devices
            return None
    return _ENGINE_EXCHANGER
