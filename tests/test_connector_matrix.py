"""Connector-runtime matrix: python ConnectorSubject streams (append /
upsert sessions, commit batching), subscribe callback ordering
(on_change -> on_time_end -> on_end), and demo stream generators
(reference tier-2: connector integration tests)."""

from __future__ import annotations

import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def _fresh_graph():
    G.clear()
    yield
    G.clear()


def _run_stream(build, timeout_s=30):
    """Build sinks, run pw.run() to stream end, return captured events."""
    events: list = []
    table = build()
    pw.io.subscribe(
        table,
        on_change=lambda key, row, time, is_addition: events.append(
            ("change", dict(row), time, is_addition)
        ),
        on_time_end=lambda time: events.append(("time_end", time)),
        on_end=lambda: events.append(("end",)),
    )
    th = threading.Thread(target=pw.run, daemon=True)
    th.start()
    th.join(timeout_s)
    assert not th.is_alive(), "stream did not terminate"
    return events


def test_python_connector_append_stream():
    from pathway_tpu.io.python import ConnectorSubject

    class Numbers(ConnectorSubject):
        def run(self):
            for i in range(7):
                self.next(v=i)

    def build():
        t = pw.io.python.read(
            Numbers(), schema=pw.schema_from_types(v=int)
        )
        return t.reduce(s=pw.reducers.sum(pw.this.v), n=pw.reducers.count())

    events = _run_stream(build)
    final_changes = [e for e in events if e[0] == "change" and e[3]]
    assert final_changes[-1][1] == {"s": 21, "n": 7}
    assert events[-1] == ("end",)


def test_python_connector_upsert_by_primary_key():
    from pathway_tpu.io.python import ConnectorSubject

    class Prices(ConnectorSubject):
        def run(self):
            self.next(ticker="AA", px=10)
            self.next(ticker="BB", px=5)
            self.next(ticker="AA", px=12)  # upsert same key

    class S(pw.Schema):
        ticker: str = pw.column_definition(primary_key=True)
        px: int

    def build():
        return pw.io.python.read(Prices(), schema=S)

    events = _run_stream(build)
    state: dict = {}
    for e in events:
        if e[0] != "change":
            continue
        _tag, row, _t, add = e
        if add:
            state[row["ticker"]] = row["px"]
        elif state.get(row["ticker"]) == row["px"]:
            del state[row["ticker"]]
    assert state == {"AA": 12, "BB": 5}


def test_subscribe_callback_ordering():
    from pathway_tpu.io.python import ConnectorSubject

    class OneShot(ConnectorSubject):
        def run(self):
            self.next(v=1)

    def build():
        return pw.io.python.read(
            OneShot(), schema=pw.schema_from_types(v=int)
        )

    events = _run_stream(build)
    kinds = [e[0] for e in events]
    assert kinds[-1] == "end"
    first_change = kinds.index("change")
    first_time_end = kinds.index("time_end")
    assert first_change < first_time_end  # changes land before their wave closes
    assert "end" not in kinds[:-1]  # end fires exactly once, last


def test_demo_range_stream_terminates_with_exact_rows():
    def build():
        t = pw.demo.range_stream(nb_rows=15, input_rate=1000)
        return t.reduce(n=pw.reducers.count(), s=pw.reducers.sum(pw.this.value))

    events = _run_stream(build)
    adds = [e[1] for e in events if e[0] == "change" and e[3]]
    assert adds[-1] == {"n": 15, "s": sum(range(15))}


def test_demo_noisy_linear_stream_schema():
    def build():
        t = pw.demo.noisy_linear_stream(nb_rows=10, input_rate=1000)
        return t.reduce(n=pw.reducers.count())

    events = _run_stream(build)
    adds = [e[1] for e in events if e[0] == "change" and e[3]]
    assert adds[-1] == {"n": 10}


def test_connector_commit_batches_respect_autocommit():
    """With a slow producer and small autocommit, results stream across
    MULTIPLE waves (not one giant batch at the end)."""
    from pathway_tpu.io.python import ConnectorSubject

    class Slow(ConnectorSubject):
        def run(self):
            for i in range(6):
                self.next(v=i)
                time.sleep(0.03)

    def build():
        t = pw.io.python.read(Slow(), schema=pw.schema_from_types(v=int))
        return t.reduce(n=pw.reducers.count())

    events: list = []
    table = build()
    pw.io.subscribe(
        table,
        on_change=lambda key, row, time, is_addition: events.append(
            (dict(row), time, is_addition)
        ),
        on_end=lambda: events.append(("end",)),
    )
    th = threading.Thread(
        target=lambda: pw.run(autocommit_duration_ms=20), daemon=True
    )
    th.start()
    th.join(30)
    assert not th.is_alive()
    add_times = {t for _r, t, a in [e for e in events if e != ("end",)] if a}
    assert len(add_times) >= 2, "counts must stream across waves"
