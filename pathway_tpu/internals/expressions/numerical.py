"""`.num` expression namespace (reference: internals/expressions/numerical.py)."""

from __future__ import annotations

import math
from typing import Any

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import ColumnExpression, MethodCallExpression, wrap_arg


def _m(name: str, expr: ColumnExpression, *args: Any, fn: Any, rt: Any, vfn: Any = None):
    return MethodCallExpression(f"num.{name}", expr, *args, fn=fn, return_type=rt,
                                vectorized_fn=vfn)


class NumericalNamespace:
    def __init__(self, expr: ColumnExpression):
        self._expr = expr

    def abs(self):
        return _m("abs", self._expr, fn=abs, rt=None, vfn=np.abs)

    def round(self, decimals: Any = 0):
        return _m("round", self._expr, wrap_arg(decimals),
                  fn=lambda x, d: round(x, d), rt=None)

    def fill_na(self, default_value: Any):
        def f(x, d):
            if x is None:
                return d
            if isinstance(x, float) and math.isnan(x):
                return d
            return x
        return _m("fill_na", self._expr, wrap_arg(default_value), fn=f, rt=None)

    def sqrt(self):
        return _m("sqrt", self._expr, fn=math.sqrt, rt=dt.FLOAT, vfn=np.sqrt)

    def exp(self):
        return _m("exp", self._expr, fn=math.exp, rt=dt.FLOAT, vfn=np.exp)

    def log(self, base: Any = math.e):
        return _m("log", self._expr, wrap_arg(base), fn=math.log, rt=dt.FLOAT)

    def floor(self):
        return _m("floor", self._expr, fn=math.floor, rt=dt.INT, vfn=np.floor)

    def ceil(self):
        return _m("ceil", self._expr, fn=math.ceil, rt=dt.INT, vfn=np.ceil)

    def sin(self):
        return _m("sin", self._expr, fn=math.sin, rt=dt.FLOAT, vfn=np.sin)

    def cos(self):
        return _m("cos", self._expr, fn=math.cos, rt=dt.FLOAT, vfn=np.cos)

    def tanh(self):
        return _m("tanh", self._expr, fn=math.tanh, rt=dt.FLOAT, vfn=np.tanh)
