"""Mesh supervisor: restart-the-mesh-from-checkpoint recovery.

A multi-process run (engine/runtime.py ``run_mesh``) detects a dead peer
on its wires and aborts with :class:`~pathway_tpu.parallel.process_mesh.
WorkerLost` instead of hanging — but *something* has to restart the job.
That something is this supervisor: it owns the worker processes of one
mesh, watches for any worker dying (injected crash, OOM-kill, WorkerLost
abort), and restarts the WHOLE generation. On restart the workers
re-negotiate the minimum committed checkpoint epoch across the mesh
(persistence/__init__.py allgather) and resume from it, so the job's
final output is identical to a crash-free run whenever the pipeline's
sources are journaled or seekable.

The whole-generation restart is deliberate: surviving workers hold
operator state *ahead* of the last committed epoch, and exchange wires
carry waves a rejoining worker never saw — a partial restart would need
distributed wave replay. Restarting the mesh from the agreed epoch is
the reference engine's model too (every worker rebuilds from
metadata → snapshots → journal tail).

By default restarted generations run with ``PATHWAY_FAULTS=0``: a
schedule is hit-count deterministic, so re-running it verbatim would
re-fire the same crash every generation. Pass
``faults_after_restart=`` to keep chaos flowing across restarts.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Sequence

__all__ = ["SupervisedMeshFailed", "run_supervised"]


class SupervisedMeshFailed(RuntimeError):
    """The mesh kept failing past ``max_restarts`` generations."""


def _spawn(
    argv: Sequence[str], n: int, first_port: int, env: dict[str, str]
) -> list[tuple[subprocess.Popen, Any]]:
    """Start the generation's workers. stdout/stderr go to unlinked spill
    files, NOT pipes: nobody drains a pipe while workers run, so a chatty
    worker (breaker warnings, chaos logging) would fill the ~64KB buffer,
    block on write, and stall the mesh until the overall timeout."""
    procs = []
    for pid in range(n):
        penv = {
            **env,
            "PATHWAY_PROCESSES": str(n),
            "PATHWAY_PROCESS_ID": str(pid),
            "PATHWAY_FIRST_PORT": str(first_port),
        }
        spill = tempfile.TemporaryFile(mode="w+", prefix=f"pw-sup-{pid}-")
        procs.append(
            (
                subprocess.Popen(
                    list(argv),
                    env=penv,
                    stdout=subprocess.DEVNULL,
                    stderr=spill,
                    text=True,
                ),
                spill,
            )
        )
    return procs


def _reap(procs: list[tuple[subprocess.Popen, Any]]) -> list[str]:
    """Kill survivors, wait everyone, return per-worker stderr."""
    for p, _spill in procs:
        if p.poll() is None:
            p.kill()
    errs = []
    for p, spill in procs:
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass
        try:
            spill.seek(0)
            errs.append(spill.read())
        except (OSError, ValueError):
            errs.append("")
        finally:
            spill.close()
    return errs


def run_supervised(
    argv: Sequence[str],
    n_processes: int,
    first_port: int,
    *,
    max_restarts: int = 3,
    env: dict[str, str] | None = None,
    faults_after_restart: str = "0",
    poll_s: float = 0.1,
    timeout_s: float = 600.0,
) -> dict[str, Any]:
    """Run ``argv`` as an ``n_processes`` mesh until every worker exits 0,
    restarting the whole mesh (same ports, same persistence roots) after
    any worker death. Returns ``{"generations": g, "stderr": [...]}`` of
    the successful generation; raises :class:`SupervisedMeshFailed` after
    ``max_restarts`` failed generations and :class:`TimeoutError` on the
    overall deadline."""
    from pathway_tpu.internals import observability as obs

    # supervisor-side black box: generation lifecycles land in the flight
    # recorder (workers dump their own rings when they crash; this is the
    # restart-decision record that stitches those dumps together)
    obs.maybe_enable_from_env()
    base_env = {**os.environ, **(env or {})}
    deadline = time.monotonic() + timeout_s
    failures: list[str] = []
    for generation in range(max_restarts + 1):
        gen_env = dict(base_env)
        if generation > 0:
            gen_env["PATHWAY_FAULTS"] = faults_after_restart
        procs = _spawn(argv, n_processes, first_port, gen_env)
        failed: str | None = None
        while True:
            if time.monotonic() > deadline:
                _reap(procs)
                raise TimeoutError(
                    f"supervised mesh did not finish within {timeout_s:.0f}s "
                    f"(generation {generation})"
                )
            codes = [p.poll() for p, _spill in procs]
            if any(c not in (None, 0) for c in codes):
                dead = [i for i, c in enumerate(codes) if c not in (None, 0)]
                # one worker died: the survivors observe WorkerLost on
                # their wires and exit on their own — kill + wait the
                # stragglers to reclaim the ports for the next generation
                errs = _reap(procs)
                obs.record(
                    "supervisor.restart", generation=generation,
                    dead_workers=dead,
                    exit_codes=[codes[i] for i in dead],
                )
                failed = (
                    f"generation {generation}: worker(s) {dead} exited "
                    f"{[codes[i] for i in dead]}"
                )
                for i, err in enumerate(errs):
                    if err.strip():
                        failed += f"\n-- worker {i} stderr --\n{err[-2000:]}"
                break
            if all(c == 0 for c in codes):
                if generation > 0:
                    # restarts happened: leave the decision record beside
                    # the workers' own crash dumps
                    obs.record(
                        "supervisor.recovered", generations=generation + 1,
                    )
                    obs.dump_flight("supervisor")
                return {
                    "generations": generation + 1,
                    "stderr": _reap(procs),
                }
            time.sleep(poll_s)
        failures.append(failed or "unknown failure")
    obs.record("supervisor.gave_up", generations=max_restarts + 1)
    obs.dump_flight("supervisor")
    raise SupervisedMeshFailed(
        f"mesh failed {max_restarts + 1} generations:\n" + "\n".join(failures)
    )


def main() -> int:
    """CLI shim: ``python -m pathway_tpu.parallel.supervisor N PORT -- cmd...``"""
    args = sys.argv[1:]
    if "--" not in args or len(args) < 4:
        print(
            "usage: python -m pathway_tpu.parallel.supervisor "
            "<n_processes> <first_port> [max_restarts] -- <cmd> [args...]",
            file=sys.stderr,
        )
        return 2
    split = args.index("--")
    head, argv = args[:split], args[split + 1:]
    n, port = int(head[0]), int(head[1])
    restarts = int(head[2]) if len(head) > 2 else 3
    out = run_supervised(argv, n, port, max_restarts=restarts)
    print(f"supervised mesh ok after {out['generations']} generation(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
