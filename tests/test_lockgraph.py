"""Lock-order analyzer tests (analysis/lockgraph.py,
docs/static-analysis.md).

Pins the recorder's semantics: order edges per thread, ABBA cycles in
the MERGED graph detected even when no interleaving deadlocked, trylock
acquisitions constraint-free (the ANN inline-retrain pattern), reentrant
re-acquisition edge-free, zero instrumentation with the flag off, and
the engine's known lock roles actually registered."""

from __future__ import annotations

import threading

import pytest

from pathway_tpu.analysis import lockgraph


@pytest.fixture(autouse=True)
def _clean_edges(monkeypatch):
    monkeypatch.setenv("PATHWAY_LOCK_CHECK", "1")
    # the atexit hook would os._exit the TEST RUN on the cycles these
    # tests create on purpose — record edges but never arm the hook
    monkeypatch.setattr(lockgraph, "_ATEXIT_ARMED", True)
    # SNAPSHOT the process-wide graph, don't discard it: under the
    # lock-order CI leg every earlier suite's real engine edges must
    # survive this file for the exit gate to check the WHOLE run
    saved = lockgraph.edges()
    lockgraph.reset()
    yield
    lockgraph.reset()
    with lockgraph._EDGES_LOCK:
        lockgraph._EDGES.update(saved)


def test_disabled_returns_raw_lock(monkeypatch):
    monkeypatch.setenv("PATHWAY_LOCK_CHECK", "0")
    lock = threading.Lock()
    out = lockgraph.register_lock("t.raw", lock)
    assert out is lock  # zero overhead off-path


def test_nested_acquisition_records_edge():
    a = lockgraph.register_lock("t.a")
    b = lockgraph.register_lock("t.b")
    with a:
        with b:
            pass
    assert ("t.a", "t.b") in lockgraph.edges()
    assert ("t.b", "t.a") not in lockgraph.edges()
    lockgraph.assert_acyclic()


def test_abba_cycle_detected_across_threads():
    a = lockgraph.register_lock("t.a")
    b = lockgraph.register_lock("t.b")

    def order_ab():
        with a:
            with b:
                pass

    def order_ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=order_ab)
    t2 = threading.Thread(target=order_ba)
    # SEQUENTIAL runs: no interleaving could deadlock here, yet the
    # merged graph still proves the ABBA precondition
    t1.start(); t1.join()
    t2.start(); t2.join()
    with pytest.raises(lockgraph.LockOrderError) as ei:
        lockgraph.assert_acyclic()
    msg = str(ei.value)
    assert "t.a -> t.b" in msg and "t.b -> t.a" in msg
    assert "first seen at" in msg
    cycle = lockgraph.find_cycle()
    assert cycle is not None and cycle[0] == cycle[-1]


def test_trylock_imposes_no_order_constraint():
    """The ANN pattern: gen -> trylock(retrain) vs retrain -> gen is
    deadlock-free by construction (the trylock fails instead of
    waiting) and must not read as a cycle."""
    gen = lockgraph.register_lock("t.gen")
    retrain = lockgraph.register_lock("t.retrain")
    with gen:
        assert retrain.acquire(blocking=False)
        retrain.release()
    with retrain:
        with gen:
            pass
    assert ("t.gen", "t.retrain") not in lockgraph.edges()
    assert ("t.retrain", "t.gen") in lockgraph.edges()
    lockgraph.assert_acyclic()


def test_held_trylock_still_constrains_later_blocking_acquires():
    a = lockgraph.register_lock("t.ta")
    b = lockgraph.register_lock("t.tb")
    assert a.acquire(blocking=False)
    with b:  # blocking acquire WHILE holding the trylocked a
        pass
    a.release()
    assert ("t.ta", "t.tb") in lockgraph.edges()


def test_reentrant_reacquisition_is_edge_free():
    r = lockgraph.register_lock("t.r", reentrant=True)
    other = lockgraph.register_lock("t.o")
    with r:
        with r:  # reentrant: no self-edge
            with other:
                pass
    assert ("t.r", "t.r") not in lockgraph.edges()
    assert ("t.r", "t.o") in lockgraph.edges()
    # the release of the INNER hold must not pop the outer one early
    with r:
        r.acquire()
        r.release()
        with other:
            pass
    lockgraph.assert_acyclic()


def test_sibling_instance_of_held_role_keeps_cross_role_edges():
    """Two INSTANCES of one role: re-holding the role must not
    suppress the cross-role edges of the second (blocking!) acquire —
    only the role-to-itself edge stays out."""
    pool_a = lockgraph.register_lock("t.pool")
    pool_b = lockgraph.register_lock("t.pool")
    other = lockgraph.register_lock("t.other")
    with pool_a:
        with other:
            with pool_b:  # blocks against siblings: a real constraint
                pass
    assert ("t.other", "t.pool") in lockgraph.edges()
    assert ("t.pool", "t.pool") not in lockgraph.edges()


def test_three_party_cycle():
    a = lockgraph.register_lock("t.c1")
    b = lockgraph.register_lock("t.c2")
    c = lockgraph.register_lock("t.c3")
    for first, second in ((a, b), (b, c), (c, a)):
        with first:
            with second:
                pass
    with pytest.raises(lockgraph.LockOrderError):
        lockgraph.assert_acyclic()


def test_wrapper_api_compat():
    lock = lockgraph.register_lock("t.api")
    assert lock.acquire(True, 0.5)
    assert lock.locked()
    lock.release()
    assert not lock.locked()


def test_engine_lock_roles_registered():
    """The instrumentation coverage floor: importing the engine stack
    registers the known lock roles (a deleted registration would
    silently shrink what the lock-order leg can see)."""
    import pathway_tpu  # noqa: F401
    import pathway_tpu.engine.device_plane  # noqa: F401
    import pathway_tpu.engine.runtime  # noqa: F401
    import pathway_tpu.indexing.ann  # noqa: F401
    import pathway_tpu.internals.observability  # noqa: F401
    import pathway_tpu.internals.telemetry  # noqa: F401
    import pathway_tpu.io._retry  # noqa: F401
    import pathway_tpu.io.http  # noqa: F401
    import pathway_tpu.parallel.column_plane  # noqa: F401
    import pathway_tpu.parallel.process_mesh  # noqa: F401
    import pathway_tpu.serving.admission  # noqa: F401
    import pathway_tpu.serving.backpressure  # noqa: F401
    import pathway_tpu.serving.continuous_batching  # noqa: F401

    # instance-scoped roles register at construction; module-scoped ones
    # at import — the floor here covers the import-time set plus any
    # instances the suite has already built
    roles = set(lockgraph.registry())
    expected_import_time = {
        "device_plane.registry", "faults.install", "runtime.async_loop",
        "workers.pool", "obs.plane", "obs.pretimes",
        "io.http_route_stats", "mesh.registry", "column_plane.stats",
        "telemetry.registry",
    }
    missing = expected_import_time - roles
    assert not missing, f"lock roles lost their registration: {missing}"

    # constructing the instances registers their roles too
    from pathway_tpu.engine.device_plane import SlotPool
    from pathway_tpu.io._retry import RetryPolicy
    from pathway_tpu.serving.admission import TokenBucket

    TokenBucket(1.0, 1.0)
    RetryPolicy("lockgraph-test")
    SlotPool("lockgraph-test", 1)
    roles = set(lockgraph.registry())
    for role in (
        "serving.token_bucket", "io.retry_breaker",
        "device_plane.slot_pool",
    ):
        assert role in roles, role
    assert len(roles) >= 15, sorted(roles)
