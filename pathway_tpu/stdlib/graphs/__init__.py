"""pw.graphs: iterative graph algorithms via pw.iterate
(reference: stdlib/graphs/ — bellman_ford/, pagerank/, louvain_communities/).
"""

from __future__ import annotations

from typing import Any

import pathway_tpu.internals.reducers as red
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals.common import coalesce, if_else, iterate
from pathway_tpu.internals.table import Table


class Graph:
    """Vertex/edge pair (reference: stdlib/graphs/graph.py:152)."""

    def __init__(self, V: Table, E: Table):
        self.V = V
        self.E = E


def pagerank(edges: Table, steps: int = 50, damping: float = 0.85) -> Table:
    """PageRank over edges(u: Pointer, v: Pointer) -> (rank: float) keyed by
    vertex (reference: stdlib/graphs/pagerank/impl.py; scaled-int ranks in
    the reference, float here)."""
    degs = edges.groupby(edges.u).reduce(edges.u, degree=red.count())
    vertices_u = edges.groupby(edges.u).reduce(vid=edges.u)
    vertices_v = edges.groupby(edges.v).reduce(vid=edges.v)
    # sources and targets overlap; reindex + groupby dedups to vertex set
    vertices = vertices_u.concat_reindex(vertices_v).groupby(
        ex.this.vid
    ).reduce(vid=ex.this.vid)

    def step(ranks: Table) -> dict[str, Table]:
        # contribution of u along each edge = rank(u) / degree(u)
        contribs = (
            edges.join(ranks, edges.u == ranks.vid)
            .select(u=ex.left.u, v=ex.left.v, rank=ex.right.rank)
            .join(degs, ex.left.u == degs.u)
            .select(v=ex.left.v, contrib=ex.left.rank / ex.right.degree)
        )
        summed = contribs.groupby(contribs.v).reduce(
            vid=contribs.v, flow=red.sum(contribs.contrib)
        )
        incoming = vertices.join_left(summed, vertices.vid == summed.vid).select(
            vid=ex.left.vid, flow=coalesce(ex.right.flow, 0.0)
        )
        new_ranks = incoming.select(
            vid=incoming.vid, rank=(1.0 - damping) + damping * incoming.flow
        ).with_id_from(ex.this.vid)
        return {"ranks": new_ranks}

    init = vertices.select(vid=vertices.vid, rank=1.0).with_id_from(ex.this.vid)
    result = iterate(lambda ranks: step(ranks), iteration_limit=steps, ranks=init)
    return result


def bellman_ford(vertices: Table, edges: Table) -> Table:
    """Shortest paths from rows with is_source=True.

    vertices: (is_source: bool); edges: (u: Pointer, v: Pointer, dist: float).
    Returns (dist_from_source: float) keyed like vertices.
    (reference: stdlib/graphs/bellman_ford/impl.py)
    """
    INF = float("inf")
    init = vertices.select(
        dist=if_else(vertices.is_source, 0.0, INF)
    )

    def step(state: Table) -> dict[str, Table]:
        relaxed = (
            edges.join(state, edges.u == state.id)
            .select(v=ex.left.v, cand=ex.right.dist + ex.left.dist)
        )
        best = relaxed.groupby(relaxed.v).reduce(
            v=relaxed.v, cand=red.min(relaxed.cand)
        ).with_id_from(ex.this.v)
        new_state = state.join_left(best, state.id == best.id).select(
            dist=if_else(
                coalesce(ex.right.cand, INF) < ex.left.dist,
                coalesce(ex.right.cand, INF),
                ex.left.dist,
            ),
            id=ex.left.id,
        )
        return {"state": new_state.with_id(ex.this.id).without("id")}

    # NOTE: join_left keeps left ids when id=left.id; we reindex back onto
    # the vertex universe each round so the fixpoint is key-stable.
    result = iterate(lambda state: step(state), state=init)
    return result


def louvain_level(G: Graph, **kwargs: Any) -> Table:
    raise NotImplementedError("louvain communities: planned (round 2)")


def louvain_communities(G: Graph, **kwargs: Any) -> Table:
    raise NotImplementedError("louvain communities: planned (round 2)")
