"""Persistence: operator snapshots, frontier metadata, journal compaction.

Mirrors the reference's wordcount recovery harness
(integration_tests/wordcount/test_recovery.py): a streaming run is killed
mid-stream, restarted with the same persistence dir, and the accumulated
output stream across both runs must consolidate to exact counts. Unlike
the r1 journal-only design, resume restores operator snapshots
(src/persistence/operator_snapshot.rs equivalent) and replays only the
journal tail after the committed offset — the compacted journal head
proves history is NOT reprocessed.
"""

import json
import os
import subprocess
import sys
import textwrap

SCRIPT = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw
    from pathway_tpu.io.python import ConnectorSubject

    CRASH_AFTER = int(sys.argv[1])  # crash after N events (-1 = run to end)
    PDIR = sys.argv[2]
    OUT = sys.argv[3]  # jsonl of deliveries, appended across runs

    class Words(ConnectorSubject):
        def run(self):
            import time
            words = [f"w{{i % 7}}" for i in range(50)]
            for i, w in enumerate(words):
                if CRASH_AFTER >= 0 and i == CRASH_AFTER:
                    os._exit(17)  # hard crash, no cleanup
                self.next(word=w)
                time.sleep(0.004)  # pace so pump waves interleave

    t = pw.io.python.read(Words(), schema=pw.schema_from_types(word=str), name="words")
    counts = t.groupby(t.word).reduce(t.word, count=pw.reducers.count())
    sink = open(OUT, "a")
    def on_change(key, row, time, is_addition):
        sink.write(__import__("json").dumps(
            {{"word": row["word"], "count": row["count"], "add": is_addition}}
        ) + "\\n")
        sink.flush()
    pw.io.subscribe(counts, on_change=on_change)
    pw.run(persistence_config=pw.persistence.Config(
        pw.persistence.Backend.filesystem(PDIR)))
    """
)


def _run(repo, crash_after, pdir, out, timeout=120):
    return subprocess.run(
        [sys.executable, "-c", SCRIPT.format(repo=repo), str(crash_after), pdir, out],
        capture_output=True,
        timeout=timeout,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def _replay_deliveries(path):
    """Consolidate the delivered update stream into final counts."""
    state = {}
    if not os.path.exists(path):
        return state, 0
    n = 0
    with open(path) as f:
        for line in f:
            n += 1
            ev = json.loads(line)
            if ev["add"]:
                state[ev["word"]] = ev["count"]
            elif state.get(ev["word"]) == ev["count"]:
                del state[ev["word"]]
    return state, n


EXPECTED = {f"w{i}": (8 if i == 0 else 7) for i in range(7)}


def test_crash_recovery_exact_counts(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pdir = str(tmp_path / "snapshots")
    out = str(tmp_path / "deliveries.jsonl")

    # phase 1: crash after 30 of 50 events
    r1 = _run(repo, 30, pdir, out)
    assert r1.returncode == 17, r1.stderr[-2000:]
    _state1, n1 = _replay_deliveries(out)
    assert n1 > 0, "no deliveries before crash"
    files = os.listdir(pdir)
    assert any(f.endswith(".seg") for f in files), files
    assert "metadata.json" in files, files
    assert os.listdir(os.path.join(pdir, "operator")), "no operator snapshots"

    # phase 2: restart with the same persistence dir, run to completion
    r2 = _run(repo, -1, pdir, out)
    assert r2.returncode == 0, r2.stderr[-2000:]
    final, n2 = _replay_deliveries(out)
    assert final == EXPECTED, final

    # the journal head was compacted: resume replayed only the tail, not
    # the whole history (VERDICT r1 acceptance criterion)
    with open(os.path.join(pdir, "metadata.json")) as f:
        meta = json.load(f)
    assert meta["offsets"]["words"] == 50, meta
    segs = sorted(
        int(f.split(".")[-2]) for f in os.listdir(pdir) if f.endswith(".seg")
    )
    assert segs and segs[0] > 0, f"journal head not compacted: {segs}"


def test_restart_without_crash_emits_nothing(tmp_path):
    """A clean restart restores operator state, skips every journaled
    event, and delivers zero new updates — restarting changes nothing."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pdir = str(tmp_path / "snapshots")
    out = str(tmp_path / "deliveries.jsonl")
    assert _run(repo, -1, pdir, out).returncode == 0
    state1, n1 = _replay_deliveries(out)
    assert state1 == EXPECTED
    assert _run(repo, -1, pdir, out).returncode == 0
    state2, n2 = _replay_deliveries(out)
    assert state2 == EXPECTED
    assert n2 == n1, f"restart re-delivered {n2 - n1} updates"


def test_checkpoint_manager_roundtrip(tmp_path):
    """Direct CheckpointManager API: snapshot -> restore on a fresh
    identical session restores every stateful node."""
    import pathway_tpu as pw
    from pathway_tpu.internals.lowering import Session
    from pathway_tpu.persistence import Backend, CheckpointManager, Config

    def build():
        t = pw.debug.table_from_markdown(
            """
            k | v | __time__ | __diff__
            a | 1 | 2        | 1
            b | 2 | 2        | 1
            a | 3 | 4        | 1
            """
        ).with_id_from(pw.this.k)
        return t.groupby(t.k).reduce(t.k, s=pw.reducers.sum(t.v))

    cfg = Config(Backend.filesystem(str(tmp_path)))

    s1 = Session()
    cap1 = s1.capture(build())
    s1.execute()
    m1 = CheckpointManager(s1, cfg)
    m1.checkpoint(finalized_time=100)

    s2 = Session()
    cap2 = s2.capture(build())
    m2 = CheckpointManager(s2, cfg)
    assert m2.signature == m1.signature
    offsets = m2.restore()
    assert m2.restored
    assert offsets == {}
    # the capture node state was restored without running anything
    assert {
        tuple(r) for r in cap2.state.rows.values()
    } == {tuple(r) for r in cap1.state.rows.values()}


def test_signature_mismatch_refuses_compacted_resume(tmp_path):
    """If the pipeline changes after compaction, resume must fail loudly
    rather than recompute from a partial journal."""
    from pathway_tpu.persistence import MetadataStore, SegmentedJournal

    j = SegmentedJournal(str(tmp_path))
    w = j.open_segment("conn", 0)
    for i in range(5):
        w.append(i, (i,), 1)
    w.flush(sync=True)
    w.close()
    # simulate: checkpoint committed offset 5, then compaction removed the
    # head, then the pipeline signature changed
    w2 = j.open_segment("conn", 5)
    w2.append(5, (5,), 1)
    w2.flush(sync=True)
    w2.close()
    j.compact("conn", 5)
    assert j.head_offset("conn") == 5

    MetadataStore(str(tmp_path)).commit(
        epoch=1, offsets={"conn": 5}, signature="other", finalized_time=10
    )

    import pathway_tpu as pw
    from pathway_tpu.internals.lowering import Session
    from pathway_tpu.persistence import Backend, CheckpointManager, Config

    s = Session()
    t = pw.debug.table_from_markdown("a\n1")
    s.capture(t)
    m = CheckpointManager(s, Config(Backend.filesystem(str(tmp_path))))
    import pytest

    with pytest.raises(RuntimeError, match="compacted"):
        m.restore()


def test_rollback_restore_rewrites_metadata(tmp_path):
    """Rolling back one epoch (multi-process coordinated recovery) must
    rewrite metadata.json so the NEXT commit chains its history and
    journal-compaction floor off the agreed epoch — a second crash in the
    same window must still find the rollback epoch (double-crash
    regression from review)."""
    import pathway_tpu as pw
    from pathway_tpu.internals.lowering import Session
    from pathway_tpu.persistence import Backend, CheckpointManager, Config

    def build():
        t = pw.debug.table_from_markdown(
            "k | v\na | 1\nb | 2"
        ).with_id_from(pw.this.k)
        return t.groupby(t.k).reduce(t.k, s=pw.reducers.sum(t.v))

    cfg = Config(Backend.filesystem(str(tmp_path)))
    s1 = Session()
    s1.capture(build())
    s1.execute()
    m1 = CheckpointManager(s1, cfg)
    m1.checkpoint(finalized_time=10)  # epoch 1
    m1.checkpoint(finalized_time=20)  # epoch 2 (history holds 1)
    assert m1.latest_epoch() == 2

    # simulate the peer-negotiated rollback to epoch 1 on a fresh process
    s2 = Session()
    s2.capture(build())
    m2 = CheckpointManager(s2, cfg)
    offsets = m2.restore(epoch=1)
    assert m2.restored and m2.epoch == 1
    # the on-disk record now reads epoch 1 — a second crash before any new
    # checkpoint still negotiates and finds epoch 1
    assert m2.latest_epoch() == 1
    s3 = Session()
    s3.capture(build())
    m3 = CheckpointManager(s3, cfg)
    m3.restore(epoch=1)
    assert m3.restored and m3.epoch == 1
    # and the next commit chains cleanly from the agreed epoch
    m3.checkpoint(finalized_time=30)
    assert m3.latest_epoch() == 2
    assert m3.metadata.record_for(1) is not None


def test_cold_recovery_preserves_journal_across_first_checkpoint(tmp_path):
    """After an agreed cold start, the resumed run's FIRST checkpoint must
    not compact the pre-existing journal — a second between-commits crash
    still negotiates epoch 0 and replays it (review regression)."""
    import pathway_tpu as pw
    from pathway_tpu.internals.lowering import Session
    from pathway_tpu.persistence import Backend, CheckpointManager, Config

    def build():
        t = pw.debug.table_from_markdown("k | v\na | 1").with_id_from(pw.this.k)
        return t.groupby(t.k).reduce(t.k, s=pw.reducers.sum(t.v))

    cfg = Config(Backend.filesystem(str(tmp_path)))
    s1 = Session()
    s1.capture(build())
    s1.execute()
    m1 = CheckpointManager(s1, cfg)
    m1.open_writer("src", 0)
    for i in range(5):
        m1.append("src", i, (i,), 1)
    m1.checkpoint(finalized_time=10)  # epoch 1, journal [0..5)

    # agreed cold start (a peer had nothing): metadata cleared, journal kept
    s2 = Session()
    s2.capture(build())
    m2 = CheckpointManager(s2, cfg)
    assert m2.restore(epoch=0) == {"src": 0}
    assert m2.latest_epoch() == 0
    # resumed run's first checkpoint: journal head must SURVIVE
    m2.open_writer("src", m2.journal.total_events("src"))
    m2.checkpoint(finalized_time=20)  # epoch 1 of the new chain
    assert m2.journal.head_offset("src") == 0, "journal head compacted"
    # a second cold negotiation still works
    s3 = Session()
    s3.capture(build())
    m3 = CheckpointManager(s3, cfg)
    assert m3.restore(epoch=0) == {"src": 0}
