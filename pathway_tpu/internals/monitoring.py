"""Console monitoring: the rich dashboard + periodic stats fallback.

Reference parity: internals/monitoring.py (:56-190) — the rich-based TUI
with per-connector and per-operator panels refreshed in place. When rich
is unavailable or stderr is not a terminal, a compact stats line per
commit-wave window goes through the standard logger instead (the
reference logs the same way in non-interactive runs).
"""

from __future__ import annotations

import logging
import time
from typing import Any

logger = logging.getLogger("pathway_tpu.monitor")


class MonitoringLevel:
    NONE = "none"
    IN_OUT = "in_out"
    ALL = "all"


class StatsMonitor:
    """Collects the per-wave snapshot both renderers share."""

    def __init__(self, session: Any):
        self.session = session
        self.waves = 0
        self.t0 = time.time()
        self.rows_at_t0 = 0
        self.started = time.time()

    def snapshot(self, wave_time: int) -> dict:
        graph = self.session.graph
        rows = sum(n.rows_out for n in graph.nodes)
        dt = time.time() - self.t0
        rate = (rows - self.rows_at_t0) / dt if dt > 0 else 0.0
        inputs = [n for n in graph.nodes if type(n).__name__ == "InputNode"]
        hot = sorted(graph.nodes, key=lambda n: -n.time_ns)[:5]
        connectors = [
            {"name": c.name, "done": c.done}
            for c in getattr(self.session, "connectors", [])
        ]
        return {
            "time": wave_time,
            "waves": self.waves,
            "uptime": time.time() - self.started,
            "operators": len(graph.nodes),
            "inputs": len(inputs),
            "rows_out": rows,
            "rate": rate,
            "hot": [
                {
                    # plan-node label + call site via describe(): two
                    # GroupByNodes (different groupbys) stay apart in the
                    # TUI/log line, not just by opaque node id
                    "op": n.describe(),
                    "rows_in": n.rows_in,
                    "rows_out": n.rows_out,
                    "latency_ms": n.time_ns / 1e6,
                }
                for n in hot
            ],
            "connectors": connectors,
            "errors": len(graph.error_log.entries),
        }

    def roll(self, snap: dict) -> None:
        self.t0 = time.time()
        self.rows_at_t0 = snap["rows_out"]


def rich_renderable(snap: dict):
    """The dashboard layout for one stats snapshot (reference TUI shape:
    header line + connectors panel + hottest-operators panel)."""
    from rich.console import Group
    from rich.panel import Panel
    from rich.table import Table as RichTable

    head = (
        f"t={snap['time']}  waves={snap['waves']}  "
        f"uptime={snap['uptime']:.0f}s  rate={snap['rate']:,.0f} rows/s  "
        f"errors={snap['errors']}"
    )
    conn = RichTable(title="connectors", expand=True)
    conn.add_column("name")
    conn.add_column("state")
    for c in snap["connectors"]:
        conn.add_row(c["name"], "done" if c["done"] else "streaming")
    ops = RichTable(title="hottest operators", expand=True)
    ops.add_column("operator")
    ops.add_column("rows in", justify="right")
    ops.add_column("rows out", justify="right")
    ops.add_column("latency", justify="right")
    for h in snap["hot"]:
        ops.add_row(
            h["op"], f"{h['rows_in']:,}", f"{h['rows_out']:,}",
            f"{h['latency_ms']:,.0f}ms",
        )
    return Panel(Group(head, conn, ops), title="pathway_tpu")


def attach_monitor(
    session: Any, every_n_waves: int = 50, use_tui: bool | None = None
) -> None:
    """Install a per-wave monitor: the rich Live dashboard on interactive
    terminals (use_tui=True forces it, e.g. tests), a logger stats line
    otherwise."""
    stats = StatsMonitor(session)
    live = None
    if use_tui is None:
        import sys

        use_tui = bool(getattr(sys.stderr, "isatty", lambda: False)())
    if use_tui:
        try:
            import sys

            from rich.console import Console
            from rich.live import Live

            # render on STDERR — the stream the tty gate checks — so a
            # piped stdout (results > file) never gets ANSI frames
            live = Live(
                auto_refresh=False,
                transient=True,
                console=Console(file=sys.stderr),
            )
            live.start()
        except Exception:  # noqa: BLE001 — no rich / broken terminal
            live = None

    def monitor(wave_time: int) -> None:
        stats.waves += 1
        if stats.waves % every_n_waves:
            return
        snap = stats.snapshot(wave_time)
        if live is not None:
            live.update(rich_renderable(snap), refresh=True)
        else:
            hot_s = ", ".join(
                f"{h['op']}={h['latency_ms']:.0f}ms"
                for h in snap["hot"] if h["latency_ms"]
            )
            logger.info(
                "t=%d waves=%d operators=%d inputs=%d rows_out=%d "
                "rate=%.0f rows/s hot=[%s]",
                snap["time"], snap["waves"], snap["operators"],
                snap["inputs"], snap["rows_out"], snap["rate"], hot_s,
            )
        stats.roll(snap)

    monitor.live = live  # tests / run teardown can reach the display
    session.monitors.append(monitor)
