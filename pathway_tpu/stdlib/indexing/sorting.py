"""Sorted-order utilities: prev/next pointer tables and non-None neighbor
value retrieval.

Reference parity: stdlib/indexing/sorting.py — there the prev/next order is
maintained by a distributed binary search tree built with `pw.iterate`
(build_sorted_index :92, sort_from_index :137) because differential dataflow
has no native order-maintenance. Our engine has one: `Table.sort` lowers to
the incremental prev/next operator (engine SortNode; the reference's
equivalent is src/engine/dataflow/operators/prev_next.rs), so `sort_from_index`
is a thin wrapper and only the iterative value-propagation
(`retrieve_prev_next_values`, reference :195) is kept as dataflow.
"""

from __future__ import annotations

from typing import Any

import pathway_tpu.internals.expression as ex
from pathway_tpu.internals.common import if_else, iterate, require
from pathway_tpu.internals.table import Table


def sort_from_index(table: Table, key: Any = None, instance: Any = None) -> Table:
    """prev/next pointers in `key` order (default: column `key`)."""
    key = key if key is not None else table.key
    return table.sort(key=key, instance=instance)


def build_sorted_index(nodes: Table) -> dict:
    """Reference-compat: returns {'index': prev/next table, 'oracle': None}.

    The reference's BST oracle supports range search; the incremental sort
    operator answers prev/next directly, which is what the stdlib consumers
    (diff, interpolate) use.
    """
    index = nodes.sort(key=nodes.key, instance=getattr(nodes, "instance", None))
    return {"index": index, "oracle": None}


def _retrieving_prev_next_value(tab: Table) -> Table:
    """One propagation step: inherit neighbor's answer when it is resolved."""
    import pathway_tpu as pw

    prev_tab = tab.ix(tab.prev, optional=True)
    next_tab = tab.ix(tab.next, optional=True)
    return tab.select(
        tab.prev,
        tab.next,
        tab.value,
        prev_value=if_else(
            prev_tab.value.is_not_none(),
            prev_tab.id,
            prev_tab.prev_value,
        ),
        next_value=if_else(
            next_tab.value.is_not_none(),
            next_tab.id,
            next_tab.next_value,
        ),
    )


def retrieve_prev_next_values(
    ordered_table: Table, value: ex.ColumnReference | None = None
) -> Table:
    """For each row: pointers to the nearest prev/next rows whose `value` is
    not None (reference: sorting.py:195)."""
    if value is None:
        value = ordered_table.value
    else:
        value = ordered_table[value]
    tab = ordered_table.select(
        ordered_table.prev, ordered_table.next, value=value
    )
    tab = tab.with_columns(
        prev_value=require(tab.id, tab.value),
        next_value=require(tab.id, tab.value),
    )
    result = iterate(_retrieving_prev_next_value, tab=tab)
    return result.select(result.prev_value, result.next_value)
