"""S3/object-store persistence backend (VERDICT r2 item 6): the staging
sync layer against the built-in directory-backed S3 fake — journal +
snapshot roundtrip, restart-from-bucket-only, and kill -9 recovery.
Reference: /root/reference/src/persistence/backends/s3.rs."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

SCRIPT = textwrap.dedent(
    """
    import os, sys, threading, time
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw
    from pathway_tpu.io.python import ConnectorSubject

    OUT = sys.argv[1]
    MODE = sys.argv[2]  # 'run' | 'crash'
    N = int(sys.argv[3])

    class Words(ConnectorSubject):
        def run(self):
            for i in range(N):
                self.next(word=f"w{{i % 7}}")
                time.sleep(0.002)

    t = pw.io.python.read(Words(), schema=pw.schema_from_types(word=str), name="words")
    counts = t.groupby(t.word).reduce(t.word, count=pw.reducers.count())
    sink = open(OUT, "a")
    def on_change(key, row, time, is_addition):
        sink.write(__import__("json").dumps(
            {{"word": row["word"], "count": row["count"], "add": is_addition}}
        ) + "\\n")
        sink.flush()
    pw.io.subscribe(counts, on_change=on_change)

    if MODE == "crash":
        def crasher():
            fake = os.environ["PATHWAY_S3_FAKE_DIR"]
            deadline = time.time() + 30
            while time.time() < deadline:
                # wait for a metadata.json OBJECT in the bucket (the
                # bucket dir itself appears only once the backend
                # constructs — don't die racing its creation)
                if os.path.isdir(fake) and any(
                    "metadata.json" in f for f in os.listdir(fake)
                ):
                    os._exit(17)
                time.sleep(0.01)
            os._exit(3)
        threading.Thread(target=crasher, daemon=True).start()

    pw.run(persistence_config=pw.persistence.Config(
        pw.persistence.Backend.s3("ckpt/root"),
        snapshot_interval_ms=50))
    """
)


def _run(repo, fake_dir, out, mode, n, timeout=120):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PATHWAY_S3_FAKE_DIR": fake_dir,
    }
    return subprocess.run(
        [sys.executable, "-c", SCRIPT.format(repo=repo), out, mode, str(n)],
        capture_output=True, timeout=timeout, text=True, env=env,
    )


def _consolidate(path):
    state = {}
    if not os.path.exists(path):
        return state
    with open(path) as f:
        for line in f:
            ev = json.loads(line)
            if ev["add"]:
                state[ev["word"]] = ev["count"]
            elif state.get(ev["word"]) == ev["count"]:
                del state[ev["word"]]
    return state


@pytest.fixture()
def repo():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_s3_sync_roundtrip(tmp_path):
    """Unit roundtrip: push a staging tree, wipe it, pull it back."""
    from pathway_tpu.persistence import _DirS3Client, _S3Sync

    fake = str(tmp_path / "bucket")
    local = str(tmp_path / "stage")
    os.makedirs(os.path.join(local, "operator"))
    with open(os.path.join(local, "words.0.seg"), "wb") as f:
        f.write(b"journal-bytes")
    with open(os.path.join(local, "operator", "n1.1.state"), "wb") as f:
        f.write(b"snapshot-bytes")
    with open(os.path.join(local, "metadata.json"), "w") as f:
        json.dump({"epoch": 1}, f)

    sync = _S3Sync(_DirS3Client(fake), "fake", "ckpt/root", local)
    sync.push()
    keys = sorted(sync._keys())
    assert keys == [
        "ckpt/root/metadata.json",
        "ckpt/root/operator/n1.1.state",
        "ckpt/root/words.0.seg",
    ]

    sync2 = _S3Sync(_DirS3Client(fake), "fake", "ckpt/root", local)
    sync2.pull()  # resets staging from the bucket
    with open(os.path.join(local, "words.0.seg"), "rb") as f:
        assert f.read() == b"journal-bytes"
    with open(os.path.join(local, "operator", "n1.1.state"), "rb") as f:
        assert f.read() == b"snapshot-bytes"
    with open(os.path.join(local, "metadata.json")) as f:
        assert json.load(f) == {"epoch": 1}

    # deletion propagates (journal compaction)
    os.unlink(os.path.join(local, "words.0.seg"))
    sync2.push()
    assert "ckpt/root/words.0.seg" not in sync2._keys()


def test_s3_backend_end_to_end_restart(repo, tmp_path):
    """A full run persists to the bucket; a SECOND run (fresh staging —
    different fake dir path is the same bucket, staging is keyed off it)
    replays nothing and emits nothing new; exact counts survive."""
    fake = str(tmp_path / "bucket")
    out = str(tmp_path / "deliveries.jsonl")
    r1 = _run(repo, fake, out, "run", 140)
    assert r1.returncode == 0, r1.stderr[-2000:]
    expected = {f"w{i}": 20 for i in range(7)}
    assert _consolidate(out) == expected
    assert any("metadata.json" in f for f in os.listdir(fake))

    r2 = _run(repo, fake, out, "run", 140)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert _consolidate(out) == expected


def test_s3_backend_kill9_recovery(repo, tmp_path):
    """kill -9 after the first bucket commit: resume pulls the staging
    tree from the bucket and finishes with exact counts. One retry: the
    crash-timing race (snapshot commit vs producer finish) is load-
    sensitive on the 1-core CI host — a real recovery bug fails both
    attempts."""
    expected = {f"w{i}": 400 // 7 + (1 if i < 400 % 7 else 0) for i in range(7)}
    last: tuple = ()
    for attempt in range(2):
        fake = str(tmp_path / f"bucket{attempt}")
        out = str(tmp_path / f"deliveries{attempt}.jsonl")
        r1 = _run(repo, fake, out, "crash", 400)
        if r1.returncode != 17:
            last = ("crash-rc", r1.returncode, r1.stderr[-2000:])
            continue
        r2 = _run(repo, fake, out, "run", 400)
        if r2.returncode != 0:
            last = ("resume-rc", r2.returncode, r2.stderr[-2000:])
            continue
        if _consolidate(out) == expected:
            return
        last = ("counts", _consolidate(out))
    raise AssertionError(f"kill9 recovery failed twice: {last}")
