"""Live Adaptive RAG service over a watched document directory.

Documents dropped into the directory are parsed, split, embedded
(on-chip via JaxEmbedder when a TPU is present) and indexed; the REST
endpoint answers questions against the CURRENT corpus with geometric
context expansion (start with a few documents, double until the answer
is supported). Reference analog: the adaptive-rag template
(xpacks/llm/question_answering.py AdaptiveRAGQuestionAnswerer).

Run:
    python app.py ./corpus --port 8000
Ask:
    curl -X POST localhost:8000/v1/pw_ai_answer \
         -H 'Content-Type: application/json' \
         -d '{"prompt": "What is the refund policy?"}'

--mock swaps the embedder/LLM for deterministic fakes (no model
weights needed — plumbing demo and test mode).
"""

import argparse

import pathway_tpu as pw
from pathway_tpu.stdlib.indexing import BruteForceKnnFactory
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.question_answering import (
    AdaptiveRAGQuestionAnswerer,
)
from pathway_tpu.xpacks.llm.splitters import TokenCountSplitter


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("corpus", help="directory of documents to index")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--mock", action="store_true", help="fake embedder+LLM")
    args = ap.parse_args()

    docs = pw.io.fs.read(
        args.corpus,
        format="binary",
        with_metadata=True,
        mode="streaming",
        autocommit_duration_ms=200,
    )

    if args.mock:
        from pathway_tpu.xpacks.llm.mocks import FakeChatModel, FakeEmbedder

        embedder: pw.UDF = FakeEmbedder(dim=32)
        llm: pw.UDF = FakeChatModel()
    else:
        from pathway_tpu.xpacks.llm.embedders import JaxEmbedder
        from pathway_tpu.xpacks.llm.llms import JaxLMChat

        embedder = JaxEmbedder()
        llm = JaxLMChat()

    store = DocumentStore(
        docs,
        retriever_factory=BruteForceKnnFactory(
            dimensions=embedder.get_embedding_dimension(), embedder=embedder
        ),
        splitter=TokenCountSplitter(min_tokens=50, max_tokens=250),
    )
    answerer = AdaptiveRAGQuestionAnswerer(llm, store)
    answerer.run_server(host=args.host, port=args.port, with_cache=False)


if __name__ == "__main__":
    main()
