"""pw.io.slack — send table rows as Slack messages.

Reference parity: python/pathway/io/slack/__init__.py (send_alerts :11),
which posts each alert row to chat.postMessage via the HTTP connector;
identical mechanism here over `requests`.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.parse_graph import G

_API_URL = "https://slack.com/api/chat.postMessage"


def send_alerts(alerts: Any, slack_channel_id: str, slack_token: str) -> None:
    """Posts every new value of the `alerts` column to a Slack channel
    (insertions only — retractions are not un-sent)."""
    import requests

    table = alerts.table.select(message=alerts)

    def write_batch(time: int, entries: list) -> None:
        for _key, row, diff in entries:
            if diff <= 0:
                continue
            resp = requests.post(
                _API_URL,
                json={"channel": slack_channel_id, "text": str(row[0])},
                headers={"Authorization": f"Bearer {slack_token}"},
                timeout=30,
            )
            resp.raise_for_status()
            body = resp.json()
            if not body.get("ok", False):
                raise RuntimeError(f"slack API error: {body.get('error')}")

    G.add_sink("output", table, write_batch=write_batch)


__all__ = ["send_alerts"]
