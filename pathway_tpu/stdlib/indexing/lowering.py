"""Lowering of the `external_index` OpSpec to the engine node.

Reference parity: graph_runner handling of use_external_index_as_of_now
(python_api.rs external index wrappers -> dataflow.rs:2224).
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine import core as eng


def build_external_index(session: Any, table: Any, spec: Any) -> eng.Node:
    index_t = spec.inputs[0]
    query_t = spec.inputs[1]
    data_t = spec.inputs[2] if len(spec.inputs) > 2 else None
    nodes = [session.node_of(index_t), session.node_of(query_t)]
    if data_t is not None:
        nodes.append(session.node_of(data_t))
    # one host/device index instance: runs whole on process 0
    nodes = session._process_exchange(nodes, None)
    mode = spec.params["mode"]

    def index_fn(key, row):
        return row[0], row[1]

    if mode == "reply":
        def query_fn(key, row):
            return row[0], row[1], row[2]
    else:
        def query_fn(key, row):
            return row[-3], row[-2], row[-1]

    return eng.ExternalIndexNode(
        session.graph,
        nodes,
        spec.params["host_index_factory"](),
        index_fn,
        query_fn,
        mode=mode,
        asof_now=spec.params["asof_now"],
        data_width=spec.params["data_width"],
    )
