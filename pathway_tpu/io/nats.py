"""pw.io.nats — streaming message-queue connector over a native protocol
client.

Reference parity: python/pathway/io/nats/__init__.py (read :23, write
:154) + the Rust-side NATS reader/writer in src/connectors/data_storage.rs.
The reference links the async-nats crate; this implementation speaks the
NATS client protocol directly over a TCP socket (INFO/CONNECT, SUB, PUB/
HPUB, MSG/HMSG, PING/PONG) — no client library required.

Semantics:
  * read(): one reader thread per connector subscribes to the topic
    (optionally in a queue group — NATS's native partitioned-reader
    mechanism: PATHWAY_PROCESS_ID-stamped members of the same group split
    the subject's traffic). Core NATS is at-most-once from subscribe time;
    replay/backfill durability comes from the framework's persistence
    layer, which journals the parsed stream and replays it on resume
    (persistence/__init__.py) — the same division of labor the reference
    uses for non-seekable sources.
  * write(): publishes one message per row with `pathway_time` and
    `pathway_diff` headers (HPUB), like the reference's message-queue
    writers.
"""

from __future__ import annotations

import json as _json
import logging
import socket
import threading
import time as _time
from typing import Any, Iterable

from pathway_tpu.engine import faults
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.analysis import lockgraph as _lockgraph
from pathway_tpu.io._retry import RetryPolicy, log_degradation

logger = logging.getLogger("pathway_tpu.io.nats")


class NatsError(RuntimeError):
    pass


def _parse_uri(uri: str) -> tuple[str, int]:
    u = uri
    if "://" in u:
        scheme, u = u.split("://", 1)
        if scheme not in ("nats", "tcp"):
            raise NatsError(f"unsupported NATS scheme {scheme!r}")
    if "@" in u:  # creds in uri: user:pass@host
        u = u.rsplit("@", 1)[1]
    host, _, port = u.partition(":")
    return host or "127.0.0.1", int(port or 4222)


class NatsConnection:
    """Minimal NATS client protocol implementation (docs.nats.io client
    protocol): text control lines + binary payloads over one TCP stream."""

    def __init__(self, uri: str, *, name: str = "pathway", timeout: float = 10.0):
        host, port = _parse_uri(uri)
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(timeout)
        self._buf = bytearray()
        self._lock = _lockgraph.register_lock(
            "io.nats_writer", threading.Lock()
        )
        self.server_info: dict = {}
        self._handshake(name)

    # ------------------------------------------------------------ protocol

    def _handshake(self, name: str) -> None:
        line = self._read_line()
        if not line.startswith(b"INFO "):
            raise NatsError(f"expected INFO, got {line[:40]!r}")
        self.server_info = _json.loads(line[5:].decode())
        connect = {
            "verbose": False,
            "pedantic": False,
            "tls_required": False,
            "name": name,
            "lang": "python",
            "version": "0",
            "protocol": 1,
            "headers": True,
        }
        self._send(b"CONNECT " + _json.dumps(connect).encode() + b"\r\n")

    def _send(self, data: bytes) -> None:
        with self._lock:
            self.sock.sendall(data)

    def _fill(self) -> None:
        chunk = self.sock.recv(65536)
        if not chunk:
            raise ConnectionError("NATS server closed the connection")
        self._buf.extend(chunk)

    def _read_line(self) -> bytes:
        while True:
            idx = self._buf.find(b"\r\n")
            if idx >= 0:
                line = bytes(self._buf[:idx])
                del self._buf[: idx + 2]
                return line
            self._fill()

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:  # payload + trailing CRLF
            self._fill()
        data = bytes(self._buf[:n])
        del self._buf[: n + 2]
        return data

    # --------------------------------------------------------- client ops

    def subscribe(self, subject: str, sid: str = "1", queue_group: str | None = None) -> None:
        if queue_group:
            self._send(f"SUB {subject} {queue_group} {sid}\r\n".encode())
        else:
            self._send(f"SUB {subject} {sid}\r\n".encode())

    def publish(
        self, subject: str, payload: bytes, headers: dict[str, str] | None = None
    ) -> None:
        if headers:
            hdr = b"NATS/1.0\r\n" + b"".join(
                f"{k}: {v}\r\n".encode() for k, v in headers.items()
            ) + b"\r\n"
            self._send(
                f"HPUB {subject} {len(hdr)} {len(hdr) + len(payload)}\r\n".encode()
                + hdr + payload + b"\r\n"
            )
        else:
            self._send(
                f"PUB {subject} {len(payload)}\r\n".encode() + payload + b"\r\n"
            )

    def next_message(self) -> tuple[str, bytes, dict[str, str]] | None:
        """Blocks for the next MSG/HMSG; answers PING transparently and
        keeps idle connections alive with client-side PINGs (a quiet
        subject must not read as a disconnect). Returns (subject, payload,
        headers) or None on control lines / keepalive rounds."""
        try:
            line = self._read_line()
        except socket.timeout:
            # idle socket: probe the server; two unanswered probes in a
            # row mean the connection is actually gone
            self._idle_probes = getattr(self, "_idle_probes", 0) + 1
            if self._idle_probes > 2:
                raise ConnectionError("NATS server unresponsive to PING") from None
            self._send(b"PING\r\n")
            return None
        self._idle_probes = 0
        if line == b"PING":
            self._send(b"PONG\r\n")
            return None
        if line in (b"PONG", b"+OK"):
            return None
        if line.startswith(b"-ERR"):
            raise NatsError(line.decode(errors="replace"))
        if line.startswith(b"MSG "):
            parts = line.decode().split(" ")
            # MSG <subject> <sid> [reply-to] <#bytes>
            subject, n = parts[1], int(parts[-1])
            return subject, self._read_exact(n), {}
        if line.startswith(b"HMSG "):
            parts = line.decode().split(" ")
            # HMSG <subject> <sid> [reply-to] <#hdr> <#total>
            subject, hn, total = parts[1], int(parts[-2]), int(parts[-1])
            blob = self._read_exact(total)
            headers: dict[str, str] = {}
            for hline in blob[:hn].split(b"\r\n")[1:]:
                if b":" in hline:
                    k, _, v = hline.decode(errors="replace").partition(":")
                    headers[k.strip()] = v.strip()
            return subject, blob[hn:], headers
        raise NatsError(f"unexpected protocol line {line[:60]!r}")

    def flush(self) -> None:
        self._send(b"PING\r\n")

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError as e:
            log_degradation(logger, "nats.socket_close", e, logging.DEBUG)


# -------------------------------------------------------------------- read


def read(
    uri: str,
    topic: str,
    *,
    schema: Any = None,
    format: str = "raw",  # noqa: A002
    autocommit_duration_ms: int | None = 1500,
    json_field_paths: dict[str, str] | None = None,
    parallel_readers: int | None = None,
    queue_group: str | None = None,
    persistent_id: str | None = None,
    name: str | None = None,
    terminate_on_disconnect: bool = False,
    debug_data: Any = None,
) -> Any:
    """Reads a NATS subject as a streaming table.

    Formats: 'raw' (bytes `data` column), 'plaintext' (utf-8 `data`),
    'json' (columns from `schema`, with optional `json_field_paths`
    dot-paths). `queue_group` joins a NATS queue group so parallel
    processes split the subject's traffic (the partitioned-reader shape).
    `terminate_on_disconnect` ends the stream when the server closes the
    connection instead of reconnecting (bounded streams / tests).
    """
    from pathway_tpu.io.python import ConnectorSubject
    from pathway_tpu.io.python import read as python_read

    if format == "json":
        if schema is None:
            raise ValueError("pw.io.nats.read(format='json') requires a schema")
    else:
        schema = sch.schema_from_types(data=bytes if format == "raw" else str)
    columns = list(schema.__columns__)
    paths = {
        col: [p for p in path.lstrip("/").replace("/", ".").split(".") if p]
        for col, path in (json_field_paths or {}).items()
    }

    class NatsSubject(ConnectorSubject):
        def run(self) -> None:
            # unified reconnect policy (same 0.2s->5s exponential timings
            # the old hand-rolled loop used, now capped, jittered, and
            # fault-injectable at io.retry.{name}); max_attempts=None:
            # a streaming subject reconnects forever
            policy = RetryPolicy(
                name or f"nats:{topic}",
                max_attempts=None,
                initial_delay_ms=200,
                backoff_factor=2.0,
                max_delay_ms=5_000,
                jitter_ms=100,
                breaker_threshold=None,
            )
            delays = policy.backoffs()
            while True:
                try:
                    faults.check(f"io.retry.{policy.name}")
                    conn = NatsConnection(uri, name=name or "pathway-reader")
                    conn.subscribe(topic, queue_group=queue_group)
                    delays = policy.backoffs()  # connected: reset backoff
                    while True:
                        msg = conn.next_message()
                        if msg is None:
                            continue
                        _subject, payload, _headers = msg
                        self._deliver(payload)
                except (ConnectionError, socket.timeout, OSError):
                    if terminate_on_disconnect:
                        return
                    _time.sleep(next(delays))

        def _deliver(self, payload: bytes) -> None:
            if format == "raw":
                self.next(data=payload)
            elif format == "plaintext":
                self.next(data=payload.decode("utf-8", errors="replace"))
            else:
                try:
                    doc = _json.loads(payload)
                except ValueError:
                    return  # unparsable message: skip (reference logs + skips)
                row = {}
                for col in columns:
                    node: Any = doc
                    for part in paths.get(col, [col]):
                        node = node.get(part) if isinstance(node, dict) else None
                    row[col] = node
                self.next(**row)

    return python_read(
        NatsSubject(),
        schema=schema,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"nats:{topic}",
        replay_style="live",  # a subject delivers new messages only
    )


# ------------------------------------------------------------------- write


def write(
    table: Any,
    uri: str,
    topic: str,
    *,
    format: str = "json",  # noqa: A002
    delimiter: str = ",",
    value: Any = None,
    headers: Iterable[Any] | None = None,
) -> None:
    """Publishes table updates to a NATS subject with pathway_time /
    pathway_diff headers (one message per row update)."""
    names = table._column_names()
    header_cols = [h.name for h in headers] if headers else []
    value_idx = 0
    if format in ("plaintext", "raw"):
        if value is not None:
            value_idx = names.index(value.name)
        elif len(names) != 1:
            raise ValueError(
                f"pw.io.nats.write(format={format!r}) needs `value` when the "
                "table has more than one column"
            )
    state: dict[str, Any] = {"conn": None}

    def _conn() -> NatsConnection:
        if state["conn"] is None:
            state["conn"] = NatsConnection(uri, name="pathway-writer")
        return state["conn"]

    def _write(time: int, entries: list, ids: list | None = None) -> None:
        conn = _conn()
        try:
            for i, (_key, row, diff) in enumerate(entries):
                hdr = {"pathway_time": str(time), "pathway_diff": str(diff)}
                if ids is not None:
                    # exactly-once replay safety (io/outbox.py): stable
                    # per-record content key for consumer-side dedup
                    hdr["pathway_msg_id"] = str(ids[i])
                for col in header_cols:
                    hdr[col] = str(row[names.index(col)])
                if format == "json":
                    payload = Json.dumps(dict(zip(names, row))).encode()
                elif format == "dsv":
                    payload = delimiter.join(str(v) for v in row).encode()
                elif format == "plaintext":
                    payload = str(row[value_idx]).encode()
                elif format == "raw":
                    v = row[value_idx]
                    payload = v if isinstance(v, bytes) else str(v).encode()
                else:
                    raise ValueError(f"unsupported NATS output format {format!r}")
                conn.publish(topic, payload, headers=hdr)
        except (ConnectionError, OSError):
            state["conn"] = None  # reconnect next batch; OutputNode retries
            raise

    def close() -> None:
        if state["conn"] is not None:
            state["conn"].close()

    G.add_sink(
        "output", table,
        write_batch=lambda time, entries: _write(time, entries),
        write_keyed=_write,
        close=close,
    )


__all__ = ["read", "write", "NatsConnection", "NatsError"]
