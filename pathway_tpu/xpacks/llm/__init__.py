"""pw.xpacks.llm — the RAG stack: embedders, chats, splitters, parsers,
rerankers, DocumentStore, QA pipelines, REST servers.

Reference parity: python/pathway/xpacks/llm/ (SURVEY.md §2.4). The local
model paths run on TPU via pathway_tpu.models instead of torch.
"""

from pathway_tpu.xpacks.llm import (
    embedders,
    llms,
    parsers,
    prompts,
    question_answering,
    rerankers,
    servers,
    splitters,
    vector_store,
)
from pathway_tpu.xpacks.llm.document_store import DocumentStore, SlidesDocumentStore

__all__ = [
    "embedders",
    "llms",
    "parsers",
    "prompts",
    "question_answering",
    "rerankers",
    "servers",
    "splitters",
    "vector_store",
    "DocumentStore",
    "SlidesDocumentStore",
]
