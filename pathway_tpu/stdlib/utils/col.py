"""Column utilities (reference: stdlib/utils/col.py:367 unpack_col etc.)."""

from __future__ import annotations

from typing import Any

import pathway_tpu.internals.expression as ex
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table


def unpack_col(
    column: ex.ColumnReference, *unpacked_columns: Any, schema: Any = None
) -> Table:
    """Unpack a tuple column into separate columns."""
    table: Table = column.table
    if schema is not None:
        names = list(schema.__columns__)
    else:
        names = [
            c.name if isinstance(c, ex.ColumnReference) else str(c)
            for c in unpacked_columns
        ]
    kwargs = {name: column[i] for i, name in enumerate(names)}
    return table.select(**kwargs)


def flatten_column(column: ex.ColumnReference, origin_id: str = "origin_id") -> Table:
    table: Table = column.table
    flat = table.flatten(column)
    return flat


def multiapply_all_rows(*args: Any, **kwargs: Any) -> Any:
    raise NotImplementedError("multiapply_all_rows is not yet implemented")


def apply_all_rows(*args: Any, **kwargs: Any) -> Any:
    raise NotImplementedError("apply_all_rows is not yet implemented")


def groupby_reduce_majority(column: ex.ColumnReference, value_column: ex.ColumnReference) -> Table:
    import pathway_tpu.internals.reducers as red

    table: Table = column.table
    counted = table.groupby(column, value_column).reduce(
        column, value_column, cnt=red.count()
    )
    return counted.groupby(counted[column.name]).reduce(
        counted[column.name],
        majority=red.argmax(counted["cnt"]),
    )
