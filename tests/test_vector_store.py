"""VectorStoreServer: plain-callable components, LangChain/LlamaIndex
adapter classmethods (duck-typed, no heavy deps needed for the embedding
path), the slides variant's metadata redaction, and client validation.
Reference: xpacks/llm/vector_store.py:38,92,136,566,629."""

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.xpacks.llm.mocks import fake_embeddings_model
from pathway_tpu.xpacks.llm.vector_store import (
    SlidesVectorStoreServer,
    VectorStoreClient,
    VectorStoreServer,
)

DIM = 12


def _docs():
    return pw.debug.table_from_rows(
        pw.schema_from_types(data=bytes, _metadata=object),
        [
            (b"quick brown fox", {"path": "a.txt", "b64_image": "XXXX"}),
            (b"stream processing engine", {"path": "b.txt", "b64_image": "YYYY"}),
        ],
    )


def _retrieve(server, query="quick brown fox", k=1):
    queries = pw.debug.table_from_rows(
        VectorStoreServer.RetrieveQuerySchema, [(query, k, None, None)]
    )
    df = pw.debug.table_to_pandas(
        server.retrieve_query(queries), include_id=False
    )
    (res,) = [
        r.result.value if hasattr(r.result, "value") else r.result
        for r in df.itertuples()
    ]
    return res


def test_plain_sync_callable_embedder():
    server = VectorStoreServer(
        _docs(), embedder=lambda x: fake_embeddings_model(x, DIM)
    )
    top = _retrieve(server)
    assert top[0]["text"] == "quick brown fox"


def test_plain_async_callable_embedder():
    async def embed(x: str):
        return fake_embeddings_model(x, DIM)

    server = VectorStoreServer(_docs(), embedder=embed)
    top = _retrieve(server, "stream processing engine")
    assert top[0]["text"] == "stream processing engine"


class _FakeLangchainEmbedder:
    """Duck-typed langchain Embeddings: aembed_documents(list) -> list."""

    async def aembed_documents(self, texts):
        return [fake_embeddings_model(t, DIM).tolist() for t in texts]


def test_from_langchain_components_embedding_only():
    server = VectorStoreServer.from_langchain_components(
        _docs(), embedder=_FakeLangchainEmbedder()
    )
    top = _retrieve(server)
    assert top[0]["text"] == "quick brown fox"


class _FakeLlamaEmbedding:
    async def aget_text_embedding(self, text):
        return fake_embeddings_model(text, DIM).tolist()


def test_from_llamaindex_components_embedding_only():
    server = VectorStoreServer.from_llamaindex_components(
        _docs(), transformations=[_FakeLlamaEmbedding()]
    )
    top = _retrieve(server, "stream processing engine")
    assert top[0]["text"] == "stream processing engine"


def test_from_llamaindex_rejects_non_embedder_tail():
    with pytest.raises(ValueError, match="embedding"):
        VectorStoreServer.from_llamaindex_components(
            _docs(), transformations=[object()]
        )
    with pytest.raises(ValueError, match="empty"):
        VectorStoreServer.from_llamaindex_components(_docs(), transformations=[])


def test_slides_server_redacts_metadata():
    server = SlidesVectorStoreServer(
        _docs(), embedder=lambda x: fake_embeddings_model(x, DIM)
    )
    queries = pw.debug.table_from_rows(
        VectorStoreServer.InputsQuerySchema, [(None, None)]
    )
    df = pw.debug.table_to_pandas(
        server.inputs_query(queries), include_id=False
    )
    (res,) = [
        r.result.value if hasattr(r.result, "value") else r.result
        for r in df.itertuples()
    ]
    assert {m["path"] for m in res} == {"a.txt", "b.txt"}
    assert all("b64_image" not in m for m in res)
    # parsed_documents_query mirrors the same listing
    df2 = pw.debug.table_to_pandas(
        server.parsed_documents_query(
            pw.debug.table_from_rows(
                VectorStoreServer.InputsQuerySchema, [(None, None)]
            )
        ),
        include_id=False,
    )
    assert len(df2) == 1


def test_slides_redaction_does_not_mutate_store():
    """Redaction must copy: the listed dicts are the store's live
    metadata objects."""
    server = SlidesVectorStoreServer(
        _docs(), embedder=lambda x: fake_embeddings_model(x, DIM)
    )
    queries = pw.debug.table_from_rows(
        VectorStoreServer.InputsQuerySchema, [(None, None)]
    )
    pw.debug.table_to_pandas(server.inputs_query(queries), include_id=False)
    # list again through the UNREDACTED base listing: images must survive
    df = pw.debug.table_to_pandas(
        server.document_store.inputs_query(
            pw.debug.table_from_rows(
                VectorStoreServer.InputsQuerySchema, [(None, None)]
            )
        ),
        include_id=False,
    )
    (res,) = [
        r.result.value if hasattr(r.result, "value") else r.result
        for r in df.itertuples()
    ]
    assert all("b64_image" in m for m in res)


def test_slides_redaction_served_over_rest():
    """run_server must register the SUBCLASS endpoints — the redacted
    inputs listing is what REST clients get."""
    import socket
    import threading
    import time

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    server = SlidesVectorStoreServer(
        _docs(), embedder=lambda x: fake_embeddings_model(x, DIM)
    )
    threading.Thread(
        target=lambda: server.run_server(
            host="127.0.0.1", port=port, with_cache=False
        ),
        daemon=True,
    ).start()
    client = VectorStoreClient(host="127.0.0.1", port=port, timeout=5)
    files = None
    for _ in range(60):
        time.sleep(0.25)
        try:
            files = client.get_input_files()
            break
        except Exception:
            continue
    assert files is not None, "server did not come up"
    assert {m["path"] for m in files} == {"a.txt", "b.txt"}
    assert all("b64_image" not in m for m in files)


def test_async_splitter_rejected_early():
    async def split(text):
        return [(text, {})]

    with pytest.raises(ValueError, match="synchronous"):
        VectorStoreServer(
            _docs(),
            embedder=lambda x: fake_embeddings_model(x, DIM),
            splitter=split,
        )


def test_embedding_dimension_probe():
    server = VectorStoreServer(
        _docs(), embedder=lambda x: np.zeros(7, np.float32)
    )
    assert server.embedder.get_embedding_dimension() == 7


def test_client_arg_validation():
    with pytest.raises(ValueError):
        VectorStoreClient(host="h", port=1, url="http://x")
    with pytest.raises(ValueError):
        VectorStoreClient()
    c = VectorStoreClient(url="http://example:123", additional_headers={"X-K": "v"})
    assert c.url == "http://example:123"
    assert c.additional_headers == {"X-K": "v"}
    # default port matches run_server's 8000
    assert VectorStoreClient(host="h").url == "http://h:8000"
