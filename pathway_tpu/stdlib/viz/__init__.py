"""pw.Table.show / .plot — notebook visualization (reference:
stdlib/viz/{table_viz,plotting}.py, panel/bokeh-backed).

The reference renders through `panel`; here `show` works with no extra
dependency: bounded tables compute a static HTML preview immediately,
tables with live sources get a LiveTable-backed view whose
`_repr_html_` snapshots the current state each render. `plot` needs
bokeh and fails with a clear ImportError without it.
"""

from pathway_tpu.stdlib.viz.plotting import PlotHandle, plot
from pathway_tpu.stdlib.viz.table_viz import TableView, _has_connectors, show

from pathway_tpu.internals.table import Table


def _table_repr_html(self: Table) -> str:
    # a bare `t` at a notebook prompt: bounded tables preview inline;
    # streaming ones must not silently start (and leak) a background run
    # per render — point at .show() instead
    if _has_connectors(self):
        return (
            "<em>streaming table — call <code>.show()</code> for a live "
            "view (and <code>.stop()</code> it when done)</em>"
        )
    return show(self)._repr_html_()


# attach like the reference does (viz/__init__ patches pw.Table)
Table.show = show  # type: ignore[attr-defined]
Table.plot = plot  # type: ignore[attr-defined]
Table._repr_html_ = _table_repr_html  # type: ignore[attr-defined]

__all__ = ["plot", "show", "TableView", "PlotHandle"]
