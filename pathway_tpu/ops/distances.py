"""Pairwise distance kernels.

Reference parity: the numpy row-wise distance functions in
`/root/reference/python/pathway/stdlib/ml/classifiers/_knn_lsh.py:50-57`
(`np.linalg.norm(data - x, axis=1)` per query) and usearch's cos/l2 metrics
(`/root/reference/src/external_integration/usearch_integration.rs:20`).

TPU-first design: all metrics are expressed as ONE `queries @ docs.T` matmul
plus cheap elementwise corrections, so the MXU does the work and XLA fuses
the rest. Inputs are promoted to bf16 for the matmul with f32 accumulation
(`preferred_element_type`), which is the native MXU mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

Array = jax.Array


def normalize(x: Array, eps: float = 1e-12) -> Array:
    """L2-normalize rows."""
    norm = jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2, axis=-1, keepdims=True))
    return (x / jnp.maximum(norm, eps)).astype(x.dtype)


def dot_products(queries: Array, docs: Array) -> Array:
    """[q, d] x [n, d] -> [q, n] inner products.

    Contracts docs on its last axis directly (no `.T` — a materialized
    transpose of a 1M-row doc matrix would cost more than the matmul).
    """
    return jax.lax.dot_general(
        queries.astype(jnp.bfloat16),
        docs.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def cosine_distances(queries: Array, docs: Array, *, normalized: bool = False) -> Array:
    """Cosine distance (1 - cos similarity), [q, n].

    `normalized=True` promises the DOC matrix rows are unit-norm (index
    serving layout — normalizing 1M docs per call would dominate the
    search). Queries are small and always normalized here.
    """
    qn = normalize(queries.astype(jnp.float32))
    dn = docs if normalized else normalize(docs.astype(jnp.float32))
    return 1.0 - dot_products(qn, dn)


def l2_distances(queries: Array, docs: Array) -> Array:
    """Squared euclidean distance via the ||q||² - 2q·d + ||d||² expansion,

    which turns the O(q·n·d) distance grid into a single MXU matmul plus two
    rank-1 corrections instead of materializing q×n×d differences.
    """
    q32 = queries.astype(jnp.float32)
    d32 = docs.astype(jnp.float32)
    qq = jnp.sum(q32 * q32, axis=-1, keepdims=True)  # [q, 1]
    dd = jnp.sum(d32 * d32, axis=-1)  # [n]
    qd = dot_products(queries, docs)  # [q, n]
    return jnp.maximum(qq - 2.0 * qd + dd[None, :], 0.0)


METRICS = {
    "cos": cosine_distances,
    "cosine": cosine_distances,
    "l2": l2_distances,
    "l2sq": l2_distances,
    "dot": lambda q, d, **_: -dot_products(q, d),  # distance = -similarity
}


@functools.lru_cache(maxsize=None)
def metric_fn(name: str):
    try:
        return METRICS[name]
    except KeyError:
        raise ValueError(
            f"unknown metric {name!r}; expected one of {sorted(METRICS)}"
        ) from None
