"""pw.io: connector families (reference: python/pathway/io/, 28 families).

Local/file and Python-subject connectors are fully native here; external
service connectors (kafka, postgres, s3, ...) are present with the same
API surface and fail at use-time if their client library is missing
(nothing is bundled in this image — the wire protocols are gated, the
descriptor/api layer is real).
"""

from pathway_tpu.io import csv, fs, jsonlines, null, outbox, plaintext, python
from pathway_tpu.io._retry import CircuitOpen, RetryPolicy
from pathway_tpu.io._subscribe import subscribe

# service-backed families (gated on their client libs)
from pathway_tpu.io import (  # noqa: E402
    airbyte,
    bigquery,
    debezium,
    deltalake,
    elasticsearch,
    gdrive,
    http,
    kafka,
    logstash,
    minio,
    mongodb,
    nats,
    postgres,
    pubsub,
    pyfilesystem,
    redpanda,
    s3,
    s3_csv,
    slack,
    sqlite,
)

__all__ = [
    "csv", "fs", "jsonlines", "null", "outbox", "plaintext", "python",
    "subscribe", "RetryPolicy", "CircuitOpen",
    "kafka", "redpanda", "s3", "s3_csv", "minio", "deltalake", "sqlite",
    "nats", "postgres", "elasticsearch", "mongodb", "debezium", "bigquery",
    "pubsub", "pyfilesystem", "logstash", "http", "gdrive", "slack", "airbyte",
]
