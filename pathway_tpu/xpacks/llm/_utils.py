"""Shared helpers of the LLM xpack (reference: xpacks/llm/_utils.py)."""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable


def _coerce_sync(fn: Callable) -> Callable:
    """Run an async callable synchronously (used for one-off introspection
    like get_embedding_dimension — reference _utils._coerce_fully_sync)."""
    if not asyncio.iscoroutinefunction(fn):
        return fn

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return asyncio.run(fn(*args, **kwargs))
        import concurrent.futures

        with concurrent.futures.ThreadPoolExecutor(max_workers=1) as pool:
            return pool.submit(asyncio.run, fn(*args, **kwargs)).result()

    return wrapper


def _check_model_accepts_arg(model_name: str, arg: str) -> bool:  # parity stub
    return True


def _extract_value(value: Any) -> Any:
    from pathway_tpu.internals.json import Json

    if isinstance(value, Json):
        return value.value
    return value
