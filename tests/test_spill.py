"""Out-of-core operator state (engine/spill.py): the LSM spill tier for
join/groupby arrangements. Unit mechanics (seal, the fence/bloom/disk
probe ladder, promotion tombstones, tiered compaction with mid-merge
replay, deferred GC), the exclusive-residency invariant, the manifest
tamper matrix (PlanVerificationError by name) vs file damage
(RuntimeError / one-epoch fallback, see test_persistence_matrix.py),
checkpoint+restore of a spilled arrangement, and A/B byte-identity:
a tiny resident budget must not change a single output byte vs
PATHWAY_SPILL=0 (docs/persistence.md §out-of-core)."""

from __future__ import annotations

import os

import pytest

import pathway_tpu as pw
from pathway_tpu.engine import spill
from pathway_tpu.internals.lowering import Session
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.verifier import PlanVerificationError
from pathway_tpu.persistence import codec


@pytest.fixture(autouse=True)
def _fresh(tmp_path):
    G.clear()
    saved = (spill._ROOT, spill._PERSISTENT)
    spill.set_root(str(tmp_path), persistent=True)
    yield
    G.clear()
    with spill._ROOT_LOCK:
        spill._ROOT, spill._PERSISTENT = saved


# ------------------------------------------------------- store mechanics


def test_seal_and_promote_roundtrip():
    store = spill.store_for("unit-a", budget=4)
    items = [
        (f"k{i:04d}".encode(), f"payload-{i}".encode() * 3)
        for i in range(300)  # > _SPARSE_EVERY: probes cross index windows
    ]
    assert store.seal(items) == 300
    assert store.has_runs and store.run_count == 1
    for i in range(0, 300, 7):
        kb, payload = items[i]
        assert store.take(kb) == payload
    assert store.promotions == 43
    # promotion marks the key dead in its run: exclusive residency means
    # the ladder must MISS it from now on (the tail owns it)
    assert store.take(b"k0007") is None
    assert store.take(b"never-sealed") is None


def test_compaction_merges_shadows_and_drops_dead():
    store = spill.store_for("unit-c", budget=4)
    store.seal([(b"a", b"pa1"), (b"b", b"pb")])
    assert store.take(b"a") == b"pa1"  # dead in run 1
    store.seal([(b"a", b"pa2")])  # re-spilled: newer run shadows run 1
    store.seal([(b"c", b"pc")])
    assert store.run_count == 3
    assert store.compact_once()
    assert store.run_count == 1
    assert store.take(b"b") == b"pb"
    assert store.take(b"a") == b"pa2"  # newest-run-first merge order
    assert store.take(b"c") == b"pc"


def test_compaction_all_dead_leaves_no_run():
    store = spill.store_for("unit-d", budget=4)
    store.seal([(b"x", b"p")])
    store.seal([(b"y", b"q")])
    store.take(b"x")
    store.take(b"y")
    assert store.compact_once()  # tombstone GC: nothing survives
    assert store.run_count == 0


def test_mid_merge_promotion_replayed_on_merged_run(monkeypatch):
    """A key promoted to the tail WHILE the merge is running (after the
    snapshot was cut) must not resurrect from the merged run: the swap
    replays the mid-merge dead set onto the new generation."""
    store = spill.store_for("unit-m", budget=4)
    store.seal([(b"a", b"pa"), (b"b", b"pb")])
    store.seal([(b"c", b"pc")])
    grabbed = {}
    real_crash = spill._faults.crash

    def crash_hook(kind):
        # the injection point sits exactly in the window: merged run
        # durable, generation swap not yet taken
        if kind == "state.compaction.mid_merge" and not grabbed:
            grabbed["a"] = store.take(b"a")
        return real_crash(kind)

    monkeypatch.setattr(spill._faults, "crash", crash_hook)
    assert store.compact_once()
    assert grabbed["a"] == b"pa"
    assert store.take(b"a") is None  # tail owns it; no resurrection
    assert store.take(b"b") == b"pb"
    spill.check_two_tier(store)


def test_deferred_gc_and_orphan_collection():
    store = spill.store_for("unit-g", budget=4)
    store.seal([(b"a", b"p")])
    store.seal([(b"b", b"q")])
    old_paths = [r.path for r in store.runs]
    assert store.compact_once()
    # persistent root: the last durable checkpoints' manifests may still
    # name the merged-away files — the unlink is deferred two ticks
    assert all(os.path.exists(p) for p in old_paths)
    assert store.collect_garbage() == 0
    assert store.collect_garbage() == 2
    assert not any(os.path.exists(p) for p in old_paths)
    # a stray half-merged run no generation references is an orphan
    stray = os.path.join(store.dir, "run-99999999.seg")
    with open(stray, "wb") as f:
        f.write(b"half-merged junk")
    assert store.gc_orphans() == 1
    assert not os.path.exists(stray)


def test_manifest_attach_roundtrip_preserves_dead_set():
    store = spill.store_for("unit-r", budget=4)
    items = [(f"k{i}".encode(), f"p{i}".encode()) for i in range(100)]
    store.seal(items[:60])
    store.seal(items[60:])
    assert store.take(b"k3") == b"p3"
    man = store.manifest()
    assert spill.is_manifest(man)
    assert man["n_runs"] == 2 and man["total_records"] == 100
    # the manifest round-trips through the snapshot codec unchanged
    man = codec.decode_value(codec.encode_value(man))
    back = spill.attach_store(man)
    assert back.run_count == 2
    assert back.take(b"k3") is None  # the tombstone survived restore
    for i in (10, 45, 75, 99):
        assert back.take(f"k{i}".encode()) == f"p{i}".encode()


# ------------------------------------------------- verification contract


def test_verify_manifest_tamper_matrix():
    store = spill.store_for("unit-v", budget=4)
    store.seal([(b"a", b"p"), (b"b", b"q")])
    store.seal([(b"c", b"r")])
    man = store.manifest()
    spill.verify_manifest(man)  # the honest manifest is clean

    bad = dict(man, n_runs=man["n_runs"] + 1)
    with pytest.raises(PlanVerificationError, match="missing from the manifest"):
        spill.verify_manifest(bad)

    bad = dict(man, total_records=man["total_records"] + 5)
    with pytest.raises(PlanVerificationError, match="missing from the manifest"):
        spill.verify_manifest(bad)

    bad = dict(man, runs=list(reversed(man["runs"])))
    with pytest.raises(PlanVerificationError, match="out of order"):
        spill.verify_manifest(bad)

    runs = [dict(man["runs"][0]), dict(man["runs"][1])]
    runs[0]["dead"] = [b"a", b"b", b"forged"]
    bad = dict(man, runs=runs)
    with pytest.raises(PlanVerificationError, match="more dead keys"):
        spill.verify_manifest(bad)

    bad = {k: v for k, v in man.items() if k != spill.MANIFEST_MARK}
    assert not spill.is_manifest(bad)
    with pytest.raises(PlanVerificationError, match="missing manifest marker"):
        spill.verify_manifest(bad)


def test_validate_manifest_files_damage_matrix():
    store = spill.store_for("unit-f", budget=4)
    store.seal([(f"k{i}".encode(), b"x" * 32) for i in range(20)])
    man = store.manifest()
    path = store.runs[0].path
    spill.validate_manifest_files(man)

    orig = open(path, "rb").read()
    # torn tail: crash mid-copy lost the last bytes
    with open(path, "wb") as f:
        f.write(orig[:-3])
    with pytest.raises(RuntimeError, match="torn segment"):
        spill.validate_manifest_files(man)
    # same length, last frame's crc no longer matches (bit rot)
    with open(path, "wb") as f:
        f.write(orig[:-4] + bytes(b ^ 0xFF for b in orig[-4:]))
    with pytest.raises(RuntimeError, match="torn segment tail"):
        spill.validate_manifest_files(man)
    # gone entirely
    os.unlink(path)
    with pytest.raises(RuntimeError, match="missing on disk"):
        spill.validate_manifest_files(man)


def test_check_two_tier_names_the_offending_tiers():
    store = spill.store_for("unit-t", budget=4)
    store.seal([(b"k", b"p1")])
    store.seal([(b"k", b"p2")])  # forged: one key live in two runs
    with pytest.raises(PlanVerificationError, match="live in runs"):
        spill.check_two_tier(store)

    store2 = spill.store_for("unit-t2", budget=4)
    store2.seal([(b"q", b"p")])
    store2.tail_keys = lambda: [b"q"]  # forged: live in tail AND a run
    with pytest.raises(PlanVerificationError, match="resident in the tail"):
        spill.check_two_tier(store2)


# --------------------------------------------- arrangement-level spill


def test_multiset_spill_promote_and_retract():
    from pathway_tpu.engine.core import (
        MultisetState,
        _spill_evict_multiset,
        freeze_value,
    )

    st = MultisetState()
    for i in range(20):
        st.update_one(f"g{i}", ("row", i), 1)
    store = spill.store_for("unit-ms", budget=5)

    def resolve(dkey):
        raw = store.take(codec.encode_value(dkey))
        if raw is None:
            return
        entries = codec.decode_value(raw)
        st.groups[dkey] = {freeze_value(p): (p, c) for p, c in entries}

    st.spill_attach(store, resolve)
    store.tail_keys = lambda: (codec.encode_value(k) for k in st.groups)

    def pack(dkey, group):
        return codec.encode_value(tuple(group.values()))

    n = _spill_evict_multiset(st, store, pack)
    assert n == 17 and store.has_runs  # coldest-first down to low water
    assert set(st.groups) == {"g17", "g18", "g19"}
    spill.check_two_tier(store)
    # read miss promotes through the resolve hook, payload intact
    assert st.get("g2") == [(("row", 2), 1)]
    assert "g2" in st.groups
    # a retraction against a spilled group promotes then folds to zero
    st.update_one("g7", ("row", 7), -1)
    assert "g7" not in st.groups
    assert store.take(codec.encode_value("g7")) is None
    spill.check_two_tier(store)


# ---------------------------------------------------- pipeline A/B + CI


def _capture(build, env: dict):
    """Run the pipeline with env overlaid; return (rows, sealed runs)."""
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        G.clear()
        s = Session()
        cap = s.capture(build())
        s.execute()
        runs = sum(
            st.run_count
            for n in s.graph.nodes
            for st in getattr(n, "spill_stores", list)()
        )
        return {tuple(r) for r in cap.state.rows.values()}, runs
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _groupby_build():
    rows = [(f"g{i % 7}", i) for i in range(40)]
    return (
        pw.debug.table_from_rows(pw.schema_from_types(g=str, v=int), rows)
        .groupby(pw.this.g)
        .reduce(
            g=pw.this.g,
            s=pw.reducers.sum(pw.this.v),
            m=pw.reducers.max(pw.this.v),  # non-native: MultisetState path
        )
    )


def _join_build():
    left = pw.debug.table_from_rows(
        pw.schema_from_types(k=int, lv=str),
        [(i % 11, f"l{i}") for i in range(50)],
    )
    right = pw.debug.table_from_rows(
        pw.schema_from_types(k=int, rv=str),
        [(i % 7, f"r{i}") for i in range(30)],
    )
    return left.join(right, left.k == right.k).select(
        left.k, left.lv, right.rv
    )


def test_groupby_spill_ab_byte_identical():
    on, runs_on = _capture(
        _groupby_build, {"PATHWAY_SPILL": "1", "PATHWAY_SPILL_BUDGET": "2"}
    )
    off, runs_off = _capture(_groupby_build, {"PATHWAY_SPILL": "0"})
    assert runs_on > 0, "a 2-group budget over 7 groups must seal runs"
    assert runs_off == 0
    assert on == off


def test_join_spill_ab_byte_identical():
    on, runs_on = _capture(
        _join_build, {"PATHWAY_SPILL": "1", "PATHWAY_SPILL_BUDGET": "2"}
    )
    off, runs_off = _capture(_join_build, {"PATHWAY_SPILL": "0"})
    assert runs_on > 0, "a 2-group budget over 11 join keys must seal runs"
    assert runs_off == 0
    assert on == off


def test_default_budget_stays_resident():
    """PATHWAY_SPILL=1 is the default, but with the default budget an
    all-resident pipeline must seal ZERO runs — the spill tier is
    byte-invisible until state actually outgrows RAM."""
    rows, runs = _capture(_groupby_build, {})
    assert runs == 0 and rows


def test_checkpoint_restore_spilled_arrangement(tmp_path, monkeypatch):
    """A checkpoint of a spilled arrangement is (manifest + tail); the
    restored node must serve the same bytes, promoting restored runs
    through the rebuilt sparse index on first touch."""
    from pathway_tpu.persistence import Backend, CheckpointManager, Config

    root = str(tmp_path / "ckpt")
    s = Session()
    cap1 = s.capture(_groupby_build())
    s.execute()
    m = CheckpointManager(s, Config(Backend.filesystem(root)))
    node = next(n for n in s.graph.nodes if hasattr(n, "_maybe_spill"))
    monkeypatch.setenv("PATHWAY_SPILL", "1")
    monkeypatch.setenv("PATHWAY_SPILL_BUDGET", "1")
    node._maybe_spill()
    assert node._spill is not None and node._spill.has_runs
    m.checkpoint(finalized_time=10)
    want = {tuple(r) for r in cap1.state.rows.values()}

    G.clear()
    s2 = Session()
    cap2 = s2.capture(_groupby_build())
    m2 = CheckpointManager(s2, Config(Backend.filesystem(root)))
    m2.restore()
    assert m2.restored
    assert {tuple(r) for r in cap2.state.rows.values()} == want
    node2 = next(n for n in s2.graph.nodes if hasattr(n, "_maybe_spill"))
    store = node2._spill
    assert store is not None and store.has_runs
    spill.check_two_tier(store, "restored reduce")
    # promotion off a restored run: the index rebuilds from one read
    run = store.runs[0]
    kb = next(
        k for (_o, _h, k, _p) in store._read_run(run) if k not in run.dead
    )
    assert store.take(kb) is not None


def test_verify_session_proves_spill_contract(monkeypatch):
    from pathway_tpu.internals import verifier

    monkeypatch.setenv("PATHWAY_SPILL", "1")
    monkeypatch.setenv("PATHWAY_SPILL_BUDGET", "2")
    G.clear()
    s = Session()
    s.capture(_groupby_build())
    s.execute()
    rep = verifier.verify_session(s)
    assert rep["checks"]["spill-contract"]["stores"] >= 1

    node = next(n for n in s.graph.nodes if hasattr(n, "_maybe_spill"))
    store = node._spill
    assert store is not None and store.has_runs
    store.seal([(b"forged", b"p1")])
    store.seal([(b"forged", b"p2")])  # violates exclusive residency
    with pytest.raises(PlanVerificationError, match="spill-two-tier"):
        verifier.verify_session(s)


# ------------------------------------------- manifest-level rescale moves
#
# Elastic rebalance (parallel/membership.py) re-homes spilled state as
# METADATA: split/merge of manifests plus hardlinks of the immutable run
# files. The spilled arrangement must never force a journal-replay
# fallback just because its state lives on disk.


def _sealed_store(label: str, n_runs: int = 3, per: int = 40):
    store = spill.store_for(label, budget=4)
    items = {}
    for r in range(n_runs):
        batch = [
            (f"{label}-k{r:02d}{i:04d}".encode(), f"p{r}-{i}".encode() * 2)
            for i in range(per)
        ]
        store.seal(batch)
        items.update(batch)
    return store, items


def _disk_run_files():
    base, _ = spill.root()
    out = []
    for dp, _dirs, files in os.walk(base):
        out.extend(os.path.join(dp, f) for f in files)
    return sorted(out)


def test_split_manifest_is_a_metadata_move():
    """1 -> n: every shard inherits the full run list as shared runs;
    nothing on disk is copied or rewritten, and each shard store still
    serves every byte."""
    store, items = _sealed_store("resc-split")
    man = store.manifest()
    before = _disk_run_files()
    parts = spill.split_manifest(man, 3)
    assert _disk_run_files() == before  # pure metadata: zero file churn
    assert len(parts) == 3
    dirs = set()
    for p in parts:
        spill.verify_manifest(p)
        dirs.add(p["dir"])
        assert all(rm.get("shared") == 1 for rm in p["runs"])
        s = spill.attach_store(p)
        for kb, payload in list(items.items())[::13]:
            assert s.take(kb) == payload
    assert len(dirs) == 3  # fresh private dirs for post-split seals


def test_merge_manifests_dedupes_split_siblings():
    """n -> 1: split siblings share physical runs; the merge dedupes by
    (dir, file), unions dead sets, and the merged store owns its runs
    privately again (compaction/GC reopen)."""
    store, items = _sealed_store("resc-merge")
    man = store.manifest()
    n_runs = len(man["runs"])
    parts = spill.split_manifest(man, 3)
    merged = spill.merge_manifests(parts)
    spill.verify_manifest(merged)
    assert len(merged["runs"]) == n_runs  # shared siblings folded back
    assert all(not rm.get("shared") for rm in merged["runs"])
    s = spill.attach_store(merged)
    for kb, payload in list(items.items())[::7]:
        assert s.take(kb) == payload
    assert s.compact_once()  # private again: compaction is legal


def test_merged_seq_counter_clears_inherited_file_names():
    """Run FILES keep their original seq-derived names across a merge,
    so the merged store's next-seal counter must start past every
    inherited seq — a fresh seal colliding with an inherited file would
    silently shadow sealed bytes."""
    store, _items = _sealed_store("resc-seq", n_runs=5, per=10)
    man = store.manifest()
    merged = spill.merge_manifests([man])
    assert merged["seq"] >= max(int(rm["seq"]) for rm in man["runs"])
    s = spill.attach_store(merged)
    inherited = {str(rm["file"]) for rm in merged["runs"]}
    s.seal([(b"post-merge-key", b"post-merge-payload")])
    newest = s.manifest()["runs"][-1]
    assert str(newest["file"]) not in inherited
    assert s.take(b"post-merge-key") == b"post-merge-payload"


def test_relocate_manifest_hardlinks_run_files(tmp_path):
    """Cross-root rebalance: run files materialize under the new root at
    the same relative layout; same inode where the fs allows links."""
    store, items = _sealed_store("resc-reloc", n_runs=2, per=15)
    man = store.manifest()
    src_root, _ = spill.root()
    dst_root = str(tmp_path / "new-proc-spill")
    moved, nbytes = spill.relocate_manifest(man, src_root, dst_root)
    assert moved == len(man["runs"]) and nbytes > 0
    for rm in man["runs"]:
        rd = str(rm.get("dir") or "") or str(man["dir"])
        src = os.path.join(src_root, rd, str(rm["file"]))
        dst = os.path.join(dst_root, rd, str(rm["file"]))
        assert os.path.exists(dst)
        assert os.stat(dst).st_size == os.stat(src).st_size


def test_spilled_groupby_state_splits_without_refusal():
    """The PR's headline regression: a groupby whose arrangement has
    SPILLED must still split/merge its shard state (manifest moves), not
    raise RescaleUnsupported and force whole-journal replay."""
    import os as _os

    _os.environ["PATHWAY_SPILL"] = "1"
    _os.environ["PATHWAY_SPILL_BUDGET"] = "1"
    try:
        G.clear()
        s = Session()
        s.capture(_groupby_build())
        s.execute()
        node = next(n for n in s.graph.nodes if hasattr(n, "_maybe_spill"))
        node._maybe_spill()
        assert node._spill is not None and node._spill.has_runs
        st = node.persist_state()
        blob = codec.encode_record(st, with_magic=True)  # codec-clean
        st = next(codec.read_records(blob, with_magic=True))
        parts = node.split_shard_state(st, 2, lambda tok: hash(tok) % 2)
        assert len(parts) == 2
        manifests = [
            m for p in parts for m in _manifests_in(p)
        ]
        assert manifests, "split states must carry the spill manifests"
        merged = node.merge_shard_states(parts)
        assert _manifests_in(merged)
    finally:
        _os.environ.pop("PATHWAY_SPILL", None)
        _os.environ.pop("PATHWAY_SPILL_BUDGET", None)
        G.clear()


def _manifests_in(v):
    found = []
    if spill.is_manifest(v):
        return [v]
    if isinstance(v, dict):
        for x in v.values():
            found.extend(_manifests_in(x))
    elif isinstance(v, (list, tuple)):
        for x in v:
            found.extend(_manifests_in(x))
    return found
