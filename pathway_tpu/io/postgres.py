"""pw.io.postgres — API-parity connector (reference: io/postgres).

Client library gated: see io/_external.py.
"""

from pathway_tpu.io._external import gated_reader, gated_writer

read = gated_reader("postgres", "psycopg2")
write = gated_writer("postgres", "psycopg2")
