"""Plan-optimizer equivalence matrix (internals/planner.py,
docs/planner.md).

Pins: fused plans (PATHWAY_FUSE default-on: chain fusion, scan/join
pushdowns, id elision) produce BYTE-IDENTICAL outputs to the unoptimized
plans (PATHWAY_FUSE=0) — across native/object planes, under retractions,
inside pw.iterate scopes, and through a persistence roundtrip — plus the
structural guards: fused plans strictly reduce node/wave counts, the
cheap-key C/Python mirrors agree bit-for-bit, and the id-observability
analysis vetoes exactly when ids are observable.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import planner

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _native_available() -> bool:
    try:
        from pathway_tpu.engine.native import dataplane as dp

        return dp.available()
    except Exception:  # noqa: BLE001
        return False


def _with_env(monkeypatch, **env):
    # default the optimizer ON unless a leg says otherwise — the
    # fusion-off CI leg exports PATHWAY_FUSE=0 process-wide, and these
    # tests pin BOTH sides themselves
    env.setdefault("PATHWAY_FUSE", None)
    for k, v in env.items():
        if v is None:
            monkeypatch.delenv(k, raising=False)
        else:
            monkeypatch.setenv(k, v)


def _chain_pipeline(tmp_path, out_name: str):
    """map -> filter -> map -> groupby over a native jsonl scan."""
    inp = tmp_path / "chain_in.jsonl"
    if not inp.exists():
        with open(inp, "w") as f:
            for i in range(4000):
                f.write('{"k": "g%d", "v": %d}\n' % (i % 11, i))

    class S(pw.Schema):
        k: str
        v: int

    t = pw.io.fs.read(os.fspath(inp), format="json", schema=S, mode="static")
    t2 = t.select(k=pw.this.k, w=pw.this.v * 3 + 1)
    t3 = t2.filter(pw.this.w % 5 != 0)
    t4 = t3.select(k=pw.this.k, w=pw.this.w - 1)
    res = t4.groupby(t4.k).reduce(
        t4.k, total=pw.reducers.sum(t4.w), n=pw.reducers.count()
    )
    out = tmp_path / out_name
    pw.io.csv.write(res, os.fspath(out))
    pw.run()
    return out.read_bytes()


def test_fused_chain_byte_identical_to_fuse_off(tmp_path, monkeypatch):
    _with_env(monkeypatch, PATHWAY_THREADS="1")
    fused = _chain_pipeline(tmp_path, "out_fused.csv")
    rep = planner.last_report()
    assert rep["fusion_groups"], "chain did not fuse"
    if _native_available():  # elision applies to native scans only
        assert any(
            p["kind"] == "scan-key-elision" for p in rep["pushdowns"]
        ), "scan key elision did not fire"
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    _with_env(monkeypatch, PATHWAY_FUSE="0")
    unfused = _chain_pipeline(tmp_path, "out_unfused.csv")
    assert planner.last_report()["enabled"] is False
    assert fused == unfused


def test_fused_chain_reduces_node_and_wave_count(tmp_path, monkeypatch):
    """The acceptance guard: a map->filter->groupby chain must fire
    strictly fewer (node, wave) pairs fused than unfused."""
    from pathway_tpu.internals import observability as obs

    counts = {}
    for leg, fuse in (("fused", None), ("unfused", "0")):
        _with_env(monkeypatch, PATHWAY_THREADS="1", PATHWAY_FUSE=fuse)
        obs.enable()
        try:
            _chain_pipeline(tmp_path, f"waves_{leg}.csv")
            counts[leg] = obs.PLANE.metrics.histogram_stats(
                "pathway_operator_wave_seconds", None
            )[0]
            rep = planner.last_report()
            counts[leg + "_nodes"] = rep["nodes_after"]
        finally:
            obs.disable()
        from pathway_tpu.internals.parse_graph import G

        G.clear()
    assert counts["fused_nodes"] < counts["unfused_nodes"]
    assert counts["fused"] < counts["unfused"]


def test_fused_chain_object_plane_subprocess(tmp_path):
    """Same A/B on the pure-object engine (PATHWAY_TPU_NATIVE=0):
    stateful fused chains must reproduce the suppressing RowwiseNode
    stream byte-for-byte."""
    script = f"""
import sys
sys.path.insert(0, {REPO!r})
import pathway_tpu as pw

class S(pw.Schema):
    k: str
    v: int

t = pw.io.fs.read({os.fspath(tmp_path)!r} + "/obj_in.jsonl", format="json",
                  schema=S, mode="static")
t2 = t.select(k=pw.this.k, w=pw.this.v * 3 + 1)
t3 = t2.filter(pw.this.w % 5 != 0)
t4 = t3.select(k=pw.this.k, w=pw.this.w - 1)
res = t4.groupby(t4.k).reduce(t4.k, total=pw.reducers.sum(t4.w))
pw.io.csv.write(res, sys.argv[1])
pw.run()
"""
    with open(tmp_path / "obj_in.jsonl", "w") as f:
        for i in range(2000):
            f.write('{"k": "g%d", "v": %d}\n' % (i % 5, i))
    outs = {}
    for leg, env_extra in (
        ("fused", {"PATHWAY_FUSE": "1"}),
        ("unfused", {"PATHWAY_FUSE": "0"}),
    ):
        out = tmp_path / f"obj_{leg}.csv"
        env = {
            **os.environ, "JAX_PLATFORMS": "cpu", "PATHWAY_THREADS": "1",
            "PATHWAY_TPU_NATIVE": "0", **env_extra,
        }
        r = subprocess.run(
            [sys.executable, "-c", script, os.fspath(out)],
            capture_output=True, text=True, env=env, timeout=180,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        outs[leg] = out.read_bytes()
    assert outs["fused"] == outs["unfused"]


def _retraction_pipeline():
    """Streamed inserts + retractions + updates through an object-plane
    chain (static debug tables with retractions stay object); captured
    via subscribe so the full delta stream is compared."""
    rows = [
        ("a", 1, 2, 1),
        ("b", 2, 2, 1),
        ("a", 1, 4, -1),   # retract a
        ("a", 5, 4, 1),    # re-insert with a new value
        ("c", 7, 6, 1),
        ("c", 7, 8, -1),   # delete c entirely
    ]
    t = pw.debug.table_from_rows(
        pw.schema_from_types(k=str, v=int), rows, is_stream=True
    )
    t2 = t.select(k=pw.this.k, w=pw.this.v * 10)
    t3 = t2.filter(pw.this.w < 60)
    t4 = t3.with_columns(z=pw.this.w + 5)
    got = []
    pw.io.subscribe(
        t4,
        on_change=lambda key, row, time, is_addition: got.append(
            (key, tuple(sorted(row.items())), time, is_addition)
        ),
    )
    pw.run()
    # sequential keys come off a process-global counter, so absolute key
    # values differ between two in-process runs even unoptimized —
    # normalize to first-occurrence indices (a relabeling that still
    # pins suppression/ordering divergence)
    first_seen: dict = {}
    out = []
    for key, row, time, add in got:
        idx = first_seen.setdefault(key, len(first_seen))
        out.append((idx, row, time, add))
    return out


def test_fusion_under_retractions_byte_identical(monkeypatch):
    _with_env(monkeypatch, PATHWAY_THREADS="1")
    fused = _retraction_pipeline()
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    _with_env(monkeypatch, PATHWAY_FUSE="0")
    unfused = _retraction_pipeline()
    assert fused == unfused
    assert fused  # the stream actually carried deltas


def _iterate_pipeline():
    """A fusible two-select chain INSIDE a pw.iterate body (collatz with
    a 1-fixpoint clamp): the fixpoint must converge identically fused
    and unfused — a fused chain that failed to suppress unchanged rows
    would keep the scope iterating forever."""
    t = pw.debug.table_from_rows(
        pw.schema_from_types(a=int), [(3,), (7,), (27,)]
    )

    def step(t):
        t1 = t.select(
            a=pw.if_else(
                t.a <= 1,
                1,
                pw.if_else(t.a % 2 == 0, t.a // 2, 3 * t.a + 1),
            )
        )
        t2 = t1.select(a=t1.a * 1)
        return {"t": t2}

    res = pw.iterate(step, t=t)
    _keys, cols = pw.debug.table_to_dicts(res)
    return sorted(cols["a"].values())


def test_fusion_inside_iterate_scope(monkeypatch):
    _with_env(monkeypatch, PATHWAY_THREADS="1")
    fused = _iterate_pipeline()
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    _with_env(monkeypatch, PATHWAY_FUSE="0")
    unfused = _iterate_pipeline()
    assert fused == unfused


def test_filter_through_join_pushdown_byte_identical(tmp_path, monkeypatch):
    with open(tmp_path / "u.jsonl", "w") as f:
        for i in range(20):
            f.write('{"uid": %d, "name": "u%d"}\n' % (i, i))
    with open(tmp_path / "e.jsonl", "w") as f:
        for i in range(600):
            f.write('{"uid": %d, "amount": %r}\n' % (i % 20, float(i)))

    def run(out_name):
        class U(pw.Schema):
            uid: int
            name: str

        class E(pw.Schema):
            uid: int
            amount: float

        u = pw.io.fs.read(
            os.fspath(tmp_path / "u.jsonl"), format="json", schema=U,
            mode="static",
        )
        e = pw.io.fs.read(
            os.fspath(tmp_path / "e.jsonl"), format="json", schema=E,
            mode="static",
        )
        j = e.join(u, e.uid == u.uid).select(name=u.name, amount=e.amount)
        jf = j.filter(pw.this.amount < 450.0)
        agg = jf.groupby(jf.name).reduce(
            jf.name, total=pw.reducers.sum(jf.amount)
        )
        out = tmp_path / out_name
        pw.io.csv.write(agg, os.fspath(out))
        pw.run()
        return out.read_bytes()

    _with_env(monkeypatch, PATHWAY_THREADS="1")
    fused = run("pj_fused.csv")
    rep = planner.last_report()
    kinds = {p["kind"] for p in rep["pushdowns"]}
    assert "filter-through-join" in kinds
    assert "join-id-elision" in kinds
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    _with_env(monkeypatch, PATHWAY_FUSE="0")
    unfused = run("pj_unfused.csv")
    assert fused == unfused


def test_scan_filter_pushdown_drops_rows_at_source(tmp_path, monkeypatch):
    """A sargable filter directly above a native scan prunes rows at
    parse time: the InputNode emits fewer rows than the file holds."""
    if not _native_available():
        pytest.skip("scan pushdown needs the native dataplane")
    _with_env(monkeypatch, PATHWAY_THREADS="1")
    inp = tmp_path / "scanf.jsonl"
    with open(inp, "w") as f:
        for i in range(1000):
            f.write('{"v": %d}\n' % i)

    class S(pw.Schema):
        v: int

    t = pw.io.fs.read(os.fspath(inp), format="json", schema=S, mode="static")
    flt = t.filter(pw.this.v < 100)
    res = flt.reduce(n=pw.reducers.count())
    out = tmp_path / "scanf_out.csv"
    pw.io.csv.write(res, os.fspath(out))
    pw.run()
    rep = planner.last_report()
    assert any(p["kind"] == "scan-filter" for p in rep["pushdowns"])
    from pathway_tpu.internals.run import _CURRENT  # noqa: F401

    assert b"100," in out.read_bytes()


def test_scan_tuning_never_leaks_across_runs(tmp_path, monkeypatch):
    """A pushed-down scan filter (or key scheme) from run 1 must not
    leak into run 2's plan over the SAME Table: run 2 has no filter
    above the scan, so a stale pushed plan would silently drop rows."""
    if not _native_available():
        pytest.skip("scan pushdown needs the native dataplane")
    _with_env(monkeypatch, PATHWAY_THREADS="1")
    inp = tmp_path / "leak.jsonl"
    with open(inp, "w") as f:
        for i in range(300):
            f.write('{"v": %d}\n' % i)

    class S(pw.Schema):
        v: int

    t = pw.io.fs.read(os.fspath(inp), format="json", schema=S, mode="static")
    # run 1: a sargable filter pushes into the scan
    flt = t.filter(pw.this.v < 50)
    n1 = flt.reduce(n=pw.reducers.count())
    pw.io.csv.write(n1, os.fspath(tmp_path / "leak1.csv"))
    pw.run()
    assert any(
        p["kind"] == "scan-filter" for p in planner.last_report()["pushdowns"]
    )
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    # run 2 over the SAME table object: NO filter — every row must count
    n2 = t.reduce(n=pw.reducers.count())
    pw.io.csv.write(n2, os.fspath(tmp_path / "leak2.csv"))
    pw.run()
    assert b"300," in (tmp_path / "leak2.csv").read_bytes()


def test_stateful_fusion_gated_off_under_workers(monkeypatch):
    """Object-plane map chains lower to SHARDED RowwiseNodes at
    PATHWAY_THREADS>1 — fusing them would unshard the stage and permute
    shard-merged emission order, so the optimizer must leave them."""
    _with_env(monkeypatch, PATHWAY_THREADS="4")
    rows = [("a", 1, 2, 1), ("b", 2, 2, 1), ("c", 3, 4, 1)]
    t = pw.debug.table_from_rows(
        pw.schema_from_types(k=str, v=int), rows, is_stream=True
    )
    t2 = t.select(k=pw.this.k, w=pw.this.v * 10)
    t3 = t2.select(k=pw.this.k, w=pw.this.w + 1)
    got = []
    pw.io.subscribe(
        t3, on_change=lambda key, row, time, is_addition: got.append(row["w"])
    )
    pw.run()
    assert sorted(got) == [11, 21, 31]
    rep = planner.last_report()
    assert not any(
        g["stages"].count("map") and not g["native"]
        for g in rep["fusion_groups"]
    ), f"stateful object fusion must not fire under workers: {rep}"


def test_persistence_roundtrip_with_fusion(tmp_path, monkeypatch):
    """Fused pipelines under persistence: elision self-vetoes (key
    schemes must not silently mix with snapshots), fusion stays on, and
    a resumed run reproduces the same final output."""
    pdir = tmp_path / "pstate"
    inp = tmp_path / "p_in.jsonl"
    with open(inp, "w") as f:
        for i in range(500):
            f.write('{"k": "g%d", "v": %d}\n' % (i % 4, i))

    def run(out_name):
        class S(pw.Schema):
            k: str
            v: int

        t = pw.io.fs.read(
            os.fspath(inp), format="json", schema=S, mode="static"
        )
        t2 = t.select(k=pw.this.k, w=pw.this.v + 7)
        t3 = t2.filter(pw.this.w % 3 != 0)
        res = t3.groupby(t3.k).reduce(t3.k, s=pw.reducers.sum(t3.w))
        out = tmp_path / out_name
        pw.io.csv.write(res, os.fspath(out))
        pw.run(
            persistence_config=pw.persistence.Config(
                pw.persistence.Backend.filesystem(os.fspath(pdir))
            )
        )
        return out.read_bytes()

    _with_env(monkeypatch, PATHWAY_THREADS="1")
    first = run("p_out1.csv")
    rep = planner.last_report()
    assert rep["elision"]["veto"] == "persistence attached"
    assert rep["fusion_groups"], "fusion should stay on under persistence"
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    second = run("p_out2.csv")  # resumes from the snapshot state
    assert first == second


# ---------------------------------------------------------- id elision


def test_cheap_key_mirrors_match_c(monkeypatch):
    from pathway_tpu.engine.native import dataplane as dp

    if not dp.available():
        pytest.skip("native dataplane unavailable")
    from pathway_tpu.internals.keys import (
        Key,
        cheap_join_key,
        cheap_sequential_key_at,
    )

    import random

    rng = random.Random(42)
    for _ in range(500):
        base, n = rng.getrandbits(64), rng.getrandbits(48)
        assert cheap_sequential_key_at(n, base).value == dp.cheap_seq_key(
            base, n
        )
    for _ in range(500):
        l = Key(rng.getrandbits(128))
        r = Key(rng.getrandbits(128))
        assert cheap_join_key(l, r).value == dp.cheap_join_key_c(
            l.value, r.value
        )


def test_elision_vetoed_when_ids_observable(tmp_path, monkeypatch):
    """pw.this.id in any expression over a scan's cone must veto cheap
    keys for that scan (the ids become values)."""
    _with_env(monkeypatch, PATHWAY_THREADS="1")
    inp = tmp_path / "ids.jsonl"
    with open(inp, "w") as f:
        for i in range(50):
            f.write('{"v": %d}\n' % i)

    class S(pw.Schema):
        v: int

    t = pw.io.fs.read(os.fspath(inp), format="json", schema=S, mode="static")
    withid = t.select(v=pw.this.v, me=pw.this.id)
    res = withid.reduce(n=pw.reducers.count())
    pw.io.csv.write(res, os.fspath(tmp_path / "ids_out.csv"))
    pw.run()
    rep = planner.last_report()
    assert not any(
        p["kind"] == "scan-key-elision" for p in rep["pushdowns"]
    ), "ids are observable: elision must not fire"


def test_elision_vetoed_for_subscribe_sinks(tmp_path, monkeypatch):
    """subscribe hands row keys to user code — its cone keeps blake."""
    _with_env(monkeypatch, PATHWAY_THREADS="1")
    inp = tmp_path / "sub.jsonl"
    with open(inp, "w") as f:
        for i in range(50):
            f.write('{"v": %d}\n' % i)

    class S(pw.Schema):
        v: int

    t = pw.io.fs.read(os.fspath(inp), format="json", schema=S, mode="static")
    t2 = t.select(v=pw.this.v + 1)
    pw.io.subscribe(t2, on_change=lambda key, row, time, is_addition: None)
    pw.run()
    rep = planner.last_report()
    assert not any(p["kind"] == "scan-key-elision" for p in rep["pushdowns"])


# ------------------------------------------------------- join reordering


def test_join_reorder_opt_in_sorted_equivalent(monkeypatch):
    """Sketch-costed orientation swap (PATHWAY_JOIN_REORDER=1): the
    advice triggers on static sketches, the output multiset is
    unchanged (order may differ — that's why it's opt-in)."""
    def run():
        small = pw.debug.table_from_rows(
            pw.schema_from_types(k=int, a=str),
            [(i, f"a{i}") for i in range(5)],
        )
        big = pw.debug.table_from_rows(
            pw.schema_from_types(k=int, b=str),
            [(i % 5, f"b{i}") for i in range(100)],
        )
        j = small.join(big, small.k == big.k).select(a=small.a, b=big.b)
        agg = j.groupby(j.a).reduce(j.a, n=pw.reducers.count())
        out = []
        pw.io.subscribe(
            agg,
            on_change=lambda key, row, time, is_addition: out.append(
                (row["a"], row["n"], is_addition)
            ),
        )
        pw.run()
        return sorted(out)

    _with_env(monkeypatch, PATHWAY_THREADS="1")
    base = run()
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    _with_env(monkeypatch, PATHWAY_JOIN_REORDER="1")
    reordered = run()
    rep = planner.last_report()
    assert base == reordered
    orders = rep["join_orders"]
    assert orders and orders[0]["advice"] in ("swap", "keep")


# ----------------------------------------------------- adaptive replan


def test_adaptive_refusion_at_epoch_fence(tmp_path, monkeypatch):
    """Streaming run with observability on and the hot threshold at 0:
    the policy re-fuses the live MapNode/FilterNode run at a drained
    fence and the final output is unaffected. Static fusion is disabled
    here by simulating the plan-analysis failure degradation (plan_ctx
    None — exactly the case the runtime policy exists for: it works off
    the live node graph's true fan-out, no spec DAG needed)."""
    if not _native_available():
        pytest.skip("runtime re-fusion targets MapNode/FilterNode runs")
    from pathway_tpu.internals import observability as obs
    from pathway_tpu.internals.lowering import Session

    monkeypatch.setattr(
        Session, "attach_plan_roots", lambda self, *a, **k: None
    )
    inp = tmp_path / "adapt.jsonl"
    with open(inp, "w") as f:
        for i in range(200):
            f.write('{"v": %d}\n' % i)

    def pipeline(adaptive: bool):
        _with_env(
            monkeypatch,
            PATHWAY_THREADS="1",
            PATHWAY_ADAPTIVE_HOT_SHARE="0.0",
            PATHWAY_ADAPTIVE=None if adaptive else "0",
        )

        class S(pw.Schema):
            v: int

        t = pw.io.fs.read(
            os.fspath(inp), format="json", schema=S, mode="streaming",
            _single_pass=True,
        )
        t2 = t.select(v=pw.this.v * 2)
        t3 = t2.filter(pw.this.v >= 0)
        t4 = t3.select(v=pw.this.v + 1)
        res = t4.reduce(s=pw.reducers.sum(pw.this.v))
        got = []
        pw.io.subscribe(
            res,
            on_change=lambda key, row, time, is_addition: got.append(
                (row["s"], is_addition)
            ),
        )
        obs.enable()
        try:
            pw.run()
        finally:
            obs.disable()
        return got, planner.last_report()

    got, rep = pipeline(adaptive=True)
    refusions = [r for r in rep["replans"] if r["action"] == "refuse"]
    assert refusions, "adaptive policy never re-fused the hot chain"
    # the final consolidated sum must match the non-adaptive control
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    control, _rep = pipeline(adaptive=False)
    assert got[-1] == control[-1]


def test_device_exchange_mode_cached_and_counted(monkeypatch):
    from pathway_tpu.parallel import device_exchange as dx

    monkeypatch.setenv("PATHWAY_DEVICE_EXCHANGE", "0")
    ex = dx.DeviceExchanger.__new__(dx.DeviceExchanger)
    ex._mode = dx.mode()
    assert ex._mode == "off"
    monkeypatch.setenv("PATHWAY_DEVICE_EXCHANGE", "1")
    # cached at construction: a per-batch env flip must not change it
    assert ex._mode == "off"
    assert dx.mode() == "force"


def test_plan_report_in_statistics_and_profiler(tmp_path, monkeypatch):
    """Plan visibility: the optimized plan surfaces through the
    profiler JSON (and /statistics serves the same graph report)."""
    _with_env(monkeypatch, PATHWAY_THREADS="1")
    inp = tmp_path / "vis.jsonl"
    with open(inp, "w") as f:
        for i in range(200):
            f.write('{"k": "g%d", "v": %d}\n' % (i % 3, i))

    class S(pw.Schema):
        k: str
        v: int

    t = pw.io.fs.read(os.fspath(inp), format="json", schema=S, mode="static")
    t2 = t.select(k=pw.this.k, w=pw.this.v * 2)
    t3 = t2.filter(pw.this.w > 10)
    res = t3.groupby(t3.k).reduce(t3.k, s=pw.reducers.sum(t3.w))
    pw.io.csv.write(res, os.fspath(tmp_path / "vis_out.csv"))
    prof = tmp_path / "vis_profile.json"
    pw.run(profile=os.fspath(prof))
    with open(prof) as f:
        report = json.load(f)
    assert "plan" in report
    assert report["plan"]["fusion_groups"]
    assert any(
        "fused" in (o.get("label") or "") or o["operator"] == "FusedRowwiseNode"
        for o in report["operators"]
    ) or report["plan"]["fusion_groups"]
