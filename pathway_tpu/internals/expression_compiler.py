"""Compile expression ASTs into row-evaluation closures.

Reference parity: the typed expression interpreter (src/engine/expression.rs)
+ RowwiseEvaluator (internals/graph_runner/expression_evaluator.py:201).
A compiled expression is `fn(key, rows) -> value` where `rows` is a tuple of
row-tuples, one per aligned input table. Vectorized (numpy/XLA) evaluation
of eligible expressions lives in engine/vectorize.py and shares this AST.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.errors import ERROR, ErrorValue
from pathway_tpu.internals.expression import (
    ApplyExpression,
    AsyncApplyExpression,
    BinaryOpExpression,
    CastExpression,
    CoalesceExpression,
    ColumnConstExpression,
    ColumnExpression,
    ColumnReference,
    ConvertExpression,
    DeclareTypeExpression,
    FillErrorExpression,
    GetExpression,
    IdReference,
    IfElseExpression,
    IsNoneExpression,
    IsNotNoneExpression,
    MakeTupleExpression,
    MethodCallExpression,
    PointerExpression,
    ReducerExpression,
    RequireExpression,
    ThisMarker,
    UnaryOpExpression,
    UnwrapExpression,
    _BIN_OPS,
)
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import Key, key_for_values


class Resolver:
    """Maps a ColumnReference to (input_index, column_index).

    tables: aligned input tables (index 0 = primary / `pw.this`).
    For join contexts, `left_table`/`right_table` map pw.left / pw.right.
    """

    def __init__(
        self,
        tables: Sequence[Any],
        left_table: Any = None,
        right_table: Any = None,
        reducer_slots: dict[int, int] | None = None,
        reducer_input: int = 0,
    ):
        self.tables = list(tables)
        self.left_table = left_table
        self.right_table = right_table
        self.reducer_slots = reducer_slots or {}
        self.reducer_input = reducer_input

    def table_of(self, ref: ColumnReference) -> Any:
        tab = ref.table
        if isinstance(tab, ThisMarker):
            side = tab._side
            if side == "left":
                if self.left_table is None:
                    raise ValueError("pw.left used outside of a join")
                return self.left_table
            if side == "right":
                if self.right_table is None:
                    raise ValueError("pw.right used outside of a join")
                return self.right_table
            return self.tables[0]
        return tab

    def resolve(self, ref: ColumnReference) -> tuple[int, int | None]:
        """Returns (input_idx, col_idx); col_idx None means the key itself."""
        table = self.table_of(ref)
        if isinstance(ref, IdReference) or ref.name == "id":
            idx = self._input_index(table)
            return (idx, None)
        idx = self._input_index(table)
        names = self.tables[idx]._column_names()
        try:
            col = names.index(ref.name)
        except ValueError:
            raise KeyError(
                f"column {ref.name!r} not found in table with columns {names}"
            ) from None
        return (idx, col)

    def _input_index(self, table: Any) -> int:
        for i, t in enumerate(self.tables):
            if t is table:
                return i
        # Tables sharing a universe may substitute for each other only if
        # registered; the lowering registers every referenced table.
        raise KeyError(f"table {table!r} is not an input of this context")


CompiledFn = Callable[[Key, tuple], Any]


def compile_expression(expr: ColumnExpression, resolver: Resolver) -> CompiledFn:
    """Build fn(key, rows) -> value."""

    def rec(e: ColumnExpression) -> CompiledFn:
        if type(e).__name__ == "_SlotRef":  # injected by lowering
            ii, ci = e.input_idx, e.col_idx  # type: ignore[attr-defined]
            return lambda key, rows: rows[ii][ci]
        if isinstance(e, ColumnConstExpression):
            v = e._value
            if isinstance(v, (dict, list)):
                v = Json(v)
            return lambda key, rows: v
        if isinstance(e, IdReference):
            idx, _ = resolver.resolve(e)
            return lambda key, rows: key
        if isinstance(e, ColumnReference):
            idx, col = resolver.resolve(e)
            if col is None:
                return lambda key, rows: key
            return lambda key, rows: rows[idx][col]
        if isinstance(e, ReducerExpression):
            slot = resolver.reducer_slots.get(id(e))
            if slot is None:
                raise ValueError("reducer used outside of reduce()")
            ridx = resolver.reducer_input
            return lambda key, rows: rows[ridx][slot]
        if isinstance(e, BinaryOpExpression):
            lf, rf = rec(e._left), rec(e._right)
            op = _BIN_OPS[e._op]
            opname = e._op
            if opname == "/":
                def run_div(key, rows):
                    a, b = lf(key, rows), rf(key, rows)
                    if isinstance(a, ErrorValue) or isinstance(b, ErrorValue):
                        return ERROR
                    if isinstance(a, int) and isinstance(b, int):
                        return a / b
                    return a / b
                return run_div
            if opname in ("==", "!="):
                def run_eq(key, rows, _neq=(opname == "!=")):
                    a, b = lf(key, rows), rf(key, rows)
                    if isinstance(a, ErrorValue) or isinstance(b, ErrorValue):
                        return ERROR
                    res = _value_eq(a, b)
                    return (not res) if _neq else res
                return run_eq

            def run_bin(key, rows):
                a, b = lf(key, rows), rf(key, rows)
                if isinstance(a, ErrorValue) or isinstance(b, ErrorValue):
                    return ERROR
                return op(a, b)
            return run_bin
        if isinstance(e, UnaryOpExpression):
            f = rec(e._expr)
            if e._op == "-":
                return lambda key, rows: _guard_err(f(key, rows), lambda v: -v)
            if e._op == "~":
                def run_not(key, rows):
                    v = f(key, rows)
                    if isinstance(v, ErrorValue):
                        return ERROR
                    if isinstance(v, (bool, np.bool_)):
                        return not v
                    return ~v
                return run_not
            if e._op == "abs":
                return lambda key, rows: _guard_err(f(key, rows), abs)
            raise NotImplementedError(e._op)
        if isinstance(e, IsNoneExpression):
            f = rec(e._expr)
            return lambda key, rows: f(key, rows) is None
        if isinstance(e, IsNotNoneExpression):
            f = rec(e._expr)
            return lambda key, rows: f(key, rows) is not None
        if isinstance(e, IfElseExpression):
            cf, tf, ef = rec(e._if), rec(e._then), rec(e._else)

            def run_ifelse(key, rows):
                c = cf(key, rows)
                if isinstance(c, ErrorValue):
                    return ERROR
                return tf(key, rows) if c else ef(key, rows)

            return run_ifelse
        if isinstance(e, CoalesceExpression):
            fns = [rec(a) for a in e._args]

            def run_coalesce(key, rows):
                for f in fns:
                    v = f(key, rows)
                    if v is not None and not isinstance(v, ErrorValue):
                        return v
                return None

            return run_coalesce
        if isinstance(e, RequireExpression):
            vf = rec(e._val)
            fns = [rec(a) for a in e._args]

            def run_require(key, rows):
                for f in fns:
                    if f(key, rows) is None:
                        return None
                return vf(key, rows)

            return run_require
        if isinstance(e, AsyncApplyExpression):
            # compiled synchronously here only when reached outside the
            # dedicated async lowering (e.g. inside iterate)
            return _compile_apply(e, resolver, rec)
        if isinstance(e, ApplyExpression):
            return _compile_apply(e, resolver, rec)
        if isinstance(e, (CastExpression, ConvertExpression)):
            f = rec(e._expr)
            target = e._target
            unwrap = getattr(e, "_unwrap", False)
            caster = _make_caster(target, isinstance(e, ConvertExpression))

            def run_cast(key, rows):
                v = f(key, rows)
                if isinstance(v, ErrorValue):
                    return ERROR
                if v is None:
                    if unwrap:
                        return ERROR
                    return None
                try:
                    return caster(v)
                except (ValueError, TypeError):
                    return ERROR

            return run_cast
        if isinstance(e, DeclareTypeExpression):
            return rec(e._expr)
        if isinstance(e, PointerExpression):
            fns = [rec(a) for a in e._args]
            inst_f = rec(e._instance) if e._instance is not None else None

            def run_pointer(key, rows):
                vals = [f(key, rows) for f in fns]
                if any(isinstance(v, ErrorValue) for v in vals):
                    return ERROR
                if e._optional and any(v is None for v in vals):
                    return None
                base = key_for_values(*vals)
                if inst_f is not None:
                    inst = inst_f(key, rows)
                    return base.with_shard_of(key_for_values(inst))
                return base

            return run_pointer
        if isinstance(e, MakeTupleExpression):
            fns = [rec(a) for a in e._args]
            return lambda key, rows: tuple(f(key, rows) for f in fns)
        if isinstance(e, GetExpression):
            of, inf = rec(e._obj), rec(e._index)
            df = rec(e._default) if e._default is not None else None
            check = e._check_if_exists

            def run_get(key, rows):
                obj = of(key, rows)
                idx = inf(key, rows)
                if isinstance(obj, ErrorValue) or isinstance(idx, ErrorValue):
                    return ERROR
                try:
                    if isinstance(obj, Json):
                        return obj[idx]
                    return obj[idx]
                except (KeyError, IndexError, TypeError):
                    if check:
                        return df(key, rows) if df is not None else None
                    return ERROR

            return run_get
        if isinstance(e, MethodCallExpression):
            fns = [rec(a) for a in e._args]
            fn = e._fn

            def run_method(key, rows):
                vals = [f(key, rows) for f in fns]
                if any(isinstance(v, ErrorValue) for v in vals):
                    return ERROR
                if vals and vals[0] is None:
                    return None
                return fn(*vals)

            return run_method
        if isinstance(e, UnwrapExpression):
            f = rec(e._expr)

            def run_unwrap(key, rows):
                v = f(key, rows)
                if v is None:
                    raise ValueError("unwrap() received None")
                return v

            return run_unwrap
        if isinstance(e, FillErrorExpression):
            f, rf = rec(e._expr), rec(e._replacement)

            def run_fill(key, rows):
                try:
                    v = f(key, rows)
                except Exception:  # noqa: BLE001
                    return rf(key, rows)
                if isinstance(v, ErrorValue):
                    return rf(key, rows)
                return v

            return run_fill
        raise NotImplementedError(f"cannot compile {type(e).__name__}")

    return rec(expr)


def _compile_apply(e: ApplyExpression, resolver: Resolver, rec) -> CompiledFn:
    arg_fns = [rec(a) for a in e._args]
    kw_fns = {k: rec(v) for k, v in e._kwargs.items()}
    fn = e._fn
    propagate_none = e._propagate_none

    def run_apply(key, rows):
        args = [f(key, rows) for f in arg_fns]
        kwargs = {k: f(key, rows) for k, f in kw_fns.items()}
        if any(isinstance(a, ErrorValue) for a in args) or any(
            isinstance(v, ErrorValue) for v in kwargs.values()
        ):
            return ERROR
        if propagate_none and (
            any(a is None for a in args) or any(v is None for v in kwargs.values())
        ):
            return None
        return fn(*args, **kwargs)

    return run_apply


def _make_caster(target: dt.DType, convert: bool) -> Callable[[Any], Any]:
    if target == dt.INT:
        if convert:
            def to_int(v: Any) -> int:
                if isinstance(v, Json):
                    r = v.as_int()
                    if r is None:
                        raise ValueError(f"Json {v!r} is not an int")
                    return r
                return int(v)
            return to_int
        return lambda v: int(v)
    if target == dt.FLOAT:
        if convert:
            def to_float(v: Any) -> float:
                if isinstance(v, Json):
                    r = v.as_float()
                    if r is None:
                        raise ValueError(f"Json {v!r} is not a float")
                    return r
                return float(v)
            return to_float
        return lambda v: float(v)
    if target == dt.STR:
        if convert:
            def to_str(v: Any) -> str:
                if isinstance(v, Json):
                    r = v.as_str()
                    if r is None:
                        raise ValueError(f"Json {v!r} is not a str")
                    return r
                return str(v)
            return to_str
        return lambda v: str(v)
    if target == dt.BOOL:
        if convert:
            def to_bool(v: Any) -> bool:
                if isinstance(v, Json):
                    r = v.as_bool()
                    if r is None:
                        raise ValueError(f"Json {v!r} is not a bool")
                    return r
                return bool(v)
            return to_bool
        return lambda v: bool(v)
    if isinstance(target, dt.Optional):
        return _make_caster(target.wrapped, convert)
    return lambda v: v


def _value_eq(a: Any, b: Any) -> bool:
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return bool(np.array_equal(a, b))
    return a == b


def _guard_err(v: Any, f: Callable[[Any], Any]) -> Any:
    if isinstance(v, ErrorValue):
        return ERROR
    return f(v)


def collect_reducers(exprs: Sequence[ColumnExpression]) -> list[ReducerExpression]:
    """All distinct ReducerExpressions in the given expression trees."""
    out: list[ReducerExpression] = []
    seen: set[int] = set()

    def rec(e: ColumnExpression) -> None:
        if id(e) in seen:
            return
        seen.add(id(e))
        if isinstance(e, ReducerExpression):
            out.append(e)
            return  # don't descend into reducer args here
        for s in e._sub_expressions():
            rec(s)

    for e in exprs:
        rec(e)
    return out


def referenced_tables(exprs: Sequence[ColumnExpression]) -> list[Any]:
    """Distinct concrete tables referenced (ThisMarkers excluded)."""
    out: list[Any] = []
    for e in exprs:
        for ref in e._column_references():
            tab = ref.table
            if not isinstance(tab, ThisMarker) and all(tab is not t for t in out):
                out.append(tab)
    return out
