"""Reducers: aggregation functions for groupby/reduce.

Reference: src/engine/reduce.rs:22 (Reducer enum) +
python/pathway/internals/reducers.py. Each reducer is described by a small
algebra: invertible reducers (sum/count) update incrementally under
retraction; non-invertible ones (min/max/unique/...) recompute from the
group's maintained value multiset. `np_sum`/`np_max` style array reducers
accumulate on the numeric plane.
"""

from __future__ import annotations

import builtins

from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ReducerExpression,
    wrap_arg,
)


class Reducer:
    """Engine-level reducer descriptor."""

    name: str = "reducer"
    invertible: bool = False
    n_args: int = 1

    def neutral(self) -> Any:
        return None

    def add(self, acc: Any, values: tuple, count: int) -> Any:
        raise NotImplementedError

    def extract(self, acc: Any) -> Any:
        return acc

    def from_multiset(self, entries: list[tuple[tuple, int]]) -> Any:
        """Recompute from [(values_tuple, count), ...]; used when not invertible."""
        acc = self.neutral()
        for values, count in entries:
            acc = self.add(acc, values, count)
        return self.extract(acc)

    def result_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return arg_dtypes[0] if arg_dtypes else dt.ANY


class CountReducer(Reducer):
    name = "count"
    invertible = True
    n_args = 0

    def neutral(self) -> int:
        return 0

    def add(self, acc: int, values: tuple, count: int) -> int:
        return acc + count

    def result_dtype(self, arg_dtypes):
        return dt.INT


class SumReducer(Reducer):
    name = "sum"
    invertible = True

    def neutral(self):
        return None

    def add(self, acc, values, count):
        v = values[0]
        if isinstance(v, np.ndarray):
            term = v * count
        else:
            term = v * count
        return term if acc is None else acc + term

    def result_dtype(self, arg_dtypes):
        return arg_dtypes[0]


class AvgReducer(Reducer):
    name = "avg"
    invertible = True

    def neutral(self):
        return (0.0, 0)

    def add(self, acc, values, count):
        s, n = acc
        return (s + values[0] * count, n + count)

    def extract(self, acc):
        s, n = acc
        return s / n if n else None

    def result_dtype(self, arg_dtypes):
        return dt.FLOAT


class MinReducer(Reducer):
    name = "min"

    def from_multiset(self, entries):
        vals = [v[0] for v, c in entries if c > 0]
        return builtins.min(vals) if vals else None


class MaxReducer(Reducer):
    name = "max"

    def from_multiset(self, entries):
        vals = [v[0] for v, c in entries if c > 0]
        return builtins.max(vals) if vals else None


class ArgMinReducer(Reducer):
    name = "argmin"
    n_args = 2

    def from_multiset(self, entries):
        best = None
        for (v, arg), c in ((e[0], e[1]) for e in entries):
            if c <= 0:
                continue
            if best is None or (v, arg) < best:
                best = (v, arg)
        return best[1] if best else None

    def result_dtype(self, arg_dtypes):
        return arg_dtypes[1] if len(arg_dtypes) > 1 else dt.ANY_POINTER


class ArgMaxReducer(Reducer):
    name = "argmax"
    n_args = 2

    def from_multiset(self, entries):
        best = None
        for (v, arg), c in ((e[0], e[1]) for e in entries):
            if c <= 0:
                continue
            if best is None or v > best[0] or (v == best[0] and arg < best[1]):
                best = (v, arg)
        return best[1] if best else None

    def result_dtype(self, arg_dtypes):
        return arg_dtypes[1] if len(arg_dtypes) > 1 else dt.ANY_POINTER


class UniqueReducer(Reducer):
    name = "unique"

    def from_multiset(self, entries):
        vals = {v[0] for v, c in entries if c > 0}
        if len(vals) != 1:
            from pathway_tpu.internals.errors import ERROR

            return ERROR
        return vals.pop()


class AnyReducer(Reducer):
    name = "any"

    def from_multiset(self, entries):
        for v, c in entries:
            if c > 0:
                return v[0]
        return None


class SortedTupleReducer(Reducer):
    name = "sorted_tuple"

    def __init__(self, skip_nones: bool = False):
        self.skip_nones = skip_nones

    def from_multiset(self, entries):
        out = []
        for v, c in entries:
            if c > 0 and not (self.skip_nones and v[0] is None):
                out.extend([v[0]] * c)
        try:
            return builtins.tuple(sorted(out))
        except TypeError:
            return builtins.tuple(sorted(out, key=repr))

    def result_dtype(self, arg_dtypes):
        return dt.List(arg_dtypes[0] if arg_dtypes else dt.ANY)


class TupleReducer(Reducer):
    """Collect values ordered by (instance/time-of-insert) — we order by key."""

    name = "tuple"
    n_args = 2  # (value, sort_key)

    def __init__(self, skip_nones: bool = False):
        self.skip_nones = skip_nones

    def from_multiset(self, entries):
        out = []
        for v, c in entries:
            if c > 0 and not (self.skip_nones and v[0] is None):
                out.extend([(v[1], v[0])] * c)
        out.sort(key=lambda p: _sort_key(p[0]))
        return builtins.tuple(v for _, v in out)

    def result_dtype(self, arg_dtypes):
        return dt.List(arg_dtypes[0] if arg_dtypes else dt.ANY)


def _sort_key(v: Any):
    try:
        hash(v)
    except TypeError:
        return (2, repr(v))
    if isinstance(v, (int, float, bool, np.integer, np.floating)):
        return (0, float(v))
    return (1, repr(v))


class NdarrayReducer(Reducer):
    name = "ndarray"
    n_args = 2  # (value, sort_key)

    def __init__(self, skip_nones: bool = False):
        self.skip_nones = skip_nones

    def from_multiset(self, entries):
        out = []
        for v, c in entries:
            if c > 0 and not (self.skip_nones and v[0] is None):
                out.extend([(v[1], v[0])] * c)
        out.sort(key=lambda p: _sort_key(p[0]))
        return np.array([v for _, v in out])

    def result_dtype(self, arg_dtypes):
        return dt.ANY_ARRAY


class EarliestReducer(Reducer):
    """Value from the row with the smallest processing time (reduce.rs Earliest)."""

    name = "earliest"
    n_args = 2  # (value, engine_time)

    def from_multiset(self, entries):
        best = None
        for (v, t), c in ((e[0], e[1]) for e in entries):
            if c > 0 and (best is None or t < best[0]):
                best = (t, v)
        return best[1] if best else None


class LatestReducer(Reducer):
    name = "latest"
    n_args = 2

    def from_multiset(self, entries):
        best = None
        for (v, t), c in ((e[0], e[1]) for e in entries):
            if c > 0 and (best is None or t >= best[0]):
                best = (t, v)
        return best[1] if best else None


class StatefulReducer(Reducer):
    """User combine_fn folded over batches in time order
    (reference: operators/stateful_reduce.rs:20)."""

    name = "stateful"

    def __init__(self, combine_fn: Callable):
        self.combine_fn = combine_fn

    def result_dtype(self, arg_dtypes):
        return dt.ANY


# ---------------------------------------------------------------- public API


def count(*args: Any) -> ReducerExpression:
    return ReducerExpression(CountReducer(), *args)


def sum(expr: Any) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(SumReducer(), expr)


def avg(expr: Any) -> ReducerExpression:
    return ReducerExpression(AvgReducer(), expr)


def int_sum(expr: Any) -> ReducerExpression:
    """Deprecated alias of sum (reference: reducers.py:611)."""
    import warnings

    warnings.warn(
        "reducers.int_sum is deprecated, use reducers.sum instead",
        DeprecationWarning,
        stacklevel=2,
    )
    return sum(expr)


def npsum(expr: Any) -> ReducerExpression:
    """Deprecated alias of sum for ndarray columns (reference:
    reducers.py:547)."""
    import warnings

    warnings.warn(
        "reducers.npsum is deprecated, use reducers.sum instead",
        DeprecationWarning,
        stacklevel=2,
    )
    return sum(expr)


def min(expr: Any) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(MinReducer(), expr)


def max(expr: Any) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(MaxReducer(), expr)


def argmin(expr: Any) -> ReducerExpression:
    from pathway_tpu.internals.expression import IdReference, this

    return ReducerExpression(ArgMinReducer(), expr, IdReference(this))


def argmax(expr: Any) -> ReducerExpression:
    from pathway_tpu.internals.expression import IdReference, this

    return ReducerExpression(ArgMaxReducer(), expr, IdReference(this))


def unique(expr: Any) -> ReducerExpression:
    return ReducerExpression(UniqueReducer(), expr)


def any(expr: Any) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(AnyReducer(), expr)


def sorted_tuple(expr: Any, *, skip_nones: bool = False) -> ReducerExpression:
    return ReducerExpression(SortedTupleReducer(skip_nones), expr)


def tuple(expr: Any, *, skip_nones: bool = False) -> ReducerExpression:  # noqa: A001
    from pathway_tpu.internals.expression import IdReference, this

    return ReducerExpression(TupleReducer(skip_nones), expr, IdReference(this))


def ndarray(expr: Any, *, skip_nones: bool = False) -> ReducerExpression:
    from pathway_tpu.internals.expression import IdReference, this

    return ReducerExpression(NdarrayReducer(skip_nones), expr, IdReference(this))


def earliest(expr: Any) -> ReducerExpression:
    return ReducerExpression(EarliestReducer(), expr, _EngineTimeMarker())


def latest(expr: Any) -> ReducerExpression:
    return ReducerExpression(LatestReducer(), expr, _EngineTimeMarker())


class _EngineTimeMarker(ColumnExpression):
    """Placeholder expression resolved to the engine processing time."""


def udf_reducer(reducer_cls: Any):
    """Decorator form for custom accumulator reducers — see custom_reducers."""
    from pathway_tpu.internals.custom_reducers import make_udf_reducer

    return make_udf_reducer(reducer_cls)


def stateful_many(combine_fn: Callable) -> Callable:
    def reducer_factory(*args: Any) -> ReducerExpression:
        return ReducerExpression(StatefulReducer(combine_fn), *args)

    return reducer_factory


def stateful_single(combine_fn: Callable) -> Callable:
    """Wrap a per-row stateful fn into stateful_many (reference: custom_reducers.py:108)."""

    def combine_many(state: Any, rows: list[tuple[list[Any], int]]) -> Any:
        for row, cnt in rows:
            if cnt <= 0:
                raise ValueError("stateful_single does not support retractions")
            for _ in range(cnt):
                state = combine_fn(state, *row)
        return state

    return stateful_many(combine_many)
