"""Admission control: token buckets + bounded queues at the serving edge.

The thin `rest_connector` accepted every concurrent request: each one
became a pending future and a staged row, so overload showed up as an
unbounded `pending` map, minutes-long p99 and eventually a dead server —
the engine never saw *less* work, just later. This module makes the edge
say no early:

* :class:`TokenBucket` — the standard (rate, burst) limiter. Refill is
  computed lazily off a monotonic clock; `try_take` never sleeps and
  returns the seconds until the next token when it refuses, which
  becomes the 429's ``Retry-After``.
* :class:`AdmissionController` — one per gateway. A route-level bucket
  plus lazily-created per-tenant buckets (the tenant is whatever field
  the gateway's config names), and a bounded in-flight counter: requests
  past ``max_queue`` are shed immediately instead of piling futures into
  the response map. Every decision lands in the metrics registry
  (``pathway_serving_admitted_total``, ``pathway_serving_shed_total``
  with a ``reason`` label, ``pathway_serving_queue_depth``).

Shedding is deliberately *cheap*: one clock read and two dict lookups on
admit, zero background threads.
"""

from __future__ import annotations

import threading
import time as _time

from pathway_tpu.internals import observability as _obs
from pathway_tpu.analysis import lockgraph as _lockgraph

__all__ = ["TokenBucket", "AdmissionController", "AdmissionDecision"]


class TokenBucket:
    """(rate, burst) limiter with lazy refill off the monotonic clock."""

    def __init__(self, rate: float, burst: float):
        if rate <= 0 or burst <= 0:
            raise ValueError(f"rate/burst must be > 0, got {rate}/{burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._last = _time.monotonic()
        self._lock = _lockgraph.register_lock(
            "serving.token_bucket", threading.Lock()
        )

    def try_take(self, n: float = 1.0) -> float:
        """Take `n` tokens if available; returns 0.0 on success, else the
        seconds until `n` tokens will have accumulated (the Retry-After)."""
        with self._lock:
            now = _time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            return (n - self._tokens) / self.rate


class AdmissionDecision:
    """The gateway's verdict on one request."""

    __slots__ = ("admitted", "reason", "retry_after")

    def __init__(self, admitted: bool, reason: str = "", retry_after: float = 0.0):
        self.admitted = admitted
        self.reason = reason  # "" | "route_rate" | "tenant_rate" | "queue_full" | "backpressure"
        self.retry_after = retry_after

    def __bool__(self) -> bool:
        return self.admitted


class AdmissionController:
    """Route-level + per-tenant token buckets and a bounded in-flight
    queue for one gateway route. Thread-safe; called from aiohttp
    handlers (any number of event loops / threads)."""

    def __init__(
        self,
        route: str,
        *,
        rate: float | None = None,
        burst: float | None = None,
        tenant_rate: float | None = None,
        tenant_burst: float | None = None,
        max_queue: int = 1024,
        max_tenants: int = 10_000,
    ):
        self.route = route
        self.max_queue = max_queue
        self._route_bucket = (
            TokenBucket(rate, burst or max(rate, 1.0))
            if rate is not None
            else None
        )
        self._tenant_rate = tenant_rate
        self._tenant_burst = tenant_burst
        self._max_tenants = max_tenants
        self._tenants: dict[str, TokenBucket] = {}
        self._lock = _lockgraph.register_lock(
            "serving.admission", threading.Lock()
        )
        self._in_flight = 0
        self.stats = {"admitted": 0, "shed": 0, "max_in_flight": 0}

    # ------------------------------------------------------------ decisions

    def _shed(self, reason: str, retry_after: float) -> AdmissionDecision:
        self.stats["shed"] += 1
        if _obs.PLANE is not None:
            _obs.PLANE.metrics.counter(
                "pathway_serving_shed_total",
                {"route": self.route, "reason": reason},
                help="requests refused at the serving edge",
            )
        return AdmissionDecision(False, reason, retry_after)

    def admit(self, tenant: str | None = None) -> AdmissionDecision:
        """Gate one request. An admitted request MUST be paired with one
        `release()` once its response future resolves (or fails)."""
        # RESERVE the queue slot atomically with the bound check — a
        # check-then-increment in two lock sections would let concurrent
        # callers overshoot max_queue, the one bound this class exists
        # to enforce. A bucket refusal below refunds the reservation.
        with self._lock:
            if self._in_flight >= self.max_queue:
                depth = self._in_flight
                decision = self._shed("queue_full", 1.0)
                self._gauge_depth(depth)
                return decision
            self._in_flight += 1
            depth = self._in_flight
        if self._route_bucket is not None:
            wait = self._route_bucket.try_take()
            if wait > 0.0:
                self.release()
                return self._shed("route_rate", wait)
        if tenant is not None and self._tenant_rate is not None:
            with self._lock:
                bucket = self._tenants.get(tenant)
                if bucket is None:
                    if len(self._tenants) >= self._max_tenants:
                        # tenant cardinality is attacker-controlled: evict
                        # the whole table rather than grow unbounded (a
                        # fresh bucket starts full, so honest tenants see
                        # at most one extra burst)
                        self._tenants.clear()
                    bucket = self._tenants[tenant] = TokenBucket(
                        self._tenant_rate,
                        self._tenant_burst or max(self._tenant_rate, 1.0),
                    )
            wait = bucket.try_take()
            if wait > 0.0:
                self.release()
                return self._shed("tenant_rate", wait)
        with self._lock:
            self.stats["admitted"] += 1
            self.stats["max_in_flight"] = max(
                self.stats["max_in_flight"], depth
            )
        if _obs.PLANE is not None:
            _obs.PLANE.metrics.counter(
                "pathway_serving_admitted_total", {"route": self.route},
                help="requests admitted past the serving edge",
            )
        self._gauge_depth(depth)
        return AdmissionDecision(True)

    def release(self) -> None:
        with self._lock:
            self._in_flight = max(self._in_flight - 1, 0)
            depth = self._in_flight
        self._gauge_depth(depth)

    def shed_external(self, reason: str, retry_after: float) -> AdmissionDecision:
        """Record a shed decided outside the controller (backpressure)."""
        return self._shed(reason, retry_after)

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._in_flight

    def _gauge_depth(self, depth: int) -> None:
        if _obs.PLANE is not None:
            _obs.PLANE.metrics.gauge(
                "pathway_serving_queue_depth", depth, {"route": self.route},
                help="admitted requests currently awaiting a response",
            )
