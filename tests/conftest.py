import os

# Force JAX onto a virtual 8-device CPU mesh for sharding tests; the real
# TPU chip is reserved for benchmarks (bench.py), not unit tests.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _clear_parse_graph():
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    yield
    G.clear()
