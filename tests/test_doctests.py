"""Run the doctest examples embedded in public API docstrings — the
reference documents its API contract with runnable examples throughout
(e.g. python/pathway/internals/table.py); these keep ours honest.

Each example resets the sequential-key counter so its printed row order
is what a fresh interpreter would produce, independent of other examples
(auto-keys hash a process-wide sequence number)."""

import doctest

import pathway_tpu as pw
from pathway_tpu.internals import keys


def _run_module_doctests(module) -> None:
    finder = doctest.DocTestFinder(exclude_empty=True)
    runner = doctest.DocTestRunner(optionflags=doctest.NORMALIZE_WHITESPACE)
    tests = [t for t in finder.find(module) if t.examples]
    assert tests, f"no doctest examples found in {module.__name__}"
    failures = []
    for test in tests:
        keys._seq_next = 0  # fresh-interpreter key order
        result = runner.run(test)
        if result.failed:
            failures.append(test.name)
    assert not failures, f"doctest failures in: {failures}"


def test_table_api_doctests():
    from pathway_tpu.internals import table

    _run_module_doctests(table)


def test_doctest_example_count():
    """The API contract must keep a minimum breadth of runnable examples."""
    from pathway_tpu.internals import table

    finder = doctest.DocTestFinder(exclude_empty=True)
    n = sum(
        len(t.examples) > 0 for t in finder.find(table)
    )
    assert n >= 6, f"only {n} documented examples in table.py"
