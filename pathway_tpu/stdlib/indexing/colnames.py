"""Shared column-name constants of the index layer.

Reference parity: python/pathway/stdlib/indexing/colnames.py.
"""

_INDEX_REPLY = "_pw_index_reply"
_INDEX_REPLY_ID = "_pw_index_reply_id"
_INDEX_REPLY_SCORE = "_pw_index_reply_score"
_QUERY_ID = "_pw_query_id"
_MATCHED_ID = "_pw_matched_id"
_SCORE = "_pw_dist"
