"""`.dt` expression namespace (reference: internals/expressions/date_time.py).

Operates on DateTimeNaive/DateTimeUtc/Duration (ns-int backed), so most
methods are integer math — the vectorized path maps them onto int64 device
columns.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.datetime_types import (
    DateTimeNaive,
    DateTimeUtc,
    Duration,
    _to_duration,
)
from pathway_tpu.internals.expression import ColumnExpression, MethodCallExpression, wrap_arg


def _m(name: str, expr: ColumnExpression, *args: Any, fn: Any, rt: Any):
    return MethodCallExpression(f"dt.{name}", expr, *args, fn=fn, return_type=rt)


def _utc_to_wall_ns(utc_ns: int, tz_name: str) -> int:
    """UTC instant (ns) -> local wall-clock ns in tz_name. Offsets are
    whole minutes, so sub-second precision carries through exactly."""
    from datetime import datetime, timezone
    from zoneinfo import ZoneInfo

    sec, rem = divmod(utc_ns, 1_000_000_000)
    local = datetime.fromtimestamp(sec, timezone.utc).astimezone(ZoneInfo(tz_name))
    offset = int(local.utcoffset().total_seconds())  # type: ignore[union-attr]
    return (sec + offset) * 1_000_000_000 + rem


def _wall_to_utc_ns(wall_ns: int, tz_name: str) -> int:
    """Local wall-clock ns in tz_name -> UTC instant ns (ambiguous DST
    times resolve to the pre-transition offset, like fold=0)."""
    from datetime import datetime, timezone
    from zoneinfo import ZoneInfo

    sec, rem = divmod(wall_ns, 1_000_000_000)
    naive = datetime.fromtimestamp(sec, timezone.utc).replace(tzinfo=None)
    local = naive.replace(tzinfo=ZoneInfo(tz_name))
    offset = int(local.utcoffset().total_seconds())  # type: ignore[union-attr]
    return (sec - offset) * 1_000_000_000 + rem


class DateTimeNamespace:
    def __init__(self, expr: ColumnExpression):
        self._expr = expr

    # field accessors
    def nanosecond(self):
        return _m("nanosecond", self._expr, fn=lambda x: x.nanosecond(), rt=dt.INT)

    def microsecond(self):
        return _m("microsecond", self._expr, fn=lambda x: x.microsecond(), rt=dt.INT)

    def millisecond(self):
        return _m("millisecond", self._expr, fn=lambda x: x.millisecond(), rt=dt.INT)

    def second(self):
        return _m("second", self._expr, fn=lambda x: x.second(), rt=dt.INT)

    def minute(self):
        return _m("minute", self._expr, fn=lambda x: x.minute(), rt=dt.INT)

    def hour(self):
        return _m("hour", self._expr, fn=lambda x: x.hour(), rt=dt.INT)

    def day(self):
        return _m("day", self._expr, fn=lambda x: x.day(), rt=dt.INT)

    def month(self):
        return _m("month", self._expr, fn=lambda x: x.month(), rt=dt.INT)

    def year(self):
        return _m("year", self._expr, fn=lambda x: x.year(), rt=dt.INT)

    def weekday(self):
        return _m("weekday", self._expr, fn=lambda x: x.weekday(), rt=dt.INT)

    def timestamp(self, unit: str = "ns"):
        return _m("timestamp", self._expr, fn=lambda x: x.timestamp(unit),
                  rt=dt.INT if unit == "ns" else dt.FLOAT)

    # parsing / formatting
    def strptime(self, fmt: Any = None, contains_timezone: bool = False):
        cls = DateTimeUtc if contains_timezone else DateTimeNaive

        def f(s, fmt_):
            return cls(s, fmt=fmt_)

        return _m("strptime", self._expr, wrap_arg(fmt), fn=f,
                  rt=dt.DATE_TIME_UTC if contains_timezone else dt.DATE_TIME_NAIVE)

    def strftime(self, fmt: Any):
        return _m("strftime", self._expr, wrap_arg(fmt),
                  fn=lambda x, fmt_: x.strftime(fmt_), rt=dt.STR)

    def to_naive(self, timezone: str = "UTC"):
        def f(x):
            if isinstance(x, DateTimeUtc):
                return DateTimeNaive(ns=x.timestamp_ns())
            return x
        return _m("to_naive", self._expr, fn=f, rt=dt.DATE_TIME_NAIVE)

    def to_utc(self, from_timezone: str = "UTC"):
        def f(x):
            if isinstance(x, DateTimeNaive):
                return DateTimeUtc(ns=_wall_to_utc_ns(x.timestamp_ns(), from_timezone))
            return x
        return _m("to_utc", self._expr, fn=f, rt=dt.DATE_TIME_UTC)

    def to_naive_in_timezone(self, timezone: str):
        """UTC instant -> wall-clock time in `timezone` (reference
        date_time.py to_naive_in_timezone)."""
        return _m(
            "to_naive_in_timezone", self._expr,
            fn=lambda x: DateTimeNaive(ns=_utc_to_wall_ns(x.timestamp_ns(), timezone)),
            rt=dt.DATE_TIME_NAIVE,
        )

    def add_duration_in_timezone(self, duration: Any, timezone: str):
        """Wall-clock addition: +24h across a DST switch lands on the same
        local hour (reference date_time.py add_duration_in_timezone)."""
        def f(x, d):
            wall = _utc_to_wall_ns(x.timestamp_ns(), timezone)
            return DateTimeUtc(
                ns=_wall_to_utc_ns(wall + _to_duration(d).nanoseconds(), timezone)
            )

        return _m("add_duration_in_timezone", self._expr, wrap_arg(duration),
                  fn=f, rt=dt.DATE_TIME_UTC)

    def subtract_duration_in_timezone(self, duration: Any, timezone: str):
        def f(x, d):
            wall = _utc_to_wall_ns(x.timestamp_ns(), timezone)
            return DateTimeUtc(
                ns=_wall_to_utc_ns(wall - _to_duration(d).nanoseconds(), timezone)
            )

        return _m("subtract_duration_in_timezone", self._expr, wrap_arg(duration),
                  fn=f, rt=dt.DATE_TIME_UTC)

    def subtract_date_time_in_timezone(self, other: Any, timezone: str):
        """Difference measured on the wall clock of `timezone` (reference
        date_time.py subtract_date_time_in_timezone)."""
        def f(x, y):
            a = _utc_to_wall_ns(x.timestamp_ns(), timezone)
            b = _utc_to_wall_ns(y.timestamp_ns(), timezone)
            return Duration(ns=a - b)

        return _m("subtract_date_time_in_timezone", self._expr, wrap_arg(other),
                  fn=f, rt=dt.DURATION)

    def round(self, duration: Any):
        return _m("round", self._expr, wrap_arg(duration),
                  fn=lambda x, d: x.round(_to_duration(d)), rt=None)

    def floor(self, duration: Any):
        return _m("floor", self._expr, wrap_arg(duration),
                  fn=lambda x, d: x.floor(_to_duration(d)), rt=None)

    def from_timestamp(self, unit: str = "s"):
        mult = {"s": 1_000_000_000, "ms": 1_000_000, "us": 1_000, "ns": 1}[unit]
        return _m("from_timestamp", self._expr,
                  fn=lambda x: DateTimeNaive(ns=int(x * mult)), rt=dt.DATE_TIME_NAIVE)

    def utc_from_timestamp(self, unit: str = "s"):
        mult = {"s": 1_000_000_000, "ms": 1_000_000, "us": 1_000, "ns": 1}[unit]
        return _m("utc_from_timestamp", self._expr,
                  fn=lambda x: DateTimeUtc(ns=int(x * mult)), rt=dt.DATE_TIME_UTC)

    # duration accessors
    def nanoseconds(self):
        return _m("nanoseconds", self._expr, fn=lambda d: d.nanoseconds(), rt=dt.INT)

    def microseconds(self):
        return _m("microseconds", self._expr, fn=lambda d: d.microseconds(), rt=dt.INT)

    def milliseconds(self):
        return _m("milliseconds", self._expr, fn=lambda d: d.milliseconds(), rt=dt.INT)

    def seconds(self):
        return _m("seconds", self._expr, fn=lambda d: d.seconds(), rt=dt.INT)

    def minutes(self):
        return _m("minutes", self._expr, fn=lambda d: d.minutes(), rt=dt.INT)

    def hours(self):
        return _m("hours", self._expr, fn=lambda d: d.hours(), rt=dt.INT)

    def days(self):
        return _m("days", self._expr, fn=lambda d: d.days(), rt=dt.INT)

    def weeks(self):
        return _m("weeks", self._expr, fn=lambda d: d.weeks(), rt=dt.INT)
