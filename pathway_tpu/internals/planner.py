"""Adaptive plan optimizer: operator fusion, pushdown, and id elision.

Sits between spec build and engine construction (internals/lowering.py
calls in here from ``Session.node_of``), plus a runtime feedback policy
(`AdaptivePolicy`) that re-plans at safe epoch fences from the metrics
registry. The reference engine plans once and never adapts (SURVEY.md §5)
— this module is the self-tuning layer on top of the static lowering.

Passes (all gated by ``PATHWAY_FUSE``; ``PATHWAY_FUSE=0`` reproduces the
unoptimized plans byte-identically, pinned by the fusion-off CI leg):

* **Chain fusion** — linear runs of rowwise operators (select /
  with_columns / filter, reindex as an object-plane chain terminator)
  collapse into one ``FusedRowwiseNode`` (engine/core.py) that evaluates
  the composed program per wave. On the native plane the fused program
  keeps intermediate values as column arrays: one source decode, no
  intermediate intern-table writes, one final row build.
* **Pushdown** — sargable (numpy-plannable) leading filters push into
  connector scans through the scan-tuning channel; single-side filters
  over inner joins push below the join (fewer rows enter the join's
  arrangements and wire). Projection pushdown below exchanges falls out
  of fusion: fused chains build without the per-operator sharded
  exchange, so projections run before rows ever cross a wire.
* **Id elision** — when the reachable spec DAG proves a scan's row
  identities can never be observed in any output, the scan derives
  sequential keys with the cheap SplitMix64 mix instead of blake2b
  (measured ~48% of the whole jsonl parse); hash-joins whose output ids
  are equally unobservable use the cheap pair mix (``id_mode="cheap"``).
  Soundness: the analysis whitelists spec kinds whose key handling is
  fully understood and vetoes the whole session otherwise; ids are
  "observed" by id-referencing expressions, key-exposing sinks
  (subscribe / capture), and any non-whitelisted operator.
* **Cardinality sketches** — row/distinct-count estimates per join input
  (static inputs sketched at plan time, live inputs incrementally by
  JoinNode) feed a join-orientation cost model. The advice is always
  recorded in the plan report; the spec-level swap is applied only under
  ``PATHWAY_JOIN_REORDER=1`` because reordering permutes intra-wave
  emission order (z-set contents are preserved, byte layout of sinks is
  not — see docs/planner.md).
* **Adaptive re-planning** — ``AdaptivePolicy`` runs at drained epoch
  fences of the streaming pump: it reads the PR-6 metrics registry
  (per-op latency histograms via the ``gauge_value`` / ``counter_value``
  / ``histogram_stats`` read API), re-fuses hot stateless runs the
  static pass could not prove single-consumer (the live node graph
  shows the true fan-out), and retunes the device-exchange batch
  threshold from the wire counters.
"""

from __future__ import annotations

import os
from typing import Any, Callable

from pathway_tpu.internals import expression as ex

# ------------------------------------------------------------------- gates


def fuse_enabled() -> bool:
    """Master optimizer gate: PATHWAY_FUSE=0 reproduces today's plans
    byte-identically (A/B-pinned by the fusion-off leg)."""
    return os.environ.get("PATHWAY_FUSE", "1") != "0"


def join_reorder_mode() -> str:
    """Sketch-costed join input reordering (permutes intra-wave emission
    order: multiset-equivalent, not byte-equivalent). Three modes:

    * ``"off"``  — ``PATHWAY_JOIN_REORDER=0``: never reorder.
    * ``"on"``   — ``PATHWAY_JOIN_REORDER=1``: reorder whenever the
      sketches say the left input is smaller (the historical opt-in).
    * ``"auto"`` — unset (the default): reorder only when the sketches
      disagree by >= ``_REORDER_AUTO_RATIO``x AND no order-sensitive sink
      (subscribe/capture — anything that observes row ids/arrival order)
      is downstream of the join, as computed by ``PlanContext`` and
      re-proved by the verifier's ``check_join_reorder``.
    """
    raw = os.environ.get("PATHWAY_JOIN_REORDER")
    if raw == "0":
        return "off"
    if raw == "1":
        return "on"
    return "auto"


# sketch ratio an "auto" reorder demands: the win must be unambiguous,
# not a coin flip between two near-equal estimates
_REORDER_AUTO_RATIO = 4


def join_reorder_enabled() -> bool:
    """Back-compat boolean view of join_reorder_mode() (forced mode)."""
    return join_reorder_mode() == "on"


def adaptive_enabled() -> bool:
    """Runtime re-planning gate (needs the observability plane for its
    signal; PATHWAY_ADAPTIVE=0 kills the policy, fusion stays static)."""
    return os.environ.get("PATHWAY_ADAPTIVE", "1") != "0"


def megakernel_enabled() -> bool:
    """Wave-cone gate: PATHWAY_MEGAKERNEL=0 skips cone installation so
    the graph executes the per-node fused plan byte-identically
    (A/B-pinned by the megakernel-off leg). Read once at the lowering
    seam (Session.execute), never per wave."""
    return os.environ.get("PATHWAY_MEGAKERNEL", "1") != "0"


# ------------------------------------------------------------ last report

_LAST_REPORT: dict | None = None


def last_report() -> dict | None:
    """The most recent session's plan report (bench / debugging hook)."""
    return _LAST_REPORT


# ------------------------------------------------------------------ sketch


class CardinalitySketch:
    """Cheap row-count + distinct-count estimate, maintained
    incrementally. Distinct counting is exact up to ``cap`` observed
    values, then becomes a lower bound (``exact`` flips False) — enough
    signal for join-orientation costing without HLL machinery."""

    __slots__ = ("rows", "exact", "_seen", "_cap")

    def __init__(self, cap: int = 8192):
        self.rows = 0
        self.exact = True
        self._seen: set[Any] = set()
        self._cap = cap

    def add(self, value: Any = None, n: int = 1) -> None:
        self.rows += n
        if value is not None and self.exact:
            self._seen.add(value)
            if len(self._seen) > self._cap:
                self.exact = False

    @property
    def distinct(self) -> int:
        return len(self._seen)

    def snapshot(self) -> dict:
        return {
            "rows": self.rows,
            "distinct": self.distinct,
            "distinct_exact": self.exact,
        }


# ------------------------------------------------------- id observability
#
# dep[spec] = frozenset of origin markers the spec's OUTPUT KEYS depend
# on. Origins: ("src", spec_id) for elidable scans, ("join", spec_id)
# for hash-joins. A marker lands in `observed` when anything can surface
# its key VALUES: an id-referencing expression, a key-exposing sink, or
# an operator whose key semantics the whitelist doesn't cover.

# spec kinds whose key derivation/usage is fully modeled below; one
# reachable spec outside this set disables id elision for the session
# (conservative global veto — sort exposes neighbor pointers, ix matches
# pointer values against keys, iterate re-keys through scopes, …).
_ELISION_KINDS = frozenset({
    "static", "static_native", "connector", "rowwise", "filter",
    "groupby", "join", "concat", "flatten", "reindex",
    "update_rows", "update_cells", "setop", "with_universe_of", "having",
    "buffer", "forget", "freeze",
})

# operators that MATCH keys across inputs: safe only when every input's
# keys derive identically (same dep set) — consistent under any
# injective key scheme
_KEY_MATCHING = frozenset({
    "update_rows", "update_cells", "setop", "with_universe_of", "having",
})


def _has_id_ref(exprs) -> bool:
    """Any IdReference (incl. join _JoinIdRef) in the expression trees."""
    seen: set[int] = set()

    def rec(e) -> bool:
        if not isinstance(e, ex.ColumnExpression) or id(e) in seen:
            return False
        seen.add(id(e))
        if isinstance(e, ex.IdReference):
            return True
        return any(rec(s) for s in e._sub_expressions())

    return any(rec(e) for e in exprs if isinstance(e, ex.ColumnExpression))


def _spec_exprs(spec) -> list:
    """Every expression a spec's params carry (shallow container sweep)."""
    out: list = []

    def add(v, depth: int = 0) -> None:
        if depth > 3:
            return
        if isinstance(v, ex.ColumnExpression):
            out.append(v)
        elif isinstance(v, (list, tuple)):
            for x in v:
                add(x, depth + 1)
        elif isinstance(v, dict):
            for x in v.values():
                add(x, depth + 1)

    for v in spec.params.values():
        add(v)
    # join `on` pairs / reducer args ride lists already; reducer
    # expressions hide args on the object
    for re_ in spec.params.get("reducer_exprs", []) or []:
        out.extend(a for a in getattr(re_, "_args", ()) if isinstance(a, ex.ColumnExpression))
    return out


def _spec_input_tables(spec) -> list:
    """spec.inputs plus tables referenced only through params (side
    tables in expressions, having indexers, iterate inputs/results) —
    the full consumer-edge set for reachability and fan-out counting."""
    from pathway_tpu.internals.expression_compiler import referenced_tables
    from pathway_tpu.internals.table import Table

    tables = list(spec.inputs)
    exprs = _spec_exprs(spec)
    if exprs:
        for t in referenced_tables(exprs):
            if isinstance(t, Table):
                tables.append(t)
    for ref in spec.params.get("indexers", []) or []:
        t = getattr(ref, "table", None)
        if isinstance(t, Table):
            tables.append(t)
    it_spec = spec.params.get("iterate")
    if it_spec is not None:
        tables.extend(getattr(it_spec, "inputs", {}).values())
    for v in spec.params.values():
        if isinstance(v, Table):
            tables.append(v)
    return tables


class PlanContext:
    """Spec-DAG-wide knowledge for one lowering session: consumer
    counts over the reachable DAG (fusion's single-consumer proofs) and
    the id-observability analysis (key/id elision)."""

    def __init__(
        self,
        roots: list,
        *,
        sink_meta: list | None = None,
        persistent: bool = False,
    ):
        # roots: tables lowering will be asked for. sink_meta: per sink
        # (table, observes_ids) — subscribe/capture expose keys, fs file
        # writers declare observes_ids=False.
        self.persistent = persistent
        self.specs: dict[int, Any] = {}
        self.consumers: dict[int, int] = {}
        self._tables: dict[int, Any] = {}
        self.elision_ok = True
        self.elision_veto_reason: str | None = None
        self.cheap_key_sources: set[int] = set()
        self.cheap_id_joins: set[int] = set()
        self.sketches: dict[int, dict] = {}
        order: list[int] = []  # postorder (inputs before consumers)
        stack = [(t, False) for t in roots]
        while stack:
            table, expanded = stack.pop()
            spec = table._spec
            self._tables.setdefault(spec.id, table)
            if expanded:
                if spec.id not in self.specs:
                    self.specs[spec.id] = spec
                    order.append(spec.id)
                continue
            if spec.id in self.specs:
                continue
            stack.append((table, True))
            for t_in in _spec_input_tables(spec):
                self.consumers[t_in._spec.id] = (
                    self.consumers.get(t_in._spec.id, 0) + 1
                )
                stack.append((t_in, False))
        # sinks consume their tables too — a chain intermediate that is
        # also directly captured/written must not fuse away
        for t in roots:
            self.consumers[t._spec.id] = (
                self.consumers.get(t._spec.id, 0) + 1
            )
        # specs upstream of an order-sensitive sink (subscribe/capture —
        # anything that observes row ids / arrival order). The "auto"
        # join-reorder mode refuses to swap any join such a sink can see,
        # because reordering permutes intra-wave emission order; fs file
        # writers declare observes_ids=False and do not pin anything.
        self.order_sensitive: set[int] = set()
        for table, observes_ids in (sink_meta or []):
            if not observes_ids:
                continue
            up = [table]
            while up:
                t = up.pop()
                sid = t._spec.id
                if sid in self.order_sensitive:
                    continue
                self.order_sensitive.add(sid)
                up.extend(_spec_input_tables(t._spec))
        self._analyze(order, sink_meta or [])

    # ---------------------------------------------------------- analysis

    def _analyze(self, order: list[int], sink_meta: list) -> None:
        if self.persistent:
            # cheap keys are deterministic per plan, but snapshots taken
            # under the other scheme must never silently mix — keep
            # persisted pipelines on blake until signatures carry the
            # key scheme
            self.elision_ok = False
            self.elision_veto_reason = "persistence attached"
        for sid in order:
            if self.specs[sid].kind not in _ELISION_KINDS:
                self.elision_ok = False
                self.elision_veto_reason = (
                    f"spec kind {self.specs[sid].kind!r} outside the "
                    "id-elision whitelist"
                )
                break
        if not self.elision_ok:
            return
        dep: dict[int, frozenset] = {}
        observed: set = set()

        def observe(markers) -> None:
            observed.update(markers)

        for sid in order:
            spec = self.specs[sid]
            kind = spec.kind
            ins = [dep.get(t._spec.id, frozenset())
                   for t in _spec_input_tables(spec)]
            exprs = _spec_exprs(spec)
            if kind == "static":
                dep[sid] = frozenset()
            elif kind == "static_native":
                dep[sid] = frozenset({("src", sid)})
            elif kind == "connector":
                if spec.params.get("native_plane") and not spec.params.get(
                    "upsert"
                ):
                    dep[sid] = frozenset({("src", sid)})
                else:
                    dep[sid] = frozenset()
            elif kind in ("rowwise", "filter", "buffer", "forget", "freeze"):
                if _has_id_ref(exprs):
                    for d in ins:
                        observe(d)
                dep[sid] = ins[0] if ins else frozenset()
            elif kind == "groupby":
                if _has_id_ref(exprs):
                    for d in ins:
                        observe(d)
                dep[sid] = frozenset()  # re-keyed by group values
            elif kind == "reindex":
                if _has_id_ref(exprs):
                    for d in ins:
                        observe(d)
                dep[sid] = frozenset()  # re-keyed by value expression
            elif kind == "join":
                if _has_id_ref(exprs):
                    for d in ins:
                        observe(d)
                l_dep = dep.get(spec.inputs[0]._spec.id, frozenset())
                r_dep = dep.get(spec.inputs[1]._spec.id, frozenset())
                id_mode = spec.params.get("id_mode", "hash")
                if id_mode == "left":
                    dep[sid] = l_dep
                elif id_mode == "right":
                    dep[sid] = r_dep
                else:
                    dep[sid] = l_dep | r_dep | frozenset({("join", sid)})
            elif kind in ("concat", "flatten"):
                # keys pass through (or derive injectively: salted
                # concat rekey, flatten child keys)
                dep[sid] = frozenset().union(*ins) if ins else frozenset()
            elif kind in _KEY_MATCHING:
                base = ins[0] if ins else frozenset()
                if all(d == base for d in ins):
                    dep[sid] = base
                else:
                    for d in ins:
                        observe(d)  # cross-origin key matching
                    dep[sid] = frozenset().union(*ins)
            else:  # unreachable given the whitelist gate
                for d in ins:
                    observe(d)
                dep[sid] = frozenset().union(*ins) if ins else frozenset()
        for table, observes_ids in sink_meta:
            if observes_ids:
                observe(dep.get(table._spec.id, frozenset()))
        for sid in order:
            spec = self.specs[sid]
            marker_src = ("src", sid)
            marker_join = ("join", sid)
            if marker_src in dep.get(sid, frozenset()) and (
                marker_src not in observed
            ):
                self.cheap_key_sources.add(sid)
            if spec.kind == "join" and marker_join not in observed and (
                spec.params.get("id_mode", "hash") == "hash"
            ):
                self.cheap_id_joins.add(sid)

    # ------------------------------------------------------------ access

    def consumer_count(self, spec) -> int:
        return self.consumers.get(spec.id, 0)

    def static_sketch(self, table) -> dict:
        """Plan-time sketch of a static input (sampled distinct count of
        the whole row). Only object-plane "static" specs carry their
        rows at plan time; lazy native scans and connectors report
        rows=None (unknown until parse/poll — the runtime view lives in
        JoinNode.sketch()), so orientation advice never costs from a
        fabricated zero."""
        spec = table._spec
        if spec.id in self.sketches:
            return self.sketches[spec.id]
        sk = CardinalitySketch()
        rows = spec.params.get("rows")
        snap: dict
        if spec.kind == "static" and isinstance(rows, list):
            for (_t, key, _row, _d) in rows[: sk._cap]:
                sk.add(key.value)
            sk.rows = len(rows)
            snap = sk.snapshot()
        else:
            snap = sk.snapshot()
            snap["rows"] = None
        self.sketches[spec.id] = snap
        return snap


# --------------------------------------------------------------- reorder


def _swap_join_spec(spec) -> None:
    """In-place orientation swap of a join spec (sketch-costed; only
    under PATHWAY_JOIN_REORDER=1 and only for unobservable hash ids —
    multiset-equivalent, wave emission order changes)."""
    spec.inputs = [spec.inputs[1], spec.inputs[0]]
    spec.params["on"] = [(r, l) for (l, r) in spec.params["on"]]
    mode = spec.params.get("mode", "inner")
    spec.params["mode"] = {"left": "right", "right": "left"}.get(mode, mode)


# ------------------------------------------------------------- wave cones


def find_cone_chains(graph) -> list[tuple]:
    """Identify wave cones on a lowered graph: scan source → optional
    fused rowwise run → bucketized groupby update (bare or sharded over
    the column-plane exchange). Returns (head, fused_or_None, target)
    triples; engine/cone.py installs them and the verifier's
    cone-contract check re-proves each one before any compile.

    Eligibility is deliberately strict — everything here is a condition
    the cone's byte-identity proof needs (docs/megakernel.md):

    * single-consumer interior: each member feeds ONLY the next member
      (one downstream edge, next member's sole input) — a second
      consumer would observe the head's merged emission the cone never
      builds;
    * the fused run must be a pure native program (no stateful
      suppression, no rekey, object stages only as the per-row BAD
      fallback) — stateful emission depends on cross-wave state the
      per-segment replay would order differently;
    * the target must hold a native groupby plan (plan-mode
      `GroupByNode`); a sharded target additionally needs the
      group-column native route so the exchange pack and the update can
      share one projection.
    """
    from pathway_tpu.engine.core import (
        FusedRowwiseNode,
        GroupByNode,
        InputNode,
    )
    from pathway_tpu.engine.workers import ShardedNode

    def _live_single_consumer(node):
        if len(node.downstream) != 1:
            return None
        nxt = node.downstream[0][0]
        if getattr(nxt, "_replaced", False) or nxt._cone_absorbed:
            return None
        if len(nxt.inputs) != 1 or nxt.inputs[0] is not node:
            return None
        return nxt

    def _plan_mode_groupby(node) -> bool:
        return (
            isinstance(node, GroupByNode)
            and node._native is not None
            and node._plan is not None
        )

    chains: list[tuple] = []
    for head in graph.nodes:
        if type(head) is not InputNode:
            continue
        if head._cone is not None or head._cone_absorbed:
            continue
        cur = _live_single_consumer(head)
        if cur is None:
            continue
        fused = None
        if isinstance(cur, FusedRowwiseNode):
            if (
                cur._program is None
                or cur._stateful
                or cur.rekey is not None
                or getattr(cur, "_replaced", False)
            ):
                continue
            nxt = _live_single_consumer(cur)
            if nxt is None:
                continue
            fused, cur = cur, nxt
        target = cur
        if isinstance(target, ShardedNode):
            if len(target.inputs) != 1:
                continue
            route = target.native_routes[0]
            if route is None or route[0] != "group":
                continue
            if not all(_plan_mode_groupby(r) for r in target.replicas):
                continue
        elif not _plan_mode_groupby(target):
            continue
        chains.append((head, fused, target))
    return chains


# ---------------------------------------------------------------- report


def new_report() -> dict:
    return {
        "enabled": fuse_enabled(),
        "fusion_groups": [],
        "pushdowns": [],
        "join_orders": [],
        "elision": {"sources": 0, "joins": 0, "veto": None},
        "nodes_before": 0,
        "nodes_after": 0,
        "replans": [],
    }


def publish_report(report: dict) -> None:
    global _LAST_REPORT
    _LAST_REPORT = report


# ------------------------------------------------------- adaptive policy


class AdaptivePolicy:
    """Metrics-fed re-planning at safe epoch fences.

    Runs from the streaming pump when the scheduler is fully drained (an
    epoch fence: no in-flight waves, all state retired through the
    current frontier). Two actions, both recorded in the plan report and
    as ``pathway_planner_*`` counters:

    * re-fuse hot stateless runs: the live node graph shows true
      fan-out, so linear Map/Filter/FusedRowwise runs that static fusion
      could not prove single-consumer (dead spec consumers, multi-sink
      programs) fuse at runtime once their measured share of wave time
      (per-op latency histograms, read via ``histogram_stats``) crosses
      ``hot_share``;
    * retune the device-exchange auto threshold: if the wire counters
      show exchanges averaging below ``min_rows_per_exchange`` rows, the
      crossover threshold doubles (bounded), so tiny batches stop paying
      dispatch overhead.
    """

    def __init__(
        self,
        graph,
        report: dict | None = None,
        hot_share: float = 0.10,
        min_rows_per_exchange: int = 64,
        interval_s: float = 2.0,
    ):
        self.graph = graph
        self.report = report if report is not None else new_report()
        self.hot_share = float(
            os.environ.get("PATHWAY_ADAPTIVE_HOT_SHARE", hot_share)
        )
        self.min_rows_per_exchange = min_rows_per_exchange
        self.interval_s = interval_s
        self._last = 0.0
        self._exchange_tuned = 0
        self._spill_tuned = 0
        self._spill_probe_seen = 0.0
        self._morsel_tuned = 0
        self._morsel_task_seen = 0.0
        self._morsel_task_total = 0.0
        # fresh tuning per run: the exchanger is a process-wide
        # singleton, and a previous run's doublings must not ratchet
        # into this one (same discipline as the scan-tuning claim)
        from pathway_tpu.parallel import column_plane as cp
        from pathway_tpu.parallel import device_exchange as dx

        if dx._ENGINE_EXCHANGER is not None:
            dx._ENGINE_EXCHANGER._auto_min = dx._ENGINE_EXCHANGER._auto_min_base
        if cp._ENGINE_EXCHANGER is not None:
            cp._ENGINE_EXCHANGER._auto_min_rows = (
                cp._ENGINE_EXCHANGER._auto_min_rows_base
            )

    # ------------------------------------------------------------ fences

    def maybe_replan(self, scheduler) -> int:
        """Called at a drained fence; returns number of plan changes."""
        import time as _time

        from pathway_tpu.internals import observability as _obs

        now = _time.monotonic()
        if now - self._last < self.interval_s:
            return 0
        self._last = now
        plane = _obs.PLANE
        if plane is None:
            return 0
        changes = self._refuse_hot_chains(plane)
        changes += self._retune_exchange(plane)
        changes += self._retune_spill(plane)
        changes += self._retune_morsels(plane)
        if changes and scheduler is not None:
            scheduler.replan_refresh()
        if changes:
            # adaptive re-fusion changes the live plan after the static
            # report was published — refresh the node count so
            # /statistics and last_report() describe what is running,
            # not the plan as lowered
            self.report["nodes_after"] = sum(
                1 for n in self.graph.nodes
                if not getattr(n, "_replaced", False)
            )
        return changes

    # ------------------------------------------------------- re-fusion

    def _wave_share(self, plane, node) -> float:
        cnt, total = plane.metrics.histogram_stats(
            "pathway_operator_wave_seconds",
            {
                "operator": type(node).__name__,
                "label": getattr(node, "label", None) or "",
                "id": str(node.node_id),
            },
        )
        if not cnt:
            return 0.0
        _all_cnt, all_total = plane.metrics.histogram_stats(
            "pathway_operator_wave_seconds", None
        )
        return total / all_total if all_total else 0.0

    def _refuse_hot_chains(self, plane) -> int:
        from pathway_tpu.engine.core import (
            FilterNode,
            FusedRowwiseNode,
            MapNode,
        )

        fusible = (MapNode, FilterNode, FusedRowwiseNode)
        changes = 0
        for node in list(self.graph.nodes):
            if not isinstance(node, fusible) or getattr(node, "_replaced", False):
                continue
            if node._cone_absorbed or node._cone is not None:
                continue  # cone members fire through the cone, not alone
            # start of a linear stateless run: single live downstream
            # that is also fusible, whose only input is this node
            chain = [node]
            cur = node
            while True:
                downs = [
                    d for d, _i in cur.downstream
                    if not getattr(d, "_replaced", False)
                ]
                if len(downs) != 1 or not isinstance(downs[0], fusible):
                    break
                nxt = downs[0]
                if len(nxt.inputs) != 1 or any(b for b in nxt.buffers):
                    break
                if nxt._cone_absorbed:
                    break
                chain.append(nxt)
                cur = nxt
            if len(chain) < 2:
                continue
            share = sum(self._wave_share(plane, n) for n in chain)
            if share < self.hot_share:
                continue
            fused = FusedRowwiseNode.from_live_nodes(self.graph, chain)
            if fused is None:
                continue
            changes += 1
            plane.metrics.counter("pathway_planner_refusions")
            plane.record(
                "replan", action="refuse",
                nodes=[n.describe() for n in chain], share=round(share, 4),
            )
            self.report["replans"].append({
                "action": "refuse", "share": round(share, 4),
                "nodes": [n.describe() for n in chain],
            })
        return changes

    # ------------------------------------------------- exchange retune

    def _retune_exchange(self, plane) -> int:
        from pathway_tpu.parallel import column_plane as cp
        from pathway_tpu.parallel import device_exchange as dx

        exchanger = dx._ENGINE_EXCHANGER
        col = cp._ENGINE_EXCHANGER
        if (exchanger is None and col is None) or self._exchange_tuned >= 4:
            return 0
        # honor an auto<->force env flip between runs on the singletons
        if exchanger is not None:
            exchanger._mode = dx.mode()
        if col is not None:
            col._mode = dx.mode()
        inv = plane.metrics.counter_value("pathway_device_exchange_invocations")
        rows = plane.metrics.counter_value("pathway_device_exchange_rows")
        if inv < 8:
            return 0
        rpi = rows / inv
        tuned = False
        if rpi < self.min_rows_per_exchange:
            # thin batches are paying dispatch overhead: raise the bar
            action = "exchange_retune"
            if exchanger is not None:
                # bounded vs the env default; a knob already saturated
                # at the bound must not burn budget or record a replan
                bound = min(exchanger._auto_min_base * 16, 1 << 26)
                if exchanger._auto_min < bound:
                    exchanger._auto_min = min(exchanger._auto_min * 2, bound)
                    tuned = True
            elif col._auto_min_rows < min(
                col._auto_min_rows_base * 16, 1 << 24
            ):
                # scalar-only workloads never build the vector exchanger:
                # tune the column plane's ROW threshold directly
                col._auto_min_rows = min(
                    col._auto_min_rows * 2,
                    col._auto_min_rows_base * 16,
                    1 << 24,
                )
                tuned = True
        elif rpi >= 8 * self.min_rows_per_exchange:
            # sustained wins (fat batches riding the wire every wave):
            # LOWER the crossover so the column lift engages earlier —
            # bounded at base/16 so auto can never reach trivial batches
            action = "exchange_retune_down"
            if exchanger is not None:
                floor = max(exchanger._auto_min_base // 16, 4096)
                if exchanger._auto_min > floor:
                    exchanger._auto_min = max(exchanger._auto_min // 2, floor)
                    tuned = True
            else:
                floor = max(
                    col._auto_min_rows_base // 16, 4096 // cp._AUTO_LANES
                )
                if col._auto_min_rows > floor:
                    col._auto_min_rows = max(col._auto_min_rows // 2, floor)
                    tuned = True
        if not tuned:
            # saturated bound or mid-band rpi: record nothing and leave
            # the retune budget for fences that can still move a knob
            return 0
        if col is not None and exchanger is not None:
            # one tuned crossover governs both planes: the column plane's
            # ROW threshold derives from the element threshold / lane count
            col._auto_min_rows = max(exchanger._auto_min // cp._AUTO_LANES, 1)
        auto_min = (
            exchanger._auto_min
            if exchanger is not None
            else col._auto_min_rows * cp._AUTO_LANES
        )
        self._exchange_tuned += 1
        plane.metrics.counter("pathway_planner_retunes")
        plane.record("replan", action=action, auto_min=auto_min)
        self.report["replans"].append({
            "action": action, "auto_min": auto_min,
        })
        return 1

    # ---------------------------------------------------- spill retune

    def _retune_spill(self, plane) -> int:
        """Thrash detection for out-of-core arrangements: when the probe
        ladder keeps landing on disk (run hits dominate the fence-to-
        fence probe window, i.e. the working set exceeds the resident
        budget), double the spilled stores' budgets — bounded at 4x the
        configured base so a genuinely huge key space cannot re-inflate
        RSS past what the operator asked for."""
        from pathway_tpu.engine import spill as _spill

        if self._spill_tuned >= 4:
            return 0
        stores = [s for s in _spill.stores() if s.has_runs]
        if not stores:
            return 0
        hits = plane.metrics.counter_value(
            "pathway_spill_probe_tier", {"tier": "run_hit"}
        )
        window = hits - self._spill_probe_seen
        self._spill_probe_seen = hits
        # thrash signal: at least one full budget's worth of groups came
        # back off disk since the last fence — the tail is too small to
        # hold the live working set
        min_budget = min(s.budget for s in stores)
        if window < max(min_budget, 64):
            return 0
        tuned = []
        for s in stores:
            bound = s.base_budget * 4
            if s.budget < bound:
                s.budget = min(s.budget * 2, bound)
                tuned.append({"store": s.label, "budget": s.budget})
        if not tuned:
            return 0
        self._spill_tuned += 1
        plane.metrics.counter("pathway_planner_retunes")
        plane.record(
            "replan", action="spill_retune",
            run_hits=int(window), stores=tuned,
        )
        self.report["replans"].append({
            "action": "spill_retune", "run_hits": int(window),
            "stores": tuned,
        })
        return 1

    # --------------------------------------------------- morsel retune

    def _retune_morsels(self, plane) -> int:
        """Morsel granularity off the wave histograms: the steal
        scheduler publishes per-morsel execution latency
        (``pathway_morsel_task_seconds``); a fence window averaging
        under ~1ms means morsels are paying more claim traffic than
        compute (double the rows), over ~50ms means a straggler is too
        coarse for stealing to smooth (halve them). Bounded by
        ``morsel.set_rows`` (16x either side of the env-configured base)
        and by the usual per-run retune budget."""
        from pathway_tpu.engine import morsel as _morsel

        if self._morsel_tuned >= 4 or not _morsel.enabled_cached():
            return 0
        cnt, total = plane.metrics.histogram_stats(
            "pathway_morsel_task_seconds", None
        )
        window = cnt - self._morsel_task_seen
        if window < 64:
            return 0  # too few morsels since the last fence to judge
        mean = (total - self._morsel_task_total) / window
        self._morsel_task_seen = cnt
        self._morsel_task_total = total
        rows = _morsel.morsel_rows_cached()
        if mean < 1e-3:
            applied = _morsel.set_rows(rows * 2)
            action = "morsel_retune_up"
        elif mean > 50e-3:
            applied = _morsel.set_rows(rows // 2)
            action = "morsel_retune_down"
        else:
            return 0
        if applied == rows:
            return 0  # saturated bound: leave the budget for live knobs
        self._morsel_tuned += 1
        plane.metrics.counter("pathway_planner_retunes")
        plane.record(
            "replan", action=action,
            mean_ms=round(mean * 1e3, 3), morsel_rows=applied,
        )
        self.report["replans"].append({
            "action": action, "mean_ms": round(mean * 1e3, 3),
            "morsel_rows": applied,
        })
        return 1
