"""pw.io.subscribe (reference: io/_subscribe.py)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def subscribe(
    table: Table,
    on_change: Callable[..., None] | None = None,
    on_end: Callable[[], None] | None = None,
    on_time_end: Callable[[int], None] | None = None,
    *,
    skip_persisted_batch: bool = True,
    name: str | None = None,
) -> None:
    """Call `on_change(key, row: dict, time: int, is_addition: bool)` for
    every change, `on_time_end(time)` after each closed engine time,
    `on_end()` at stream end."""
    names = table._column_names()

    def wrapped_on_change(key: Any, row: tuple, time: int, is_addition: bool) -> None:
        if on_change is not None:
            on_change(key=key, row=dict(zip(names, row)), time=time, is_addition=is_addition)

    G.add_sink(
        "subscribe",
        table,
        on_change=wrapped_on_change if on_change is not None else None,
        on_time_end=on_time_end,
        on_end=on_end,
    )
