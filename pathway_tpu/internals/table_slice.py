"""TableSlice: a reorderable, renamable collection of column references
(reference: internals/table_slice.py). Created by `Table.slice`; usable
anywhere select/with_columns accept columns.

>>> import pathway_tpu as pw
>>> t1 = pw.debug.table_from_markdown('''
... age | owner | pet
... 10  | Alice | dog
... 9   | Bob   | dog
... ''')
>>> t1.slice.without("age").with_suffix("_col")
TableSlice({'owner_col': <table>.owner, 'pet_col': <table>.pet})
"""

from __future__ import annotations

from typing import Any, Iterable

from pathway_tpu.internals.expression import ColumnReference


class TableSlice:
    _mapping: dict[str, ColumnReference]
    _table: Any

    def __init__(self, mapping: dict[str, ColumnReference], table: Any):
        self._mapping = dict(mapping)
        self._table = table

    def __iter__(self) -> Iterable[ColumnReference]:
        # renamed entries yield refs carrying their OUTPUT name, so
        # `t.select(*slice.with_suffix(...))` lands the new names even
        # though * unpacks before select sees the slice
        for name, ref in self._mapping.items():
            if ref.name != name:
                ref = ColumnReference(ref.table, ref.name)
                ref._out_name = name
            yield ref

    def __len__(self) -> int:
        return len(self._mapping)

    def __repr__(self) -> str:
        cols = ", ".join(f"{k!r}: <table>.{v.name}" for k, v in self._mapping.items())
        return "TableSlice({" + cols + "})"

    def keys(self):
        return self._mapping.keys()

    def items(self):
        return self._mapping.items()

    def _name_of(self, arg: str | ColumnReference) -> str:
        if isinstance(arg, ColumnReference):
            if arg.table is not self._table:
                raise ValueError(
                    "TableSlice expects columns of its own table"
                )
            return arg.name
        return arg

    def __getitem__(self, arg):
        if isinstance(arg, (list, tuple)):
            names = [self._name_of(a) for a in arg]
            return TableSlice(
                {n: self._mapping[n] for n in names}, self._table
            )
        return self._mapping[self._name_of(arg)]

    def __getattr__(self, name: str) -> ColumnReference:
        mapping = self.__dict__.get("_mapping")
        if mapping is not None and name in mapping:
            return mapping[name]
        raise AttributeError(name)

    def without(self, *cols: str | ColumnReference) -> "TableSlice":
        drop = {self._name_of(c) for c in cols}
        unknown = drop - set(self._mapping)
        if unknown:
            raise KeyError(f"columns {sorted(unknown)} not in the slice")
        return TableSlice(
            {k: v for k, v in self._mapping.items() if k not in drop},
            self._table,
        )

    def rename(self, mapping: dict[str | ColumnReference, str]) -> "TableSlice":
        renames = {self._name_of(k): v for k, v in mapping.items()}
        unknown = set(renames) - set(self._mapping)
        if unknown:
            raise KeyError(f"columns {sorted(unknown)} not in the slice")
        return TableSlice(
            {renames.get(k, k): v for k, v in self._mapping.items()},
            self._table,
        )

    def with_prefix(self, prefix: str) -> "TableSlice":
        return TableSlice(
            {prefix + k: v for k, v in self._mapping.items()}, self._table
        )

    def with_suffix(self, suffix: str) -> "TableSlice":
        return TableSlice(
            {k + suffix: v for k, v in self._mapping.items()}, self._table
        )

    @property
    def slice(self) -> "TableSlice":
        return self

    def ix_ref(self, *args: Any, **kwargs: Any) -> "TableSlice":
        # look up through ORIGINAL column names; keep this slice's
        # (possibly renamed) output names
        target = self._table.ix_ref(*args, **kwargs)
        return TableSlice(
            {
                name: ColumnReference(target, ref.name)
                for name, ref in self._mapping.items()
            },
            target,
        )
