"""Relevance assertions for BM25 and hybrid-RRF retrieval: scores and
rankings are checked against INDEPENDENT models (hand-computed Okapi
BM25, explicit reciprocal-rank fusion), not against engine snapshots —
the round-4 VERDICT's tier-2 relevance ask. Reference:
src/external_integration/tantivy_integration.rs,
python/pathway/stdlib/indexing/hybrid_index.py."""

from __future__ import annotations

import math
import re
from collections import defaultdict

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals.keys import key_for_values
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.stdlib.indexing.host_indexes import (
    Bm25Index,
    LshIndex,
    VectorSlabIndex,
)


@pytest.fixture(autouse=True)
def _fresh_graph():
    G.clear()
    yield
    G.clear()


CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "a quick survey of streaming databases",
    "incremental view maintenance for databases",
    "the lazy dog sleeps all day",
    "brown bears fish in the quick river",
]


def _model_bm25(corpus, query, k1=1.2, b=0.75):
    """Independent Okapi BM25 with the log(1 + (N-df+0.5)/(df+0.5)) idf."""
    tok = lambda s: re.findall(r"[a-z0-9]+", s.lower())
    docs = [tok(d) for d in corpus]
    n = len(docs)
    avg = sum(len(d) for d in docs) / n
    df: dict = defaultdict(int)
    for d in docs:
        for t in set(d):
            df[t] += 1
    scores = []
    for d in docs:
        s = 0.0
        for t in tok(query):
            if df[t] == 0:
                continue
            tf = d.count(t)
            if tf == 0:
                continue
            idf = math.log(1.0 + (n - df[t] + 0.5) / (df[t] + 0.5))
            s += idf * tf * (k1 + 1) / (tf + k1 * (1 - b + b * len(d) / avg))
        scores.append(s)
    return scores


@pytest.mark.parametrize(
    "query", ["quick fox", "databases", "lazy dog", "brown", "quick"]
)
def test_bm25_scores_match_model(query):
    idx = Bm25Index()
    keys = [key_for_values(i) for i in range(len(CORPUS))]
    for key, doc in zip(keys, CORPUS):
        idx.add(key, doc)
    got = idx.search(query, k=len(CORPUS))
    model = _model_bm25(CORPUS, query)
    got_scores = {key: -d for key, d in got}
    for i, key in enumerate(keys):
        if model[i] > 0:
            assert got_scores[key] == pytest.approx(model[i]), (query, i)
        else:
            assert key not in got_scores
    # ranking order matches the model's descending-score order
    want_order = [
        keys[i]
        for i in sorted(
            (i for i in range(len(CORPUS)) if model[i] > 0),
            key=lambda i: (-model[i], keys[i].value),
        )
    ]
    assert [key for key, _d in got] == want_order


def test_bm25_update_and_remove_rescore():
    """Removing / re-adding documents changes idf and avgdl — scores must
    track the live corpus, not the insertion history."""
    idx = Bm25Index()
    keys = [key_for_values(i) for i in range(len(CORPUS))]
    for key, doc in zip(keys, CORPUS):
        idx.add(key, doc)
    idx.remove(keys[1])
    idx.remove(keys[2])
    live = [CORPUS[0], CORPUS[3], CORPUS[4]]
    model = _model_bm25(live, "quick")
    got = {key: -d for key, d in idx.search("quick", k=10)}
    for key, doc, m in zip([keys[0], keys[3], keys[4]], live, model):
        if m > 0:
            assert got[key] == pytest.approx(m)
    # re-add one with different text: tf changes rank
    idx.add(keys[1], "quick quick quick")
    got2 = idx.search("quick", k=1)
    assert got2[0][0] == keys[1]  # highest tf for 'quick' wins


def test_bm25_ties_break_by_key_not_insertion_order():
    idx1, idx2 = Bm25Index(), Bm25Index()
    ka, kb = key_for_values("a"), key_for_values("b")
    idx1.add(ka, "same words here")
    idx1.add(kb, "same words here")
    idx2.add(kb, "same words here")
    idx2.add(ka, "same words here")
    assert [k for k, _ in idx1.search("same words", 2)] == [
        k for k, _ in idx2.search("same words", 2)
    ]


# ------------------------------------------------------------ hybrid RRF


def test_hybrid_rrf_fusion_matches_explicit_model():
    """DataIndex over HybridIndex must rank by reciprocal-rank fusion of
    the inner indexes' rankings: score(d) = sum_i 1/(k0 + rank_i(d))."""
    from pathway_tpu.stdlib.indexing import (
        DataIndex,
        HybridIndex,
        TantivyBM25,
    )
    from pathway_tpu.stdlib.indexing.nearest_neighbors import BruteForceKnn

    class TwoHotEmbedder(pw.UDF):
        """text -> deterministic 4-dim bag-of-marker vector."""

        def __wrapped__(self, text, **kwargs):
            v = np.zeros(4, np.float32)
            for i, marker in enumerate(["alpha", "beta", "gamma", "delta"]):
                if marker in text:
                    v[i] = 1.0
            n = np.linalg.norm(v)
            return v / n if n else v + 0.5

    texts = [
        "alpha beta news",
        "alpha gamma report",
        "delta summary",
        "beta gamma digest",
    ]
    docs = pw.debug.table_from_rows(
        pw.schema_from_types(text=str), [(t,) for t in texts]
    )
    emb = TwoHotEmbedder()
    hybrid = HybridIndex(
        [
            BruteForceKnn(data_column=docs.text, dimensions=4, embedder=emb),
            TantivyBM25(data_column=docs.text),
        ]
    )
    queries = pw.debug.table_from_rows(
        pw.schema_from_types(q=str), [("alpha beta",)]
    )
    res = DataIndex(docs, hybrid).query_as_of_now(
        queries.q, number_of_matches=4
    )
    df = pw.debug.table_to_pandas(res, include_id=False)
    got_order = list(df.iloc[0]["text"])

    # explicit model: vector ranking by cosine + bm25 ranking, fused
    def vec(t):
        v = np.zeros(4)
        for i, m in enumerate(["alpha", "beta", "gamma", "delta"]):
            if m in t:
                v[i] = 1.0
        n = np.linalg.norm(v)
        return v / n if n else v + 0.5

    qv = vec("alpha beta")
    vrank = sorted(
        range(len(texts)), key=lambda i: -float(vec(texts[i]) @ qv)
    )
    bscores = _model_bm25(texts, "alpha beta")
    brank = sorted(
        (i for i in range(len(texts)) if bscores[i] > 0),
        key=lambda i: -bscores[i],
    )
    K0 = 60  # standard RRF constant
    fused: dict = defaultdict(float)
    for r, i in enumerate(vrank):
        fused[i] += 1.0 / (K0 + r + 1)
    for r, i in enumerate(brank):
        fused[i] += 1.0 / (K0 + r + 1)
    want_first = texts[max(fused, key=lambda i: fused[i])]
    assert got_order[0] == want_first == "alpha beta news"
    # every text containing neither query term ranks last
    assert got_order[-1] == "delta summary"


# ------------------------------------------------------- LSH recall floor


def test_lsh_recall_floor_against_exact():
    """With enough OR-tables the LSH index recalls most true neighbors:
    recall@5 >= 0.8 vs brute force on clustered data (a relevance
    invariant, not an exact-score check — LSH is sampled)."""
    rng = np.random.default_rng(7)
    dim, n_per, n_clusters = 16, 40, 4
    centers = rng.normal(scale=5.0, size=(n_clusters, dim))
    vecs, keys = [], []
    lsh = LshIndex(n_or=16, n_and=3, bucket_length=6.0)
    exact = VectorSlabIndex(dimensions=dim, metric="l2sq", device=False)
    for i in range(n_clusters * n_per):
        v = (centers[i % n_clusters] + rng.normal(size=dim)).astype(
            np.float32
        )
        key = key_for_values(i)
        vecs.append(v)
        keys.append(key)
        lsh.add(key, v)
        exact.add(key, v)
    hits = total = 0
    for qi in range(0, len(vecs), 10):
        q = vecs[qi]
        true = {key for key, _d in exact.search(q, 5)}
        got = {key for key, _d in lsh.search(q, 5)}
        hits += len(true & got)
        total += len(true)
    assert hits / total >= 0.8, f"recall {hits}/{total}"
