#!/usr/bin/env python
"""Crash-recovery equivalence drills: the exactly-once claim, regression-tested.

For a matrix of seeded fault schedules × fault kinds, this harness runs
the SAME streaming pipeline (a journaled python source → groupby counts →
a batched device-plane UDF → subscribe sink) three ways:

  1. fault-free baseline (``PATHWAY_FAULTS=0``),
  2. with an injected fault — crash mid-wave, torn metadata commit,
     truncated journal segment, lost operator snapshot, flapping
     connector reads, failing device dispatches,
  3. (for crash kinds) a recovery generation that resumes from the same
     persistence directory.

and asserts the **consolidated final output table is byte-identical** to
the baseline's — the persistence layer's exactly-once contract, the
connector retry policy, and the device plane's degradation ladder, all
proven against deterministic failures (engine/faults.py).

Usage::

    python scripts/chaos_drill.py --quick          # 4 kinds x 1 seed (CI leg)
    python scripts/chaos_drill.py                  # 6 kinds x 3 seeds
    python scripts/chaos_drill.py --kinds torn_metadata --seeds 0,1,2
    python scripts/chaos_drill.py --json /tmp/chaos.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CRASH_EXIT = 17  # engine/faults.py CRASH_EXIT_CODE

# --------------------------------------------------------------- workload
#
# One pipeline exercising every failure domain: a paced seekable source
# whose reads go through pw.io.RetryPolicy (connector domain), journaled
# persistence with operator snapshots (persistence domain), a groupby
# (operator state), and a batched UDF dispatching through a DevicePlane
# program (device domain). Deliveries append to a jsonl the harness
# consolidates across crash generations.

WORKLOAD = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    import pathway_tpu as pw
    from pathway_tpu.engine.device_plane import DeviceProgram, get_device_plane
    from pathway_tpu.internals import observability as obs
    from pathway_tpu.io import RetryPolicy
    from pathway_tpu.io.python import ConnectorSubject

    PDIR, OUT, N_EVENTS = sys.argv[1], sys.argv[2], int(sys.argv[3])
    SPEC = os.environ.get("PATHWAY_FAULTS", "0")
    # arm the flight recorder BEFORE any fault can fire: every shot of
    # the schedule must land in the recorder timeline (harness asserts)
    obs.maybe_enable_from_env()

    DeviceProgram.PROBE_BASE_S = 0.01  # drill-speed re-probe backoff
    plane = get_device_plane()
    prog = plane.program("chaos_double", lambda x: x * 2 + 1)

    @pw.udf(batched=True, deterministic=True)
    def boost(vs: list[int]) -> list[int]:
        arr = np.asarray(vs, dtype=np.int32)
        b = plane.buckets.rows_bucket(len(arr))
        out = prog(np.pad(arr, (0, b - len(arr))), bucket=b)
        return [int(x) for x in np.asarray(out)[: len(arr)]]

    src_policy = RetryPolicy(
        "chaos-src", max_attempts=10, initial_delay_ms=1,
        backoff_factor=1.0, jitter_ms=0, breaker_threshold=None,
    )

    def committed_offset() -> int:
        try:
            with open(os.path.join(PDIR, "metadata.json")) as f:
                return int(json.load(f).get("offsets", {{}}).get("words", 0))
        except Exception:
            return 0

    class Words(ConnectorSubject):
        def run(self):
            import time
            for i in range(N_EVENTS):
                # the injectable read: io.retry.chaos-src faults land
                # here and the unified policy absorbs them
                w = src_policy.call(lambda i=i: f"w{{i % 7}}")
                self.next(word=w)
                time.sleep(0.004)
                if i % 10 == 9:
                    # deterministic mid-run epochs: stall until a commit
                    # covers everything emitted so far (in-flight device
                    # holds resolve, the cadence checkpoint cuts). Time-
                    # based gaps are flaky on slow CI boxes — the commit
                    # count then varies and seeded @hit schedules miss.
                    deadline = time.monotonic() + 5.0
                    while (
                        committed_offset() < i + 1
                        and time.monotonic() < deadline
                    ):
                        time.sleep(0.002)

    t = pw.io.python.read(
        Words(), schema=pw.schema_from_types(word=str), name="words"
    )
    counts = t.groupby(t.word).reduce(
        t.word, count=pw.reducers.count()
    )
    counts = counts.select(
        counts.word, counts.count, boosted=boost(counts.count)
    )
    sink = open(OUT, "a")
    # newline guard: a previous generation's hard crash may have left a
    # torn final line; without this, the first record of THIS generation
    # would concatenate onto it and both would be lost
    sink.write("\\n")
    def on_change(key, row, time, is_addition):
        sink.write(json.dumps({{
            "w": row["word"], "c": row["count"], "b": row["boosted"],
            "add": is_addition,
        }}) + "\\n")
        sink.flush()
    pw.io.subscribe(counts, on_change=on_change)
    pw.run(persistence_config=pw.persistence.Config(
        pw.persistence.Backend.filesystem(PDIR)))

    # a non-crash fault schedule must actually have exercised its domain
    if "io.retry.chaos-src" in SPEC:
        assert src_policy.retries_total > 0, "flap schedule never flapped"
    if "device.dispatch" in SPEC:
        assert prog.host_fallbacks > 0, "device schedule never degraded"
    # normal-exit black box (hard crashes dump inside faults.hard_crash)
    obs.dump_flight("drill-end")
    """
)


# ------------------------------------------------------------ fault kinds
#
# Hit numbers are seeded so each seed crashes at a different wave /
# commit / journal offset; all stay comfortably inside the run's hit
# budget (~25+ pumped waves, N_EVENTS journal appends, and — thanks to
# the source's wait-for-commit pacing — at least N_EVENTS/10 + 2
# checkpoint commits).

KINDS = {
    "crash_mid_wave": lambda seed: f"seed={seed};runtime.wave@{3 + 3 * seed}",
    "torn_metadata": lambda seed: (
        f"seed={seed};persistence.metadata.torn@{2 + seed}"
    ),
    "torn_journal": lambda seed: (
        f"seed={seed};persistence.journal.torn@{10 + 9 * seed}"
    ),
    # crash right AFTER a mid-run commit, then the harness deletes one of
    # that epoch's snapshot files: restore must catch the manifest hole
    # and fall back to the history epoch
    "lost_snapshot": lambda seed: (
        f"seed={seed};persistence.checkpoint.post_commit@{2 + seed}"
    ),
    "connector_flap": lambda seed: f"seed={seed};io.retry.chaos-src~0.25",
    "device_dispatch": lambda seed: (
        f"seed={seed};device.dispatch.chaos_double@1+2"
    ),
}
CRASH_KINDS = {"crash_mid_wave", "torn_metadata", "torn_journal", "lost_snapshot"}
QUICK_KINDS = ["crash_mid_wave", "torn_metadata", "connector_flap", "device_dispatch"]
MAX_GENERATIONS = 4  # a schedule may land a crash in the recovery window


def _run_workload(
    pdir: str, out: str, spec: str, n_events: int,
    flight_dir: str | None = None,
) -> int:
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PATHWAY_FAULTS": spec}
    if flight_dir is not None:
        env["PATHWAY_OBSERVABILITY"] = "1"
        env["PATHWAY_FLIGHT_DIR"] = flight_dir
        # a roomy ring: the default 4096 could evict early fault events
        # behind a long run's wave spans, failing _check_flight falsely
        env.setdefault("PATHWAY_OBS_RING", "65536")
    r = subprocess.run(
        [sys.executable, "-c", WORKLOAD.format(repo=REPO),
         pdir, out, str(n_events)],
        capture_output=True, text=True, timeout=240,
        env=env,
    )
    if r.returncode not in (0, CRASH_EXIT):
        raise RuntimeError(
            f"workload failed rc={r.returncode} (spec={spec!r}):\n"
            + r.stderr[-3000:]
        )
    return r.returncode


def _check_flight(flight_dir: str, kind: str, seed: int) -> dict:
    """Assert the flight-recorder contract on a faulted case's dumps:
    every shot the schedule logged (`faults_fired`) has a matching
    `fault` event in the recorder timeline — the postmortem never hides
    an injected failure. Returns summary counts for the case record."""
    import glob

    dumps = sorted(glob.glob(os.path.join(flight_dir, "flight-*.json")))
    assert dumps, f"{kind} seed {seed}: no flight-recorder dumps written"
    events: list[dict] = []
    fired: list[tuple] = []
    for path in dumps:
        with open(path) as f:
            payload = json.load(f)
        events.extend(payload.get("events", []))
        fired.extend(tuple(x) for x in payload.get("faults_fired", []))
    fault_events = {
        (e.get("point"), e.get("hit"))
        for e in events if e.get("k") == "fault"
    }
    missing = [shot for shot in fired if shot not in fault_events]
    assert not missing, (
        f"{kind} seed {seed}: {len(missing)} injected fault(s) absent from "
        f"the flight-recorder timeline: {missing[:5]}"
    )
    assert fired, (
        f"{kind} seed {seed}: schedule fired nothing — dumps carry no shots"
    )
    return {
        "dumps": len(dumps),
        "fault_shots": len(fired),
        "wave_events": sum(1 for e in events if e.get("k") == "wave"),
    }


def consolidate(deliveries_path: str) -> bytes:
    """Canonical bytes of the final output table: consolidate the
    add/remove delivery stream (possibly spanning crash generations)
    into final rows, sorted, compact JSON."""
    state: dict[str, tuple] = {}
    if os.path.exists(deliveries_path):
        with open(deliveries_path) as f:
            for line in f:
                if not line.strip():
                    continue  # generation-boundary newline guard
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue  # torn line from a hard crash
                if ev["add"]:
                    state[ev["w"]] = (ev["c"], ev["b"])
                elif state.get(ev["w"]) == (ev["c"], ev["b"]):
                    del state[ev["w"]]
    rows = sorted((w, c, b) for w, (c, b) in state.items())
    return json.dumps(rows, separators=(",", ":")).encode()


def _tamper_lost_snapshot(pdir: str, seed: int) -> str:
    """Simulate a lost operator-snapshot file: delete one snapshot of the
    newest committed epoch (seed picks which). Restore must detect the
    manifest hole and fall back one epoch."""
    with open(os.path.join(pdir, "metadata.json")) as f:
        epoch = int(json.load(f)["epoch"])
    op_dir = os.path.join(pdir, "operator")
    files = sorted(
        fn for fn in os.listdir(op_dir) if fn.endswith(f".{epoch}.state")
    )
    if not files:
        return f"epoch {epoch} had no snapshots to lose"
    victim = files[seed % len(files)]
    os.unlink(os.path.join(op_dir, victim))
    return f"deleted {victim} (epoch {epoch})"


def run_case(kind: str, seed: int, n_events: int, workdir: str) -> dict:
    """One drill: fault run (+ recovery generations) in a fresh
    persistence dir; returns the case record incl. canonical output."""
    pdir = os.path.join(workdir, f"{kind}-s{seed}-pdir")
    out = os.path.join(workdir, f"{kind}-s{seed}-deliveries.jsonl")
    flight_dir = os.path.join(workdir, f"{kind}-s{seed}-flight")
    spec = KINDS[kind](seed)
    t0 = time.monotonic()
    rc = _run_workload(pdir, out, spec, n_events, flight_dir=flight_dir)
    generations = 1
    note = ""
    if kind in CRASH_KINDS:
        assert rc == CRASH_EXIT, (
            f"{kind} seed {seed}: schedule {spec!r} never crashed (rc={rc})"
        )
        if kind == "lost_snapshot":
            note = _tamper_lost_snapshot(pdir, seed)
        # recovery generations run fault-free (a hit-count schedule would
        # deterministically re-fire the same crash); a crash landing in
        # an earlier recovery window is itself recovered from
        while rc == CRASH_EXIT:
            if generations > MAX_GENERATIONS:
                raise AssertionError(f"{kind} seed {seed}: kept crashing")
            rc = _run_workload(pdir, out, "0", n_events,
                               flight_dir=flight_dir)
            generations += 1
    assert rc == 0, f"{kind} seed {seed}: final generation rc={rc}"
    flight = _check_flight(flight_dir, kind, seed)
    return {
        "kind": kind,
        "seed": seed,
        "spec": spec,
        "generations": generations,
        "seconds": round(time.monotonic() - t0, 2),
        "note": note,
        "flight": flight,
        "output": consolidate(out).decode(),
    }


def run_matrix(
    kinds: list[str], seeds: list[int], n_events: int = 50,
    workdir: str | None = None,
) -> dict:
    own = workdir is None
    if own:
        workdir = tempfile.mkdtemp(prefix="pathway-chaos-")
    assert workdir is not None
    try:
        return _run_matrix(kinds, seeds, n_events, workdir)
    finally:
        if own:
            shutil.rmtree(workdir, ignore_errors=True)


def _run_matrix(
    kinds: list[str], seeds: list[int], n_events: int, workdir: str
) -> dict:
    t0 = time.monotonic()
    base_pdir = os.path.join(workdir, "baseline-pdir")
    base_out = os.path.join(workdir, "baseline-deliveries.jsonl")
    rc = _run_workload(base_pdir, base_out, "0", n_events)
    assert rc == 0, f"baseline rc={rc}"
    baseline = consolidate(base_out)
    assert baseline != b"[]", "baseline produced no output"
    cases = []
    failures = []
    for kind in kinds:
        for seed in seeds:
            case = run_case(kind, seed, n_events, workdir)
            case["equivalent"] = case["output"].encode() == baseline
            cases.append(case)
            if not case["equivalent"]:
                failures.append(
                    f"{kind} seed {seed}: output diverged from baseline\n"
                    f"  baseline: {baseline.decode()}\n"
                    f"  got:      {case['output']}"
                )
            status = "OK " if case["equivalent"] else "FAIL"
            print(
                f"[{status}] {kind:16s} seed={seed} "
                f"gen={case['generations']} {case['seconds']:.1f}s "
                f"spec={case['spec']!r}"
                + (f" ({case['note']})" if case["note"] else "")
            )
    report = {
        "ok": not failures,
        "baseline": baseline.decode(),
        "kinds": kinds,
        "seeds": seeds,
        "n_events": n_events,
        "cases": cases,
        "seconds": round(time.monotonic() - t0, 1),
    }
    if failures:
        report["failures"] = failures
    return report


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="4 kinds x 1 seed (the tier-1 CI leg, <=60s)")
    ap.add_argument("--kinds", default=None,
                    help=f"comma list from {sorted(KINDS)}")
    ap.add_argument("--seeds", default=None, help="comma list of ints")
    ap.add_argument("--events", type=int, default=50)
    ap.add_argument("--json", dest="json_out", default=None)
    args = ap.parse_args()
    if args.quick:
        kinds = QUICK_KINDS
        seeds = [0]
    else:
        kinds = sorted(KINDS)
        seeds = [0, 1, 2]
    if args.kinds:
        kinds = [k.strip() for k in args.kinds.split(",") if k.strip()]
        for k in kinds:
            if k not in KINDS:
                ap.error(f"unknown kind {k!r} (have {sorted(KINDS)})")
    if args.seeds:
        seeds = [int(s) for s in args.seeds.split(",")]
    report = run_matrix(kinds, seeds, n_events=args.events)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=2)
    print(
        f"chaos drill: {len(report['cases'])} cases, "
        f"{'ALL EQUIVALENT' if report['ok'] else 'FAILURES'} "
        f"in {report['seconds']}s"
    )
    if not report["ok"]:
        for f_ in report["failures"]:
            print(f_, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
