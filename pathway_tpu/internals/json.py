"""pw.Json — JSON value wrapper (reference: python/pathway/internals/json.py:1)."""

from __future__ import annotations

import json as _json
from typing import Any, Iterator


class Json:
    """Immutable wrapper around a parsed JSON value."""

    NULL: "Json"

    __slots__ = ("_value",)

    def __init__(self, value: Any = None):
        if isinstance(value, Json):
            value = value._value
        self._value = value

    @property
    def value(self) -> Any:
        return self._value

    def __repr__(self) -> str:
        return f"pw.Json({self._value!r})"

    def __str__(self) -> str:
        return Json.dumps(self._value)

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, Json):
            return self._value == other._value
        return self._value == other

    def __hash__(self) -> int:
        return hash(Json.dumps(self._value))

    def __getitem__(self, key: Any) -> "Json":
        v = self._value
        if isinstance(key, Json):
            key = key._value
        try:
            return Json(v[key])
        except (KeyError, IndexError, TypeError):
            raise KeyError(key)

    def get(self, key: Any, default: Any = None) -> Any:
        try:
            return self[key]
        except KeyError:
            return Json(default) if not isinstance(default, Json) else default

    def __iter__(self) -> Iterator["Json"]:
        if isinstance(self._value, dict):
            return (Json(k) for k in self._value)
        if isinstance(self._value, (list, tuple)):
            return (Json(v) for v in self._value)
        raise TypeError(f"pw.Json {self._value!r} is not iterable")

    def __len__(self) -> int:
        return len(self._value)

    def __bool__(self) -> bool:
        return bool(self._value)

    # --- conversions (API parity with reference .as_* methods) ---
    def as_int(self) -> int | None:
        if isinstance(self._value, bool):
            return None
        return self._value if isinstance(self._value, int) else None

    def as_float(self) -> float | None:
        if isinstance(self._value, (int, float)) and not isinstance(self._value, bool):
            return float(self._value)
        return None

    def as_str(self) -> str | None:
        return self._value if isinstance(self._value, str) else None

    def as_bool(self) -> bool | None:
        return self._value if isinstance(self._value, bool) else None

    def as_list(self) -> list | None:
        return self._value if isinstance(self._value, list) else None

    def as_dict(self) -> dict | None:
        return self._value if isinstance(self._value, dict) else None

    @staticmethod
    def parse(s: str | bytes) -> "Json":
        return Json(_json.loads(s))

    @staticmethod
    def dumps(value: Any, **kwargs: Any) -> str:
        return _json.dumps(value, sort_keys=True, separators=(",", ":"), default=_default, **kwargs)


def _default(obj: Any) -> Any:
    if isinstance(obj, Json):
        return obj.value
    import numpy as np

    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    from pathway_tpu.internals.keys import Key

    if isinstance(obj, Key):
        return str(obj)
    return str(obj)


Json.NULL = Json(None)
