"""Shared machinery for service-backed connectors.

The reference implements these against native client crates
(src/connectors/data_storage.rs). Here each family exposes the same
read()/write() API; families whose client library is absent in the runtime
raise a clear error at call time (the API surface and descriptors stay
importable so templates/YAML configs parse).
"""

from __future__ import annotations

import importlib
from typing import Any, Callable


def require_module(name: str, family: str) -> Any:
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(
            f"pw.io.{family} requires the {name!r} package, which is not "
            f"installed in this environment"
        ) from e


def gated_reader(family: str, module: str) -> Callable:
    def read(*args: Any, **kwargs: Any) -> Any:
        require_module(module, family)
        raise NotImplementedError(
            f"pw.io.{family}.read: client {module!r} unavailable in this build"
        )

    return read


def gated_writer(family: str, module: str) -> Callable:
    def write(*args: Any, **kwargs: Any) -> None:
        require_module(module, family)
        raise NotImplementedError(
            f"pw.io.{family}.write: client {module!r} unavailable in this build"
        )

    return write
