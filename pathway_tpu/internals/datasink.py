"""DataSink: descriptor consumed by Table.to (reference: internals/datasink.py)."""

from __future__ import annotations

from typing import Any, Callable


class DataSink:
    """Base: sinks register an output in the global parse graph."""

    def consume(self, table: Any) -> None:
        raise NotImplementedError


class CallbackDataSink(DataSink):
    def __init__(
        self,
        write_batch: Callable[[int, list], None],
        flush: Callable[[], None] | None = None,
        close: Callable[[], None] | None = None,
    ):
        self.write_batch = write_batch
        self.flush = flush
        self.close = close

    def consume(self, table: Any) -> None:
        from pathway_tpu.internals.parse_graph import G

        G.add_sink(
            "output",
            table,
            write_batch=self.write_batch,
            flush=self.flush,
            close=self.close,
        )
