"""Frontier-based progress tracking: per-operator watermarks over the DAG.

Reference parity: timely's progress tracking
(external/timely-dataflow/timely/src/progress/frontier.rs +
reachability.rs). The reference computes, per operator port, an
antichain of timestamps that may still arrive, by propagating source
capabilities through a one-shot reachability summary of the static
dataflow; an operator is notified for time t only once its input
frontier has passed t.

This module is the same idea over the engine's total-ordered even-ms
timestamp domain, where every antichain collapses to a single integer
watermark:

  * every SOURCE (a connector-fed ``InputNode``, a static batch set, or
    a remote exchange wire) carries a watermark W — a promise that no
    future delivery from it has time <= W (``DONE`` = the empty
    frontier: the source is finished);
  * a one-shot :class:`ReachabilityIndex` pass over the static DAG
    gives every node its upstream-source set (the reachability
    summary), including the implicit edges of operators that feed
    their outputs imperatively (iterate / row-transformer out_nodes);
  * a node's INPUT FRONTIER is the min over its upstream sources'
    watermarks, bounded by in-flight waves upstream of it, and the
    :class:`FrontierScheduler` fires ``finish_time(t)`` on a node as
    soon as that frontier passes t — per NODE, not per wave: an
    operator whose own inputs have settled runs ahead even while a
    sibling branch (or a peer worker across the process mesh) is still
    catching up on older timestamps.

Out-of-order ACROSS operators, always in-order AT each operator: waves
an operator cannot yet consume are stashed per-timestamp beside it and
replayed the moment its frontier passes them. This is what retires the
global BSP wave barrier (``Runtime.run_lockstep``): a straggler delays
exactly the operators that causally consume its data.
"""

from __future__ import annotations

import math
from time import perf_counter_ns
from typing import Any, Callable, Iterable

from pathway_tpu.internals import observability as _obs

# The empty frontier: the source has promised it will never deliver
# again. min() over mixed int/float watermarks keeps working.
DONE = math.inf


class ReachabilityIndex:
    """One-shot reachability over the static dataflow DAG.

    Node creation order is a topological order (a node's inputs exist
    before it; imperatively-fed out_nodes are created after the node
    that feeds them), so same-timestamp notifications run in node-id
    order.
    """

    def __init__(self, graph: Any):
        nodes = list(graph.nodes)
        self.graph = graph
        self.children: list[list[int]] = [[] for _ in nodes]
        # nodes fed imperatively (iterate / row-transformer outputs):
        # they have no .inputs edge but ARE downstream of their feeder
        self.implicitly_fed: set[int] = set()
        for node in nodes:
            for inp in node.inputs:
                self.children[inp.node_id].append(node.node_id)
            for out in getattr(node, "out_nodes", {}).values():
                self.children[node.node_id].append(out.node_id)
                self.implicitly_fed.add(out.node_id)

    def cone(self, node_id: int, include_self: bool = True) -> set[int]:
        """All node ids reachable downstream of node_id."""
        seen: set[int] = set()
        stack = [node_id]
        while stack:
            nid = stack.pop()
            if nid in seen:
                continue
            seen.add(nid)
            stack.extend(self.children[nid])
        if not include_self:
            seen.discard(node_id)
        return seen

    def orphan_inputs(self) -> list[int]:
        """Nodes with no dataflow inputs and no imperative feeder: the
        potential sources. Anything here that no runtime registers as a
        live source is auto-closed (watermark DONE) so frontiers that
        merge it never stall — e.g. the static-table InputNodes of
        non-owner processes, which hold no rows on this process."""
        return [
            node.node_id
            for node in self.graph.nodes
            if not node.inputs and node.node_id not in self.implicitly_fed
        ]

    def exchange_depth(self) -> int:
        """Max number of exchange boundaries on any source->sink path
        (nodes with a ``wire_id``). Bounds how many propagation rounds a
        distributed quiescence fence needs: each round flushes one more
        exchange stage."""
        depth = [0] * len(self.graph.nodes)
        best = 0
        for node in self.graph.nodes:  # creation order is topological
            d = depth[node.node_id]
            if getattr(node, "wire_id", None) is not None:
                d += 1
                depth[node.node_id] = d
            best = max(best, d)
            for c in self.children[node.node_id]:
                depth[c] = max(depth[c], d)
        return best


class ScopeFrontier:
    """The input frontier of an iterate sub-scope.

    The loop body runs in a nested scope whose timestamps are
    (outer_time, round) products in the reference; here the outer
    coordinate is the times already released into the scope and the
    inner coordinate is the round counter. ``quiescent`` is the scope's
    progress statement: no feedback capability is held at the current
    outer time, so the fixpoint for everything released so far is
    complete. A truncated convergence (iteration_limit) keeps the
    capability, and the runtime keeps scheduling waves for the scope
    until it drops it."""

    __slots__ = ("released_through", "inner", "quiescent")

    def __init__(self) -> None:
        self.released_through: float = -1  # outer times fed to the scope
        self.inner = 0  # inner round watermark (body-graph timestamps)
        self.quiescent = True

    def release(self, outer_time: float) -> None:
        if outer_time > self.released_through:
            self.released_through = outer_time

    def advance_round(self, inner_t: int) -> None:
        self.inner = inner_t

    def hold(self) -> None:
        """Keep the feedback capability: convergence is incomplete."""
        self.quiescent = False

    def drop(self) -> None:
        self.quiescent = True


class _Pend:
    """Everything queued for one (operator, timestamp) notification:
    source payloads to deliver, and input stashed while the operator's
    frontier had not yet passed the timestamp."""

    __slots__ = ("payloads", "stash", "t0")

    def __init__(self) -> None:
        self.payloads: list[tuple[str, Any]] = []  # (kind, payload)
        self.stash: list[tuple[list, list, list | None]] = []
        # wave tracing: when this notification was first queued — the
        # fire-time delta is the wave's queue wait (observability plane
        # only; 0 keeps the disabled hot path at one predicate test)
        self.t0 = perf_counter_ns() if _obs.PLANE is not None else 0


class FrontierScheduler:
    """Fires operators per-timestamp as their input frontiers advance.

    An operator whose recent waves averaged above ``_SLOW_NS`` is
    treated as expensive: each pump pass fires at most one expensive
    wave, with every admissible cheap wave drained around it.


    Every pending notification is keyed by a SLOT and a timestamp. A
    slot is ``2*node_id`` for the operator itself (stashed input +
    source payloads + kicks) or ``2*node_id + 1`` for an exchange
    node's wire deliveries — remote buckets inject BELOW the node, so
    they must not count against the node's own outgoing watermark.

    ``pump()`` repeatedly fires the earliest admissible notification:
    (slot, t) is admissible when every source that can reach the
    operator promises nothing at or below t is still coming (watermark
    gate) and no earlier in-flight notification upstream could still
    emit to it (pending gate). Operators over settled inputs therefore
    run arbitrarily far ahead of straggling branches; emissions landing
    at a blocked operator are stashed per-timestamp and replayed, in
    order, when its frontier catches up.
    """

    _SLOW_NS = 5_000_000  # >= 5 ms average per wave = expensive operator

    def __init__(self, graph: Any, monitors: Iterable[Callable] = ()):
        self.graph = graph
        self.nodes = list(graph.nodes)
        self.monitors = list(monitors)
        self.reach = ReachabilityIndex(graph)
        self._wm: dict[Any, float] = {}
        self._kind: dict[Any, str] = {}
        self._node_of: dict[Any, Any] = {}
        self._token_cone: dict[Any, set[int]] = {}
        self._pending: dict[int, dict[float, _Pend]] = {}  # slot -> t -> pend
        self._upstream: dict[int, set] = {}  # node_id -> source tokens
        self._desc: dict[int, set[int]] = {}  # slot -> reachable node ids
        self._sealed = False
        # observability: last timestamp each operator completed
        self.completed_through: dict[int, float] = {}
        self.waves_fired = 0
        self._monitored_through: float = -1
        # per-slot cost estimate (EMA of fire wall-time, ns): drives the
        # cooperative two-tier pump — cheap operators drain freely
        # between expensive waves, so a grinding UDF never dams up the
        # causally-unrelated work (and watermarks) behind it
        self._cost_ns: dict[int, float] = {}
        # pumps that poll for deferred completions (Runtime.run and the
        # frontier static pump) opt in; the mesh pump keeps synchronous
        # async-apply semantics for now (its quiescence barriers assume
        # a drained scheduler between rounds)
        self.allow_async = False
        # stage overlap: (slot, t) -> done() for waves an operator has
        # CONSUMED but whose emission is still computing off-thread (a
        # deferred device dispatch). A hold gates downstream frontiers
        # exactly like an in-flight notification — but not the holding
        # operator's own later timestamps, which is what lets wave t+1
        # stage while wave t computes (see docs/serving.md).
        self._async_waves: dict[tuple[int, float], Callable[[], bool]] = {}

    # ------------------------------------------------------------- sources

    def _register(
        self, token: Any, node: Any, kind: str, watermark: float, cone: set
    ) -> Any:
        assert not self._sealed, "sources must be registered before pumping"
        self._wm[token] = watermark
        self._kind[token] = kind
        self._node_of[token] = node
        self._token_cone[token] = cone
        return token

    def add_source(self, node: Any, watermark: float = 0) -> Any:
        """A locally-fed InputNode (connector session or static rows)."""
        return self._register(
            node.node_id, node, "local", watermark,
            self.reach.cone(node.node_id),
        )

    def add_remote_source(self, exchange_node: Any, peer: int) -> Any:
        """Data arriving over a mesh wire from `peer`, injected BELOW
        the exchange node: its reach excludes the node itself, so the
        node's outgoing watermark never depends on its own incoming
        wires (that cycle would freeze both sides at frontier 0).
        Watermark follows the peer's announcements."""
        token = ("wire", exchange_node.wire_id, peer)
        return self._register(
            token, exchange_node, "remote", 0,
            self.reach.cone(exchange_node.node_id, include_self=False),
        )

    def add_kick_source(self, node: Any) -> Any:
        """Capability-holding operator (iterate): lets the runtime
        schedule empty waves through it so a truncated convergence
        resumes without new input."""
        return self._register(
            ("kick", node.node_id), node, "kick", 0,
            self.reach.cone(node.node_id),
        )

    def seal(self) -> None:
        """Close registration: auto-complete orphan inputs and build
        each node's upstream-source set (the reachability summary)."""
        if self._sealed:
            return
        registered_nodes = {
            self._node_of[tok].node_id
            for tok, kind in self._kind.items()
            if kind == "local"
        }
        for nid in self.reach.orphan_inputs():
            if nid not in registered_nodes:
                # nothing will ever feed it on this worker: empty frontier
                self._register(
                    nid, self.nodes[nid], "local", DONE, self.reach.cone(nid)
                )
        self._sealed = True
        for nid in range(len(self.nodes)):
            self._upstream[nid] = set()
        for tok, cone in self._token_cone.items():
            for nid in cone:
                self._upstream[nid].add(tok)

    def _slot_of(self, token: Any) -> int:
        node = self._node_of[token]
        if self._kind[token] == "remote":
            return 2 * node.node_id + 1  # wire deliveries: below the node
        return 2 * node.node_id

    def _desc_of(self, slot: int) -> set[int]:
        """Node ids a pending notification at `slot` can still reach."""
        desc = self._desc.get(slot)
        if desc is None:
            nid, below = divmod(slot, 2)
            desc = self.reach.cone(nid, include_self=not below)
            self._desc[slot] = desc
        return desc

    # ----------------------------------------------------------- progress

    def stage(self, token: Any, time: float, payload: Any = None) -> None:
        """Stage one wave from a source; delivery happens at pump time,
        once the target operator's frontier passes `time`."""
        slot = self._slot_of(token)
        pend = self._pending.setdefault(slot, {}).setdefault(time, _Pend())
        pend.payloads.append((self._kind[token], payload))
        if self._wm[token] < time:
            # a source never delivers at or below its own watermark
            self._wm[token] = time

    def advance(self, token: Any, watermark: float) -> None:
        if watermark > self._wm[token]:
            self._wm[token] = watermark

    def advance_local(self, watermark: float) -> None:
        """Advance every local + kick source (the runtime's clock tick:
        any future poll will be stamped later than `watermark`)."""
        for tok, kind in self._kind.items():
            if kind in ("local", "kick") and self._wm[tok] < watermark:
                self._wm[tok] = watermark

    def close(self, token: Any) -> None:
        self._wm[token] = DONE

    def watermark(self, token: Any) -> float:
        return self._wm[token]

    def frontier_of_node(self, node: Any) -> float:
        """The node's input frontier: min over upstream source
        watermarks, bounded by in-flight notifications (including the
        node's own — an exchange node has not SENT a wave it has not
        fired, so its announced watermark must stay below it)."""
        self.seal()
        nid = node.node_id
        ups = self._upstream.get(nid)
        f = min((self._wm[tok] for tok in ups), default=DONE) if ups else DONE
        for slot, times in self._pending.items():
            if times and nid in self._desc_of(slot):
                f = min(f, min(times) - 1)
        for (slot, t) in self._async_waves:
            if nid in self._desc_of(slot):
                f = min(f, t - 1)
        return f

    # -------------------------------------------------- async stage overlap

    def hold_async(
        self, node: Any, time: float, done_fn: Callable[[], bool]
    ) -> None:
        """Register a deferred wave: `node` consumed its input for `time`
        and will emit once `done_fn()` turns true. Downstream frontiers
        stay below `time` until then; the node itself may keep firing
        later timestamps (pipelining)."""
        self._async_waves[(2 * node.node_id, time)] = done_fn

    def has_async(self) -> bool:
        return bool(self._async_waves)

    def _poll_async(self) -> int:
        """Convert completed deferred waves into notifications: the node
        fires again at the held time to emit its results."""
        converted = 0
        for (slot, t), done in list(self._async_waves.items()):
            if done():
                del self._async_waves[(slot, t)]
                self._pending.setdefault(slot, {}).setdefault(t, _Pend())
                converted += 1
        return converted

    def fully_drained(self) -> bool:
        return not any(self._pending.values()) and not self._async_waves

    def replan_refresh(self) -> None:
        """Refresh every topology-derived cache after the adaptive
        planner rewired the live graph (internals/planner.py re-fusion
        at a drained epoch fence): node list, reachability, per-slot
        descendant cones, source cones, and the upstream summaries.
        Caller must hold the fence (fully_drained() — no in-flight
        notifications reference the old cones)."""
        assert self.fully_drained(), "replan requires a drained scheduler"
        self.nodes = list(self.graph.nodes)
        self.reach = ReachabilityIndex(self.graph)
        self._desc.clear()
        for token, kind in self._kind.items():
            node = self._node_of[token]
            self._token_cone[token] = self.reach.cone(
                node.node_id, include_self=kind != "remote"
            )
        self._upstream.clear()
        for nid in range(len(self.nodes)):
            self._upstream[nid] = set()
        for tok, cone in self._token_cone.items():
            for nid in cone:
                self._upstream[nid].add(tok)

    def global_frontier(self) -> float:
        """Min over every source watermark and in-flight notification —
        the fully-retired time: state at or below it can never change
        again (persistence cuts checkpoints here)."""
        self.seal()
        f = min(self._wm.values(), default=DONE)
        for times in self._pending.values():
            if times:
                f = min(f, min(times) - 1)
        for (_slot, t) in self._async_waves:
            f = min(f, t - 1)
        return f

    # -------------------------------------------------------------- firing

    def _stash_emissions(self, slot: int, time: float) -> None:
        """Move freshly-received input out of the fired cone's buffers
        into per-timestamp stashes. Run after each notification:
        operators whose frontier has not passed `time` keep the wave
        parked, in timestamp order, until their own notification
        fires."""
        from pathway_tpu.engine.core import InputNode

        for nid in self._desc_of(slot):
            node = self.nodes[nid]
            bufs = node.buffers
            # ONLY an InputNode's `pending` is a push inbox; on other
            # nodes an attribute of that name is operator STATE (e.g.
            # BufferNode's postponed rows) and must never be stashed
            pending = node.pending if isinstance(node, InputNode) else None
            has_bufs = any(bufs)
            if not has_bufs and not pending:
                continue
            pend = self._pending.setdefault(2 * node.node_id, {}).setdefault(
                time, _Pend()
            )
            if has_bufs:
                node.buffers = [[] for _ in bufs]
                nsegs = node._nseg
                node._nseg = [0] * len(nsegs)
            else:
                bufs, nsegs = [], []
            if pending:
                node.pending = []
                pend.stash.append((bufs, nsegs, pending))
            else:
                pend.stash.append((bufs, nsegs, None))

    def _restore_stash(self, node: Any, pend: _Pend) -> None:
        for bufs, nsegs, input_pending in pend.stash:
            for i, buf in enumerate(bufs):
                if buf:
                    node.buffers[i].extend(buf)
                    node._nseg[i] += nsegs[i]
            if input_pending:
                node.pending.extend(input_pending)

    def _admissible(self, slot: int, t: float) -> bool:
        nid = slot // 2
        ups = self._upstream.get(nid)
        if ups and any(self._wm[tok] < t for tok in ups):
            return False  # an upstream source may still deliver <= t
        for other, times in self._pending.items():
            if other == slot or not times:
                continue
            mt = min(times)
            if mt > t:
                continue
            desc = self._desc_of(other)
            if nid in desc and (mt < t or slot // 2 != other // 2):
                # an earlier (or same-time upstream) in-flight wave can
                # still emit into this operator: deliver it first
                return False
        for (oslot, ot) in self._async_waves:
            if oslot == slot:
                # the operator's own deferred wave never gates its later
                # timestamps — consuming wave t+1 while t computes is the
                # double buffer; emissions still land in time order via
                # the per-timestamp stash
                continue
            if ot > t:
                continue
            if nid in self._desc_of(oslot) and (ot < t or slot // 2 != oslot // 2):
                return False
        # own earlier timestamps fire first (per-operator time order)
        own = self._pending.get(slot)
        if own and min(own) < t:
            return False
        return True

    def _fire(self, slot: int, t: float, pend: _Pend) -> None:
        nid, below = divmod(slot, 2)
        node = self.nodes[nid]
        t0 = perf_counter_ns()
        if below:
            for _kind, payload in pend.payloads:
                if payload is not None:
                    node.inject_remote(t, payload)
        else:
            for kind, payload in pend.payloads:
                if kind == "local" and payload is not None:
                    node.push(payload)
            self._restore_stash(node, pend)
            node.finish_time(t)
            self.completed_through[nid] = t
        elapsed = perf_counter_ns() - t0
        if not below:
            node.time_ns += elapsed
        ema = self._cost_ns.get(slot)
        self._cost_ns[slot] = (
            elapsed if ema is None else 0.5 * ema + 0.5 * elapsed
        )
        plane = _obs.PLANE
        if plane is None:
            self._stash_emissions(slot, t)
        else:
            s0 = perf_counter_ns()
            self._stash_emissions(slot, t)
            plane.wave(
                node, t,
                exec_ns=elapsed,
                queue_ns=max(t0 - pend.t0, 0) if pend.t0 else 0,
                stash_ns=perf_counter_ns() - s0,
                injected=bool(below),
            )
        self.waves_fired += 1

    def pump(self, budget: int | None = None) -> int:
        """Fire currently-admissible notifications; returns the count.
        A blocked notification never blocks an unrelated one — that is
        the straggler isolation the global wave barrier could not give.

        `budget` caps the notifications fired in this call: the mesh
        pump runs in chunks so watermark announcements and remote
        deliveries interleave with long-running operators — otherwise a
        grinding wave would freeze this process's outgoing frontiers
        and transitively stall every peer operator gated on them."""
        self.seal()
        fired = 0
        while budget is None or fired < budget:
            # deferred waves that finished computing become ordinary
            # notifications (the operator fires again at the held time
            # to emit); polled per pass, never waited on — the pump
            # returns to its caller when only in-flight work remains
            self._poll_async()
            # drain the whole CHEAP tier, then fire exactly one
            # expensive wave. Causal order is enforced by _admissible,
            # not by global firing order, so a straggler's backlog of
            # early-timestamped expensive waves must not dam up
            # causally-independent cheap work — cheap operators (and
            # with them this worker's outgoing watermarks) keep flowing
            # between expensive waves (timely's cooperative
            # activation/fuel idea, with an EMA cost model).
            cheap = 0
            while budget is None or fired < budget:
                n = self._fire_pass(slow_tier=False)
                cheap += n
                fired += n
                if n == 0:
                    break
            slow = 0
            if budget is None or fired < budget:
                slow = self._fire_pass(slow_tier=True, limit=1)
                fired += slow
            if cheap == 0 and slow == 0:
                break
        plane = _obs.PLANE
        if plane is not None:
            # depth of the work-stealing morsel queues left behind by the
            # waves this pass fired (engine/morsel.py). Sampled here — not
            # inside the steal loop — so the steady-state reading costs one
            # gauge per pump instead of one per morsel. Nonzero at the
            # sample point means a wave returned while stolen morsels were
            # still draining, i.e. stealing actually overlapped the pump.
            from pathway_tpu.engine import morsel as _morsel

            plane.metrics.gauge(
                "pathway_morsel_queue_depth",
                float(_morsel.live_depth()),
                help="morsels queued across live steal schedulers",
            )
        return fired

    def _fire_pass(self, slow_tier: bool, limit: int | None = None) -> int:
        """One pass over the tier's slots: each fires at most its
        earliest pending time, in timestamp order."""
        slow_ns = self._SLOW_NS
        cands = sorted(
            ((min(times), slot)
             for slot, times in self._pending.items()
             if times
             and (self._cost_ns.get(slot, 0.0) >= slow_ns) == slow_tier),
            key=lambda pair: pair[0],
        )
        fired = 0
        for t, slot in cands:
            times = self._pending.get(slot)
            # re-validate against CURRENT state: an earlier fire in this
            # pass may have delivered new (earlier) waves here
            if not times or t not in times or min(times) != t:
                continue
            if not self._admissible(slot, t):
                continue
            pend = times.pop(t)
            if not times:
                del self._pending[slot]
            self._fire(slot, t, pend)
            fired += 1
            if t > self._monitored_through:
                self._monitored_through = t
                for m in self.monitors:
                    m(t)
            if limit is not None and fired >= limit:
                break
        return fired
