"""ML utilities.

Reference parity: stdlib/ml/utils.py (classifier_accuracy :13,
_predict_asof_now :34). The reference's asof-now prediction trick
(forget-immediately query passthrough) is built into this framework's
index layer — `DataIndex.query_as_of_now` / the external-index operator's
asof_now mode — so prediction functions here use those directly.
"""

from __future__ import annotations

from typing import Any


def classifier_accuracy(predicted_labels: Any, exact_labels: Any) -> Any:
    """Counts matching / non-matching predictions.

    `predicted_labels` must carry `predicted_label` keyed like
    `exact_labels`' rows carry `label`. Returns Table(cnt, value) with one
    row per match-boolean (reference :13).
    """
    import pathway_tpu as pw

    comparative = predicted_labels.select(
        predicted_label=predicted_labels.predicted_label,
        label=exact_labels.ix(predicted_labels.id).label,
    )
    flagged = comparative.select(
        match=comparative.label == comparative.predicted_label
    )
    return flagged.groupby(flagged.match).reduce(
        cnt=pw.reducers.count(),
        value=flagged.match,
    )


__all__ = ["classifier_accuracy"]
