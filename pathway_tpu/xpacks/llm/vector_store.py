"""VectorStoreServer / VectorStoreClient — the self-contained
embed + index + REST service.

Reference parity: xpacks/llm/vector_store.py `VectorStoreServer` (:38,
from_langchain_components :92, from_llamaindex_components :136,
run_server :456), `SlidesVectorStoreServer` (:566) and
`VectorStoreClient` (:629). The indexing pipeline itself delegates to
DocumentStore (the reference kept a parallel implementation; one code
path is enough here), while this module owns what the reference's class
owns on top of it: plain-callable component adapters (LangChain /
LlamaIndex interop), embedding-dimension probing, the slides variant
with metadata redaction, and the HTTP client.
"""

from __future__ import annotations

import asyncio
import json
import urllib.error
import urllib.request
from typing import Any, Callable

import pathway_tpu as pw
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.table import Table
from pathway_tpu.xpacks.llm.document_store import (
    DocumentStore,
    _plain,
    default_retriever_factory,
)


def _call_maybe_async(fn: Callable, *args: Any) -> Any:
    res = fn(*args)
    if asyncio.iscoroutine(res):
        # run on the engine's dedicated loop thread: asyncio.run would
        # raise under an already-running loop (Jupyter, async apps)
        from pathway_tpu.engine.runtime import _get_async_loop

        return asyncio.run_coroutine_threadsafe(res, _get_async_loop()).result()
    return res


class _CallableUDF(pw.UDF):
    """Adapter: a plain (sync or async) callable used where the pipeline
    expects a pw.UDF. The reference's VectorStoreServer accepts raw
    callables for embedder/parser/splitter; this preserves that API over
    the UDF-based DocumentStore. deterministic defaults False (memoize
    results) — an API-backed embedder is not bit-stable across calls, and
    recompute-on-retraction would retract values never inserted."""

    def __init__(self, fn: Callable, *, deterministic: bool = False):
        super().__init__(deterministic=deterministic)
        self._fn = fn
        if asyncio.iscoroutinefunction(fn):

            async def _w(x: Any, **kwargs: Any) -> Any:
                return await fn(x)

        else:

            def _w(x: Any, **kwargs: Any) -> Any:  # type: ignore[misc]
                return fn(x)

        self.__wrapped__ = _w  # type: ignore[method-assign]


class _CallableEmbedder(_CallableUDF):
    def get_embedding_dimension(self, **kwargs: Any) -> int:
        # probe like the reference: embed a sentinel and measure
        return len(_call_maybe_async(self._fn, "."))


def _as_embedder(embedder: Any) -> Any:
    if embedder is None or isinstance(embedder, pw.UDF):
        return embedder
    return _CallableEmbedder(embedder)


def _as_processor(fn: Any) -> Any:
    if fn is None or isinstance(fn, pw.UDF):
        return fn
    if asyncio.iscoroutinefunction(fn):
        # DocumentStore applies parsers/splitters synchronously inside
        # the document pipeline (only the embedder rides async-apply) —
        # failing here beats a coroutine-is-not-iterable crash at runtime
        raise ValueError(
            "parser/splitter callables must be synchronous; wrap async "
            "work in an async embedder or a pw.UDF with an async executor"
        )
    return _CallableUDF(fn)


class VectorStoreServer:
    """Builds the document indexing pipeline and serves it over REST
    (reference: vector_store.py:38). Accepts either pw.UDF components or
    plain callables (the reference's calling convention)."""

    def __init__(
        self,
        *docs: Table,
        embedder: Any = None,
        parser: Any = None,
        splitter: Any = None,
        doc_post_processors: list[Callable] | None = None,
        index_factory: Any = None,
        ann: bool | None = None,
        with_bm25: bool = False,
    ):
        if embedder is None and index_factory is None:
            from pathway_tpu.xpacks.llm.embedders import JaxEmbedder

            embedder = JaxEmbedder()
        embedder = _as_embedder(embedder)
        self.embedder = embedder
        if index_factory is None:
            # ann=True -> incremental IVF-PQ tier; None defers to
            # PATHWAY_ANN (exact default); with_bm25 adds RRF text
            # fusion. See docs/retrieval.md.
            index_factory = default_retriever_factory(
                embedder, ann=ann, with_bm25=with_bm25
            )
        self.document_store = DocumentStore(
            list(docs),
            retriever_factory=index_factory,
            parser=_as_processor(parser),
            splitter=_as_processor(splitter),
            doc_post_processors=doc_post_processors,
        )

    # ------------------------------------------------ component adapters

    @classmethod
    def from_langchain_components(
        cls,
        *docs: Table,
        embedder: Any,
        parser: Any = None,
        splitter: Any = None,
        **kwargs: Any,
    ) -> "VectorStoreServer":
        """Build from LangChain components (reference:
        vector_store.py:92): `embedder` is a langchain Embeddings object
        (`aembed_documents`), `splitter` a BaseDocumentTransformer.
        langchain_core is only imported when a splitter is given (its
        Document type is needed to feed transform_documents)."""
        generic_splitter = None
        if splitter is not None:
            try:
                from langchain_core.documents import Document
            except ImportError as e:
                raise ImportError(
                    "a LangChain splitter needs langchain_core: "
                    "`pip install langchain_core`"
                ) from e

            def generic_splitter(x: str) -> list[tuple[str, dict]]:
                return [
                    (doc.page_content, doc.metadata)
                    for doc in splitter.transform_documents(
                        [Document(page_content=x)]
                    )
                ]

        async def generic_embedder(x: str) -> Any:
            res = await embedder.aembed_documents([x])
            import numpy as np

            return np.asarray(res[0], dtype=np.float32)

        return cls(
            *docs,
            embedder=generic_embedder,
            parser=parser,
            splitter=generic_splitter,
            **kwargs,
        )

    @classmethod
    def from_llamaindex_components(
        cls,
        *docs: Table,
        transformations: list[Any],
        parser: Any = None,
        **kwargs: Any,
    ) -> "VectorStoreServer":
        """Build from LlamaIndex TransformComponents (reference:
        vector_store.py:136): the LAST transformation must be an
        embedding component (`aget_text_embedding`); earlier ones run as
        the splitter. llama_index is only imported when there are node
        transformations to run."""
        if not transformations:
            raise ValueError("Transformations list cannot be None or empty.")
        transformations = list(transformations)
        embedder = transformations.pop()
        if not hasattr(embedder, "aget_text_embedding"):
            raise ValueError(
                "Last step of transformations should be an embedding "
                f"component (aget_text_embedding), found {type(embedder)}."
            )

        async def embedding_callable(x: str) -> Any:
            import numpy as np

            return np.asarray(
                await embedder.aget_text_embedding(x), dtype=np.float32
            )

        generic_transformer = None
        if transformations:
            try:
                from llama_index.core.ingestion.pipeline import (
                    run_transformations,
                )
                from llama_index.core.schema import MetadataMode, TextNode
            except ImportError as e:
                raise ImportError(
                    "LlamaIndex node transformations need llama-index-core: "
                    "`pip install llama-index-core`"
                ) from e

            def generic_transformer(x: str) -> list[tuple[str, dict]]:
                nodes = run_transformations([TextNode(text=x)], transformations)
                return [
                    (
                        node.get_content(metadata_mode=MetadataMode.NONE),
                        node.extra_info or {},
                    )
                    for node in nodes
                ]

        return cls(
            *docs,
            embedder=embedding_callable,
            parser=parser,
            splitter=generic_transformer,
            **kwargs,
        )

    # ---------------------------------------------------------- services

    RetrieveQuerySchema = DocumentStore.RetrieveQuerySchema
    StatisticsQuerySchema = DocumentStore.StatisticsQuerySchema
    InputsQuerySchema = DocumentStore.InputsQuerySchema

    def retrieve_query(self, queries: Table) -> Table:
        return self.document_store.retrieve_query(queries)

    def statistics_query(self, queries: Table) -> Table:
        return self.document_store.statistics_query(queries)

    def inputs_query(self, queries: Table) -> Table:
        return self.document_store.inputs_query(queries)

    @property
    def index(self):
        return self.document_store.index

    def __repr__(self) -> str:
        return (
            f"{type(self).__name__}(embedder={type(self.embedder).__name__}, "
            f"store={type(self.document_store).__name__})"
        )

    def run_server(
        self,
        host: str = "0.0.0.0",
        port: int = 8000,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend: Any = None,
        **kwargs: Any,
    ):
        from pathway_tpu.xpacks.llm.servers import DocumentStoreServer

        # serve SELF (duck-typed store), not the inner DocumentStore —
        # subclass endpoint overrides (SlidesVectorStoreServer's redacted
        # inputs listing) must be what REST clients reach
        server = DocumentStoreServer(host, port, self)
        return server.run(
            threaded=threaded,
            with_cache=with_cache,
            cache_backend=cache_backend,
            **kwargs,
        )


class SlidesVectorStoreServer(VectorStoreServer):
    """Vector index for the slide-search template (reference:
    vector_store.py:566): `inputs` lists metadata AFTER parsing and
    post-processing (one entry per parsed slide, not per input file),
    with bulky fields (the base64 slide image) redacted."""

    excluded_response_metadata: list[str] = ["b64_image"]

    def inputs_query(self, input_queries: Table) -> Table:
        from pathway_tpu.stdlib.indexing.filters import compile_filter

        store = self.document_store
        all_metas = store.parsed_docs.reduce(
            metadatas=pw.reducers.tuple(store.parsed_docs.metadata)
        )
        queries = DocumentStore.merge_filters(input_queries)
        excluded = list(self.excluded_response_metadata)

        def fmt(metas: Any, metadata_filter: Any) -> Json:
            out = [_plain(m) for m in (metas or ())]
            if metadata_filter:
                pred = compile_filter(str(metadata_filter))
                out = [m for m in out if pred(m)]
            # copy before redacting: _plain returns the LIVE metadata
            # dicts — popping in place would strip the slide images from
            # the store itself for every later consumer
            redacted = []
            for m in out:
                if isinstance(m, dict):
                    m = {k: v for k, v in m.items() if k not in excluded}
                redacted.append(m)
            return Json(redacted)

        return queries.join_left(all_metas, id=queries.id).select(
            result=pw.apply(fmt, pw.right.metadatas, pw.left.metadata_filter)
        )

    def parsed_documents_query(self, parse_docs_queries: Table) -> Table:
        return self.inputs_query(parse_docs_queries)


class VectorStoreClient:
    """HTTP client for the vector-store endpoints (reference:
    vector_store.py:629)."""

    def __init__(
        self,
        host: str | None = None,
        port: int | None = None,
        url: str | None = None,
        timeout: float | None = 15.0,
        additional_headers: dict[str, str] | None = None,
    ):
        err = "Either (`host` and `port`) or `url` must be set, but not both."
        if url is not None:
            if host is not None or port is not None:
                raise ValueError(err)
            self.url = url
        else:
            if host is None:
                raise ValueError(err)
            # default matches run_server's port=8000 — a silent :80
            # fallback would point at the wrong service
            port = port or 8000
            self.url = f"http://{host}:{port}"
        self.timeout = timeout
        self.additional_headers = additional_headers or {}

    def _post(self, route: str, payload: dict) -> Any:
        req = urllib.request.Request(
            self.url + route,
            data=json.dumps(payload).encode(),
            headers={
                "Content-Type": "application/json",
                **self.additional_headers,
            },
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            raise RuntimeError(
                f"vector store request {route} failed: HTTP {e.code} {detail}"
            ) from e

    def query(
        self,
        query: str,
        k: int = 3,
        metadata_filter: str | None = None,
        filepath_globpattern: str | None = None,
    ) -> list[dict]:
        return self._post(
            "/v1/retrieve",
            {
                "query": query,
                "k": k,
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )

    __call__ = query

    def get_vectorstore_statistics(self) -> dict:
        return self._post("/v1/statistics", {})

    def get_input_files(
        self,
        metadata_filter: str | None = None,
        filepath_globpattern: str | None = None,
    ) -> list[dict]:
        return self._post(
            "/v1/inputs",
            {
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )
