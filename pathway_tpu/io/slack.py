"""pw.io.slack — API-parity connector (reference: io/slack).

Client library gated: see io/_external.py.
"""

from pathway_tpu.io._external import gated_reader, gated_writer

read = gated_reader("slack", "requests")
write = gated_writer("slack", "requests")
