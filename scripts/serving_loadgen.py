#!/usr/bin/env python
"""Closed-loop serving load bench: N concurrent clients against a live
gateway-fronted RAG pipeline, measuring p50/p99 latency and goodput.

One process hosts both sides (bench.py runs one invocation per rung, the
established one-pw.run-per-process discipline):

* **server** — `rest_connector` (+ optional `ServingGateway`) feeding a
  RAG-shaped stage: hash-embed the query, cosine-retrieve over a small
  in-memory doc matrix, answer with the top doc. An optional straggler
  rides the fault plane: the stage probes the `serving.straggler`
  injection point and sleeps ``--straggler-ms`` when the installed
  ``PATHWAY_FAULTS`` schedule fires it — the 20 ms straggler of the
  acceptance run is ``PATHWAY_FAULTS="serving.straggler@1+"``.
* **clients** — ``--clients`` closed-loop asyncio workers: each POSTs,
  awaits the response, then immediately POSTs again, for ``--duration``
  seconds. A 429 honors ``Retry-After`` up to a small cap (a shed
  request must not spin the loop).

The report separates *goodput* (HTTP 200/sec) from raw throughput and
records the server-side queue observables: ``max_pending`` (response
futures piled into the connector — the thing admission control bounds)
and the gateway's shed/queue counters. The acceptance contrast
(docs/serving.md §6): under the straggler, a gateway run keeps p99
bounded by shedding at the edge, while the ``--no-gateway`` control's
pending map grows to the full client count.

``--rolling-upgrade`` adds the zero-downtime rung (docs/robustness.md
§elasticity): mid-bench, a REAL blue/green plan swap
(parallel/bluegreen.py — clone, green replay, verified gates, atomic
rename commit) runs against a persisted pipeline root on the same host
while the client fleet keeps hammering the live server. The report then
splits p99 into during-swap vs outside-swap windows and records the
swap's own duration and verdict — the claim under test is that an
upgrade swap never stalls serving (blue never stops). On a 1-CPU host
the swap subprocess and the server serialize on the same core, which
measures the scheduler, not the swap — the rung skips with an explicit
reason instead of reporting a junk p99.

Usage:
  python scripts/serving_loadgen.py --clients 100 --duration 5
  PATHWAY_FAULTS="serving.straggler@1+" python scripts/serving_loadgen.py \
      --clients 100 --duration 5 --straggler-ms 20 [--no-gateway]
  python scripts/serving_loadgen.py --clients 50 --duration 6 --rolling-upgrade

Prints ONE JSON line; --json PATH also writes it to a file.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys
import tempfile
import textwrap
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_DOCS = 512
DIM = 64


def build_server(args, port: int):
    """Register the pipeline (rest_connector -> RAG-shaped stage) and
    return (webserver, gateway, run_thread_starter)."""
    import numpy as np

    import pathway_tpu as pw
    from pathway_tpu.engine import faults

    rng = np.random.default_rng(7)
    docs = rng.normal(size=(N_DOCS, DIM)).astype(np.float32)
    docs /= np.linalg.norm(docs, axis=1, keepdims=True)
    straggle_s = args.straggler_ms / 1000.0

    def embed(text: str) -> "np.ndarray":
        v = np.zeros(DIM, np.float32)
        for i, tok in enumerate(text.split()):
            v[hash(tok) % DIM] += 1.0 + (i % 3)
        n = np.linalg.norm(v)
        return v / n if n > 0 else v

    @pw.udf
    def rag_answer(q: str) -> str:
        # the straggler: a seeded PATHWAY_FAULTS schedule decides which
        # requests hit the slow path (serving.straggler@1+ = all of them)
        if straggle_s > 0 and faults.fire("serving.straggler"):
            time.sleep(straggle_s)
        scores = docs @ embed(q)
        top = int(np.argmax(scores))
        return f"doc{top}:{scores[top]:.3f}"

    gateway = None
    if not args.no_gateway:
        backpressure = None
        if args.backpressure:
            backpressure = pw.serving.WatermarkBackpressure(
                delay_lag_s=args.delay_lag_s, shed_lag_s=args.shed_lag_s
            )
        gateway = pw.serving.ServingGateway(
            rate=args.rate,
            burst=args.burst or args.rate,
            max_queue=args.max_queue,
            backpressure=backpressure,
        )
    webserver = pw.io.http.PathwayWebserver(host="127.0.0.1", port=port)
    queries, writer = pw.io.http.rest_connector(
        webserver=webserver,
        route="/answer",
        schema=pw.schema_from_types(query=str, user=str),
        gateway=gateway,
        delete_completed_queries=True,
        timeout_s=args.timeout_s,
    )
    writer(queries.select(result=rag_answer(pw.this.query)))

    def start_run() -> threading.Thread:
        t = threading.Thread(target=pw.run, daemon=True, name="pw-loadgen-run")
        t.start()
        return t

    return webserver, gateway, start_run


# the pipeline whose root the rolling-upgrade rung swaps: a paced
# streaming groupby persisted to ROOT with a real jsonlines sink (the
# same shape the blue/green drills in scripts/chaos_drill.py use)
UPGRADE_SOLO = textwrap.dedent(
    """
    import os, sys, time
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw
    from pathway_tpu.io.python import ConnectorSubject

    ROOT, OUT, N = sys.argv[1], sys.argv[2], int(sys.argv[3])

    class Nums(ConnectorSubject):
        def run(self):
            for i in range(N):
                self.next(g=f"g{{i % 4}}", v=i)
                time.sleep(0.005)

    t = pw.io.python.read(
        Nums(), schema=pw.schema_from_types(g=str, v=int), name="nums"
    )
    agg = t.groupby(t.g).reduce(
        t.g, total=pw.reducers.sum(t.v), n=pw.reducers.count()
    )
    pw.io.jsonlines.write(agg, OUT)
    pw.run(persistence_config=pw.persistence.Config(
        pw.persistence.Backend.filesystem(ROOT)))
    """
).format(repo=REPO)


def _upgrade_solo(root: str, out: str, n: int) -> None:
    r = subprocess.run(
        [sys.executable, "-c", UPGRADE_SOLO, root, out, str(n)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PATHWAY_THREADS": "1",
             "PATHWAY_FAULTS": "0"},
    )
    if r.returncode != 0:
        raise RuntimeError(
            f"upgrade pipeline rc={r.returncode}\n" + r.stderr[-2000:]
        )


def _upgrade_table(n: int) -> dict:
    exp: dict = {}
    for i in range(n):
        g = f"g{i % 4}"
        t0, n0 = exp.get(g, (0, 0))
        exp[g] = (t0 + i, n0 + 1)
    return exp


def _upgrade_sink_state(path: str) -> dict:
    state: dict = {}
    if os.path.exists(path):
        for line in open(path):
            rec = json.loads(line)
            if rec["diff"] > 0:
                state[rec["g"]] = (rec["total"], rec["n"])
            elif state.get(rec["g"]) == (rec["total"], rec["n"]):
                del state[rec["g"]]
    return state


def run_rolling_swap(workdir: str, info: dict) -> None:
    """One real blue/green swap: blue persisted at 32 events, green
    replays the full 64-event stream from the clone, gates verify, the
    rename pair commits. Fills `info` in place (the bench thread reads
    it after joining)."""
    from pathway_tpu.parallel import bluegreen as bg

    blue = os.path.join(workdir, "blue")
    try:
        t0 = time.perf_counter()

        def green(stage):
            out = os.path.join(workdir, "green.jsonl")
            _upgrade_solo(stage, out, 64)
            return _upgrade_sink_state(out)

        res = bg.swap_plan(blue, green, baseline=_upgrade_table(64))
        info["swap_seconds"] = round(time.perf_counter() - t0, 3)
        info["swap_committed"] = bool(res["committed"])
        if not res["committed"]:
            info["swap_reason"] = res["reason"]
    except Exception as e:  # noqa: BLE001 — the bench must still report
        info["swap_committed"] = False
        info["swap_reason"] = f"{type(e).__name__}: {e}"
    finally:
        info["t_end"] = time.perf_counter()


async def drive_clients(args, port: int) -> dict:
    """Closed-loop client fleet; returns raw measurements."""
    import aiohttp

    url = f"http://127.0.0.1:{port}/answer"
    latencies: list[float] = []
    stamps: list[float] = []  # completion time of each 200, for windowing
    counts = {"ok": 0, "shed": 0, "timeout": 0, "error": 0}
    stop_at = time.perf_counter() + args.duration
    conn = aiohttp.TCPConnector(limit=0)
    timeout = aiohttp.ClientTimeout(total=args.timeout_s + 30)
    async with aiohttp.ClientSession(connector=conn, timeout=timeout) as sess:

        async def client(i: int) -> None:
            n = 0
            while time.perf_counter() < stop_at:
                n += 1
                t0 = time.perf_counter()
                try:
                    async with sess.post(
                        url, json={"query": f"query {i} {n}", "user": f"u{i}"}
                    ) as resp:
                        await resp.read()
                        dt = time.perf_counter() - t0
                        if resp.status == 200:
                            counts["ok"] += 1
                            latencies.append(dt)
                            stamps.append(time.perf_counter())
                        elif resp.status == 429:
                            counts["shed"] += 1
                            ra = float(resp.headers.get("Retry-After", "1"))
                            await asyncio.sleep(min(ra, 0.25))
                        elif resp.status == 504:
                            counts["timeout"] += 1
                        else:
                            counts["error"] += 1
                except Exception:  # noqa: BLE001 — count, keep looping
                    counts["error"] += 1
                    await asyncio.sleep(0.05)

        await asyncio.gather(*(client(i) for i in range(args.clients)))
    return {"latencies": latencies, "stamps": stamps, **counts}


def percentile(xs: list[float], p: float) -> float | None:
    if not xs:
        return None
    xs = sorted(xs)
    k = min(int(round((p / 100.0) * (len(xs) - 1))), len(xs) - 1)
    return xs[k]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", type=int, default=100)
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--no-gateway", action="store_true")
    ap.add_argument("--rate", type=float, default=None,
                    help="route token-bucket rate (default: queue bound only)")
    ap.add_argument("--burst", type=float, default=None)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--backpressure", action="store_true",
                    help="arm watermark backpressure (needs observability)")
    ap.add_argument("--delay-lag-s", type=float, default=1.0)
    ap.add_argument("--shed-lag-s", type=float, default=5.0)
    ap.add_argument("--straggler-ms", type=float, default=0.0,
                    help="slow-path sleep when serving.straggler fires")
    ap.add_argument("--timeout-s", type=float, default=30.0)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--rolling-upgrade", action="store_true",
                    help="run a real blue/green plan swap mid-bench and "
                         "report during-swap vs outside-swap p99")
    ap.add_argument("--json", dest="json_path", default=None)
    args = ap.parse_args()

    if args.rolling_upgrade and (os.cpu_count() or 1) < 2:
        # the swap subprocess and the server would timeshare one core:
        # the p99 split would measure the OS scheduler, not the swap
        line = json.dumps({
            "skipped": True,
            "reason": "rolling-upgrade rung needs >=2 CPUs "
                      f"(os.cpu_count()={os.cpu_count()}); a 1-core host "
                      "serializes the swap against the server and the "
                      "p99 contrast is meaningless",
        })
        print(line)
        if args.json_path:
            with open(args.json_path, "w") as f:
                f.write(line + "\n")
        return 0

    port = args.port
    if port == 0:
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()

    import pathway_tpu as pw

    webserver, gateway, start_run = build_server(args, port)
    start_run()
    webserver._ready.wait(timeout=15)
    deadline = time.time() + 10  # wait until the pipeline answers
    import requests

    while time.time() < deadline:
        try:
            r = requests.post(
                f"http://127.0.0.1:{port}/answer",
                json={"query": "warmup", "user": "warmup"}, timeout=10,
            )
            if r.status_code in (200, 429):
                break
        except Exception:  # noqa: BLE001
            pass
        time.sleep(0.2)

    # sample server-side queue depth while the fleet runs
    depth_samples: list[int] = []
    sampling = True

    def sampler() -> None:
        while sampling:
            st = pw.io.http.route_stats().get("/answer", {})
            depth_samples.append(int(st.get("pending", 0)))
            time.sleep(0.05)

    st_thread = threading.Thread(target=sampler, daemon=True)
    st_thread.start()

    # rolling upgrade: stage blue BEFORE the bench (its pipeline run is
    # not part of the serving measurement), swap DURING it
    swap_info: dict = {}
    swap_thread = None
    upgrade_dir = None
    if args.rolling_upgrade:
        upgrade_dir = tempfile.mkdtemp(prefix="pathway-upgrade-")
        _upgrade_solo(
            os.path.join(upgrade_dir, "blue"),
            os.path.join(upgrade_dir, "blue.jsonl"), 32,
        )

        def _swapper() -> None:
            time.sleep(args.duration / 3.0)  # let the fleet reach steady state
            swap_info["t_start"] = time.perf_counter()
            run_rolling_swap(upgrade_dir, swap_info)

        swap_thread = threading.Thread(target=_swapper, daemon=True)

    t0 = time.perf_counter()
    if swap_thread is not None:
        swap_thread.start()
    raw = asyncio.run(drive_clients(args, port))
    wall = time.perf_counter() - t0
    sampling = False
    st_thread.join(timeout=2)
    if swap_thread is not None:
        swap_thread.join(timeout=120)

    lat = raw.pop("latencies")
    stamps = raw.pop("stamps")
    route = pw.io.http.route_stats().get("/answer", {})
    out = {
        "clients": args.clients,
        "duration_s": round(wall, 3),
        "gateway": not args.no_gateway,
        "max_queue": None if args.no_gateway else args.max_queue,
        "straggler_ms": args.straggler_ms,
        "ok": raw["ok"],
        "shed": raw["shed"],
        "timeout": raw["timeout"],
        "error": raw["error"],
        "p50_ms": round(1000 * percentile(lat, 50), 2) if lat else None,
        "p99_ms": round(1000 * percentile(lat, 99), 2) if lat else None,
        "goodput_rps": round(raw["ok"] / wall, 1) if wall > 0 else None,
        # the queue observable: futures piled into the connector
        "max_pending": int(max(depth_samples, default=0)),
        "route_max_pending": int(route.get("max_pending", 0)),
        "server_timeouts": int(route.get("timeouts", 0)),
    }
    if gateway is not None:
        out["gateway_stats"] = gateway.snapshot()
    if args.rolling_upgrade:
        t_start = swap_info.get("t_start")
        t_end = swap_info.get("t_end")
        during, outside = [], []
        if t_start is not None and t_end is not None:
            for ts, dt in zip(stamps, lat):
                (during if t_start <= ts <= t_end else outside).append(dt)
        out["rolling_upgrade"] = {
            "swap_committed": swap_info.get("swap_committed", False),
            "swap_seconds": swap_info.get("swap_seconds"),
            "ok_during_swap": len(during),
            "p99_ms_during_swap": (
                round(1000 * percentile(during, 99), 2) if during else None
            ),
            "p99_ms_outside_swap": (
                round(1000 * percentile(outside, 99), 2) if outside else None
            ),
        }
        if "swap_reason" in swap_info:
            out["rolling_upgrade"]["swap_reason"] = swap_info["swap_reason"]
        if upgrade_dir:
            import shutil

            shutil.rmtree(upgrade_dir, ignore_errors=True)
    line = json.dumps(out)
    print(line)
    if args.json_path:
        with open(args.json_path, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
